#!/usr/bin/env python
"""ZeroWire smoke check — one-pass integrity + shm lane, end to end
against live daemons (ISSUE 15).

Asserts the evidence the zero-copy wire claims:

  * ONE crc pass per byte: with client csums precomputed (the
    staged-in-HBM shape), a put's payload is scanned exactly once —
    the daemon's verify — and BlueStore adopts the verified sub-crcs
    (``trusted_csum_bytes`` advances, ``scan_store_bytes`` does NOT);
    counted by the perf('wire.zero') scan hook, not assumed;
  * the shm lane NEGOTIATES on a vstart pair and actually carries the
    payload bytes (client ``shm_frames``/daemon ``shm_frames_served``
    advance), with readback byte-identical;
  * TCP/socket fallback: with ``wire_shm_ring_kib=0`` the same ops
    complete with no ring traffic — the lane is an optimization, not
    a dependency.

Runs on CPU (no accelerator needed):

    JAX_PLATFORMS=cpu python scripts/check_wire.py

Also wired as a fast pytest test (tests/test_wire_zero.py, `smoke`
marker) so CI covers it without a separate job.
"""
from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


def _daemon_counters(cluster_dir: str, n_osds: int) -> dict:
    from ceph_tpu.common import crcutil
    return crcutil.wire_zero_counters(cluster_dir, n_osds,
                                      include_local=False)


def run_checks(cluster_dir: str, n_osds: int) -> int:
    from ceph_tpu.client.remote import RemoteCluster
    from ceph_tpu.common import crcutil
    from ceph_tpu.common.options import config
    from ceph_tpu.common.perf_counters import perf

    rc = RemoteCluster(cluster_dir)
    pool = rc.osdmap.pools[1]

    # 1) exactly one crc pass per byte via the scan-counting hook
    data = os.urandom(4 << 20)
    cs = crcutil.Csums.scan(data)       # the device-crc stand-in
    pg = rc._pg_for(pool, "cw-onepass")
    tgt = [o for o in rc._up(pool, pg) if o >= 0][0]
    d0 = _daemon_counters(cluster_dir, n_osds)
    c0 = perf("wire.zero").dump()
    rc.osd_call(tgt, {"cmd": "put_shard", "coll": [1, pg],
                      "oid": "0:cw-onepass", "data": data,
                      "_csums": cs, "attrs": {}})
    d1 = _daemon_counters(cluster_dir, n_osds)
    c1 = perf("wire.zero").dump()
    n = len(data)
    verify = d1.get("scan_verify_bytes", 0) - \
        d0.get("scan_verify_bytes", 0)
    store = d1.get("scan_store_bytes", 0) - \
        d0.get("scan_store_bytes", 0)
    trusted = d1.get("trusted_csum_bytes", 0) - \
        d0.get("trusted_csum_bytes", 0)
    sent = c1.get("scan_send_bytes", 0) - c0.get("scan_send_bytes", 0)
    if not (n <= verify < 1.05 * n + 65536):
        return _fail(f"daemon verify scanned {verify} bytes of {n} "
                     f"(want exactly one pass)")
    if store:
        return _fail(f"store re-scanned {store} bytes despite "
                     f"trusted csums")
    if trusted < n:
        return _fail(f"only {trusted} bytes adopted trusted csums")
    if sent >= 65536:
        return _fail(f"client re-scanned {sent} bytes despite "
                     f"precomputed csums")

    # 2) shm negotiation + payload movement on the vstart pair
    blob = os.urandom(2 << 20)
    s0 = perf("wire.zero").dump().get("shm_bytes", 0)
    rc.put(1, "cw-shm", blob)
    if rc.get(1, "cw-shm") != blob:
        return _fail("shm-lane readback diverged")
    moved = perf("wire.zero").dump().get("shm_bytes", 0) - s0
    served = _daemon_counters(cluster_dir, n_osds) \
        .get("shm_frames_served", 0)
    if moved < len(blob):
        return _fail(f"shm ring moved only {moved} bytes "
                     f"(lane did not negotiate?)")
    if not served:
        return _fail("daemon served no shm frames")

    # 3) fallback: ring disabled -> same ops, zero ring traffic.
    # The option is read when an objecter builds its stream pools, so
    # the check uses a FRESH client handle (the existing one's pools
    # legitimately keep their negotiated rings).
    config().set("wire_shm_ring_kib", 0)
    rc2 = RemoteCluster(cluster_dir)
    try:
        f0 = perf("wire.zero").dump().get("shm_frames", 0)
        blob2 = os.urandom(1 << 20)
        rc2.aio_put(1, "cw-sock", blob2).get_return_value()
        if rc2.get(1, "cw-sock") != blob2:
            return _fail("socket-fallback readback diverged")
        if perf("wire.zero").dump().get("shm_frames", 0) != f0:
            return _fail("ring traffic with the lane disabled")
    finally:
        rc2.close()
        config().clear("wire_shm_ring_kib")

    rc.close()
    print(f"OK: ZeroWire verified (verify={verify}B store=0 "
          f"trusted={trusted}B shm_moved={moved}B)")
    return 0


def main() -> int:
    import shutil
    import tempfile
    from ceph_tpu.tools.vstart import Vstart, build_cluster_dir

    n_osds = 2
    tmp = tempfile.mkdtemp(prefix="check-wire-")
    d = os.path.join(tmp, "cluster")
    build_cluster_dir(d, n_osds=n_osds, osds_per_host=1, fsync=False)
    v = Vstart(d)
    v.start(n_osds, hb_interval=60.0)
    try:
        return run_checks(d, n_osds)
    finally:
        v.stop()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
