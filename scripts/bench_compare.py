#!/usr/bin/env python3
"""Compare the newest two BENCH_r*.json snapshots and fail on regression.

Each BENCH_r*.json is a driver snapshot ``{n, cmd, rc, tail, parsed}``
where ``parsed`` is the headline JSON line bench.py prints
(``{"metric", "unit", "value", "vs_baseline", "extras": {...}}``).
This script diffs the named headline metrics between the newest two
snapshots and exits nonzero when any of them regressed by more than
the threshold (default 30%).  Higher is better for throughput/rate
metrics; the LOWER_BETTER set (per-byte cost counters: daemon crc
passes/MiB, reply-lane copies/MiB) regresses when it RISES — and a
zero-to-nonzero move on those is always a regression, threshold or
not (the whole point of a counter-backed zero is that it cannot
quietly stop being zero).

Usage:
    python scripts/bench_compare.py [--dir REPO] [--threshold 0.30]

Exit codes: 0 ok / nothing to compare with <2 files, 1 regression,
2 malformed snapshots.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

# Headline metrics ("metric" itself plus dotted paths into "extras").
# Only metrics present in BOTH snapshots are compared — a metric that
# first appears in the newer run is new coverage, not a regression.
HEADLINE = (
    "ec_encode_rs8_3_gbps",
    "extras.ec_decode_rs8_3_gbps",
    "extras.crush_mappings_per_s",
    "extras.cluster_system.put_gbps",
    "extras.cluster_system.degraded_get_gbps",
    # RingReply per-byte cost counters (lower is better): the
    # device-resident daemon's host crc passes and the reply lane's
    # send passes / copies — all 0 after ISSUE 20; a rise fails the
    # smoke gate
    "extras.wire_zero.after_device.crc_passes_per_mib",
    "extras.wire_zero.reply.after.send_passes_per_mib",
    "extras.wire_zero.reply.after.copies_per_mib",
)

# metrics where a RISE is the regression (per-byte costs, not rates)
LOWER_BETTER = frozenset(
    n for n in HEADLINE
    if n.endswith("_per_mib"))


def _load_parsed(path: str):
    with open(path, "r", encoding="utf-8") as fh:
        snap = json.load(fh)
    parsed = snap.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        return parsed
    # fall back to scraping the tail for the headline JSON line
    for line in reversed((snap.get("tail") or "").splitlines()):
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def _lookup(parsed: dict, name: str):
    """Resolve a headline name against a parsed snapshot: the bare
    metric name matches ``parsed["metric"]``; dotted ``extras.*``
    paths walk into the extras tree."""
    if not name.startswith("extras."):
        if parsed.get("metric") == name:
            return parsed.get("value")
        return None
    node = parsed.get("extras") or {}
    for part in name.split(".")[1:]:
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node if isinstance(node, (int, float)) else None


def compare(old: dict, new: dict, threshold: float):
    """Return (rows, regressions) comparing headline metrics.
    Rate metrics regress on a drop past the threshold; LOWER_BETTER
    cost counters regress on a rise — including any move off an
    exact 0 (no threshold shelters breaking a counter-backed zero)."""
    rows, regressions = [], []
    for name in HEADLINE:
        a, b = _lookup(old, name), _lookup(new, name)
        if a is None or b is None:
            continue
        if name in LOWER_BETTER:
            if a == 0:
                if b == 0:
                    rows.append((name, a, b, 0.0))
                    continue
                delta = float("inf")
            else:
                delta = (b - a) / abs(a)
            rows.append((name, a, b, delta))
            if delta > threshold or (a == 0 and b > 0):
                regressions.append((name, a, b, delta))
        else:
            if not a:
                continue
            delta = (b - a) / abs(a)
            rows.append((name, a, b, delta))
            if delta < -threshold:
                regressions.append((name, a, b, delta))
    return rows, regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".."))
    ap.add_argument("--threshold", type=float, default=0.30,
                    help="fractional drop that counts as a regression")
    ns = ap.parse_args(argv)

    files = glob.glob(os.path.join(ns.dir, "BENCH_r*.json"))
    # newest two by run number (BENCH_r05 > BENCH_r04), not mtime —
    # a checkout touches every mtime
    files.sort(key=lambda p: int(
        re.search(r"BENCH_r(\d+)", p).group(1)))
    if len(files) < 2:
        print(f"bench_compare: {len(files)} snapshot(s), nothing to "
              "compare")
        return 0
    old_p, new_p = files[-2], files[-1]
    old, new = _load_parsed(old_p), _load_parsed(new_p)
    if old is None or new is None:
        print(f"bench_compare: malformed snapshot "
              f"({old_p if old is None else new_p})", file=sys.stderr)
        return 2

    rows, regressions = compare(old, new, ns.threshold)
    print(f"bench_compare: {os.path.basename(old_p)} -> "
          f"{os.path.basename(new_p)}  (threshold "
          f"{ns.threshold:.0%})")
    bad = {name for name, *_ in regressions}
    for name, a, b, delta in rows:
        flag = "  REGRESSED" if name in bad else ""
        arrow = " (lower is better)" if name in LOWER_BETTER else ""
        print(f"  {name:44s} {a:12.3f} -> {b:12.3f}  "
              f"{delta:+7.1%}{flag}{arrow}")
    if not rows:
        print("  (no shared headline metrics)")
    if regressions:
        print(f"bench_compare: {len(regressions)} headline metric(s) "
              f"regressed >{ns.threshold:.0%}", file=sys.stderr)
        return 1
    print("bench_compare: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
