#!/usr/bin/env python3
"""Generate golden test vectors from the reference CRUSH C implementation.

Dev-time-only script: compiles the reference C core (mounted read-only at
/root/reference) into a scratch shared library under /tmp, drives it through
ctypes, and writes:

  - tests/golden/hash_vectors.json    rjenkins1 hash outputs
  - tests/golden/crush_vectors.json   crush_do_rule results over a family of maps
  - ceph_tpu/placement/data/crush_ln_u16.npy
        the 65536-entry crush_ln LUT (int64).  straw2 only ever evaluates
        crush_ln(u) for u in [0, 0xffff] (reference: src/crush/mapper.c:334-359),
        so the whole 2^44*log2(x+1) fixed-point pipeline collapses to this LUT.
        NOTE: the reference's __LL_tbl deviates from its stated generating
        formula in 235/256 entries (a long-standing upstream quirk kept for
        compatibility); the LUT is therefore extracted, not regenerated.

The committed artifacts are pure interoperability data (golden outputs and
fixed-point constants), not code.
"""
import ctypes
import json
import os
import re
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = os.environ.get("CEPH_REFERENCE", "/root/reference")
BUILD = "/tmp/refcrush_golden"


def build_oracle():
    os.makedirs(BUILD, exist_ok=True)
    open(os.path.join(BUILD, "acconfig.h"), "w").close()
    so = os.path.join(BUILD, "librefcrush.so")
    srcs = [os.path.join(REF, "src/crush", f)
            for f in ("hash.c", "mapper.c", "crush.c", "builder.c")]
    subprocess.check_call(
        ["gcc", "-O2", "-shared", "-fPIC", "-I" + BUILD, "-I" + os.path.join(REF, "src"),
         "-o", so] + srcs)
    return ctypes.CDLL(so)


# ---------------------------------------------------------------- ln LUT ----

def parse_ln_tables():
    src = open(os.path.join(REF, "src/crush/crush_ln_table.h")).read()

    def parse(name):
        m = re.search(name + r"\[[^]]*\] = \{(.*?)\};", src, re.S)
        return [int(v, 16) for v in re.findall(r"0x([0-9a-fA-F]+)[ul]*l*", m.group(1))]

    return parse("__RH_LH_tbl"), parse("__LL_tbl")


def crush_ln(xin, rh_lh, ll):
    """Fixed-point 2^44*log2(x+1); semantics of reference src/crush/mapper.c:248-290."""
    x = (xin + 1) & 0xFFFFFFFF
    iexpon = 15
    if not (x & 0x18000):
        bits = 0
        v = x & 0x1FFFF
        while not (v & 0x18000):
            v = (v << 1) & 0x1FFFF
            bits += 1
        x = (x << bits) & 0xFFFFFFFF
        iexpon = 15 - bits
    index1 = (x >> 8) << 1
    RH = rh_lh[index1 - 256]
    LH = rh_lh[index1 + 1 - 256]
    xl64 = (x * RH) >> 48
    result = iexpon << 44
    index2 = xl64 & 0xFF
    LL = ll[index2]
    return result + ((LH + LL) >> 4)


def gen_ln_lut():
    rh_lh, ll = parse_ln_tables()
    lut = np.array([crush_ln(u, rh_lh, ll) for u in range(0x10000)], dtype=np.int64)
    out = os.path.join(REPO, "ceph_tpu/placement/data/crush_ln_u16.npy")
    np.save(out, lut)
    print(f"wrote {out}: [{lut[0]}, {lut[1]}, ..., {lut[-1]}]")
    return lut


# ------------------------------------------------------------ hash golden ----

def gen_hash_vectors(lib):
    lib.crush_hash32.restype = ctypes.c_uint32
    lib.crush_hash32_2.restype = ctypes.c_uint32
    lib.crush_hash32_3.restype = ctypes.c_uint32
    lib.crush_hash32_4.restype = ctypes.c_uint32
    lib.crush_hash32_5.restype = ctypes.c_uint32
    rng = np.random.RandomState(1234)
    vals = [0, 1, 2, 0xFFFF, 0x10000, 0x7FFFFFFF, 0x80000000, 0xFFFFFFFF]
    vals += [int(v) for v in rng.randint(0, 2**32, size=24, dtype=np.uint64)]
    out = {"inputs": vals, "h1": [], "h2": [], "h3": [], "h4": [], "h5": []}
    u = ctypes.c_uint32
    for i, a in enumerate(vals):
        b = vals[(i + 7) % len(vals)]
        c = vals[(i + 13) % len(vals)]
        d = vals[(i + 19) % len(vals)]
        e = vals[(i + 23) % len(vals)]
        out["h1"].append(lib.crush_hash32(0, u(a)))
        out["h2"].append(lib.crush_hash32_2(0, u(a), u(b)))
        out["h3"].append(lib.crush_hash32_3(0, u(a), u(b), u(c)))
        out["h4"].append(lib.crush_hash32_4(0, u(a), u(b), u(c), u(d)))
        out["h5"].append(lib.crush_hash32_5(0, u(a), u(b), u(c), u(d), u(e)))
    path = os.path.join(REPO, "tests/golden/hash_vectors.json")
    json.dump(out, open(path, "w"))
    print(f"wrote {path} ({len(vals)} inputs)")


# ----------------------------------------------------------- crush golden ----

class CrushMapStruct(ctypes.Structure):
    _fields_ = [
        ("buckets", ctypes.c_void_p),
        ("rules", ctypes.c_void_p),
        ("max_buckets", ctypes.c_int32),
        ("max_rules", ctypes.c_uint32),
        ("max_devices", ctypes.c_int32),
        ("choose_local_tries", ctypes.c_uint32),
        ("choose_local_fallback_tries", ctypes.c_uint32),
        ("choose_total_tries", ctypes.c_uint32),
        ("chooseleaf_descend_once", ctypes.c_uint32),
        ("chooseleaf_vary_r", ctypes.c_uint8),
        ("chooseleaf_stable", ctypes.c_uint8),
        ("working_size", ctypes.c_size_t),
        ("straw_calc_version", ctypes.c_uint8),
        ("allowed_bucket_algs", ctypes.c_uint32),
        ("choose_tries", ctypes.c_void_p),
    ]


TUNABLE_PROFILES = {
    # CrushWrapper.h:144-210
    "argonaut": dict(choose_local_tries=2, choose_local_fallback_tries=5,
                     choose_total_tries=19, chooseleaf_descend_once=0,
                     chooseleaf_vary_r=0, chooseleaf_stable=0),
    "bobtail": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                    choose_total_tries=50, chooseleaf_descend_once=1,
                    chooseleaf_vary_r=0, chooseleaf_stable=0),
    "firefly": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                    choose_total_tries=50, chooseleaf_descend_once=1,
                    chooseleaf_vary_r=1, chooseleaf_stable=0),
    "jewel": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                  choose_total_tries=50, chooseleaf_descend_once=1,
                  chooseleaf_vary_r=1, chooseleaf_stable=1),
}


class Oracle:
    def __init__(self, lib):
        self.lib = lib
        lib.crush_create.restype = ctypes.POINTER(CrushMapStruct)
        lib.crush_make_bucket.restype = ctypes.c_void_p
        lib.crush_make_rule.restype = ctypes.c_void_p
        lib.crush_do_rule.restype = ctypes.c_int

    def build(self, spec):
        lib = self.lib
        m = lib.crush_create()
        mp = m.contents
        for k, v in spec["tunables"].items():
            setattr(mp, k, v)
        for b in spec["buckets"]:
            n = len(b["items"])
            items = (ctypes.c_int * n)(*b["items"])
            weights = (ctypes.c_int * n)(*b["weights"])
            bkt = lib.crush_make_bucket(m, b["alg"], 0, b["type"], n, items, weights)
            assert bkt, f"make_bucket failed for {b}"
            idout = ctypes.c_int()
            r = lib.crush_add_bucket(m, b["id"], ctypes.c_void_p(bkt),
                                     ctypes.byref(idout))
            assert r == 0 and idout.value == b["id"], (r, idout.value, b["id"])
        for ri, rule in enumerate(spec["rules"]):
            steps = rule["steps"]
            rr = lib.crush_make_rule(len(steps), 0, 1, 1, 10)
            for i, (op, a1, a2) in enumerate(steps):
                lib.crush_rule_set_step(ctypes.c_void_p(rr), i, op, a1, a2)
            rno = lib.crush_add_rule(m, ctypes.c_void_p(rr), ri)
            assert rno == ri, (rno, ri)
        lib.crush_finalize(m)
        return m

    def do_rule(self, m, ruleno, x, result_max, weights):
        mp = m.contents
        ws = ctypes.create_string_buffer(mp.working_size + 3 * result_max * 4 + 64)
        self.lib.crush_init_workspace(m, ws)
        result = (ctypes.c_int * result_max)()
        n = len(weights)
        warr = (ctypes.c_uint32 * n)(*weights)
        rl = self.lib.crush_do_rule(m, ruleno, ctypes.c_int(x), result,
                                    ctypes.c_int(result_max), warr,
                                    ctypes.c_int(n), ws, None)
        return [result[i] for i in range(rl)]


OP = dict(take=1, choose_firstn=2, choose_indep=3, emit=4,
          chooseleaf_firstn=6, chooseleaf_indep=7,
          set_choose_tries=8, set_chooseleaf_tries=9,
          set_choose_local_tries=10, set_choose_local_fallback_tries=11,
          set_chooseleaf_vary_r=12, set_chooseleaf_stable=13)

UNIFORM, LIST, TREE, STRAW, STRAW2 = 1, 2, 3, 4, 5


def make_specs():
    specs = []
    W = 0x10000  # 1.0 in 16.16 fixed point

    # --- 1. flat straw2, 12 osds, mixed weights
    flat = {
        "name": "flat_straw2",
        "tunables": TUNABLE_PROFILES["jewel"],
        "buckets": [
            {"id": -1, "alg": STRAW2, "type": 1,
             "items": list(range(12)),
             "weights": [W, W, 2 * W, W // 2, W, 3 * W, W, W, W // 4, W, W, 5 * W]},
        ],
        "rules": [
            {"steps": [(OP["take"], -1, 0), (OP["choose_firstn"], 0, 0), (OP["emit"], 0, 0)]},
            {"steps": [(OP["take"], -1, 0), (OP["choose_indep"], 0, 0), (OP["emit"], 0, 0)]},
        ],
        "num_devices": 12,
    }
    specs.append(flat)

    # --- 2. two-level host/osd tree: 6 hosts x 4 osds, chooseleaf
    hosts = []
    root_items, root_w = [], []
    for h in range(6):
        osds = list(range(h * 4, h * 4 + 4))
        w = [W, 2 * W, W, W]
        hosts.append({"id": -(2 + h), "alg": STRAW2, "type": 1,
                      "items": osds, "weights": w})
        root_items.append(-(2 + h))
        root_w.append(sum(w))
    two = {
        "name": "two_level",
        "tunables": TUNABLE_PROFILES["jewel"],
        "buckets": [{"id": -1, "alg": STRAW2, "type": 2,
                     "items": root_items, "weights": root_w}] + hosts,
        "rules": [
            {"steps": [(OP["take"], -1, 0), (OP["chooseleaf_firstn"], 0, 1), (OP["emit"], 0, 0)]},
            {"steps": [(OP["take"], -1, 0), (OP["chooseleaf_indep"], 0, 1), (OP["emit"], 0, 0)]},
            {"steps": [(OP["take"], -1, 0), (OP["choose_firstn"], 0, 1),
                       (OP["choose_firstn"], 1, 0), (OP["emit"], 0, 0)]},
            {"steps": [(OP["take"], -1, 0), (OP["set_chooseleaf_tries"], 5, 0),
                       (OP["chooseleaf_firstn"], 0, 1), (OP["emit"], 0, 0)]},
        ],
        "num_devices": 24,
    }
    specs.append(two)

    # --- 3. same two-level shape, legacy tunables (exercises local retries)
    legacy = dict(two)
    legacy = json.loads(json.dumps(two))
    legacy["name"] = "two_level_argonaut"
    legacy["tunables"] = TUNABLE_PROFILES["argonaut"]
    specs.append(legacy)

    bobtail = json.loads(json.dumps(two))
    bobtail["name"] = "two_level_bobtail"
    bobtail["tunables"] = TUNABLE_PROFILES["bobtail"]
    specs.append(bobtail)

    # --- 4. three-level rack/host/osd with firstn over racks
    racks, all_hosts = [], []
    hid = 0
    for r in range(3):
        rk_items, rk_w = [], []
        for hh in range(3):
            osds = [hid * 3 + i for i in range(3)]
            w = [W] * 3
            all_hosts.append({"id": -(10 + hid), "alg": STRAW2, "type": 1,
                              "items": osds, "weights": w})
            rk_items.append(-(10 + hid))
            rk_w.append(sum(w))
            hid += 1
        racks.append({"id": -(2 + r), "alg": STRAW2, "type": 2,
                      "items": rk_items, "weights": rk_w})
    three = {
        "name": "three_level",
        "tunables": TUNABLE_PROFILES["jewel"],
        "buckets": [{"id": -1, "alg": STRAW2, "type": 3,
                     "items": [-2, -3, -4], "weights": [9 * W] * 3}] + racks + all_hosts,
        "rules": [
            # replicated across racks
            {"steps": [(OP["take"], -1, 0), (OP["chooseleaf_firstn"], 0, 2), (OP["emit"], 0, 0)]},
            # EC-style: 2 racks, 2 osds each? -> choose 3 racks indep, chooseleaf 1
            {"steps": [(OP["take"], -1, 0), (OP["choose_indep"], 3, 2),
                       (OP["chooseleaf_indep"], 2, 1), (OP["emit"], 0, 0)]},
            # choose firstn hosts then osds
            {"steps": [(OP["take"], -1, 0), (OP["choose_firstn"], 2, 2),
                       (OP["choose_firstn"], 2, 1), (OP["choose_firstn"], 1, 0),
                       (OP["emit"], 0, 0)]},
        ],
        "num_devices": 27,
    }
    specs.append(three)

    # --- 5. other bucket algs (uniform / list / tree / straw) flat maps
    for alg, name in ((UNIFORM, "uniform"), (LIST, "list"), (TREE, "tree"), (STRAW, "straw")):
        specs.append({
            "name": f"flat_{name}",
            "tunables": TUNABLE_PROFILES["jewel"],
            "buckets": [{"id": -1, "alg": alg, "type": 1,
                         "items": list(range(8)),
                         "weights": [W] * 8 if alg == UNIFORM else
                         [W, W, 2 * W, W, W // 2, W, W, 3 * W]}],
            "rules": [
                {"steps": [(OP["take"], -1, 0), (OP["choose_firstn"], 0, 0), (OP["emit"], 0, 0)]},
                {"steps": [(OP["take"], -1, 0), (OP["choose_indep"], 0, 0), (OP["emit"], 0, 0)]},
            ],
            "num_devices": 8,
        })

    # --- 6. big flat straw2 bucket (exercises the whole ln LUT range)
    rng = np.random.RandomState(7)
    nbig = 100
    specs.append({
        "name": "big_flat_straw2",
        "tunables": TUNABLE_PROFILES["jewel"],
        "buckets": [{"id": -1, "alg": STRAW2, "type": 1,
                     "items": list(range(nbig)),
                     "weights": [int(w) for w in rng.randint(W // 8, 8 * W, size=nbig)]}],
        "rules": [
            {"steps": [(OP["take"], -1, 0), (OP["choose_firstn"], 0, 0), (OP["emit"], 0, 0)]},
            {"steps": [(OP["take"], -1, 0), (OP["choose_indep"], 0, 0), (OP["emit"], 0, 0)]},
        ],
        "num_devices": nbig,
    })
    return specs


def gen_crush_vectors(lib):
    oracle = Oracle(lib)
    specs = make_specs()
    cases = []
    rng = np.random.RandomState(42)
    for si, spec in enumerate(specs):
        m = oracle.build(spec)
        nd = spec["num_devices"]
        weight_sets = {
            "all_in": [0x10000] * nd,
            "some_out": [0 if i % 5 == 0 else 0x10000 for i in range(nd)],
            "reweighted": [int(w) for w in rng.randint(0, 0x10001, size=nd)],
        }
        xs = list(range(64)) + [int(v) for v in rng.randint(0, 2**31 - 1, size=64)]
        for ruleno in range(len(spec["rules"])):
            for wname, wv in weight_sets.items():
                for result_max in (3, 5):
                    for x in xs:
                        res = oracle.do_rule(m, ruleno, x, result_max, wv)
                        cases.append({"map": si, "rule": ruleno, "x": x,
                                      "result_max": result_max, "weights": wname,
                                      "result": res})
    out = {"specs": specs, "weight_set_names": ["all_in", "some_out", "reweighted"],
           "cases": cases}
    path = os.path.join(REPO, "tests/golden/crush_vectors.json")
    json.dump(out, open(path, "w"))
    print(f"wrote {path}: {len(specs)} maps, {len(cases)} cases")


if __name__ == "__main__":
    lib = build_oracle()
    gen_ln_lut()
    gen_hash_vectors(lib)
    gen_crush_vectors(lib)
