#!/usr/bin/env python
"""Static-analysis smoke check — CTLint v2 verified end to end.

Two tiers, both fast enough for the smoke sweep:

  1. SEEDED tier: a throwaway fixture tree carries one deliberate
     violation per headline family — a cross-module host sync under
     jit (CTL101, resolvable only by the whole-program graph), a raw
     daemon-plane lock (CTL302), an undeclared faultpoint fire
     (CTL601), an unstamped data-path send through a cross-module
     wrapper (CTL701), a typo'd wire cmd (CTL801), an unstamped
     mutating send (CTL802), a short send missing a handler-read key
     (CTL803), and a duplicate faultpoint declare (CTL804).  Every
     seeded violation must be caught, or the gate is lying.

  2. REAL tier: the repo tree must be lint-clean against the
     committed baseline with ZERO stale entries, inside the 30 s
     wall-time budget the tier-1 gate depends on.

Runs on CPU:

    python scripts/check_static.py            # both tiers
    python scripts/check_static.py --quick    # seeded tier only

Also wired as a fast pytest test (tests/test_lint.py, `smoke`
marker) so CI covers it without a separate job.
"""
from __future__ import annotations

import os
import sys
import tempfile
import textwrap
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


# (relpath, source, expected rule ids at least once in that file)
_SEEDS = (
    ("pkg/__init__.py", "", ()),
    ("pkg/hot_helper.py", """
        import numpy as np

        def mix(y):
            return np.asarray(y).item()
        """, ("CTL101",)),
    ("pkg/hot_entry.py", """
        import jax
        from .hot_helper import mix

        @jax.jit
        def f(x):
            return mix(x)
        """, ()),
    ("cluster/locks.py", """
        import threading
        L = threading.Lock()
        """, ("CTL302",)),
    ("cluster/fire.py", """
        from ceph_tpu.common import faults

        def send():
            return faults.fire("never.declared")
        """, ("CTL601",)),
    ("cluster/wrapper.py", """
        def fanout(conn, req):
            return conn.call(req)
        """, ()),
    ("cluster/sender.py", """
        from .wrapper import fanout

        def gap(conn, coll, oid):
            # CTL701 reports at the call site handing the unstamped
            # dict to the cross-module raw-send wrapper
            return fanout(conn, {"cmd": "get_shard", "coll": coll,
                                 "oid": oid})

        def typo(conn, coll):
            return conn.osd_call(0, {"cmd": "get_shrad",
                                     "coll": coll, "oid": "o"})

        def unstamped(conn, coll, data):
            return conn.call({"cmd": "put_thing", "coll": coll,
                              "data": data, "tctx": None})

        def short(conn, coll):
            return conn.osd_call(0, {"cmd": "put_thing",
                                     "coll": coll, "tctx": None})
        """, ("CTL701", "CTL801", "CTL802", "CTL803")),
    ("cluster/daemon.py", """
        _REPLAY_CMDS = frozenset(("put_thing",))

        class Daemon:
            def _handle(self, entity, req):
                cmd = req["cmd"]
                if cmd == "put_thing":
                    return (req["coll"], req["data"])
                if cmd == "get_shard":
                    return req["oid"]
        """, ()),
    ("cluster/decl.py", """
        from ceph_tpu.common import faults
        faults.declare("twice.over", "first")
        """, ()),
    ("cluster/decl2.py", """
        from ceph_tpu.common import faults
        faults.declare("twice.over", "second site")
        """, ("CTL804",)),
    # ShardCheck seeds: an unbound collective axis (CTL1001) and a
    # per-shard reduction returned through a replicated out_spec with
    # no psum (CTL1005) — the two SPMD bugs that trace fine on the
    # forced-CPU CI mesh and detonate only on a real multi-device host
    ("parallel/mesh.py", """
        SHARD_AXIS = "shard"
        """, ()),
    ("parallel/plane.py", """
        import jax
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from .mesh import SHARD_AXIS

        def _body(x):
            total = jnp.sum(x)
            moved = jax.lax.ppermute(
                x, SHRAD_AXIS, perm=[(0, 1), (1, 0)])
            return moved, total

        SHRAD_AXIS = "shrad"

        def build(mesh):
            return jax.jit(shard_map(
                _body, mesh=mesh,
                in_specs=(P(SHARD_AXIS),),
                out_specs=(P(SHARD_AXIS), P())))
        """, ("CTL1001", "CTL1005")),
)


def _check_seeded() -> int:
    from ceph_tpu.analysis import runner
    with tempfile.TemporaryDirectory(prefix="ctlint-smoke-") as tmp:
        for rel, src, _want in _SEEDS:
            p = os.path.join(tmp, rel)
            os.makedirs(os.path.dirname(p), exist_ok=True)
            with open(p, "w", encoding="utf-8") as f:
                f.write(textwrap.dedent(src))
        res = runner.run(tmp, paths=["."], evidence_paths=[],
                         baseline=None)
        got = {}
        for f in res.findings:
            got.setdefault(f.path, set()).add(f.rule)
        for rel, _src, want in _SEEDS:
            missed = set(want) - got.get(rel, set())
            if missed:
                return _fail(
                    f"seeded violation(s) NOT caught in {rel}: "
                    f"{sorted(missed)} (caught: "
                    f"{sorted(got.get(rel, set()))})")
    n = sum(len(w) for _r, _s, w in _SEEDS)
    print(f"OK: seeded tier — all {n} seeded violations caught")
    return 0


def _check_real_tree() -> int:
    from ceph_tpu.analysis import runner
    t0 = time.perf_counter()
    res = runner.run(
        _REPO,
        baseline=os.path.join(_REPO, "scripts",
                              "lint_baseline.json"))
    elapsed = time.perf_counter() - t0
    if res.findings:
        lines = "\n  ".join(f.render() for f in res.findings[:20])
        return _fail(f"tree is not lint-clean:\n  {lines}")
    if res.stale_baseline:
        return _fail(f"stale baseline entries: "
                     f"{res.stale_baseline}")
    if elapsed >= 30.0:
        return _fail(f"full-tree lint took {elapsed:.1f}s — past "
                     f"the 30 s CI budget")
    print(f"OK: real tier — tree clean, "
          f"{len(res.baselined)} baselined, "
          f"{elapsed:.1f}s (< 30 s budget)")
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    rc = _check_seeded()
    if rc:
        return rc
    if "--quick" not in argv:
        rc = _check_real_tree()
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
