#!/usr/bin/env python
"""Robustness smoke check — a seeded thrash run, invariants asserted.

Drives the whole ISSUE-3 failure pipeline in one pass: a small seeded
kill/revive soak (cluster/thrasher.py) with the wire-drop and
device-EIO faultpoints armed under client writes, then asserts the
self-healing invariants —

  * every client op completed (OpTracker: zero stuck in flight),
  * zero data loss (readback matches the oracle for every object),
  * deep scrub reports 0 inconsistencies after repair,
  * health converged to HEALTH_OK within the tick bound,
  * every armed faultpoint FIRED at least once (perf-counter proof),
  * the identical seed reproduces the identical schedule and fire
    counts (the regression-test property),

then a quick NETSPLIT soak (ISSUE 6: seeded partition/heal cycles via
``net.partition`` with ``msg.drop_ack`` losing committed completions)
asserting the same set PLUS replay idempotency (no op applies twice
under session replay) and linear mon epoch history (no split brain).

Runs on CPU (no accelerator needed):

    JAX_PLATFORMS=cpu python scripts/check_robustness.py

Also wired as a fast pytest test (tests/test_thrasher.py, `smoke`
marker) so CI covers it without a separate job — the
check_observability.py pattern.
"""
from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable as `python scripts/check_robustness.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


def run_once(seed: int, cycles: int = 3, netsplit: bool = False):
    from ceph_tpu.cluster.thrasher import (NETSPLIT_FAULTPOINTS,
                                           Thrasher, ThrashConfig,
                                           build_default_stack)
    from ceph_tpu.common import faults
    sim, mon = build_default_stack()
    try:
        cfg = ThrashConfig(seed=seed, cycles=cycles,
                           objects=4, writes_per_cycle=2)
        if netsplit:
            cfg.netsplit = True
            cfg.faultpoints = NETSPLIT_FAULTPOINTS
            cfg.settle_ticks = 40
        t = Thrasher(sim, mon, [1, 2], cfg)
        return t.run()
    finally:
        sim.shutdown()
        faults.reset()


def run_crash_smoke(workdir=None) -> int:
    """CrashDev smoke (ISSUE 9): a seeded BlueStore workload recorded
    through the BlockDevice shim, a compact crash-state enumeration
    (every barrier cut + seeded torn/lost/reordered images), the
    acked-write contract asserted on each image — and the
    falsifiability probe: the deliberately-broken ordering (KV commit
    acked before its WAL fsync) MUST be caught."""
    import tempfile
    from ceph_tpu.cluster.crashdev import CrashHarness
    own = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="crashdev-smoke-")
    try:
        h = CrashHarness(os.path.join(workdir, "run"), seed=0,
                         n_txns=22)
        h.run_workload()
        rep = h.enumerate_and_check(
            os.path.join(workdir, "imgs"), seeds=(0,),
            images_per_seed=30, barrier_stride=3,
            double_crash_every=6)
        if rep["violations"]:
            print("FAIL: crash-sim contract broken: "
                  + "; ".join(rep["violations"][:5]), file=sys.stderr)
            return 1
        # determinism: the same seed enumerates the same images
        h2 = CrashHarness(os.path.join(workdir, "run2"), seed=0,
                          n_txns=22)
        log2 = h2.run_workload()
        if [r[:3] for r in h.log if r[0] != "write"] != \
                [r[:3] for r in log2 if r[0] != "write"]:
            print("FAIL: same seed produced a different write "
                  "stream", file=sys.stderr)
            return 1
        # falsifiability: broken ordering must FAIL the harness
        # compaction off: a snapshot's fsync+rename would seal the
        # acked state and mask the missing WAL barrier
        hb = CrashHarness(os.path.join(workdir, "broken"), seed=1,
                          n_txns=16, kv_fsync=False,
                          compact_bytes=1 << 20)
        hb.run_workload()
        img, upto = hb.lost_tail_image(os.path.join(workdir, "bimg"))
        if not hb.check_image(img, upto):
            print("FAIL: KV-commit-before-WAL-fsync was NOT caught "
                  "— the crash harness is vacuous", file=sys.stderr)
            return 1
        print(f"crash smoke OK: {rep['barrier_cuts']} barrier cuts + "
              f"{rep['seeded']} seeded images clean, "
              f"{rep['double_crash']} double-crash probes, broken "
              f"ordering caught")
        return 0
    finally:
        if own:
            import shutil
            shutil.rmtree(workdir, ignore_errors=True)


def main() -> int:
    crc = run_crash_smoke()
    if crc:
        return crc
    seed = 5
    r1 = run_once(seed)
    if not r1["ok"]:
        return _fail("invariants broken: " + "; ".join(r1["failures"]))
    inv = r1["invariants"]
    if inv["ops_in_flight"] != 0:
        return _fail(f"{inv['ops_in_flight']} ops stuck in flight")
    if inv["data_loss"]:
        return _fail(f"data loss: {inv['data_loss']}")
    if inv["scrub_inconsistencies"] != 0:
        return _fail(f"scrub found {inv['scrub_inconsistencies']} "
                     f"inconsistencies after repair")
    if inv["health"] != "HEALTH_OK":
        return _fail(f"health ended {inv['health']}")
    for name, n in r1["fire_counts"].items():
        if n < 1:
            return _fail(f"faultpoint {name} never fired")
    if not r1["fire_counts"]:
        return _fail("no faultpoint fired — the soak injected nothing")

    # determinism: the identical seed reproduces the identical
    # schedule and fire counts (what makes a chaos pass a regression
    # test rather than an anecdote)
    r2 = run_once(seed)
    if r1["schedule"] != r2["schedule"]:
        return _fail("same seed produced a different thrash schedule")
    if r1["fire_counts"] != r2["fire_counts"]:
        return _fail(f"same seed produced different fire counts: "
                     f"{r1['fire_counts']} vs {r2['fire_counts']}")

    # netsplit scenario (ISSUE 6): seeded partition/heal cycles with
    # the full invariant set PLUS replay idempotency (no op applies
    # twice under session replay) and linear mon epoch history
    rn = run_once(seed=7, netsplit=True)
    if not rn["ok"]:
        return _fail("netsplit invariants broken: " +
                     "; ".join(rn["failures"]))
    ninv = rn["invariants"]
    if ninv["replay_double_commits"] != 0:
        return _fail(f"replay applied "
                     f"{ninv['replay_double_commits']} ops twice")
    if not ninv["mon_epochs_linear"]:
        return _fail("mon epoch history forked or gapped")
    if rn["fire_counts"].get("net.partition", 0) < 1:
        return _fail("netsplit soak never severed a frame")

    print(f"OK: {len(r1['schedule'])} scheduled events over "
          f"{r1['cycles']} cycles, fires={r1['fire_counts']}, "
          f"{inv['objects_checked']} objects verified, "
          f"health {inv['health']} in {inv['health_ticks']} ticks, "
          f"schedule reproducible; netsplit: "
          f"{rn['fire_counts']['net.partition']} severed frames, "
          f"{ninv['replay_dups_suppressed']} replays suppressed, "
          f"epochs linear, health {ninv['health']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
