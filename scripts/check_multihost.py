#!/usr/bin/env python
"""Multi-host smoke check — the MeshPlane2D scale-out boot, verified.

Three layers of evidence, cheapest first:

  * fallback: with no coordinator configured ``ensure_initialized``
    is a no-op, rank reads report (0, 1), and ``stripe_order`` is the
    identity — the single-process plane is byte-for-byte untouched,
  * single-process 2-D reference: the (stripe, shard) mesh runs the
    encode + collective-rebuild dispatches bit-identically to the
    unsharded kernel and writes one counter cell per mesh position,
  * the REAL fleet: two ``jax.distributed`` processes (gloo CPU
    collectives, 4 forced devices each) boot one global 2x4 mesh,
    run the SAME dispatches, and must produce the same bytes while
    each rank accounts ONLY its own row — the parent sums the two
    ranks' per-(host, chip) cells through the mgr's
    ``ClusterStats.mesh_rollup`` and requires the totals to equal the
    single-process run's.

Runs on CPU (no accelerator needed):

    python scripts/check_multihost.py            # full check
    python scripts/check_multihost.py --quick    # skip the fleet pair

Also wired as a fast pytest test (tests/test_multihost.py, `smoke`
marker) so CI covers it without a separate job.
"""
from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

_CHILD_DEVICES = 4          # per-process forced CPU devices
_PARENT_DEVICES = 2 * _CHILD_DEVICES

if "--child" not in sys.argv and \
        "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        f" --xla_force_host_platform_device_count="
        f"{_PARENT_DEVICES}").strip()


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


def _dispatch_payload():
    """The shared dispatch mix every layer runs: one replicated-mask
    encode + one collective rebuild over fixed operands, hashed.
    Deterministic, so the single-process reference and both fleet
    ranks must produce identical digests."""
    import hashlib

    import numpy as np

    from ceph_tpu.ops import gf, xor_kernel
    from ceph_tpu.parallel import data_plane as dpmod

    rng = np.random.default_rng(17)
    k, m, W8 = 4, 2, 16
    words = rng.integers(0, 2 ** 31, (6, 8 * k, W8), dtype=np.uint32)
    bitm = gf.gf8_bitmatrix(gf.vandermonde_parity(k, m))
    masks = xor_kernel.masks_to_device(bitm)
    dp = dpmod.plane()
    if dp is None:
        return None
    enc = np.asarray(dp.xor_matmul_w32(masks, words, kind="put"))
    reb = np.asarray(dp.rebuild_collective(masks, words,
                                           kind="recover"))
    # bit-identity against the unsharded kernel, locally
    ref = np.asarray(xor_kernel.xor_matmul_w32(masks, words))
    if not (np.array_equal(enc, ref) and np.array_equal(reb, ref)):
        raise AssertionError("plane dispatch diverged from the "
                             "single-device kernel")
    return {
        "mesh_shape": list(dp.mesh.devices.shape),
        "sha_encode": hashlib.sha256(enc.tobytes()).hexdigest(),
        "sha_rebuild": hashlib.sha256(reb.tobytes()).hexdigest(),
        "cells": sorted(f"r{f // dp.n_cols}c{f % dp.n_cols}"
                        for f in sorted(dp._local_cells)),
    }


def _child(rank: int, port: int) -> int:
    """One fleet process: join via jax.distributed, resolve the
    global 2-D plane, run the dispatch mix, report counters."""
    os.environ["CEPH_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
    os.environ["CEPH_TPU_NUM_PROCESSES"] = "2"
    os.environ["CEPH_TPU_PROCESS_ID"] = str(rank)
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={_CHILD_DEVICES}")

    from ceph_tpu.common.options import config
    from ceph_tpu.common.perf_counters import perf
    from ceph_tpu.parallel import multihost

    if not multihost.ensure_initialized():
        return _fail(f"child {rank}: fleet did not initialize")
    import jax
    config().set("parallel_data_plane", True)
    perf("dataplane").reset()
    payload = _dispatch_payload()
    if payload is None:
        return _fail(f"child {rank}: no plane resolved")
    payload.update({
        "rank": multihost.process_index(),
        "nprocs": multihost.process_count(),
        "host": multihost.host_label(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
        "perf": {"dataplane": perf("dataplane").dump_typed()},
    })
    print("CHILD " + json.dumps(payload), flush=True)
    multihost.shutdown()
    return 0


def _run_pair(ref) -> int:
    """Spawn the two-process fleet and check its collective story."""
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k != "XLA_FLAGS" and not k.startswith("CEPH_TPU_")}
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--child", str(rank), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, env=env, cwd=_REPO) for rank in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            return _fail("fleet pair timed out")
        if p.returncode != 0:
            return _fail(f"fleet child exited {p.returncode}: "
                         f"{err[-800:]}")
        outs.append(out)
    reports = []
    for out in outs:
        lines = [ln for ln in out.splitlines()
                 if ln.startswith("CHILD ")]
        if not lines:
            return _fail(f"child produced no report: {out[-400:]}")
        reports.append(json.loads(lines[-1][len("CHILD "):]))
    reports.sort(key=lambda r: r["rank"])

    for r in reports:
        if r["nprocs"] != 2 or r["global_devices"] != _PARENT_DEVICES \
                or r["local_devices"] != _CHILD_DEVICES:
            return _fail(f"rank {r['rank']}: fleet shape wrong: {r}")
        if r["mesh_shape"] != [2, _CHILD_DEVICES]:
            return _fail(f"rank {r['rank']}: global mesh "
                         f"{r['mesh_shape']}, want "
                         f"[2, {_CHILD_DEVICES}]")
        if (r["sha_encode"], r["sha_rebuild"]) != \
                (ref["sha_encode"], ref["sha_rebuild"]):
            return _fail(f"rank {r['rank']}: fleet dispatch bytes "
                         f"diverged from single-process reference")
    # locality gating: each rank owns exactly its stripe row
    own0, own1 = (set(r["cells"]) for r in reports)
    if own0 & own1 or len(own0 | own1) != _PARENT_DEVICES:
        return _fail(f"per-rank cell ownership wrong: {own0} / {own1}")
    if {r["host"] for r in reports} != {"host0", "host1"}:
        return _fail("host labels wrong: "
                     f"{[r['host'] for r in reports]}")

    # mgr rollup: two ranks ingest as two daemons, totals must equal
    # the single-process run (each cell incremented exactly once)
    import time as _time

    from ceph_tpu.mgr.cluster_stats import ClusterStats
    stats = ClusterStats()
    for r in reports:
        stats.ingest(f"client.{r['host']}",
                     {"perf": r["perf"], "ts": _time.time(),
                      "host": r["host"]})
    roll = stats.mesh_rollup()
    if roll["n_hosts"] != 2 or roll["n_chips"] != _PARENT_DEVICES:
        return _fail(f"mesh_rollup shape wrong: {roll['n_hosts']} "
                     f"hosts, {roll['n_chips']} chips")
    if roll["shape"] != [2, _CHILD_DEVICES]:
        return _fail(f"mesh_rollup grid {roll['shape']}")
    for key, want in ref["cell_totals"].items():
        got = roll["totals"].get(key, 0.0)
        if got != want:
            return _fail(f"rollup totals[{key}] = {got}, "
                         f"single-process run says {want}")
    print(f"OK: 2-process fleet verified (global 2x{_CHILD_DEVICES} "
          f"mesh, identical bytes, rollup totals match)")
    return 0


def main() -> int:
    quick = "--quick" in sys.argv

    from ceph_tpu.common.options import config
    from ceph_tpu.common.perf_counters import perf
    from ceph_tpu.parallel import multihost

    # ---- fallback: no coordinator -> everything single-process ----
    if multihost.ensure_initialized():
        return _fail("ensure_initialized active without a "
                     "coordinator configured")
    if multihost.process_index() != 0 or \
            multihost.process_count() != 1:
        return _fail("inactive rank reads must be (0, 1)")
    if multihost.stripe_order([5, 3, 8]) != [0, 1, 2]:
        return _fail("inactive stripe_order must be the identity")

    # ---- single-process 2-D reference -----------------------------
    import jax
    n_dev = len(jax.devices())
    if n_dev < 4 or n_dev % 2:
        return _fail(f"need an even device count >= 4, have {n_dev}")
    config().set("parallel_data_plane", True)
    config().set("parallel_data_plane_stripes", 2)
    try:
        perf("dataplane").reset()
        ref = _dispatch_payload()
        if ref is None:
            return _fail("no 2-D plane resolved single-process")
        if ref["mesh_shape"] != [2, n_dev // 2]:
            return _fail(f"reference mesh {ref['mesh_shape']}")
        if len(ref["cells"]) != n_dev:
            return _fail("single-process plane must own every cell, "
                         f"owns {ref['cells']}")
        # totals per counter NAME summed over the r<r>c<c> cells —
        # the same reduction mesh_rollup applies to the fleet's cells
        import re
        d = perf("dataplane").dump()
        totals = {}
        for k, v in d.items():
            m = re.match(r"^r\d+c\d+\.(.+)$", k)
            if m and v:
                totals[m.group(1)] = totals.get(m.group(1), 0.0) + v
        ref["cell_totals"] = totals
        if not ref["cell_totals"]:
            return _fail("no per-(row, col) counters accounted")
    finally:
        config().clear("parallel_data_plane")
        config().clear("parallel_data_plane_stripes")

    if quick:
        print(f"OK: multihost fallback + single-process 2-D "
              f"reference verified on {n_dev} devices (--quick: "
              f"fleet pair skipped)")
        return 0
    return _run_pair(ref)


if __name__ == "__main__":
    if "--child" in sys.argv:
        i = sys.argv.index("--child")
        sys.exit(_child(int(sys.argv[i + 1]), int(sys.argv[i + 2])))
    sys.exit(main())
