#!/usr/bin/env python3
"""Generate the EC non-regression corpus.

Role of the reference's ceph_erasure_code_non_regression + archived
corpus (src/test/erasure-code/ceph_erasure_code_non_regression.cc,
ceph-erasure-code-corpus/): encode a FIXED payload under every
(plugin, technique, k, m) configuration and archive the parity bytes,
so any change to codec output across rounds fails loudly — roundtrip
tests alone cannot catch a self-consistent wire-format change.

Writes tests/golden/ec_corpus.npz.  Regenerate ONLY for an intentional
format change:  python scripts/gen_ec_corpus.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

PAYLOAD_LEN = 4096


def payload() -> bytes:
    """Fixed deterministic payload (an LCG, no RNG library drift)."""
    x = 0x12345678
    out = bytearray()
    for _ in range(PAYLOAD_LEN):
        x = (1103515245 * x + 12345) & 0x7FFFFFFF
        out.append((x >> 16) & 0xFF)
    return bytes(out)


CONFIGS = [
    ("jax", "reed_sol_van", 4, 2), ("jax", "reed_sol_van", 8, 3),
    ("jax", "cauchy", 4, 2), ("jax", "cauchy_good", 6, 3),
    ("jax", "isa_rs", 8, 4),
    ("jerasure", "reed_sol_van", 4, 2), ("jerasure", "reed_sol_van", 8, 3),
    ("jerasure", "reed_sol_r6_op", 4, 2),
    ("jerasure", "cauchy_orig", 4, 2), ("jerasure", "cauchy_good", 6, 3),
    ("isa", "reed_sol_van", 4, 2), ("isa", "cauchy", 6, 2),
    ("shec", None, 4, 3), ("lrc", None, 4, 2), ("clay", None, 4, 2),
    # RAID-6 bitmatrix techniques (packet layout; w pinned per technique)
    ("jerasure", "liberation", 5, 2), ("jerasure", "liberation", 7, 2),
    ("jerasure", "blaum_roth", 6, 2), ("jerasure", "liber8tion", 8, 2),
    # flagship bitsliced layout of the jax codec
    ("jax", "bitsliced", 8, 3), ("jax", "bitsliced", 4, 2),
]


def profile_for(plugin, technique, k, m):
    prof = {"k": str(k), "m": str(m)}
    if technique:
        prof["technique"] = technique
    if plugin == "shec":
        prof["c"] = "2"
    if plugin == "lrc":
        prof["l"] = "3"
        prof.pop("technique", None)
    if technique == "liberation":
        prof["w"] = "7"
    elif technique == "blaum_roth":
        prof["w"] = "6"
    elif technique == "liber8tion":
        prof["w"] = "8"
    elif technique == "bitsliced":
        # jax codec: default RS technique under the bitsliced layout
        prof["technique"] = "reed_sol_van"
        prof["layout"] = "bitsliced"
    return prof


def main():
    from ceph_tpu.ec import instance as ec_registry
    data = payload()
    out = {}
    for plugin, technique, k, m in CONFIGS:
        prof = profile_for(plugin, technique, k, m)
        codec = ec_registry().factory(plugin, prof)
        n = codec.get_chunk_count()
        chunks = codec.encode(set(range(n)), data)
        key = f"{plugin}.{technique or 'default'}.k{k}m{m}"
        for c, buf in sorted(chunks.items()):
            out[f"{key}.c{c}"] = np.asarray(buf, dtype=np.uint8)
        print(f"{key}: {n} chunks x {len(chunks[0])} bytes")
    dest = os.path.join(os.path.dirname(__file__), "..",
                        "tests", "golden", "ec_corpus.npz")
    np.savez_compressed(dest, **out)
    print(f"wrote {dest} ({len(out)} arrays)")


if __name__ == "__main__":
    main()
