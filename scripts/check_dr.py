#!/usr/bin/env python
"""GeoSync DR smoke check — multisite replication, verified (ISSUE 18).

Three assertions, small enough for the smoke sweep:

  1. DRILL GREEN: the sim-tier two-zone DR drill (sever -> failover
     -> heal, with a mid-catch-up reshard) converges — every acked
     ETag readable in BOTH zones, zero double-applies, zero
     full-sync restarts, a generation cutover recorded, and the
     replication-lag p99 was actually read from the merged
     histograms (samples > 0).

  2. FALSIFIABILITY: the seeded lost-bilog-entry fault
     (``rgw.bilog_lost_entry`` dropping ONE acked write's log append)
     turns the SAME drill red with a nonzero exit — a convergence
     gate that cannot fail proves nothing.

  3. DETERMINISM: two drills on the same seed produce an identical
     workload schedule digest (the replayable-drill contract).

Runs on CPU:

    python scripts/check_dr.py            # all three
    python scripts/check_dr.py --quick    # determinism only

Also wired as a fast pytest test (tests/test_dr_drill.py, ``smoke``
marker) so CI covers it without a separate job.
"""
from __future__ import annotations

import io
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


def _check_drill_green() -> int:
    from ceph_tpu.cluster.dr_drill import DrillConfig, run_drill
    r = run_drill(DrillConfig(seed=0))
    if not r["ok"]:
        return _fail(f"DR drill seed 0 failed the convergence gate: "
                     f"{r['failures']}")
    if not r["sever_verified"]:
        return _fail("the net.partition sever never blocked a pump")
    if not r["lag_samples"]:
        return _fail("no replication-lag samples — the lag bound was "
                     "never read from the histogram merge")
    cuts = sum(a["gen_cutovers"] for a in r["agents"].values())
    if r["resharded"] and not cuts:
        return _fail("mid-catch-up reshard never cut a generation "
                     "over")
    print(f"drill green: {r['keys']} oracle keys converged in both "
          f"zones, lag p99 {r['lag_p99_s']}s over "
          f"{r['lag_samples']} samples, {cuts} gen cutover(s)")
    return 0


def _check_drill_falsifiable() -> int:
    from ceph_tpu.cluster.dr_drill import drill_main
    buf = io.StringIO()
    rc = drill_main(["--seed", "0", "--lose-bilog"], out=buf)
    text = buf.getvalue()
    if rc == 0:
        return _fail("lost-bilog drill PASSED the gate — the "
                     "convergence gate is not falsifiable")
    if "lost-canary" not in text:
        return _fail(f"lost-bilog drill failed without naming the "
                     f"lost key:\n{text}")
    print("falsifiability ok: seeded lost-bilog-entry fault exits "
          "nonzero naming the unreplicated key")
    return 0


def _check_determinism() -> int:
    from ceph_tpu.cluster.dr_drill import DrillConfig, run_drill
    a = run_drill(DrillConfig(seed=2, phase_ops=12, keys=8,
                              reshard_to=0))
    b = run_drill(DrillConfig(seed=2, phase_ops=12, keys=8,
                              reshard_to=0))
    if a["schedule_digest"] != b["schedule_digest"]:
        return _fail(f"same-seed drills diverged: "
                     f"{a['schedule_digest'][:12]} != "
                     f"{b['schedule_digest'][:12]}")
    print(f"determinism ok: seed-2 schedule digest "
          f"{a['schedule_digest'][:12]} reproduced")
    return 0


def main() -> int:
    rc = _check_determinism()
    if rc:
        return rc
    if "--quick" not in sys.argv:
        rc = _check_drill_green() or _check_drill_falsifiable()
        if rc:
            return rc
    print("check_dr: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
