#!/usr/bin/env python
"""Observability smoke check — drives real ops, asserts the pipeline.

Exercises the whole ISSUE-1 data path in one pass: a few objecter ops
flow through the OSD batch queue and device dispatch, and the script
asserts every surface they should light up actually lit up —

  * `dump_historic_ops` is non-empty and each op carries the typed
    lifecycle trail (initiated -> queued -> reached_osd ->
    dispatched_device -> done),
  * the per-stage latency histograms in the `op_tracker` perf group
    have observations,
  * the Prometheus exporter serves a scrapeable /metrics payload whose
    histogram families are internally consistent (`_bucket` cumulative,
    `+Inf` bucket == `_count`).

Runs on CPU (no accelerator needed):

    JAX_PLATFORMS=cpu python scripts/check_observability.py

Also wired as a fast pytest test (tests/test_op_tracker.py, `smoke`
marker) so CI covers it without a separate job.
"""
from __future__ import annotations

import os
import re
import sys
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable as `python scripts/check_observability.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


def check_metrics_payload(text: str, family: str) -> str:
    """Validate one Prometheus histogram family; '' if OK else why."""
    if f"# TYPE {family} histogram" not in text:
        return f"missing '# TYPE {family} histogram'"
    buckets = [(m.group(1), int(m.group(2))) for m in re.finditer(
        rf'^{re.escape(family)}_bucket{{le="([^"]+)"}} (\d+)$',
        text, re.M)]
    if not buckets:
        return f"{family}: no _bucket samples"
    counts = [n for _, n in buckets]
    if counts != sorted(counts):
        return f"{family}: buckets not cumulative: {counts}"
    if buckets[-1][0] != "+Inf":
        return f"{family}: last bucket is {buckets[-1][0]}, not +Inf"
    m = re.search(rf"^{re.escape(family)}_count (\d+)$", text, re.M)
    if m is None:
        return f"{family}: missing _count"
    if int(m.group(1)) != buckets[-1][1]:
        return (f"{family}: +Inf bucket {buckets[-1][1]} != "
                f"_count {m.group(1)}")
    if int(m.group(1)) == 0:
        return f"{family}: zero observations"
    return ""


def main() -> int:
    from ceph_tpu.cluster.monitor import Monitor
    from ceph_tpu.cluster.objecter import Objecter
    from ceph_tpu.cluster.osdmap import OSDMap, PGPool, POOL_REPLICATED
    from ceph_tpu.cluster.simulator import ClusterSim
    from ceph_tpu.common.op_tracker import tracker
    from ceph_tpu.common.perf_counters import perf
    from ceph_tpu.mgr import MgrModuleHost, prometheus_module
    from ceph_tpu.placement.builder import build_flat_cluster
    from ceph_tpu.placement.crush_map import (
        RULE_CHOOSELEAF_FIRSTN, RULE_EMIT, RULE_TAKE, Rule)

    cmap, root = build_flat_cluster(n_hosts=4, osds_per_host=2, seed=3)
    cmap.add_rule(Rule(steps=[(RULE_TAKE, root, 0),
                              (RULE_CHOOSELEAF_FIRSTN, 0, 1),
                              (RULE_EMIT, 0, 0)]))
    om = OSDMap(cmap)
    om.mark_all_in_up()
    om.add_pool(PGPool(id=1, name="rep", type=POOL_REPLICATED, size=3,
                       pg_num=16, crush_rule=0))
    sim = ClusterSim(om)
    mon = Monitor(sim.osdmap)
    client = Objecter(sim, mon)

    n_ops = 4
    for i in range(n_ops):
        data = bytes([i]) * 2048
        client.put(1, f"smoke-{i}", data)
        if client.get(1, f"smoke-{i}") != data:
            return _fail(f"smoke-{i}: readback mismatch")

    # 1) historic ring holds the ops, each with the full lifecycle trail
    hist = tracker().dump_historic_ops()
    if hist["num_ops"] < 2 * n_ops:
        return _fail(f"dump_historic_ops: {hist['num_ops']} ops "
                     f"recorded, wanted >= {2 * n_ops}")
    smoke = [op for op in hist["ops"]
             if str(op.get("obj", "")).startswith("smoke-")]
    if len(smoke) < 2 * n_ops:
        return _fail(f"only {len(smoke)} smoke ops in the ring")
    for op in smoke:
        events = [e["event"] for e in op["events"]]
        for want in ("initiated", "queued", "reached_osd",
                     "dispatched_device", "done"):
            if want not in events:
                return _fail(f"op {op['op_id']} ({op['type']} "
                             f"{op['obj']}): missing {want!r} "
                             f"in {events}")

    # 2) per-stage histograms populated
    trk_dump = perf("op_tracker").dump()
    for key in ("stage_init_to_queue_s", "stage_osd_to_device_s"):
        if trk_dump.get(key, {}).get("count", 0) == 0:
            return _fail(f"op_tracker.{key}: no observations")

    # 3) /metrics scrapes and the histogram families are well-formed
    host = MgrModuleHost(sim)
    prometheus_module.register(host)
    mod = host.enable("prometheus")
    port = mod.start_http(0)
    try:
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) \
            .read().decode()
    finally:
        mod.stop_http()
    for family in ("ceph_tpu_objecter_op_e2e_s",
                   "ceph_tpu_osd_service_dispatch_s"):
        why = check_metrics_payload(text, family)
        if why:
            return _fail(why)

    # ---- ClusterTelemetry (ISSUE 10): the cluster-level plane ----
    import time

    from ceph_tpu.cluster.heartbeat import HeartbeatMonitor
    from ceph_tpu.common import tracer as tracing
    from ceph_tpu.common.options import config

    # 4) cluster Prometheus scrape: daemons report over the heartbeat
    # path, the mon's ClusterStats merges, and ONE scrape serves
    # per-daemon labeled families plus merged cluster histograms
    hb = HeartbeatMonitor(sim, mon)
    hb.tick()
    hb.tick()
    chost = MgrModuleHost(sim, mon)
    prometheus_module.register(chost)
    cmod = chost.enable("prometheus")
    cport = cmod.start_http(0)
    try:
        ctext = urllib.request.urlopen(
            f"http://127.0.0.1:{cport}/metrics", timeout=10) \
            .read().decode()
    finally:
        cmod.stop_http()
    if 'ceph_daemon="osd.0"' not in ctext:
        return _fail("cluster scrape: no per-daemon labels")
    fams = [ln.split()[2] for ln in ctext.splitlines()
            if ln.startswith("# TYPE ")]
    dup = sorted({f for f in fams if fams.count(f) > 1})
    if dup:
        return _fail(f"cluster scrape: duplicate # TYPE families "
                     f"{dup} (a Prometheus parser rejects the whole "
                     f"scrape)")
    if "# TYPE ceph_cluster_op_tracker_op_e2e_s" not in ctext and \
            "# TYPE ceph_cluster_objecter_op_e2e_s" not in ctext:
        return _fail("cluster scrape: no merged cluster histogram "
                     "families")
    if 'quantile="0.99"' not in ctext:
        return _fail("cluster scrape: no merged p99 quantile gauges")
    # merged quantiles must agree with the per-daemon sources
    cs = mon.cluster_stats
    qq = cs.merged_quantiles()
    fam = qq.get("objecter.op_e2e_s")
    if not fam or fam["count"] == 0 or fam["p99"] is None:
        return _fail(f"cluster stats: empty merged op_e2e_s ({fam})")
    src = perf("objecter").dump()["op_e2e_s"]
    if fam["count"] != src["count"]:
        return _fail(f"merged count {fam['count']} != source "
                     f"{src['count']}")

    # 5) slow-op auto-sampling: force one slow op, assert its trace
    # assembles end-to-end (>= 5 linked stages), retrievable by op id
    config().set("op_tracker_complaint_time", 0.01)
    for svc in sim.services:
        svc.inject_execute_delay = 0.02
    try:
        client.put(1, "slowpoke", b"s" * 2048)
    finally:
        for svc in sim.services:
            svc.inject_execute_delay = 0.0
        config().clear("op_tracker_complaint_time")
    slow = tracker().dump_historic_slow_ops()
    rec = next((op for op in slow["ops"]
                if op.get("obj") == "slowpoke"), None)
    if rec is None or not rec.get("trace_id"):
        return _fail("slow op missing from the slow ring or carries "
                     "no trace_id")
    trees = tracing.assemble(
        tracing.tracer().spans_for(rec["trace_id"]))
    tree = trees.get(rec["trace_id"])
    if tree is None or tree["spans"] < 5:
        return _fail(f"auto-sampled slow trace too thin: {tree}")
    if rec["trace_id"] not in tracing.tracer().sampled_traces():
        return _fail("slow trace was not pinned (auto-sampling)")

    # 6) disarmed tracing is one dict-miss (the faultpoint contract)
    tracing.disarm()
    try:
        t0 = time.perf_counter()
        for _ in range(100_000):
            tracing.stamp({"cmd": "put_shard"})
            with tracing.child_span("x"):
                pass
        dt = time.perf_counter() - t0
    finally:
        tracing.arm()
    if dt > 1.0:
        return _fail(f"disarmed trace sites cost {dt:.2f}s per 100k "
                     f"(want << 1s)")

    # ---- ClusterScope (ISSUE 16): history, heat, compile spans ----
    import glob
    import math
    import subprocess

    # 7) telemetry history range query: writes between two heartbeat
    # deliveries become a per-daemon counter series whose derived
    # rates are finite, non-negative, and somewhere positive
    for i in range(6):
        client.put(1, f"hist-{i}", b"h" * 4096)
    time.sleep(0.02)             # distinct report timestamps
    hb.tick()
    hq = cs.history.query("osd.io.wr_ops")
    if not hq.get("series"):
        return _fail("telemetry history: query returned no series")
    n_samples = 0
    any_pos = False
    for daemon, ser in hq["series"].items():
        if len(ser["samples"]) < 2:
            continue
        n_samples = max(n_samples, len(ser["samples"]))
        vals = [v for _, v in ser["samples"]]
        if ser.get("resets", 0) == 0 and vals != sorted(vals):
            return _fail(f"history[{daemon}]: non-monotonic counter "
                         f"series without a counted reset: {vals}")
        for _ts, r in ser["rates"]:
            if not (r >= 0.0) or math.isinf(r) or math.isnan(r):
                return _fail(f"history[{daemon}]: insane rate {r}")
            any_pos = any_pos or r > 0.0
    if n_samples < 2:
        return _fail("telemetry history: no daemon retained >= 2 "
                     "samples")
    if not any_pos:
        return _fail("telemetry history: writes landed but every "
                     "derived rate is zero")

    # 8) a forced cold compile inside a traced op must surface as a
    # `jit.compile` child span in that op's assembled trace — and the
    # executing-daemon spans must carry their OWN service identity
    from ceph_tpu.cluster.osdmap import POOL_ERASURE
    from ceph_tpu.ops import gf_jax
    sim.create_ec_profile("obsec", {"plugin": "jax", "k": "2",
                                    "m": "1"})
    sim.osdmap.add_pool(PGPool(id=2, name="ecobs", type=POOL_ERASURE,
                               size=3, pg_num=8, crush_rule=0,
                               erasure_code_profile="obsec"))
    import copy
    client.osdmap = copy.deepcopy(sim.osdmap)   # resync client view
    from ceph_tpu.ops import xor_kernel
    with gf_jax._seen_lock:      # force the encode matrix cold
        gf_jax._seen_matrices.clear()
    gf_jax._bitmatrix_device.cache_clear()
    with xor_kernel._seen_lock:  # and the masked-XOR executable
        xor_kernel._seen_shapes.clear()
    config().set("op_tracker_complaint_time", 0.0001)
    try:
        client.put(2, "coldpoke", b"c" * 8192)
    finally:
        config().clear("op_tracker_complaint_time")
    slow = tracker().dump_historic_slow_ops()
    crec = next((op for op in slow["ops"]
                 if op.get("obj") == "coldpoke"), None)
    if crec is None or not crec.get("trace_id"):
        return _fail("cold-compile op missing from the slow ring or "
                     "carries no trace_id")
    cspans = tracing.tracer().spans_for(crec["trace_id"])
    jit_spans = [s for s in cspans if s["name"] == "jit.compile"]
    if not jit_spans:
        return _fail(f"cold-compile trace has no jit.compile span "
                     f"({sorted({s['name'] for s in cspans})})")
    comps = {s.get("tags", {}).get("component") for s in jit_spans}
    if not any(str(c).startswith("ec.") for c in comps):
        return _fail(f"jit.compile span not attributed to an EC "
                     f"component: {sorted(map(str, comps))}")
    osd_svcs = {s.get("service") for s in cspans
                if s["name"] in ("osd.dispatch", "device.dispatch")}
    if not any(str(s).startswith("osd.") for s in osd_svcs):
        return _fail(f"executor spans carry no osd.N service "
                     f"identity: {sorted(map(str, osd_svcs))}")

    # 9) balancer advisor: on a skewed heat fixture the proposed
    # mapping must re-score strictly better — and stay a DRY RUN
    from ceph_tpu.mgr import balancer_advisor
    for _ in range(40):
        client.put(1, "hotspot", b"H" * 8192)
    time.sleep(0.01)
    hb.tick()
    heat_rows = cs.pg_heat(top=5)
    if not heat_rows or heat_rows[0]["heat"] <= 0:
        return _fail(f"pg heat: no hot rows after skewed traffic "
                     f"({heat_rows})")
    if heat_rows[0]["tot_wr_ops"] < 40:
        return _fail(f"pg heat: hottest row only "
                     f"{heat_rows[0]['tot_wr_ops']} writes — the "
                     f"hotspot PG is not on top")
    epoch0 = sim.osdmap.epoch
    upmaps0 = (dict(sim.osdmap.pg_upmap),
               dict(sim.osdmap.pg_upmap_items))
    rep = balancer_advisor.evaluate(sim.osdmap, cs, max_moves=8)
    if sim.osdmap.epoch != epoch0 or \
            (dict(sim.osdmap.pg_upmap),
             dict(sim.osdmap.pg_upmap_items)) != upmaps0:
        return _fail("balancer advisor ACTUATED (osdmap changed on a "
                     "dry run)")
    if rep["score_before"] <= 0:
        return _fail(f"advisor: zero imbalance on a skewed fixture "
                     f"({rep})")
    if not rep["proposals"]:
        return _fail(f"advisor proposed no moves on a skewed fixture "
                     f"(score {rep['score_before']})")
    if not rep["score_after"] < rep["score_before"]:
        return _fail(f"advisor score did not improve: "
                     f"{rep['score_before']} -> {rep['score_after']}")

    # 10) bench regression gate rides the smoke path whenever two
    # driver snapshots exist to diff
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    benches = glob.glob(os.path.join(repo, "BENCH_r*.json"))
    bench_note = "no BENCH snapshots"
    if len(benches) >= 2:
        rcmp = subprocess.run(
            [sys.executable,
             os.path.join(repo, "scripts", "bench_compare.py")]
        ).returncode
        if rcmp != 0:
            return _fail(f"bench_compare exited {rcmp} (headline "
                         f"metric regression)")
        bench_note = f"bench_compare OK over {len(benches)} snapshots"

    print(f"OK: {len(smoke)} tracked ops, per-stage histograms live, "
          f"/metrics scrapeable ({len(text)} bytes), cluster scrape "
          f"{len(ctext)} bytes ({len(cs.daemons())} daemons), slow "
          f"trace {tree['spans']} spans, disarmed 100k in {dt:.3f}s, "
          f"history {n_samples} samples, {len(jit_spans)} jit.compile "
          f"span(s), advisor {rep['score_before']} -> "
          f"{rep['score_after']} in {rep['moves']} moves, "
          f"{bench_note}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
