#!/usr/bin/env python
"""Async-objecter smoke check — the multi-stream wire data path,
verified end to end against live daemons (ISSUE 7).

Asserts the evidence the async core claims:

  * completions FIRE: every ``call_async``/``aio_*`` completion
    resolves, ``set_complete_callback`` callbacks run, and overlapping
    same-object writes land in submission order;
  * OpTracker coverage: tracked ops carry the ``dispatched_wire``
    event and the ``stage_wire_to_done_s`` histogram observes them
    (``dump_ops_in_flight`` shows the in-flight wire window);
  * the blocking shims are BYTE-IDENTICAL to async submission: the
    same objects written sync and async read back equal through both
    paths, over both data modes (crc and secure streams);
  * the stream pool actually striped: >= 1 live stream per touched
    daemon, submits/resubmit accounting on ``perf("objecter.wire")``.

Runs on CPU (no accelerator needed):

    JAX_PLATFORMS=cpu python scripts/check_async.py

Also wired as a fast pytest test (tests/test_msgr_inject.py, `smoke`
marker) so CI covers it without a separate job — the
check_observability.py pattern.
"""
from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable as `python scripts/check_async.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


def run_checks(cluster_dir: str) -> int:
    from ceph_tpu.client.remote import RemoteCluster
    from ceph_tpu.client.remote_ioctx import RemoteIoCtx
    from ceph_tpu.common.op_tracker import tracker
    from ceph_tpu.common.perf_counters import perf

    rc = RemoteCluster(cluster_dir)
    io = RemoteIoCtx(rc, "rep")
    tracker().reset()

    # 1) completions fire, callbacks run, same-object ordering holds
    fired = []
    payloads = [bytes([0x61 + i]) * (1500 + i) for i in range(6)]
    comps = [io.aio_write_full("smoke-ord", p) for p in payloads]
    comps[0].set_complete_callback(lambda c: fired.append(c))
    for i, c in enumerate(comps):
        if c.wait_for_complete(30.0) != 0:
            return _fail(f"completion {i} did not signal")
        c.get_return_value()
        if not all(comps[j].is_complete() for j in range(i)):
            return _fail(f"op {i} completed before an earlier "
                         f"same-object op (ordering broken)")
    if not fired:
        return _fail("set_complete_callback never fired")
    if io.read("smoke-ord") != payloads[-1]:
        return _fail("same-object async writes did not land in "
                     "submission order")

    # 2) sync-vs-async byte identity through the shared core
    names = {f"smoke-{i}": os.urandom(2048 + 31 * i)
             for i in range(8)}
    sync_names = list(names)[:4]
    for n in sync_names:                     # blocking shim path
        io.write_full(n, names[n])
    cs = [io.aio_write_full(n, names[n])
          for n in list(names)[4:]]          # async path
    for c in cs:
        c.get_return_value()
    for n, want in names.items():
        got_sync = io.read(n)
        got_async = io.aio_read(n).get_return_value()
        if got_sync != want or got_async != want:
            return _fail(f"{n}: sync/async readback diverged "
                         f"(sync ok={got_sync == want}, "
                         f"async ok={got_async == want})")

    # 3) OpTracker: dispatched_wire event + stage histogram
    hist = tracker().dump_historic_ops()
    wire_ops = [o for o in hist["ops"]
                if any(e["event"] == "dispatched_wire"
                       for e in o["events"])]
    if not wire_ops:
        return _fail("no dispatched_wire event in dump_historic_ops")
    trk = perf("op_tracker").dump()
    if trk.get("stage_wire_to_done_s", {}).get("count", 0) == 0:
        return _fail("op_tracker.stage_wire_to_done_s: "
                     "no observations")

    # 4) the stream pool striped + accounted
    pw = perf("objecter.wire").dump()
    if not pw.get("submits"):
        return _fail("objecter.wire.submits never incremented")
    pool = rc.osdmap.pools[1]
    touched = {rc._up(pool, rc._pg_for(pool, n))[0] for n in names}
    for osd in touched:
        if rc.aio.streams_live(osd) < 1:
            return _fail(f"osd.{osd}: no live stream after the "
                         f"workload")

    rc.close()
    print(f"OK: async objecter verified ({len(wire_ops)} wire ops "
          f"tracked, {int(pw['submits'])} submits, "
          f"{len(touched)} stream pools)")
    return 0


def main() -> int:
    import tempfile
    import shutil
    from ceph_tpu.tools.vstart import Vstart, build_cluster_dir

    tmp = tempfile.mkdtemp(prefix="check-async-")
    d = os.path.join(tmp, "cluster")
    build_cluster_dir(d, n_osds=3, osds_per_host=1, fsync=False)
    v = Vstart(d)
    v.start(3, hb_interval=60.0)
    try:
        return run_checks(d)
    finally:
        v.stop()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
