#!/usr/bin/env python3
"""cephtpu-lint driver — the static-analysis CI gate.

Thin wrapper over ceph_tpu.analysis.runner (also surfaced as
``ceph_tpu.tools.ceph_cli lint``).  Typical invocations::

    python scripts/lint.py                   # human-readable report
    python scripts/lint.py --check           # CI gate: exit 1 on any
                                             # unsuppressed finding OR
                                             # stale baseline entry
    python scripts/lint.py --json            # machine-readable (shape
                                             # documented in runner.py)
    python scripts/lint.py --sarif           # SARIF 2.1.0 for GitHub
                                             # code scanning (inline
                                             # diff annotations in CI)
    python scripts/lint.py --select CTL3     # one rule family
    python scripts/lint.py --rule CTL8       # same, triage spelling
    python scripts/lint.py --graph daemon._recover_pg
                                             # whole-program call-graph
                                             # dump around one function
    python scripts/lint.py --list-rules
    python scripts/lint.py --write-baseline  # grandfather current
                                             # findings (review the
                                             # diff!)

Suppression: inline ``# noqa: CTL###`` next to a deliberate
exception (preferred), or an entry in scripts/lint_baseline.json.
The tier-1 test tests/test_lint.py::test_tree_is_lint_clean runs the
equivalent of ``--check`` on every pytest run, so a new violation
fails the suite before review.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ceph_tpu.analysis.runner import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
