#!/usr/bin/env python
"""Recovery smoke check — device-speed recovery, verified (ISSUE 11).

Two tiers, both fast enough for the smoke sweep:

  1. SIM tier: a small cluster takes a batched put, loses one whole
     OSD (kill + out), and runs ONE recovery pass under a traced
     root span.  Asserts ZERO data loss (every object reads back
     byte-exact), shards actually moved (rebuilt + copied > 0), and
     the trace-driven ``stage_breakdown`` is present and attributes
     the sweep (the PR-10 telemetry the rebuild bench quotes).

  2. PROCESS tier (skipped with ``--quick``): a 3-daemon vstart
     cluster, replicated objects, one OSD killed + outed, then the
     reservation-gated CONCURRENT ``recover_pool`` sweep.  Asserts
     zero data loss and the reservation counters are CONSISTENT:
     every daemon's held counts drained to zero and no peak ever
     exceeded ``osd_max_backfills``.

Runs on CPU:

    python scripts/check_recovery.py            # both tiers
    python scripts/check_recovery.py --quick    # sim tier only

Also wired as a fast pytest test (tests/test_process_cluster.py,
`smoke` marker) so CI covers it without a separate job.
"""
from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


def _check_sim_tier() -> int:
    import numpy as np
    from ceph_tpu.common.tracer import tracer
    from ceph_tpu.cluster.osdmap import OSDMap, PGPool, POOL_ERASURE
    from ceph_tpu.cluster.simulator import ClusterSim
    from ceph_tpu.placement.builder import (TYPE_HOST,
                                            build_flat_cluster)
    from ceph_tpu.placement.crush_map import (
        RULE_CHOOSELEAF_INDEP, RULE_EMIT, RULE_TAKE, Rule)
    cmap, root = build_flat_cluster(n_hosts=8, osds_per_host=2)
    cmap.add_rule(Rule(steps=[(RULE_TAKE, root, 0),
                              (RULE_CHOOSELEAF_INDEP, 0, TYPE_HOST),
                              (RULE_EMIT, 0, 0)]))
    om = OSDMap(cmap)
    om.mark_all_in_up()
    om.add_pool(PGPool(id=1, name="ec", type=POOL_ERASURE, size=6,
                       pg_num=32, crush_rule=0,
                       erasure_code_profile="p", stripe_unit=1 << 14))
    sim = ClusterSim(om)
    try:
        sim.create_ec_profile("p", {"plugin": "jax", "k": "4",
                                    "m": "2"})
        rng = np.random.default_rng(0)
        blobs = {f"o{i}": rng.integers(0, 256, 40_000,
                                       dtype=np.uint8).tobytes()
                 for i in range(12)}
        placed = sim.put_many(1, list(blobs), list(blobs.values()))
        counts: dict = {}
        for osds in placed.values():
            for o in osds:
                counts[o] = counts.get(o, 0) + 1
        victim = max(counts, key=counts.get)
        sim.kill_osd(victim)
        sim.out_osd(victim)
        tracer().reset()
        with tracer().start_span("rebuild.sweep"):
            st = sim.recover_all(1)
        if st.get("shards_rebuilt", 0) + st.get("shards_copied",
                                                0) <= 0:
            return _fail(f"no shards moved rebuilding osd.{victim}: "
                         f"{st}")
        for name, data in blobs.items():
            if sim.get(1, name) != data:
                return _fail(f"data loss after rebuild: {name}")
        from ceph_tpu.common.tracer import stage_breakdown
        spans = tracer().dump_traces()["spans"]
        ids = {s["trace_id"] for s in spans
               if s.get("name") == "rebuild.sweep"}
        bd = stage_breakdown([s for s in spans
                              if s.get("trace_id") in ids])
        if "rebuild.sweep" not in bd:
            return _fail(f"stage_breakdown missing the rebuild root: "
                         f"{sorted(bd)}")
        print(f"sim tier ok: osd.{victim} rebuilt "
              f"({st['shards_rebuilt']} rebuilt / "
              f"{st['shards_copied']} copied), zero loss, "
              f"stages={sorted(bd)}")
        return 0
    finally:
        sim.shutdown()


def _check_process_tier() -> int:
    import tempfile
    import shutil
    import time
    import numpy as np
    from ceph_tpu.client.remote import RemoteCluster
    from ceph_tpu.tools.vstart import Vstart, build_cluster_dir
    tmp = tempfile.mkdtemp(prefix="check-recovery-")
    d = os.path.join(tmp, "cluster")
    n_osds = 3
    build_cluster_dir(d, n_osds=n_osds, osds_per_host=1, fsync=False)
    v = Vstart(d)
    v.start(n_osds, hb_interval=0.25)
    try:
        rc = RemoteCluster(d)
        rng = np.random.default_rng(1)
        blobs = {f"r{i}": rng.integers(0, 256, 3000,
                                       dtype=np.uint8).tobytes()
                 for i in range(8)}
        for name, data in blobs.items():
            if rc.put(1, name, data) < 2:
                return _fail(f"{name}: put under-replicated")
        v.kill9("osd.2")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if rc.status()["n_up"] <= n_osds - 1:
                break
            time.sleep(0.25)
        rc.mon_call({"cmd": "mark_out", "osd": 2})
        rc.refresh_map()
        stats = rc.recover_pool(1)
        if "deferred_pgs" in stats:
            return _fail(f"recovery left deferred PGs: {stats}")
        for name, data in blobs.items():
            if rc.get(1, name) != data:
                return _fail(f"data loss after recovery: {name}")
        peaks = 0
        for o in range(n_osds - 1):
            st = rc.osd_call(o, {"cmd": "status"})
            resv = st.get("recovery_reservations")
            if resv is None:
                return _fail(f"osd.{o}: no reservation counters")
            if resv["held"] != {"local": 0, "remote": 0}:
                return _fail(f"osd.{o}: reservations leaked: {resv}")
            for role, peak in resv["peak"].items():
                if peak > 1:       # osd_max_backfills default
                    return _fail(f"osd.{o}: {role} peak {peak} "
                                 f"exceeds osd_max_backfills")
                peaks += peak
        if peaks <= 0:
            return _fail("no daemon ever took a reservation — the "
                         "gate did not run")
        rc.close()
        print(f"process tier ok: zero loss, reservations consistent "
              f"(sum of peaks {peaks}, cap held)")
        return 0
    finally:
        v.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    rc = _check_sim_tier()
    if rc:
        return rc
    if "--quick" not in sys.argv:
        rc = _check_process_tier()
        if rc:
            return rc
    print("check_recovery: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
