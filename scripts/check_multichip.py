#!/usr/bin/env python
"""Multi-chip smoke check — the sharded cluster data plane, verified.

Drives a real ClusterSim step (batched put -> degraded get -> recovery
rebuild -> map_pgs_batch sweep) twice on a forced multi-device host
mesh — single-device and with ``parallel_data_plane`` on — and asserts
the evidence the MULTICHIP output claims:

  * every result bit-identical between the two modes (bytes, recovery
    stats, mapping arrays),
  * nonzero per-chip ``dataplane.shard<i>.*`` perf counters on every
    chip (put stripes/bytes) plus decode/recover/map dispatch counts
    and the psum'd row counter (the ICI collective),
  * the ``dispatched_mesh`` event lands on tracked ops,
  * ``__graft_entry__._cluster_sharded_impl`` produces a well-formed
    ``cluster_sharded`` section (the MULTICHIP payload contract).

Runs on CPU (no accelerator needed):

    python scripts/check_multichip.py            # full check
    python scripts/check_multichip.py --quick    # skip the section run

Also wired as a fast pytest test (tests/test_data_plane.py, `smoke`
marker) so CI covers it without a separate job.
"""
from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count=8").strip()
# runnable as `python scripts/check_multichip.py` from anywhere
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


def main() -> int:
    quick = "--quick" in sys.argv
    import numpy as np

    from ceph_tpu.common.options import config
    from ceph_tpu.common.perf_counters import perf

    import jax
    n_dev = len(jax.devices())
    if n_dev < 2:
        return _fail(f"need >= 2 devices, have {n_dev} "
                     f"(set --xla_force_host_platform_device_count)")

    sys.path.insert(0, os.path.join(_REPO, "tests"))
    from tests.test_simulator import make_sim

    def drive(shard: bool):
        config().set("parallel_data_plane", shard)
        try:
            sim = make_sim()
            rng = np.random.default_rng(11)
            names = [f"c{i}" for i in range(8)]
            datas = [rng.integers(0, 256, int(s),
                                  dtype=np.uint8).tobytes()
                     for s in rng.integers(400, 20000, len(names))]
            sim.put_many(2, names, datas)
            pool = sim.osdmap.pools[2]
            up = sim.pg_up(pool, sim.object_pg(pool, names[0]))
            victims = [o for o in up if o >= 0][:2]
            for v in victims:
                sim.kill_osd(v)
            gets = [sim.get(2, n) for n in names]
            for v in victims:
                sim.out_osd(v)
            rec = sim.recover_all(2)
            up1, _ = sim.osdmap.map_pgs_batch(2)
            sim.shutdown()
            return datas, gets, rec, up1.tolist()
        finally:
            config().clear("parallel_data_plane")

    single = drive(False)
    perf("dataplane").reset()
    sharded = drive(True)

    if sharded[1] != single[1] or sharded[1] != single[0]:
        return _fail("degraded gets diverged between sharded and "
                     "single-device paths")
    if sharded[2] != single[2]:
        return _fail(f"recovery stats diverged: {sharded[2]} vs "
                     f"{single[2]}")
    if sharded[3] != single[3]:
        return _fail("map_pgs_batch diverged under the mesh")

    d = perf("dataplane").dump()
    for i in range(n_dev):
        if not d.get(f"shard{i}.put_stripes"):
            return _fail(f"chip {i}: no put-stripe accounting "
                         f"(dataplane.shard{i}.put_stripes)")
    for key in ("put_dispatches", "decode_dispatches",
                "recover_dispatches", "map_dispatches", "psum_rows"):
        if not d.get(key):
            return _fail(f"dataplane.{key} never incremented")

    # dispatched_mesh rides tracked ops (dump_historic_ops evidence)
    from ceph_tpu.common.op_tracker import tracker
    from ceph_tpu.cluster.monitor import Monitor
    from ceph_tpu.cluster.objecter import Objecter
    config().set("parallel_data_plane", True)
    try:
        sim = make_sim()
        client = Objecter(sim, Monitor(sim.osdmap))
        tracker().reset()
        client.put_many(2, ["m0", "m1"], [b"x" * 3000, b"y" * 5000])
        hist = tracker().dump_historic_ops()
        sim.shutdown()
    finally:
        config().clear("parallel_data_plane")
    mesh_ops = [o for o in hist["ops"]
                if any(e["event"] == "dispatched_mesh"
                       for e in o["events"])]
    if not mesh_ops:
        return _fail("no dispatched_mesh event in dump_historic_ops")

    if not quick:
        # the MULTICHIP payload contract: a well-formed section with
        # per-chip accounting and the bit-identity verdict
        import __graft_entry__
        section = __graft_entry__._cluster_sharded_impl(n_dev)
        for key in ("bit_identical_to_single_device",
                    "degraded_get_ok", "per_chip", "psum_rows"):
            if key not in section:
                return _fail(f"cluster_sharded section missing {key}")
        if not section["bit_identical_to_single_device"]:
            return _fail("cluster_sharded reports divergence")
        if not section["per_chip"]:
            return _fail("cluster_sharded has no per-chip accounting")

    print(f"OK: sharded data plane verified on {n_dev} chips "
          f"(bit-identical step, per-chip counters, dispatched_mesh, "
          f"psum_rows={d.get('psum_rows')})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
