#!/usr/bin/env python
"""S3Serve smoke check — the serving subsystem, verified (ISSUE 14).

Three assertions, small enough for the smoke sweep:

  1. DEFAULT GATE GREEN: a small multi-tenant serve run over live
     daemons (sharded bucket indexes, per-tenant dmClock classes)
     passes the SLO/QoS gate, the per-tenant p99s were read from the
     mon's cluster histogram merge (samples > 0), and every tenant's
     dmClock class actually dispatched on the daemons.

  2. FALSIFIABILITY: the deliberately starved config exits NONZERO
     with a per-tenant breach report naming the starved tenant — a
     gate that cannot fail proves nothing.

  3. SHARDING SEMANTICS: listing a bucket is IDENTICAL across shard
     counts (1 vs 8, same keys), and `bucket limit check` sees the
     shard layout.

Runs on CPU:

    python scripts/check_serving.py            # all three
    python scripts/check_serving.py --quick    # skip the live runs

Also wired as a fast pytest test (tests/test_s3_serving.py, `smoke`
marker) so CI covers it without a separate job.
"""
from __future__ import annotations

import io
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


def _check_sharding_semantics() -> int:
    from ceph_tpu.client.rados import Rados
    from ceph_tpu.cluster.monitor import Monitor
    from ceph_tpu.rgw import RGWGateway
    from tests.test_snaps import make_sim
    sim = make_sim(k=2, m=1)
    try:
        io_ = Rados(sim, Monitor(sim.osdmap)).connect() \
            .open_ioctx("ec")
        gw = RGWGateway(io_)
        keys = [f"k{i:03d}" for i in range(40)]
        b1 = gw.create_bucket("one", num_shards=1)
        b8 = gw.create_bucket("eight", num_shards=8)
        for k in keys:
            b1.put_object(k, k.encode())
            b8.put_object(k, k.encode())
        l1 = [c["key"] for c in
              b1.list_objects(max_keys=1000)["contents"]]
        l8 = [c["key"] for c in
              b8.list_objects(max_keys=1000)["contents"]]
        if l1 != l8 or l1 != sorted(keys):
            return _fail(f"listing differs across shard counts: "
                         f"{len(l1)} vs {len(l8)}")
        counts = b8.shard_entry_counts()
        if len(counts) != 8 or sum(counts) != len(keys):
            return _fail(f"shard entry counts wrong: {counts}")
        rows = {r["bucket"]: r for r in gw.bucket_limit_check()}
        if rows["eight"]["num_shards"] != 8:
            return _fail(f"limit check missed shards: {rows}")
        print(f"sharding ok: listing identical across 1/8 shards, "
              f"entries per shard {counts}")
        return 0
    finally:
        sim.shutdown()


def _check_gate_green() -> int:
    from ceph_tpu.rgw.serving import (ServeConfig, TenantSpec,
                                      run_serve)
    cfg = ServeConfig(seed=0, n_osds=3, index_shards=4, tenants=[
        TenantSpec("gold", clients=2, ops=30, qos_res=0.4,
                   min_share=0.05),
        TenantSpec("bronze", clients=3, ops=45, qos_res=0.0,
                   qos_wgt=4.0)])
    r = run_serve(cfg)
    if not r["ok"]:
        return _fail(f"default serve config breached the gate: "
                     f"{r['breaches']}")
    for name, m in r["tenants"].items():
        if m["ops"] and (not m["samples"] or m["p99_s"] is None):
            return _fail(f"{name}: no cluster-merged quantiles — "
                         f"the SLO was never read from the "
                         f"histogram merge")
    shares = r["scheduler"]["tenant_shares"]
    if not shares.get("gold") or not shares.get("bronze"):
        return _fail(f"tenant dmClock classes never dispatched on "
                     f"the daemons: {r['scheduler']}")
    print(f"gate green: {r['total_ops']} ops at {r['ops_s']} op/s, "
          f"dmClock tenant shares {shares}")
    return 0


def _check_gate_falsifiable() -> int:
    from ceph_tpu.rgw.serving import serve_main
    buf = io.StringIO()
    rc = serve_main(["--starve", "--osds", "3",
                     "--ops-scale", "0.4"], out=buf)
    text = buf.getvalue()
    if rc == 0:
        return _fail("starved config PASSED the gate — the SLO "
                     "gate is not falsifiable")
    if "BREACH" not in text or "gold" not in text:
        return _fail(f"starved run failed without a per-tenant "
                     f"breach report:\n{text}")
    print("falsifiability ok: starved config exits nonzero with a "
          "per-tenant breach report")
    return 0


def main() -> int:
    rc = _check_sharding_semantics()
    if rc:
        return rc
    if "--quick" not in sys.argv:
        rc = _check_gate_green() or _check_gate_falsifiable()
        if rc:
            return rc
    print("check_serving: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
