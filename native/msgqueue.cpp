// Messenger-analog host runtime: batching request queues with
// backpressure, in C++ behind a flat C ABI (ctypes-loaded).
//
// The reference's Messenger (src/msg/Messenger.cc, AsyncMessenger event
// loops + DispatchQueue + Throttle policies) moves typed messages
// between daemons over TCP/RDMA.  On this runtime the equivalent hop is
// host threads feeding a jitted device program: what must be preserved
// (SURVEY.md §2.4) is typed envelopes, BACKPRESSURE, and fan-out/gather
// to k+m shard queues — not sockets.  This file implements that core:
//
//   * ceph_tpu_mq_create(capacity_items, capacity_bytes)
//       bounded MPSC queue; producers block (with deadline) when either
//       throttle is exhausted — the Throttle/Policy role.
//   * ceph_tpu_mq_push(q, type, id, shard, payload, len, timeout_us)
//   * ceph_tpu_mq_pop_batch(q, max_items, max_bytes, wait_us, ...)
//       dispatcher side: waits for the first envelope, then drains up
//       to max_items/max_bytes or until the linger deadline — the
//       batch-forming step in front of a device dispatch (the role
//       DispatchQueue plays in front of ms_fast_dispatch).
//   * stats: depth, bytes, pushed, popped, throttle_waits.
//
// Envelopes are copied in (the queue owns its memory); pop hands out
// stable pointers freed by ceph_tpu_mq_free_batch.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <new>
#include <thread>

namespace {

struct Envelope {
    uint32_t type;
    uint64_t id;
    int32_t shard;
    uint64_t len;
    uint8_t *payload;
};

struct Queue {
    std::mutex mu;
    std::condition_variable not_empty;
    std::condition_variable not_full;
    std::deque<Envelope> items;
    uint64_t cap_items;
    uint64_t cap_bytes;
    uint64_t cur_bytes = 0;
    uint64_t pushed = 0;
    uint64_t popped = 0;
    uint64_t throttle_waits = 0;
    // every thread currently inside ANY queue entry point (including
    // those still blocked acquiring mu, parked in a condvar, or
    // notifying after unlock) — destroy spins on this before delete
    std::atomic<int> inflight{0};
    bool closed = false;
};

// RAII in-flight counter taken at entry-point scope, BEFORE the mutex
// is acquired, so destroy cannot free the Queue while any thread can
// still touch its mutex/condvars.
struct CallScope {
    Queue &q;
    explicit CallScope(Queue &queue) : q(queue) {
        q.inflight.fetch_add(1, std::memory_order_acquire);
    }
    ~CallScope() { q.inflight.fetch_sub(1, std::memory_order_release); }
};

bool has_room(const Queue &q, uint64_t len) {
    return q.items.size() < q.cap_items &&
           (q.cur_bytes + len) <= q.cap_bytes;
}

}  // namespace

extern "C" {

void *ceph_tpu_mq_create(uint64_t capacity_items, uint64_t capacity_bytes) {
    Queue *q = new (std::nothrow) Queue();
    if (!q) return nullptr;
    q->cap_items = capacity_items ? capacity_items : UINT64_MAX;
    q->cap_bytes = capacity_bytes ? capacity_bytes : UINT64_MAX;
    return q;
}

// Safe against concurrent users already REGISTERED inside
// push/pop_batch/stats (CallScope taken as the call's first action):
// closes the queue, wakes every blocked producer/consumer under the
// lock, then spins until the in-flight call count drains before
// deleting.  A call that has entered but not yet reached its CallScope
// fetch_add is indistinguishable from a new call — preventing calls
// from STARTING once destroy begins is the caller's responsibility
// (the Python wrapper nulls its handle; dispatch threads must be
// stopped, not joined-while-parked).
void ceph_tpu_mq_destroy(void *qp) {
    Queue *q = static_cast<Queue *>(qp);
    {
        std::lock_guard<std::mutex> lk(q->mu);
        q->closed = true;
        for (auto &e : q->items) delete[] e.payload;
        q->items.clear();
        q->cur_bytes = 0;
        q->not_empty.notify_all();
        q->not_full.notify_all();
    }
    while (q->inflight.load(std::memory_order_acquire) != 0)
        std::this_thread::yield();
    delete q;
}

void ceph_tpu_mq_close(void *qp) {
    Queue *q = static_cast<Queue *>(qp);
    CallScope cs(*q);
    {
        std::lock_guard<std::mutex> lk(q->mu);
        q->closed = true;
    }
    q->not_empty.notify_all();
    q->not_full.notify_all();
}

// rc: 0 ok, -1 timeout (throttle full), -2 closed, -3 oversized,
//     -4 payload allocation failure
int ceph_tpu_mq_push(void *qp, uint32_t type, uint64_t id, int32_t shard,
                     const uint8_t *payload, uint64_t len,
                     int64_t timeout_us) {
    Queue *q = static_cast<Queue *>(qp);
    CallScope cs(*q);
    std::unique_lock<std::mutex> lk(q->mu);
    if (len > q->cap_bytes) return -3;
    if (!has_room(*q, len)) {
        q->throttle_waits++;
        auto pred = [&] { return q->closed || has_room(*q, len); };
        if (timeout_us < 0) {
            q->not_full.wait(lk, pred);
        } else if (!q->not_full.wait_for(
                       lk, std::chrono::microseconds(timeout_us), pred)) {
            return -1;
        }
    }
    if (q->closed) return -2;
    Envelope e{type, id, shard, len, nullptr};
    if (len) {
        e.payload = new (std::nothrow) uint8_t[len];
        if (!e.payload) return -4;  // allocation failure != throttle timeout
        std::memcpy(e.payload, payload, len);
    }
    q->items.push_back(e);
    q->cur_bytes += len;
    q->pushed++;
    lk.unlock();
    q->not_empty.notify_one();
    return 0;
}

// Drain up to max_items (and max_bytes) envelopes.  Blocks up to
// wait_first_us for the FIRST envelope, then keeps draining whatever
// is immediately available plus anything arriving within linger_us
// (the batch-forming window).  Returns item count (0 on timeout/close).
// Caller owns the returned payload pointers until mq_free_batch.
int64_t ceph_tpu_mq_pop_batch(void *qp, int64_t max_items,
                              uint64_t max_bytes, int64_t wait_first_us,
                              int64_t linger_us, uint32_t *types,
                              uint64_t *ids, int32_t *shards,
                              uint8_t **payloads, uint64_t *lens) {
    Queue *q = static_cast<Queue *>(qp);
    CallScope cs(*q);
    std::unique_lock<std::mutex> lk(q->mu);
    if (q->items.empty()) {
        auto pred = [&] { return q->closed || !q->items.empty(); };
        if (wait_first_us < 0) {
            q->not_empty.wait(lk, pred);
        } else {
            q->not_empty.wait_for(
                lk, std::chrono::microseconds(wait_first_us), pred);
        }
    }
    if (q->closed && q->items.empty()) return 0;  // destroy-safe exit
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::microseconds(linger_us > 0 ? linger_us : 0);
    int64_t n = 0;
    uint64_t bytes = 0;
    bool byte_capped = false;
    for (;;) {
        while (n < max_items && !q->items.empty()) {
            Envelope &e = q->items.front();
            if (n > 0 && bytes + e.len > max_bytes) {
                byte_capped = true;  // next envelope won't fit this batch
                break;
            }
            types[n] = e.type;
            ids[n] = e.id;
            shards[n] = e.shard;
            payloads[n] = e.payload;
            lens[n] = e.len;
            bytes += e.len;
            q->cur_bytes -= e.len;
            q->items.pop_front();
            q->popped++;
            n++;
        }
        if (n >= max_items || bytes >= max_bytes || byte_capped ||
            q->closed || linger_us <= 0)
            break;
        auto now = std::chrono::steady_clock::now();
        if (now >= deadline) break;
        if (q->items.empty()) {
            q->not_empty.wait_until(lk, deadline, [&] {
                return q->closed || !q->items.empty();
            });
            if (q->items.empty()) break;
        }
    }
    lk.unlock();
    if (n) q->not_full.notify_all();
    return n;
}

void ceph_tpu_mq_free_payload(uint8_t *p) { delete[] p; }

void ceph_tpu_mq_stats(void *qp, uint64_t *depth, uint64_t *bytes,
                       uint64_t *pushed, uint64_t *popped,
                       uint64_t *throttle_waits) {
    Queue *q = static_cast<Queue *>(qp);
    CallScope cs(*q);
    std::lock_guard<std::mutex> lk(q->mu);
    *depth = q->items.size();
    *bytes = q->cur_bytes;
    *pushed = q->pushed;
    *popped = q->popped;
    *throttle_waits = q->throttle_waits;
}

}  // extern "C"
