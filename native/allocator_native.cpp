// Block-space bitmap allocator — the BlueStore Allocator family role
// (reference: src/os/bluestore/BitmapAllocator.h, Allocator.h — re-designed,
// not ported: state is a caller-owned uint64 bitmap so Python owns
// persistence/rebuild and the C++ side is pure, reentrant bit-scan math).
//
// Bit semantics: bit SET = block allocated, bit CLEAR = free.
// Words are little-endian uint64; block b lives in words[b >> 6] bit (b & 63).
//
// ceph_tpu_alloc_runs: allocate `want` blocks as few contiguous runs,
// first-fit from `hint` with 64-bit full-word skip, greedy longest-run
// extension.  Marks bits in place and emits (start,len) run pairs.
// Returns run count, or -1 on insufficient space / run-table overflow
// (state is rolled back on failure so the bitmap never leaks).
#include <cstdint>
#include <cstring>

extern "C" {

static inline int ctz64(uint64_t v) { return __builtin_ctzll(v); }

int64_t ceph_tpu_alloc_count_free(const uint64_t* words, int64_t n_blocks) {
    int64_t n_words = (n_blocks + 63) >> 6;
    int64_t used = 0;
    for (int64_t i = 0; i < n_words; ++i)
        used += __builtin_popcountll(words[i]);
    // bits past n_blocks in the tail word are kept SET by init so they
    // can never be handed out; they count as "used" here, which cancels
    // exactly against the (n_words << 6) - n_blocks padding
    return (n_words << 6) - used;
}

// Seal tail bits (past n_blocks) as allocated so scans never return them.
void ceph_tpu_alloc_init(uint64_t* words, int64_t n_blocks) {
    int64_t n_words = (n_blocks + 63) >> 6;
    memset(words, 0, (size_t)n_words * 8);
    int rem = (int)(n_blocks & 63);
    if (rem)
        words[n_words - 1] = ~0ULL << rem;
}

// Mark [start, start+len) allocated.  Returns 0, or -1 if any bit was
// already set (double allocation — fsck uses this to detect overlap).
int ceph_tpu_alloc_mark(uint64_t* words, int64_t n_blocks,
                        int64_t start, int64_t len) {
    if (start < 0 || len <= 0 || start + len > n_blocks) return -1;
    for (int64_t b = start; b < start + len;) {
        int64_t w = b >> 6;
        int bit = (int)(b & 63);
        int take = 64 - bit;
        if (b + take > start + len) take = (int)(start + len - b);
        uint64_t mask = (take == 64) ? ~0ULL : (((1ULL << take) - 1) << bit);
        if (words[w] & mask) return -1;
        words[w] |= mask;
        b += take;
    }
    return 0;
}

// Free [start, start+len).  Returns 0, or -1 if any bit was already
// clear (double free).
int ceph_tpu_alloc_release(uint64_t* words, int64_t n_blocks,
                           int64_t start, int64_t len) {
    if (start < 0 || len <= 0 || start + len > n_blocks) return -1;
    for (int64_t b = start; b < start + len;) {
        int64_t w = b >> 6;
        int bit = (int)(b & 63);
        int take = 64 - bit;
        if (b + take > start + len) take = (int)(start + len - b);
        uint64_t mask = (take == 64) ? ~0ULL : (((1ULL << take) - 1) << bit);
        if ((words[w] & mask) != mask) return -1;
        words[w] &= ~mask;
        b += take;
    }
    return 0;
}

// Length of the free run starting exactly at block b (0 if allocated).
static int64_t run_len_at(const uint64_t* words, int64_t n_bits, int64_t b,
                          int64_t cap) {
    int64_t len = 0;
    while (b < n_bits && len < cap) {
        int64_t w = b >> 6;
        int bit = (int)(b & 63);
        uint64_t v = words[w] >> bit;      // shifted: bit0 = block b
        int avail = 64 - bit;
        if (v == 0) { len += avail; b += avail; continue; }
        int first_set = ctz64(v);
        len += first_set;
        return len > cap ? cap : len;
    }
    return len > cap ? cap : len;
}

int ceph_tpu_alloc_runs(uint64_t* words, int64_t n_blocks, int64_t want,
                        int64_t hint, int64_t* out_runs, int max_runs) {
    if (want <= 0) return 0;
    int64_t n_words = (n_blocks + 63) >> 6;
    int64_t n_bits = n_words << 6;         // tail bits are sealed SET
    if (hint < 0 || hint >= n_blocks) hint = 0;
    int nruns = 0;
    int64_t got = 0;
    // two passes: [hint, end) then [0, hint)
    for (int pass = 0; pass < 2 && got < want; ++pass) {
        int64_t b = pass ? 0 : hint;
        int64_t end = pass ? hint : n_blocks;
        while (b < end && got < want) {
            int64_t w = b >> 6;
            int bit = (int)(b & 63);
            uint64_t v = ~(words[w] | ((bit == 0) ? 0ULL
                                       : ((1ULL << bit) - 1)));
            if (v == 0) { b = (w + 1) << 6; continue; }   // word full
            int64_t free_b = (w << 6) + ctz64(v);
            if (free_b >= end) break;
            int64_t len = run_len_at(words, n_bits, free_b, want - got);
            if (free_b + len > end) len = end - free_b;
            if (len <= 0) { b = free_b + 1; continue; }
            if (nruns >= max_runs) goto fail;
            ceph_tpu_alloc_mark(words, n_blocks, free_b, len);
            out_runs[2 * nruns] = free_b;
            out_runs[2 * nruns + 1] = len;
            ++nruns;
            got += len;
            b = free_b + len;
        }
    }
    if (got < want) goto fail;
    return nruns;
fail:
    for (int i = 0; i < nruns; ++i)
        ceph_tpu_alloc_release(words, n_blocks, out_runs[2 * i],
                               out_runs[2 * i + 1]);
    return -1;
}

}  // extern "C"
