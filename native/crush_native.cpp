// ceph_tpu native runtime — C++ CRUSH mapper.
//
// A from-scratch C++17 implementation of the CRUSH placement semantics
// (reference behavior: src/crush/mapper.c — rule machine, five bucket
// algorithms, collision/out/retry handling), exposed through a flat-array
// C ABI so the Python control plane drives it via ctypes.  This is the
// fast host-side mapper: the per-x scalar oracle for the XLA batch path
// and the low-latency fallback for maps outside the vectorized subset.
//
// The map is passed as dense arrays (the same CompiledMap layout the XLA
// path uses) plus per-bucket auxiliary tables for the legacy algorithms.
// Everything is reentrant: all mutable state lives in a caller-owned
// workspace.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr uint32_t kHashSeed = 1315423911u;
constexpr int32_t kItemUndef = 0x7FFFFFFE;
constexpr int32_t kItemNone = 0x7FFFFFFF;
constexpr int64_t kS64Min = INT64_MIN;

// bucket algorithms
enum Alg { UNIFORM = 1, LIST = 2, TREE = 3, STRAW = 4, STRAW2 = 5 };
// rule opcodes
enum Op {
  TAKE = 1, CHOOSE_FIRSTN = 2, CHOOSE_INDEP = 3, EMIT = 4,
  CHOOSELEAF_FIRSTN = 6, CHOOSELEAF_INDEP = 7,
  SET_CHOOSE_TRIES = 8, SET_CHOOSELEAF_TRIES = 9,
  SET_CHOOSE_LOCAL_TRIES = 10, SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11,
  SET_CHOOSELEAF_VARY_R = 12, SET_CHOOSELEAF_STABLE = 13,
};

#define MIX(a, b, c)                      \
  do {                                    \
    a -= b; a -= c; a ^= (c >> 13);       \
    b -= c; b -= a; b ^= (a << 8);        \
    c -= a; c -= b; c ^= (b >> 13);       \
    a -= b; a -= c; a ^= (c >> 12);       \
    b -= c; b -= a; b ^= (a << 16);       \
    c -= a; c -= b; c ^= (b >> 5);        \
    a -= b; a -= c; a ^= (c >> 3);        \
    b -= c; b -= a; b ^= (a << 10);       \
    c -= a; c -= b; c ^= (b >> 15);       \
  } while (0)

uint32_t hash2(uint32_t a, uint32_t b) {
  uint32_t hash = kHashSeed ^ a ^ b;
  uint32_t x = 231232u, y = 1232u;
  MIX(a, b, hash);
  MIX(x, a, hash);
  MIX(b, y, hash);
  return hash;
}

uint32_t hash3(uint32_t a, uint32_t b, uint32_t c) {
  uint32_t hash = kHashSeed ^ a ^ b ^ c;
  uint32_t x = 231232u, y = 1232u;
  MIX(a, b, hash);
  MIX(c, x, hash);
  MIX(y, a, hash);
  MIX(b, x, hash);
  MIX(y, c, hash);
  return hash;
}

uint32_t hash4(uint32_t a, uint32_t b, uint32_t c, uint32_t d) {
  uint32_t hash = kHashSeed ^ a ^ b ^ c ^ d;
  uint32_t x = 231232u, y = 1232u;
  MIX(a, b, hash);
  MIX(c, d, hash);
  MIX(a, x, hash);
  MIX(y, b, hash);
  MIX(c, x, hash);
  MIX(y, d, hash);
  return hash;
}

struct MapView {
  int32_t n_buckets = 0;
  int32_t max_size = 0;
  const int32_t* items = nullptr;        // [B, S]
  const int32_t* weights = nullptr;      // [B, S] straw2/list weights
  const int32_t* sizes = nullptr;        // [B]
  const int32_t* types = nullptr;        // [B]
  const int32_t* algs = nullptr;         // [B]
  // legacy-algorithm aux tables (same padding; may be null if unused)
  const int32_t* sum_weights = nullptr;  // [B, S] LIST prefix sums
  const int32_t* straws = nullptr;       // [B, S] STRAW scalers
  const int32_t* node_weights = nullptr; // [B, 2S] TREE interior weights
  const int32_t* num_nodes = nullptr;    // [B]
  const int64_t* ln_table = nullptr;     // [65536]
  int32_t max_devices = 0;
  // tunables
  int32_t choose_local_tries = 0;
  int32_t choose_local_fallback_tries = 0;
  int32_t choose_total_tries = 50;
  int32_t chooseleaf_descend_once = 1;
  int32_t chooseleaf_vary_r = 1;
  int32_t chooseleaf_stable = 1;
};

// per-bucket lazily built permutation (UNIFORM buckets)
struct PermState {
  uint32_t perm_x = 0;
  uint32_t perm_n = 0;
  std::vector<int32_t> perm;
};

struct Workspace {
  std::vector<PermState> perm;  // one per bucket index
  explicit Workspace(const MapView& m) : perm(m.n_buckets) {
    for (int32_t i = 0; i < m.n_buckets; ++i)
      perm[i].perm.assign(m.sizes[i], 0);
  }
};

struct Row {
  // pointer, not reference: Row must stay copy-assignable (the descent
  // loops reassign `in` as they walk down the hierarchy)
  const MapView* m;
  int32_t b;  // bucket index
  int32_t id() const { return -1 - b; }
  int32_t size() const { return m->sizes[b]; }
  int32_t alg() const { return m->algs[b]; }
  int32_t type() const { return m->types[b]; }
  int32_t item(int32_t i) const { return m->items[b * m->max_size + i]; }
  int32_t weight(int32_t i) const { return m->weights[b * m->max_size + i]; }
};

int32_t perm_choose(const Row& bk, PermState& w, uint32_t x, uint32_t r) {
  uint32_t pr = r % bk.size();
  if (w.perm_x != x || w.perm_n == 0) {
    w.perm_x = x;
    if (pr == 0) {
      int32_t s = hash3(x, (uint32_t)bk.id(), 0) % bk.size();
      w.perm[0] = s;
      w.perm_n = 0xFFFF;  // marker: only slot 0 valid
      return bk.item(s);
    }
    for (int32_t i = 0; i < bk.size(); ++i) w.perm[i] = i;
    w.perm_n = 0;
  } else if (w.perm_n == 0xFFFF) {
    for (int32_t i = 1; i < bk.size(); ++i) w.perm[i] = i;
    w.perm[w.perm[0]] = 0;
    w.perm_n = 1;
  }
  while (w.perm_n <= pr) {
    uint32_t p = w.perm_n;
    if ((int32_t)p < bk.size() - 1) {
      uint32_t i = hash3(x, (uint32_t)bk.id(), p) % (bk.size() - p);
      if (i) std::swap(w.perm[p + i], w.perm[p]);
    }
    w.perm_n++;
  }
  return bk.item(w.perm[pr]);
}

int32_t list_choose(const Row& bk, uint32_t x, uint32_t r) {
  const int32_t* sums = bk.m->sum_weights + bk.b * bk.m->max_size;
  for (int32_t i = bk.size() - 1; i >= 0; --i) {
    uint64_t w = hash4(x, (uint32_t)bk.item(i), r, (uint32_t)bk.id());
    w &= 0xFFFF;
    // tables hold u32 values reinterpreted as i32: zero-extend, never
    // sign-extend, and compare unsigned (mapper.c bucket_list_choose)
    w = (w * (uint64_t)(uint32_t)sums[i]) >> 16;
    if (w < (uint64_t)(uint32_t)bk.weight(i)) return bk.item(i);
  }
  return bk.item(0);
}

int32_t tree_choose(const Row& bk, uint32_t x, uint32_t r) {
  const int32_t* nw = bk.m->node_weights + bk.b * 2 * bk.m->max_size;
  int32_t n = bk.m->num_nodes[bk.b] >> 1;
  while (!(n & 1)) {
    uint64_t t =
        ((uint64_t)hash4(x, (uint32_t)n, r, (uint32_t)bk.id()) *
         (uint64_t)(uint32_t)nw[n]) >> 32;   // u32 weight, zero-extended
    int32_t h = 0, tn = n;
    while ((tn & 1) == 0) { h++; tn >>= 1; }
    int32_t left = n - (1 << (h - 1));
    n = (t < (uint64_t)(uint32_t)nw[left]) ? left : (n + (1 << (h - 1)));
  }
  return bk.item(n >> 1);
}

int32_t straw_choose(const Row& bk, uint32_t x, uint32_t r) {
  const int32_t* straws = bk.m->straws + bk.b * bk.m->max_size;
  int32_t high = 0;
  uint64_t high_draw = 0;
  for (int32_t i = 0; i < bk.size(); ++i) {
    uint64_t draw = (hash3(x, (uint32_t)bk.item(i), r) & 0xFFFF) *
                    (uint64_t)(uint32_t)straws[i];
    if (i == 0 || draw > high_draw) { high = i; high_draw = draw; }
  }
  return bk.item(high);
}

int32_t straw2_choose(const Row& bk, uint32_t x, uint32_t r,
                      const int32_t* arg_ids, const int32_t* arg_weights) {
  int32_t high = 0;
  int64_t high_draw = 0;
  for (int32_t i = 0; i < bk.size(); ++i) {
    int32_t w = arg_weights ? arg_weights[i] : bk.weight(i);
    int32_t id = arg_ids ? arg_ids[i] : bk.item(i);
    int64_t draw;
    if (w) {
      uint32_t u = hash3(x, (uint32_t)id, r) & 0xFFFF;
      int64_t ln = bk.m->ln_table[u] - 0x1000000000000LL;
      // ln <= 0, w > 0: truncating division toward zero
      draw = -((-ln) / w);
    } else {
      draw = kS64Min;
    }
    if (i == 0 || draw > high_draw) { high = i; high_draw = draw; }
  }
  return bk.item(high);
}

struct ChooseArgs {
  // optional per-bucket overrides, flattened [B, P, S] / [B, S]
  const int32_t* weight_sets = nullptr;
  const int32_t* ids = nullptr;
  int32_t n_positions = 0;
};

int32_t bucket_choose(const Row& bk, Workspace& ws, uint32_t x, uint32_t r,
                      const ChooseArgs* args, int32_t position) {
  switch (bk.alg()) {
    case UNIFORM: return perm_choose(bk, ws.perm[bk.b], x, r);
    case LIST: return list_choose(bk, x, r);
    case TREE: return tree_choose(bk, x, r);
    case STRAW: return straw_choose(bk, x, r);
    case STRAW2: {
      const int32_t* aw = nullptr;
      const int32_t* ai = nullptr;
      if (args && args->weight_sets) {
        int32_t p = position < args->n_positions ? position
                                                 : args->n_positions - 1;
        aw = args->weight_sets +
             ((int64_t)bk.b * args->n_positions + p) * bk.m->max_size;
      }
      if (args && args->ids) ai = args->ids + (int64_t)bk.b * bk.m->max_size;
      return straw2_choose(bk, x, r, ai, aw);
    }
  }
  return bk.item(0);
}

bool is_out(const MapView& m, const int32_t* weight, int32_t item,
            uint32_t x) {
  if (item >= m.max_devices) return true;
  int32_t w = weight[item];
  if (w >= 0x10000) return false;
  if (w == 0) return true;
  return (hash2(x, (uint32_t)item) & 0xFFFF) >= (uint32_t)w;
}

struct RuleCtx {
  const MapView& m;
  Workspace& ws;
  const int32_t* weight;
  const ChooseArgs* args;
  uint32_t x;
};

int choose_firstn(RuleCtx& c, Row bucket, int32_t numrep, int32_t type,
                  int32_t* out, int32_t outpos, int32_t out_size,
                  int32_t tries, int32_t recurse_tries,
                  int32_t local_retries, int32_t local_fallback_retries,
                  bool recurse_to_leaf, int32_t vary_r, int32_t stable,
                  int32_t* out2, int32_t parent_r) {
  int32_t count = out_size;
  for (int32_t rep = stable ? 0 : outpos; rep < numrep && count > 0;
       ++rep) {
    int32_t ftotal = 0;
    bool skip_rep = false;
    int32_t item = 0;
    bool retry_descent = true;
    while (retry_descent) {
      retry_descent = false;
      Row in = bucket;
      int32_t flocal = 0;
      bool retry_bucket = true;
      while (retry_bucket) {
        retry_bucket = false;
        bool collide = false, reject = false;
        uint32_t r = rep + parent_r + ftotal;
        if (in.size() == 0) {
          reject = true;
        } else {
          if (local_fallback_retries > 0 &&
              flocal >= (in.size() >> 1) &&
              flocal > local_fallback_retries) {
            item = perm_choose(in, c.ws.perm[in.b], c.x, r);
          } else {
            item = bucket_choose(in, c.ws, c.x, r, c.args, outpos);
          }
          if (item >= c.m.max_devices) { skip_rep = true; break; }
          int32_t itemtype = item < 0 ? c.m.types[-1 - item] : 0;
          if (itemtype != type) {
            if (item >= 0 || (-1 - item) >= c.m.n_buckets) {
              skip_rep = true;
              break;
            }
            in = Row{&c.m, -1 - item};
            retry_bucket = true;
            continue;
          }
          for (int32_t i = 0; i < outpos; ++i)
            if (out[i] == item) { collide = true; break; }
          if (!collide && recurse_to_leaf) {
            if (item < 0) {
              int32_t sub_r = vary_r ? (int32_t)(r >> (vary_r - 1)) : 0;
              if (choose_firstn(c, Row{&c.m, -1 - item},
                                stable ? 1 : outpos + 1, 0, out2, outpos,
                                count, recurse_tries, 0, local_retries,
                                local_fallback_retries, false, vary_r,
                                stable, nullptr, sub_r) <= outpos)
                reject = true;
            } else {
              out2[outpos] = item;
            }
          }
          if (!reject && !collide && type == 0)
            reject = is_out(c.m, c.weight, item, c.x);
        }
        if (reject || collide) {
          ftotal++;
          flocal++;
          if (collide && flocal <= local_retries) {
            retry_bucket = true;
          } else if (local_fallback_retries > 0 &&
                     flocal <= in.size() + local_fallback_retries) {
            retry_bucket = true;
          } else if (ftotal < tries) {
            retry_descent = true;
          } else {
            skip_rep = true;
          }
        }
      }
      if (skip_rep) break;
    }
    if (!skip_rep) {
      out[outpos] = item;
      outpos++;
      count--;
    }
  }
  return outpos;
}

void choose_indep(RuleCtx& c, Row bucket, int32_t left, int32_t numrep,
                  int32_t type, int32_t* out, int32_t outpos,
                  int32_t tries, int32_t recurse_tries,
                  bool recurse_to_leaf, int32_t* out2, int32_t parent_r) {
  const int32_t endpos = outpos + left;
  for (int32_t rep = outpos; rep < endpos; ++rep) {
    out[rep] = kItemUndef;
    if (out2) out2[rep] = kItemUndef;
  }
  for (int32_t ftotal = 0; left > 0 && ftotal < tries; ++ftotal) {
    for (int32_t rep = outpos; rep < endpos; ++rep) {
      if (out[rep] != kItemUndef) continue;
      Row in = bucket;
      for (;;) {
        uint32_t r = rep + parent_r;
        if (in.alg() == UNIFORM && in.size() % numrep == 0)
          r += (numrep + 1) * ftotal;
        else
          r += numrep * ftotal;
        if (in.size() == 0) break;
        int32_t item = bucket_choose(in, c.ws, c.x, r, c.args, outpos);
        if (item >= c.m.max_devices) {
          out[rep] = kItemNone;
          if (out2) out2[rep] = kItemNone;
          left--;
          break;
        }
        int32_t itemtype = item < 0 ? c.m.types[-1 - item] : 0;
        if (itemtype != type) {
          if (item >= 0 || (-1 - item) >= c.m.n_buckets) {
            out[rep] = kItemNone;
            if (out2) out2[rep] = kItemNone;
            left--;
            break;
          }
          in = Row{&c.m, -1 - item};
          continue;
        }
        bool collide = false;
        for (int32_t i = outpos; i < endpos; ++i)
          if (out[i] == item) { collide = true; break; }
        if (collide) break;
        if (recurse_to_leaf) {
          if (item < 0) {
            choose_indep(c, Row{&c.m, -1 - item}, 1, numrep, 0, out2, rep,
                         recurse_tries, 0, false, nullptr, r);
            if (out2 && out2[rep] == kItemNone) break;
          } else if (out2) {
            out2[rep] = item;
          }
        }
        if (itemtype == 0 && is_out(c.m, c.weight, item, c.x)) break;
        out[rep] = item;
        left--;
        break;
      }
    }
  }
  for (int32_t rep = outpos; rep < endpos; ++rep) {
    if (out[rep] == kItemUndef) out[rep] = kItemNone;
    if (out2 && out2[rep] == kItemUndef) out2[rep] = kItemNone;
  }
}

int do_rule(const MapView& m, Workspace& ws, const int32_t* steps,
            int32_t n_steps, uint32_t x, int32_t result_max,
            const int32_t* weight, const ChooseArgs* args,
            int32_t* result) {
  std::vector<int32_t> w(result_max + 1), o(result_max + 1),
      co(result_max + 1);
  int32_t wsize = 0;
  int32_t result_len = 0;

  int32_t choose_tries = m.choose_total_tries + 1;
  int32_t choose_leaf_tries = 0;
  int32_t local_retries = m.choose_local_tries;
  int32_t local_fallback = m.choose_local_fallback_tries;
  int32_t vary_r = m.chooseleaf_vary_r;
  int32_t stable = m.chooseleaf_stable;

  RuleCtx ctx{m, ws, weight, args, x};

  for (int32_t s = 0; s < n_steps; ++s) {
    const int32_t op = steps[s * 3], arg1 = steps[s * 3 + 1],
                  arg2 = steps[s * 3 + 2];
    bool firstn = false;
    switch (op) {
      case TAKE:
        if ((arg1 >= 0 && arg1 < m.max_devices) ||
            (-1 - arg1 >= 0 && -1 - arg1 < m.n_buckets)) {
          w[0] = arg1;
          wsize = 1;
        }
        break;
      case SET_CHOOSE_TRIES:
        if (arg1 > 0) choose_tries = arg1;
        break;
      case SET_CHOOSELEAF_TRIES:
        if (arg1 > 0) choose_leaf_tries = arg1;
        break;
      case SET_CHOOSE_LOCAL_TRIES:
        if (arg1 >= 0) local_retries = arg1;
        break;
      case SET_CHOOSE_LOCAL_FALLBACK_TRIES:
        if (arg1 >= 0) local_fallback = arg1;
        break;
      case SET_CHOOSELEAF_VARY_R:
        if (arg1 >= 0) vary_r = arg1;
        break;
      case SET_CHOOSELEAF_STABLE:
        if (arg1 >= 0) stable = arg1;
        break;
      case CHOOSE_FIRSTN:
      case CHOOSELEAF_FIRSTN:
      case CHOOSE_INDEP:
      case CHOOSELEAF_INDEP: {
        if (wsize == 0) break;
        firstn = (op == CHOOSE_FIRSTN || op == CHOOSELEAF_FIRSTN);
        const bool leaf =
            (op == CHOOSELEAF_FIRSTN || op == CHOOSELEAF_INDEP);
        int32_t osize = 0;
        for (int32_t i = 0; i < wsize; ++i) {
          int32_t numrep = arg1;
          if (numrep <= 0) {
            numrep += result_max;
            if (numrep <= 0) continue;
          }
          int32_t bno = -1 - w[i];
          if (bno < 0 || bno >= m.n_buckets) continue;
          Row bucket{&m, bno};
          if (firstn) {
            int32_t recurse_tries =
                choose_leaf_tries ? choose_leaf_tries
                : (m.chooseleaf_descend_once ? 1 : choose_tries);
            osize = choose_firstn(
                ctx, bucket, numrep, arg2, o.data() + osize, 0,
                result_max - osize, choose_tries, recurse_tries,
                local_retries, local_fallback, leaf, vary_r, stable,
                co.data() + osize, 0) + osize;
          } else {
            int32_t out_size = std::min(numrep, result_max - osize);
            choose_indep(ctx, bucket, out_size, numrep, arg2,
                         o.data() + osize, 0, choose_tries,
                         choose_leaf_tries ? choose_leaf_tries : 1, leaf,
                         co.data() + osize, 0);
            osize += out_size;
          }
        }
        if (leaf)
          for (int32_t i = 0; i < osize; ++i) o[i] = co[i];
        std::swap(w, o);
        wsize = osize;
        break;
      }
      case EMIT:
        for (int32_t i = 0; i < wsize && result_len < result_max; ++i)
          result[result_len++] = w[i];
        wsize = 0;
        break;
    }
    (void)firstn;
  }
  return result_len;
}

}  // namespace

extern "C" {

// Batched do_rule over xs: results [n_xs, result_max] filled with
// ITEM_NONE padding; returns 0 on success.
int ceph_tpu_do_rule_batch(
    // map arrays
    int32_t n_buckets, int32_t max_size, const int32_t* items,
    const int32_t* weights, const int32_t* sizes, const int32_t* types,
    const int32_t* algs, const int32_t* sum_weights, const int32_t* straws,
    const int32_t* node_weights, const int32_t* num_nodes,
    const int64_t* ln_table, int32_t max_devices,
    // tunables
    int32_t choose_local_tries, int32_t choose_local_fallback_tries,
    int32_t choose_total_tries, int32_t chooseleaf_descend_once,
    int32_t chooseleaf_vary_r, int32_t chooseleaf_stable,
    // rule
    const int32_t* steps, int32_t n_steps,
    // choose args (nullable)
    const int32_t* arg_weight_sets, const int32_t* arg_ids,
    int32_t n_positions,
    // query
    const uint32_t* xs, int64_t n_xs, int32_t result_max,
    const int32_t* device_weights, int32_t* results) {
  MapView m;
  m.n_buckets = n_buckets;
  m.max_size = max_size;
  m.items = items;
  m.weights = weights;
  m.sizes = sizes;
  m.types = types;
  m.algs = algs;
  m.sum_weights = sum_weights;
  m.straws = straws;
  m.node_weights = node_weights;
  m.num_nodes = num_nodes;
  m.ln_table = ln_table;
  m.max_devices = max_devices;
  m.choose_local_tries = choose_local_tries;
  m.choose_local_fallback_tries = choose_local_fallback_tries;
  m.choose_total_tries = choose_total_tries;
  m.chooseleaf_descend_once = chooseleaf_descend_once;
  m.chooseleaf_vary_r = chooseleaf_vary_r;
  m.chooseleaf_stable = chooseleaf_stable;

  ChooseArgs args;
  args.weight_sets = arg_weight_sets;
  args.ids = arg_ids;
  args.n_positions = n_positions;
  const ChooseArgs* argp =
      (arg_weight_sets || arg_ids) ? &args : nullptr;

  Workspace ws(m);
  for (int64_t i = 0; i < n_xs; ++i) {
    int32_t* res = results + i * result_max;
    for (int32_t j = 0; j < result_max; ++j) res[j] = kItemNone;
    do_rule(m, ws, steps, n_steps, xs[i], result_max, device_weights,
            argp, res);
  }
  return 0;
}

uint32_t ceph_tpu_hash2(uint32_t a, uint32_t b) { return hash2(a, b); }
uint32_t ceph_tpu_hash3(uint32_t a, uint32_t b, uint32_t c) {
  return hash3(a, b, c);
}

}  // extern "C"
