// ceph_tpu native runtime — GF(2^8) region coding.
//
// SIMD erasure-encode/decode over byte regions: the role ISA-L's
// ec_encode_data plays in the reference (src/erasure-code/isa/
// ErasureCodeIsa.cc:129).  Each constant multiply is two 16-entry nibble
// table lookups; with AVX2 the lookups are _mm256_shuffle_epi8 over 32
// bytes per instruction, otherwise a portable scalar path runs.
//
// This is the honest local CPU baseline for the TPU plugin's throughput
// comparison (BASELINE.md) and the host-side fallback codec.

#include <cstdint>
#include <cstring>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

constexpr uint16_t kPoly = 0x11D;

struct Tables {
  uint8_t mul[256][256];
  bool ready = false;
};

Tables& tables() {
  static Tables t;
  if (!t.ready) {
    for (int a = 0; a < 256; ++a) {
      for (int b = 0; b < 256; ++b) {
        uint16_t r = 0, x = a, y = b;
        while (y) {
          if (y & 1) r ^= x;
          y >>= 1;
          x <<= 1;
          if (x & 0x100) x ^= kPoly;
        }
        t.mul[a][b] = (uint8_t)r;
      }
    }
    t.ready = true;
  }
  return t;
}

// nibble tables for constant c: prod = lo[x & 0xF] ^ hi[x >> 4]
void nibble_tables(uint8_t c, uint8_t lo[16], uint8_t hi[16]) {
  Tables& t = tables();
  for (int i = 0; i < 16; ++i) {
    lo[i] = t.mul[c][i];
    hi[i] = t.mul[c][i << 4];
  }
}

// dst ^= c * src over len bytes
void region_mul_xor(uint8_t* dst, const uint8_t* src, uint8_t c,
                    int64_t len) {
  if (c == 0) return;
  if (c == 1) {
    int64_t i = 0;
#if defined(__AVX2__)
    for (; i + 32 <= len; i += 32) {
      __m256i d = _mm256_loadu_si256((const __m256i*)(dst + i));
      __m256i s = _mm256_loadu_si256((const __m256i*)(src + i));
      _mm256_storeu_si256((__m256i*)(dst + i), _mm256_xor_si256(d, s));
    }
#endif
    for (; i < len; ++i) dst[i] ^= src[i];
    return;
  }
  uint8_t lo[16], hi[16];
  nibble_tables(c, lo, hi);
  int64_t i = 0;
#if defined(__AVX2__)
  const __m256i vlo = _mm256_broadcastsi128_si256(
      _mm_loadu_si128((const __m128i*)lo));
  const __m256i vhi = _mm256_broadcastsi128_si256(
      _mm_loadu_si128((const __m128i*)hi));
  const __m256i mask = _mm256_set1_epi8(0x0F);
  for (; i + 32 <= len; i += 32) {
    __m256i s = _mm256_loadu_si256((const __m256i*)(src + i));
    __m256i l = _mm256_shuffle_epi8(vlo, _mm256_and_si256(s, mask));
    __m256i h = _mm256_shuffle_epi8(
        vhi, _mm256_and_si256(_mm256_srli_epi64(s, 4), mask));
    __m256i p = _mm256_xor_si256(l, h);
    __m256i d = _mm256_loadu_si256((const __m256i*)(dst + i));
    _mm256_storeu_si256((__m256i*)(dst + i), _mm256_xor_si256(d, p));
  }
#endif
  const Tables& t = tables();
  for (; i < len; ++i) dst[i] ^= t.mul[c][src[i]];
}

}  // namespace

extern "C" {

// out[m][chunk] = matrix[m][k] (GF) x data[k][chunk]; out zeroed here.
int ceph_tpu_gf_matmul_regions(const uint8_t* matrix, int32_t rows,
                               int32_t k, const uint8_t* data,
                               uint8_t* out, int64_t chunk) {
  std::memset(out, 0, (size_t)rows * chunk);
  for (int32_t r = 0; r < rows; ++r)
    for (int32_t c = 0; c < k; ++c)
      region_mul_xor(out + (int64_t)r * chunk, data + (int64_t)c * chunk,
                     matrix[r * k + c], chunk);
  return 0;
}

// dst ^= c * src (exposed for tests / XOR fast paths)
void ceph_tpu_gf_region_mul_xor(uint8_t* dst, const uint8_t* src,
                                uint8_t c, int64_t len) {
  region_mul_xor(dst, src, c, len);
}

// Bit-sliced (jerasure-packet) region-XOR codec: out plane r = XOR of
// input planes where bitmat[r][c] == 1.  The CPU counterpart of the TPU
// masked-XOR kernel and the role of jerasure's schedule execution
// (jerasure_schedule_encode, src/erasure-code/jerasure/
// ErasureCodeJerasure.cc:162) — pure wide XOR, no table lookups, i.e.
// the FASTEST possible CPU formulation of the same technique, which
// keeps the TPU-vs-CPU comparison honest for bitsliced layouts.
// bitmat [R, C] 0/1; planes [C, P] contiguous; out [R, P] zeroed here.
int ceph_tpu_gf2_xor_regions(const uint8_t* bitmat, int32_t R, int32_t C,
                             const uint8_t* planes, uint8_t* out,
                             int64_t P) {
  std::memset(out, 0, (size_t)R * P);
  for (int32_t r = 0; r < R; ++r) {
    uint8_t* dst = out + (int64_t)r * P;
    for (int32_t c = 0; c < C; ++c) {
      if (!bitmat[r * C + c]) continue;
      const uint8_t* src = planes + (int64_t)c * P;
      int64_t i = 0;
#if defined(__AVX2__)
      for (; i + 32 <= P; i += 32) {
        __m256i d = _mm256_loadu_si256((const __m256i*)(dst + i));
        __m256i s = _mm256_loadu_si256((const __m256i*)(src + i));
        _mm256_storeu_si256((__m256i*)(dst + i), _mm256_xor_si256(d, s));
      }
#endif
      for (; i < P; ++i) dst[i] ^= src[i];
    }
  }
  return 0;
}

int ceph_tpu_has_avx2(void) {
#if defined(__AVX2__)
  return 1;
#else
  return 0;
#endif
}

}  // extern "C"
