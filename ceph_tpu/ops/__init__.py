"""Low-level array ops: hashes, GF arithmetic, device kernels."""
