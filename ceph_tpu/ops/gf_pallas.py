"""Pallas TPU kernel for the GF(2^8) bit-plane matmul.

Streams [k, TILE] byte tiles into VMEM, unpacks to bit-planes IN VMEM,
runs the int8 MXU matmul, and packs parity bytes before they leave the
core, so the 8x bit expansion never touches HBM.  Measured on v5e-1 it
currently matches the XLA lowering (~7.5 ms per 134 MB batch for
RS(8,3)) — both are bound by MXU shape utilization (M=8m=24, K=8k=64
against the 128x128 array) and the int32 bit-twiddling this Mosaic
forces (u8 vector shifts/compares/adds all fail to legalize).  Kept as
the TPU-kernel foothold: shape-packing or plane-major-at-rest layouts
improve from here without touching callers.

Same math, bit-for-bit: out = pack((B @ unpack(d)) & 1) with the
bit-row convention of gf.gf8_bitmatrix (row 8i+b = bit b of symbol row
i).  Wired into the jax codec's encode/decode via the `ec_kernel`
option (auto = this kernel on TPU, XLA elsewhere).

Reference roles: ISA-L ec_encode_data (src/erasure-code/isa/
ErasureCodeIsa.cc:129), jerasure bitmatrix schedules
(src/erasure-code/jerasure/ErasureCodeJerasure.cc:162).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# pallas is TPU-only here; import lazily so CPU test runs never touch it
_TILE = 2048          # byte lanes per program (multiple of 128)


def _kernel(bitmat_ref, data_ref, out_ref):
    """bitmat [8m, 8k] i8 (VMEM-resident), data [1, k, T] u8 ->
    out [1, m, T] u8.  Bit twiddling stays in uint8 so the VPU packs
    4x the lanes per cycle vs int32."""
    d = data_ref[0]                              # [k, T] uint8
    k, T = d.shape
    # int32 twiddling throughout: this Mosaic rejects u8 vector shifts,
    # u8 compares, i8 adds AND i1/i8 reshapes — i32 is the only
    # vector-legal route (measured equal to the XLA lowering anyway;
    # the kernel is MXU-shape-bound at M=8m, K=8k, not unpack-bound)
    shifts = jax.lax.broadcasted_iota(jnp.int32, (k, 8, T), 1)
    bits = ((d[:, None, :].astype(jnp.int32) >> shifts) & 1)
    bits = bits.reshape(8 * k, T).astype(jnp.int8)
    acc = jax.lax.dot_general(
        bitmat_ref[:], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)        # [8m, T]
    m = acc.shape[0] // 8
    bit_i32 = (acc & 1).reshape(m, 8, T)
    out = bit_i32[:, 0, :]
    for b in range(1, 8):
        out = out | (bit_i32[:, b, :] << b)
    out_ref[0] = out.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=())
def _bitplane_matmul_pallas(bitmat, data):
    """bitmat [8m, 8k] int8, data [B, k, L] uint8 -> [B, m, L] uint8.
    L must be a multiple of _TILE (caller pads)."""
    from jax.experimental import pallas as pl
    B, k, L = data.shape
    m = bitmat.shape[0] // 8
    grid = (B, L // _TILE)
    # index maps must be i32: under jax_enable_x64 (which the CRUSH
    # mapper turns on process-wide) they trace as i64 and Mosaic fails
    # to legalize the func.return
    with jax.enable_x64(False):
        return pl.pallas_call(
            _kernel,
            out_shape=jax.ShapeDtypeStruct((B, m, L), jnp.uint8),
            grid=grid,
            in_specs=[
                pl.BlockSpec((bitmat.shape[0], bitmat.shape[1]),
                             lambda b, l: (0, 0)),
                pl.BlockSpec((1, k, _TILE), lambda b, l: (b, 0, l)),
            ],
            out_specs=pl.BlockSpec((1, m, _TILE), lambda b, l: (b, 0, l)),
        )(bitmat, data)


def available() -> bool:
    """Pallas path only on real TPU backends."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def bitplane_matmul(bitmat, data) -> jax.Array:
    """Drop-in for gf_jax.bitplane_matmul with VMEM bit-unpacking.

    data [..., k, L] uint8; leading axes flattened to one batch dim;
    L padded to the tile size and cropped after.
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    lead = data.shape[:-2]
    k, L = data.shape[-2], data.shape[-1]
    B = int(np.prod(lead)) if lead else 1
    d3 = data.reshape(B, k, L)
    pad = (-L) % _TILE
    if pad:
        d3 = jnp.pad(d3, ((0, 0), (0, 0), (0, pad)))
    out = _bitplane_matmul_pallas(jnp.asarray(bitmat, jnp.int8), d3)
    if pad:
        out = out[..., :L]
    m = out.shape[-2]
    return out.reshape(lead + (m, L))
