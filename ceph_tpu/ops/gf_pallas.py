"""Pallas TPU kernel for the GF(2^8) bit-plane matmul.

Streams [k, TILE] byte tiles into VMEM, unpacks to bit-planes IN VMEM,
runs the int8 MXU matmul, and packs parity bytes before they leave the
core, so the 8x bit expansion never touches HBM.  Measured on v5e-1 it
currently matches the XLA lowering (~7.5 ms per 134 MB batch for
RS(8,3)) — both are bound by MXU shape utilization (M=8m=24, K=8k=64
against the 128x128 array) and the int32 bit-twiddling this Mosaic
forces (u8 vector shifts/compares/adds all fail to legalize).  Kept as
the TPU-kernel foothold: shape-packing or plane-major-at-rest layouts
improve from here without touching callers.

Same math, bit-for-bit: out = pack((B @ unpack(d)) & 1) with the
bit-row convention of gf.gf8_bitmatrix (row 8i+b = bit b of symbol row
i).  Wired into the jax codec's encode/decode via the `ec_kernel`
option (auto = this kernel on TPU, XLA elsewhere).

Reference roles: ISA-L ec_encode_data (src/erasure-code/isa/
ErasureCodeIsa.cc:129), jerasure bitmatrix schedules
(src/erasure-code/jerasure/ErasureCodeJerasure.cc:162).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# pallas is TPU-only here; import lazily so CPU test runs never touch it
_TILE = 2048          # byte lanes per program (multiple of 128)


def _kernel(bitmat_ref, data_ref, out_ref):
    """bitmat [8m, 8k] i8 (VMEM-resident), data [1, k, T] u8 ->
    out [1, m, T] u8.  Bit twiddling stays in uint8 so the VPU packs
    4x the lanes per cycle vs int32."""
    d = data_ref[0]                              # [k, T] uint8
    k, T = d.shape
    # int32 twiddling throughout: this Mosaic rejects u8 vector shifts,
    # u8 compares, i8 adds AND i1/i8 reshapes — i32 is the only
    # vector-legal route (measured equal to the XLA lowering anyway;
    # the kernel is MXU-shape-bound at M=8m, K=8k, not unpack-bound)
    shifts = jax.lax.broadcasted_iota(jnp.int32, (k, 8, T), 1)
    bits = ((d[:, None, :].astype(jnp.int32) >> shifts) & 1)
    bits = bits.reshape(8 * k, T).astype(jnp.int8)
    acc = jax.lax.dot_general(
        bitmat_ref[:], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)        # [8m, T]
    m = acc.shape[0] // 8
    bit_i32 = (acc & 1).reshape(m, 8, T)
    out = bit_i32[:, 0, :]
    for b in range(1, 8):
        out = out | (bit_i32[:, b, :] << b)
    out_ref[0] = out.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=())
def _bitplane_matmul_pallas(bitmat, data):
    """bitmat [8m, 8k] int8, data [B, k, L] uint8 -> [B, m, L] uint8.
    L must be a multiple of _TILE (caller pads)."""
    from jax.experimental import pallas as pl
    B, k, L = data.shape
    m = bitmat.shape[0] // 8
    grid = (B, L // _TILE)
    # index maps must be i32: under jax_enable_x64 (which the CRUSH
    # mapper turns on process-wide) they trace as i64 and Mosaic fails
    # to legalize the func.return
    with jax.enable_x64(False):
        return pl.pallas_call(
            _kernel,
            out_shape=jax.ShapeDtypeStruct((B, m, L), jnp.uint8),
            grid=grid,
            in_specs=[
                pl.BlockSpec((bitmat.shape[0], bitmat.shape[1]),
                             lambda b, l: (0, 0)),
                pl.BlockSpec((1, k, _TILE), lambda b, l: (b, 0, l)),
            ],
            out_specs=pl.BlockSpec((1, m, _TILE), lambda b, l: (b, 0, l)),
        )(bitmat, data)


def _fused_kernel(bitmat_ref, crcA_ref, data_ref,
                  par_ref, dcrc_ref, pcrc_ref):
    """One ragged block: data [1, k, T] u8 -> parity [1, m, T] u8 plus
    the crc32 BIT accumulators of every data and parity row ([1, k, 32]
    and [1, m, 32] i32, packed to u32 values by the caller — the bit
    packing needs u32 shifts Mosaic's vector path dislikes, and at 32
    lanes per row it is free outside).

    Fusion shape: ONE bit unpack feeds the GF(2^8) MXU matmul and the
    crc GF(2) contraction, and the parity rows' crcs are contracted
    straight from the parity bit planes before byte packing.  The crc
    matrix arrives pre-sliced per bit plane (crcA [8, T, 32] i8 with
    crcA[b, t] = A[8t+b] of crc32_gf2.crc_matrix), so each plane is a
    plain [*, T] x [T, 32] dot — no in-kernel transposes, which this
    Mosaic will not legalize (same constraint family as the i32-only
    bit twiddling in _kernel above)."""
    d = data_ref[0]                              # [k, T] uint8
    k, T = d.shape
    shifts = jax.lax.broadcasted_iota(jnp.int32, (k, 8, T), 1)
    bits3 = ((d[:, None, :].astype(jnp.int32) >> shifts) & 1)
    gf_bits = bits3.reshape(8 * k, T).astype(jnp.int8)
    acc = jax.lax.dot_general(
        bitmat_ref[:], gf_bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)        # [8m, T]
    m = acc.shape[0] // 8
    bit_p = (acc & 1).reshape(m, 8, T)
    out = bit_p[:, 0, :]
    for b in range(1, 8):
        out = out | (bit_p[:, b, :] << b)
    par_ref[0] = out.astype(jnp.uint8)
    dacc = jnp.zeros((k, 32), jnp.int32)
    pacc = jnp.zeros((m, 32), jnp.int32)
    for b in range(8):
        Ab = crcA_ref[b]                         # [T, 32] int8
        dacc = dacc + jax.lax.dot_general(
            bits3[:, b, :].astype(jnp.int8), Ab,
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32)
        pacc = pacc + jax.lax.dot_general(
            bit_p[:, b, :].astype(jnp.int8), Ab,
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32)
    dcrc_ref[0] = dacc & 1
    pcrc_ref[0] = pacc & 1


def fused_ragged_matmul(bitmat, crcA8, pool):
    """TPU dispatch of the fused ragged traversal: bitmat [8m, 8k]
    int8, crcA8 [8, T, 32] int8 (ragged_fused._crc_a8), pool
    [G, k, T] uint8 -> (parity [G, m, T] u8, data crc bits
    [G, k, 32] i32, parity crc bits [G, m, 32] i32).  One grid program
    per staged block; the matrices stay VMEM-resident across the
    grid.  Bit-identical to ragged_fused.fused_block_math (asserted
    on TPU by tests/test_ragged_fused.py; gated by :func:`available`).
    """
    from jax.experimental import pallas as pl
    bitmat = jnp.asarray(bitmat, jnp.int8)
    crcA8 = jnp.asarray(crcA8, jnp.int8)
    pool = jnp.asarray(pool, jnp.uint8)
    G, k, T = pool.shape
    m = bitmat.shape[0] // 8
    with jax.enable_x64(False):
        return pl.pallas_call(
            _fused_kernel,
            out_shape=(
                jax.ShapeDtypeStruct((G, m, T), jnp.uint8),
                jax.ShapeDtypeStruct((G, k, 32), jnp.int32),
                jax.ShapeDtypeStruct((G, m, 32), jnp.int32),
            ),
            grid=(G,),
            in_specs=[
                pl.BlockSpec((bitmat.shape[0], bitmat.shape[1]),
                             lambda g: (0, 0)),
                pl.BlockSpec((8, T, 32), lambda g: (0, 0, 0)),
                pl.BlockSpec((1, k, T), lambda g: (g, 0, 0)),
            ],
            out_specs=(
                pl.BlockSpec((1, m, T), lambda g: (g, 0, 0)),
                pl.BlockSpec((1, k, 32), lambda g: (g, 0, 0)),
                pl.BlockSpec((1, m, 32), lambda g: (g, 0, 0)),
            ),
        )(bitmat, crcA8, pool)


def available() -> bool:
    """Pallas path only on real TPU backends."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def bitplane_matmul(bitmat, data) -> jax.Array:
    """Drop-in for gf_jax.bitplane_matmul with VMEM bit-unpacking.

    data [..., k, L] uint8; leading axes flattened to one batch dim;
    L padded to the tile size and cropped after.
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    lead = data.shape[:-2]
    k, L = data.shape[-2], data.shape[-1]
    B = int(np.prod(lead)) if lead else 1
    d3 = data.reshape(B, k, L)
    pad = (-L) % _TILE
    if pad:
        d3 = jnp.pad(d3, ((0, 0), (0, 0), (0, pad)))
    out = _bitplane_matmul_pallas(jnp.asarray(bitmat, jnp.int8), d3)
    if pad:
        out = out[..., :L]
    m = out.shape[-2]
    return out.reshape(lead + (m, L))
