"""rjenkins1 32-bit mixing hash — CRUSH's only randomness source.

Three implementations sharing one spec (reference: src/crush/hash.c:12-90):

  * python-int scalars (`hash1`..`hash5`)   — used by the scalar reference mapper
  * numpy vectorized  (`np_hash2/np_hash3`) — host-side batch utilities
  * jax vectorized    (`jx_hash2/jx_hash3`) — traced into the TPU placement kernels

All arithmetic is modulo 2^32; the seed constant is 1315423911 (hash.c:24).
The mix schedule (which operands feed each 9-op mixing round) differs per arity
and is part of the wire-compatible spec.
"""
from __future__ import annotations

import numpy as np

M32 = 0xFFFFFFFF
SEED = 1315423911
MIX_X = 231232
MIX_Y = 1232


# ---------------------------------------------------------------- scalar ----

def _mix(a: int, b: int, c: int):
    a = (a - b) & M32; a = (a - c) & M32; a = a ^ (c >> 13)
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 8)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c = c ^ (b >> 13)
    a = (a - b) & M32; a = (a - c) & M32; a = a ^ (c >> 12)
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 16)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c = c ^ (b >> 5)
    a = (a - b) & M32; a = (a - c) & M32; a = a ^ (c >> 3)
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 10)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c = c ^ (b >> 15)
    return a, b, c


def hash1(a: int) -> int:
    a &= M32
    h = (SEED ^ a) & M32
    b, x, y = a, MIX_X, MIX_Y
    b, x, h = _mix(b, x, h)
    y, a, h = _mix(y, a, h)
    return h


def hash2(a: int, b: int) -> int:
    a &= M32; b &= M32
    h = (SEED ^ a ^ b) & M32
    x, y = MIX_X, MIX_Y
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def hash3(a: int, b: int, c: int) -> int:
    a &= M32; b &= M32; c &= M32
    h = (SEED ^ a ^ b ^ c) & M32
    x, y = MIX_X, MIX_Y
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


def hash4(a: int, b: int, c: int, d: int) -> int:
    a &= M32; b &= M32; c &= M32; d &= M32
    h = (SEED ^ a ^ b ^ c ^ d) & M32
    x, y = MIX_X, MIX_Y
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    a, x, h = _mix(a, x, h)
    y, b, h = _mix(y, b, h)
    c, x, h = _mix(c, x, h)
    y, d, h = _mix(y, d, h)
    return h


def hash5(a: int, b: int, c: int, d: int, e: int) -> int:
    a &= M32; b &= M32; c &= M32; d &= M32; e &= M32
    h = (SEED ^ a ^ b ^ c ^ d ^ e) & M32
    x, y = MIX_X, MIX_Y
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    e, x, h = _mix(e, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    d, x, h = _mix(d, x, h)
    y, e, h = _mix(y, e, h)
    return h


def str_hash_rjenkins(data: bytes) -> int:
    """Object-name hash (reference: src/common/ceph_hash.cc
    ceph_str_hash_rjenkins) — the object→ps step of placement."""
    a = 0x9E3779B9
    b = a
    c = 0
    i, length = 0, len(data)
    left = length
    while left >= 12:
        a = (a + int.from_bytes(data[i:i + 4], "little")) & M32
        b = (b + int.from_bytes(data[i + 4:i + 8], "little")) & M32
        c = (c + int.from_bytes(data[i + 8:i + 12], "little")) & M32
        a, b, c = _mix(a, b, c)
        i += 12
        left -= 12
    c = (c + length) & M32
    tail = data[i:]
    if left >= 11:
        c = (c + (tail[10] << 24)) & M32
    if left >= 10:
        c = (c + (tail[9] << 16)) & M32
    if left >= 9:
        c = (c + (tail[8] << 8)) & M32
    if left >= 8:
        b = (b + (tail[7] << 24)) & M32
    if left >= 7:
        b = (b + (tail[6] << 16)) & M32
    if left >= 6:
        b = (b + (tail[5] << 8)) & M32
    if left >= 5:
        b = (b + tail[4]) & M32
    if left >= 4:
        a = (a + (tail[3] << 24)) & M32
    if left >= 3:
        a = (a + (tail[2] << 16)) & M32
    if left >= 2:
        a = (a + (tail[1] << 8)) & M32
    if left >= 1:
        a = (a + tail[0]) & M32
    a, b, c = _mix(a, b, c)
    return c


# ----------------------------------------------------------------- numpy ----

def _np_mix(a, b, c):
    a = a - b; a = a - c; a = a ^ (c >> np.uint32(13))
    b = b - c; b = b - a; b = b ^ (a << np.uint32(8))
    c = c - a; c = c - b; c = c ^ (b >> np.uint32(13))
    a = a - b; a = a - c; a = a ^ (c >> np.uint32(12))
    b = b - c; b = b - a; b = b ^ (a << np.uint32(16))
    c = c - a; c = c - b; c = c ^ (b >> np.uint32(5))
    a = a - b; a = a - c; a = a ^ (c >> np.uint32(3))
    b = b - c; b = b - a; b = b ^ (a << np.uint32(10))
    c = c - a; c = c - b; c = c ^ (b >> np.uint32(15))
    return a, b, c


def np_hash2(a, b):
    a = np.asarray(a, np.uint32); b = np.asarray(b, np.uint32)
    h = np.uint32(SEED) ^ a ^ b
    x = np.broadcast_to(np.uint32(MIX_X), h.shape).copy()
    y = np.broadcast_to(np.uint32(MIX_Y), h.shape).copy()
    a, b, h = _np_mix(a, b, h)
    x, a, h = _np_mix(x, a, h)
    b, y, h = _np_mix(b, y, h)
    return h


def np_hash3(a, b, c):
    a = np.asarray(a, np.uint32); b = np.asarray(b, np.uint32)
    c = np.asarray(c, np.uint32)
    h = np.uint32(SEED) ^ a ^ b ^ c
    x = np.broadcast_to(np.uint32(MIX_X), h.shape).copy()
    y = np.broadcast_to(np.uint32(MIX_Y), h.shape).copy()
    a, b, h = _np_mix(a, b, h)
    c, x, h = _np_mix(c, x, h)
    y, a, h = _np_mix(y, a, h)
    b, x, h = _np_mix(b, x, h)
    y, c, h = _np_mix(y, c, h)
    return h


# ------------------------------------------------------------------- jax ----
# imported lazily so host-only users never pay for jax import

def _jx():
    import jax.numpy as jnp
    return jnp


def _jx_mix(a, b, c):
    jnp = _jx()
    u = lambda n: jnp.uint32(n)
    a = a - b; a = a - c; a = a ^ (c >> u(13))
    b = b - c; b = b - a; b = b ^ (a << u(8))
    c = c - a; c = c - b; c = c ^ (b >> u(13))
    a = a - b; a = a - c; a = a ^ (c >> u(12))
    b = b - c; b = b - a; b = b ^ (a << u(16))
    c = c - a; c = c - b; c = c ^ (b >> u(5))
    a = a - b; a = a - c; a = a ^ (c >> u(3))
    b = b - c; b = b - a; b = b ^ (a << u(10))
    c = c - a; c = c - b; c = c ^ (b >> u(15))
    return a, b, c


def jx_hash2(a, b):
    jnp = _jx()
    a = a.astype(jnp.uint32); b = b.astype(jnp.uint32)
    h = jnp.uint32(SEED) ^ a ^ b
    x = jnp.full_like(h, MIX_X); y = jnp.full_like(h, MIX_Y)
    a, b, h = _jx_mix(a, b, h)
    x, a, h = _jx_mix(x, a, h)
    b, y, h = _jx_mix(b, y, h)
    return h


def jx_hash3(a, b, c):
    jnp = _jx()
    a = a.astype(jnp.uint32); b = b.astype(jnp.uint32); c = c.astype(jnp.uint32)
    h = jnp.uint32(SEED) ^ a ^ b ^ c
    x = jnp.full_like(h, MIX_X); y = jnp.full_like(h, MIX_Y)
    a, b, h = _jx_mix(a, b, h)
    c, x, h = _jx_mix(c, x, h)
    y, a, h = _jx_mix(y, a, h)
    b, x, h = _jx_mix(b, x, h)
    y, c, h = _jx_mix(y, c, h)
    return h


def jx_hash4(a, b, c, d):
    jnp = _jx()
    a = a.astype(jnp.uint32); b = b.astype(jnp.uint32)
    c = c.astype(jnp.uint32); d = d.astype(jnp.uint32)
    h = jnp.uint32(SEED) ^ a ^ b ^ c ^ d
    x = jnp.full_like(h, MIX_X); y = jnp.full_like(h, MIX_Y)
    a, b, h = _jx_mix(a, b, h)
    c, d, h = _jx_mix(c, d, h)
    a, x, h = _jx_mix(a, x, h)
    y, b, h = _jx_mix(y, b, h)
    c, x, h = _jx_mix(c, x, h)
    y, d, h = _jx_mix(y, d, h)
    return h
