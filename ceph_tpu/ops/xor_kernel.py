"""Batched GF(2) region-XOR "matmul" — the flagship TPU erasure kernel.

Computes, for the bit-sliced plane layout of ops/gf2.py,

    out[b, r, :] = XOR_c ( planes[b, c, :] & masks[., r, c] )

i.e. a masked-XOR matrix product over byte regions.  The masks operand
(0 / -1 int32 words from gf2.bitmatrix_masks) is DATA, not program:
new erasure signatures reuse the same compiled kernel, and the masks
may carry a batch axis so every stripe in a recovery batch can decode
under its own signature in one dispatch.

Why this beats the bit-plane MXU matmul (ops/gf_pallas.py): the byte
layout forces an 8x bit unpack/pack on the VPU around a tiny
[8m, 8k] matmul (~2% MXU utilization for RS(8,3)); here the planes stay
packed — every int32 word carries 32 independent GF(2) lanes and the
whole contraction is R*C AND+XOR vector ops per tile, bound by the
~TB/s VPU and HBM rather than matmul shape.  Reference roles:
jerasure_schedule_encode / jerasure_schedule_decode_lazy
(src/erasure-code/jerasure/ErasureCodeJerasure.cc:162,274), ISA-L
ec_encode_data (src/erasure-code/isa/ErasureCodeIsa.cc:129).

Two backends, bit-identical (tests/test_gf2.py):
  * Pallas TPU kernel: grid (batch, lane-tiles); each program holds a
    [C, T] int32 tile in VMEM and unrolls the masked-XOR contraction.
  * XLA fallback (CPU/GPU/interpret): same unrolled graph under vmap.

Byte views: uint8 planes are bitcast to int32 words (4 bytes/word) at
the boundary; XOR commutes with any byte order, so the round trip is
exact whatever the platform endianness.
"""
from __future__ import annotations

import functools
import math
import threading

import jax
import jax.numpy as jnp
import numpy as np

# int32 lanes per pallas program (4 KiB of bytes per plane row).  Swept
# on v5e with the chained-marginal methodology over 134 MB RS(8,3)
# batches: 512 -> 227 GB/s, 1024 -> 413 GB/s, 2048 -> 258 GB/s.
_TILE = 1024


# ------------------------------------------------------------- conversions --

def _u8_to_i32(x: jax.Array) -> jax.Array:
    """[..., P] uint8 -> [..., P//4] int32 (P % 4 == 0)."""
    s = x.shape
    return jax.lax.bitcast_convert_type(
        x.reshape(s[:-1] + (s[-1] // 4, 4)), jnp.int32)


def _i32_to_u8(x: jax.Array) -> jax.Array:
    """[..., W] int32 -> [..., 4W] uint8 (inverse of _u8_to_i32)."""
    y = jax.lax.bitcast_convert_type(x, jnp.uint8)
    return y.reshape(y.shape[:-2] + (y.shape[-2] * 4,))


# -------------------------------------------------------------- contraction --

def _combine(mk, d):
    """masks [R, C] i32, words [C, T] i32 -> [R, T] i32.  Static unroll
    over the contraction axis (C <= a few hundred) — identical code
    feeds both the Pallas kernel body and the XLA fallback."""
    R, C = mk.shape
    acc = mk[:, 0:1] & d[0:1, :]
    for c in range(1, C):
        acc = acc ^ (mk[:, c:c + 1] & d[c:c + 1, :])
    return acc


def _kernel(masks_ref, data_ref, out_ref):
    out_ref[0] = _combine(masks_ref[0], data_ref[0])


@functools.partial(jax.jit, static_argnames=("per_batch", "tile"))
def _xor_matmul_pallas(masks, words, per_batch, tile=_TILE):
    """masks [Bm, R, C] i32, words [B, C, W] i32 -> [B, R, W] i32.
    W must be a multiple of ``tile`` (caller pads)."""
    from jax.experimental import pallas as pl
    B, C, W = words.shape
    R = masks.shape[1]
    grid = (B, W // tile)
    # i32 index maps (Mosaic rejects i64 traces under jax_enable_x64)
    with jax.enable_x64(False):
        return pl.pallas_call(
            _kernel,
            out_shape=jax.ShapeDtypeStruct((B, R, W), jnp.int32),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, R, C),
                             (lambda b, l: (b, 0, 0)) if per_batch
                             else (lambda b, l: (0, 0, 0))),
                pl.BlockSpec((1, C, tile), lambda b, l: (b, 0, l)),
            ],
            out_specs=pl.BlockSpec((1, R, tile), lambda b, l: (b, 0, l)),
        )(masks, words)


@functools.partial(jax.jit, static_argnames=("per_batch",))
def _xor_matmul_xla(masks, words, per_batch):
    """Fallback: same contraction as one fused XLA graph."""
    if per_batch:
        return jax.vmap(_combine)(masks, words)
    return jax.vmap(lambda d: _combine(masks[0], d))(words)


def use_pallas() -> bool:
    # backend probe: resolved ONCE at trace time by design — the
    # branch bakes the right kernel into the executable, it never
    # syncs per step (justified trace-time host access)
    try:
        return jax.devices()[0].platform == "tpu"  # noqa: CTL1003
    except Exception:  # pragma: no cover - no backend at all
        return False


# ------------------------------------------------------------------ public --

def xor_matmul_w32(masks, words) -> jax.Array:
    """int32-domain entry: masks [R, C] or [..., R, C], words
    [..., C, W] int32 -> [..., R, W] int32 (device array).

    A leading batch axis on ``masks`` must match ``words``'s leading
    axes elementwise (per-stripe decode signatures).
    """
    words = jnp.asarray(words, jnp.int32)
    masks = jnp.asarray(masks, jnp.int32)
    lead = words.shape[:-2]
    C, W = words.shape[-2:]
    per_batch = masks.ndim > 2
    if per_batch and masks.shape[:-2] != lead:
        raise ValueError(
            f"mask batch {masks.shape[:-2]} != data batch {lead}")
    if masks.shape[-1] != C:
        raise ValueError(
            f"masks contract {masks.shape[-1]} columns, data has {C} planes")
    B = math.prod(lead)
    w3 = words.reshape(B, C, W)
    R = masks.shape[-2]
    m3 = masks.reshape(B if per_batch else 1, R, masks.shape[-1])
    if use_pallas():
        # small chunks don't pad out to the full tile: clamp to the
        # next 128-word multiple so a 16-word plane costs 128 lanes,
        # not 1024 (the jit/pallas executable is shape-keyed anyway)
        tile = min(_TILE, -(-W // 128) * 128)
        pad = (-W) % tile
        if pad:
            w3 = jnp.pad(w3, ((0, 0), (0, 0), (0, pad)))
        with _compile_cm(True, per_batch, m3.shape, (B, C, W + pad)):
            out = _xor_matmul_pallas(m3, w3, per_batch, tile)
        if pad:
            out = out[..., :W]
    else:
        with _compile_cm(False, per_batch, m3.shape, (B, C, W)):
            out = _xor_matmul_xla(m3, w3, per_batch)
    return out.reshape(lead + (R, W))


# the jitted contractions above are shape-keyed: a first-seen
# (backend, per_batch, masks-shape, words-shape) tuple means XLA
# compiles a fresh executable on this dispatch — tag it with a
# jit.compile child span + jit.compiles counters so the triggering
# op's flame trace can explain the stall (same role as
# gf_jax.matrix_to_device's content-keyed tag)
_seen_shapes: set = set()
_seen_lock = threading.Lock()


def _compile_cm(pallas: bool, per_batch: bool, mshape, wshape):
    key = (pallas, per_batch, tuple(mshape), tuple(wshape))
    with _seen_lock:
        compiled = key not in _seen_shapes
        # compile events ARE trace-time events: XLA compiles exactly
        # when this runs under trace, so once-per-trace is the
        # correct count here, not a silent lie
        _seen_shapes.add(key)  # noqa: CTL1002
    from ..common.jit_profile import compile_event
    sig = (f"{'pallas' if pallas else 'xla'}:"
           f"{'x'.join(str(d) for d in mshape)}@"
           f"{'x'.join(str(d) for d in wshape)}")
    return compile_event("ec.xor_kernel", sig, compiled)


def xor_matmul(masks, planes) -> jax.Array:
    """uint8-domain entry: planes [..., C, P] uint8 (P % 4 == 0) ->
    [..., R, P] uint8 on device."""
    planes = jnp.asarray(planes, dtype=jnp.uint8)
    out = xor_matmul_w32(masks, _u8_to_i32(planes))
    return _i32_to_u8(out)


@functools.lru_cache(maxsize=4096)
def _masks_device(key: bytes, R: int, C: int) -> jax.Array:
    from . import gf2
    bm = np.frombuffer(key, dtype=np.uint8).reshape(R, C)
    return jnp.asarray(gf2.bitmatrix_masks(bm))


def masks_to_device(bitmat: np.ndarray) -> jax.Array:
    """Host GF(2) bit-matrix [R, C] 0/1 -> cached device mask operand
    [R, C] int32 (0 / -1), keyed by content (the ISA-L table-cache role,
    src/erasure-code/isa/ErasureCodeIsaTableCache.h:35)."""
    bm = np.ascontiguousarray(bitmat, dtype=np.uint8)
    return _masks_device(bm.tobytes(), *bm.shape)
