"""GF(2^8) linear algebra as XLA programs — the TPU erasure-code data path.

Formulation (validated bit-for-bit against the table oracle in
ceph_tpu.ops.gf): GF(2^8) multiplication by a constant is GF(2)-linear in
the operand bits, so a GF(2^8) matrix A [m,k] expands to a GF(2) bit
matrix B [8m,8k] and

    parity = pack( (B @ unpack(data)) mod 2 )

where unpack/pack move between byte rows and 0/1 bit-plane rows.  The
inner product is an ordinary integer matmul — int8 x int8 -> int32 — which
XLA tiles onto the MXU; mod-2 is a trailing bitwise AND that fuses into
the matmul epilogue.  Accumulation depth is 8k <= 2048 << 2^31, so int32
accumulation is exact.

This replaces the reference's per-stripe SIMD loops (ISA-L ec_encode_data,
jerasure matrix/bitmatrix encode — src/erasure-code/isa/ErasureCodeIsa.cc:129,
src/erasure-code/jerasure/ErasureCodeJerasure.cc:162) with one batched
compiled call over [batch, k, chunk_bytes] stripes.
"""
from __future__ import annotations

import collections
import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..common.op_tracker import mark_active as _mark_active
from . import gf


def unpack_bits(data: jax.Array) -> jax.Array:
    """[..., k, L] uint8 -> [..., 8k, L] int8 of 0/1 (bit b of row i at
    row 8i+b, matching gf.bytes_to_bits)."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (data[..., :, None, :] >> shifts[None, :, None]) & jnp.uint8(1)
    s = bits.shape
    return bits.reshape(s[:-3] + (s[-3] * 8, s[-1])).astype(jnp.int8)


def pack_bits(bits: jax.Array) -> jax.Array:
    """[..., 8m, L] 0/1 -> [..., m, L] uint8."""
    s = bits.shape
    b = bits.reshape(s[:-2] + (s[-2] // 8, 8, s[-1])).astype(jnp.uint8)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    return (b << shifts[None, :, None]).sum(-2, dtype=jnp.uint8)


@jax.jit
def bitplane_matmul(bitmat: jax.Array, data: jax.Array) -> jax.Array:
    """GF(2^8) matmul: bitmat [8m, 8k] (from gf.gf8_bitmatrix), data
    [..., k, L] uint8 -> [..., m, L] uint8.  Batched over leading axes."""
    bits = unpack_bits(data)
    acc = jnp.einsum(
        "rc,...cl->...rl", bitmat.astype(jnp.int8), bits,
        preferred_element_type=jnp.int32)
    return pack_bits((acc & 1).astype(jnp.uint8))


_MATRIX_CACHE_SIZE = 4096


@functools.lru_cache(maxsize=_MATRIX_CACHE_SIZE)
def _bitmatrix_device(key: bytes, m: int, k: int) -> jax.Array:
    mat = np.frombuffer(key, dtype=np.uint8).reshape(m, k)
    return jnp.asarray(gf.gf8_bitmatrix(mat))


# content keys already materialized on device: the per-call compiled/
# cached tag must come from THIS call's key, not the global lru miss
# counter (reading that before/after the call mis-tags ops when another
# thread's miss lands in between).  Same capacity and per-access
# recency update as the lru above, so eviction tracks it and a
# re-materialized matrix is tagged compiled again.  Locked: OSD
# dispatcher threads hit this concurrently and the compound
# insert/move/evict is not atomic under the GIL.
_seen_matrices: collections.OrderedDict = collections.OrderedDict()
_seen_lock = threading.Lock()


def matrix_to_device(A: np.ndarray) -> jax.Array:
    """Host GF(2^8) matrix -> device bit-matrix, cached by content.

    A first-seen matrix means a NEW encode/decode matrix reached the
    device plane — the compile-vs-cached proxy tagged onto the active
    tracked op (a fresh matrix usually also means a fresh XLA constant
    fold)."""
    A = np.ascontiguousarray(A, dtype=np.uint8)
    key = (A.tobytes(), A.shape)
    with _seen_lock:
        compiled = key not in _seen_matrices
        _seen_matrices[key] = True
        _seen_matrices.move_to_end(key)
        while len(_seen_matrices) > _MATRIX_CACHE_SIZE:
            _seen_matrices.popitem(last=False)
    from ..common.jit_profile import compile_event, signature_of
    # compile_event is a no-op on cache hit; a first-seen matrix gets
    # a jit.compile child span + jit.compiles counters (the cost the
    # triggering op's flame trace must be able to explain)
    with compile_event("ec.gf_jax", signature_of(A), compiled):
        out = _bitmatrix_device(key[0], *A.shape)
    _mark_active("dispatched_device", component="ec.gf_jax",
                 compiled=compiled)
    return out


def gf8_matmul(A: np.ndarray, data) -> jax.Array:
    """Convenience: numpy GF matrix x device/host data."""
    return bitplane_matmul(matrix_to_device(A),
                           jnp.asarray(data, dtype=jnp.uint8))
