"""Batched CRC32 as a GF(2) matmul — the device crc kernel (ZeroWire).

CRC32 over a fixed-length block is an AFFINE map over GF(2): for a
block of B bytes viewed as a bit vector m in GF(2)^(8B),

    crc(m) = A @ m  ^  c        (A: 32 x 8B over GF(2), c = crc(0^B))

which puts per-block wire checksums on the same hardware path as the
erasure-code contraction (ops/xor_kernel.py's region-XOR matmuls —
PAPERS 2108.02692's program-optimization framing: integrity folded
into the GF(2) algebra the kernels already run).  A batch of N staged
blocks is ONE [N, 8B] @ [8B, 32] matmul — no host scan at all when
the shards already sit in HBM.

The matrix is built from the crc's own algebra, not 8B brute-force
scans: column (p, b) — bit b of byte p — equals Z^(B-1-p) @ L0[b],
where L0[b] is the linear crc of the single byte (1<<b) and Z is the
advance-one-zero-byte operator (common/crcutil's combine matrix), so
construction is an O(B) table walk.

On CPU backends the matmul costs more than a zlib scan — callers gate
on :func:`device_worthwhile` (TPU/GPU backends) or pass small batches
for equivalence testing; the NumPy oracle :func:`crc32_blocks_np`
validates the jax path bit-for-bit.
"""
from __future__ import annotations

import zlib
from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..common import crcutil

_M32 = 0xFFFFFFFF

# block -> (A [8B, 32] uint8, affine const crc(0^B))
_matrix_cache: Dict[int, Tuple[np.ndarray, int]] = {}


def crc_matrix(block: int) -> Tuple[np.ndarray, int]:
    """The affine map of crc32 over ``block``-byte messages:
    (A [8*block, 32] uint8 over GF(2), c = crc32 of the zero block).
    Row 8p+b of A is the crc image of bit b of byte p."""
    hit = _matrix_cache.get(block)
    if hit is not None:
        return hit
    if block <= 0:
        raise ValueError(f"block must be positive, got {block}")
    z0 = zlib.crc32(b"\x00")
    base = [zlib.crc32(bytes([1 << b])) ^ z0 for b in range(8)]
    z1 = crcutil._zero_op(1)           # advance one zero byte

    def _adv(v: int) -> int:
        return (z1[0][v & 0xFF] ^ z1[1][(v >> 8) & 0xFF] ^
                z1[2][(v >> 16) & 0xFF] ^ z1[3][v >> 24])

    cols = np.zeros((8 * block,), dtype=np.uint32)
    cur = list(base)
    for p in range(block - 1, -1, -1):
        for b in range(8):
            cols[8 * p + b] = cur[b]
        cur = [_adv(v) for v in cur]
    # unpack each column's 32 output bits -> [8B, 32] uint8
    bits = ((cols[:, None] >> np.arange(32, dtype=np.uint32)[None, :])
            & 1).astype(np.uint8)
    const = zlib.crc32(b"\x00" * block)
    _matrix_cache[block] = (bits, const)
    return bits, const


def _block_bits_np(blocks: np.ndarray) -> np.ndarray:
    """[N, B] uint8 -> [N, 8B] bit planes, bit b of byte p at 8p+b
    (matching crc_matrix's row order)."""
    a = np.ascontiguousarray(blocks, dtype=np.uint8)
    return np.unpackbits(a, axis=-1, bitorder="little")


def crc32_blocks_np(blocks: np.ndarray) -> np.ndarray:
    """NumPy oracle: crc32 of each row of ``blocks`` [N, B] uint8."""
    a = np.ascontiguousarray(blocks, dtype=np.uint8)
    if a.ndim != 2:
        raise ValueError("blocks must be [N, B]")
    A, const = crc_matrix(a.shape[1])
    bits = _block_bits_np(a).astype(np.int64)
    out_bits = (bits @ A.astype(np.int64)) & 1
    vals = (out_bits.astype(np.uint64)
            << np.arange(32, dtype=np.uint64)[None, :]).sum(
                axis=1).astype(np.uint32)
    return vals ^ np.uint32(const)


# -------------------------------------------------------------- device ---

_jit_cache: Dict[int, object] = {}


def _device_fn(block: int):
    """jit'd [N, B] uint8 -> [N] uint32 crc kernel for one block size
    (the GF(2) matmul; shape-cached like the EC kernels)."""
    fn = _jit_cache.get(block)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp
    A, const = crc_matrix(block)
    A_dev = jnp.asarray(A.astype(np.int32))

    @jax.jit
    def kern(blocks):
        b = blocks.astype(jnp.uint8)
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = ((b[..., None] >> shifts) & 1).astype(jnp.int32)
        bits = bits.reshape(bits.shape[0], -1)       # [N, 8B]
        out = jnp.matmul(bits, A_dev) & 1            # GF(2) matmul
        weights = (jnp.uint32(1) <<
                   jnp.arange(32, dtype=jnp.uint32))
        vals = jnp.sum(out.astype(jnp.uint32) * weights, axis=1,
                       dtype=jnp.uint32)
        return vals ^ jnp.uint32(const)

    _jit_cache[block] = kern
    return kern


def crc32_blocks(blocks, block: int = crcutil.CSUM_BLOCK) -> np.ndarray:
    """Device-batched crc32 of ``blocks`` ([N, block] uint8, device or
    host array): ONE GF(2) matmul dispatch for the whole batch."""
    import jax.numpy as jnp
    arr = jnp.asarray(blocks, dtype=jnp.uint8)
    if arr.ndim != 2 or arr.shape[1] != block:
        raise ValueError(f"blocks must be [N, {block}]")
    out = _device_fn(block)(arr)
    vals = np.asarray(out).astype(np.uint32)
    _counters_inc(int(arr.shape[0]) * block)
    return vals


def device_worthwhile() -> bool:
    """True when the default jax backend is an accelerator — the
    matmul beats a host zlib scan there; on CPU backends it does not
    (``wire_device_crc`` option: auto/on/off)."""
    try:
        import jax
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def _counters_inc(nbytes: int) -> None:
    from ..common.perf_counters import perf
    pc = perf("wire.zero")
    pc.inc("device_crc_dispatches")
    pc.inc("device_crc_bytes", int(nbytes))


def csums_for(buf, block: int = crcutil.CSUM_BLOCK) -> crcutil.Csums:
    """One buffer's Csums with the full blocks crc'd ON DEVICE (one
    matmul) and only the sub-block tail scanned by the host — zero
    host passes over the aligned payload body."""
    return csums_many([buf], block=block)[0]


def csums_many(bufs: Sequence, block: int = crcutil.CSUM_BLOCK
               ) -> List[crcutil.Csums]:
    """Batched Csums for many buffers: every full block across every
    buffer rides ONE device dispatch; tails (len % block) fall back to
    a host scan (counted, negligible)."""
    views = [crcutil.as_u8(np.ascontiguousarray(buf)
                           if isinstance(buf, np.ndarray) else buf)
             for buf in bufs]
    stacked: List[np.ndarray] = []
    spans: List[Tuple[int, int]] = []     # (first_row, n_rows) per buf
    row = 0
    for mv in views:
        n_full = len(mv) // block
        if n_full:
            stacked.append(np.frombuffer(
                mv[:n_full * block], dtype=np.uint8).reshape(
                    n_full, block))
        spans.append((row, n_full))
        row += n_full
    full_crcs = (crc32_blocks(np.concatenate(stacked, axis=0), block)
                 if stacked else np.zeros((0,), dtype=np.uint32))
    out: List[crcutil.Csums] = []
    for mv, (first, n_full) in zip(views, spans):
        subs = [int(c) for c in full_crcs[first:first + n_full]]
        tail = mv[n_full * block:]
        if len(tail):
            subs.append(zlib.crc32(tail))
            crcutil.note_scan(len(tail), "device_tail")
        out.append(crcutil.Csums(block, subs, len(mv)))
    return out
