"""Bit-sliced (packet/plane) GF(2) erasure-code layout — host/NumPy layer.

The byte-symbol codec path (ops/gf.py + ops/gf_jax.py) treats each byte
of a chunk as one GF(2^8) symbol and must therefore unpack bytes into
bit-planes around every device matmul — an 8x VPU expansion that caps
throughput.  jerasure's *bitmatrix* techniques (cauchy schedules,
liberation / blaum_roth / liber8tion — reference:
src/erasure-code/jerasure/ErasureCodeJerasure.h:174-240 and the
jerasure_schedule_encode call sites in ErasureCodeJerasure.cc:162,265)
sidestep exactly this on CPUs: each chunk is divided into w=8 equal
"packets" (planes), and one GF(2^8) codeword is formed by taking the
SAME bit position of the SAME byte offset across the 8 planes.  Under
that layout, multiplying by the GF(2) bit-matrix B [8m, 8k] is a pure
region-XOR program:

    out_plane[r] = XOR over { in_plane[c] : B[r, c] = 1 }

No bit unpacking ever happens — every bit lane of a 32-bit word is an
independent GF(2) codeword, so XOR on packed int32 words advances 32
codewords per ALU op.  This module is the NumPy oracle + layout algebra
for that path; the batched device kernel lives in ops/xor_kernel.py.

Layout notes (all pure reshapes, no data movement):
  chunk [L] bytes  ->  planes [8, L/8]   (plane p = bytes [pL/8, (p+1)L/8))
  k chunks [k, L]  ->  planes [8k, L/8]  (chunk-major: plane 8i+p)
The bit-matrix convention matches gf.gf8_bitmatrix: row/col 8i+b is bit
b of symbol i, so encode planes = gf8_bitmatrix(parity) and decode
planes = gf8_bitmatrix(decode_matrix) with NO new matrix machinery.

Equivalence to the byte-symbol path (validated by tests/test_gf2.py):
bit b of byte t of plane group i is bit b of GF symbol (i, t); the
region XOR computes exactly gf8_bitmatmul on the bit-transposed view.
"""
from __future__ import annotations

import numpy as np

from . import gf


# ----------------------------------------------------------------- layout --

def chunks_to_planes(chunks: np.ndarray) -> np.ndarray:
    """[..., n, L] uint8 -> [..., 8n, L//8] plane view (pure reshape).

    L must be divisible by 8 (get_chunk_size guarantees alignment).
    """
    a = np.asarray(chunks)
    n, L = a.shape[-2], a.shape[-1]
    if L % 8:
        raise ValueError(f"chunk length {L} not divisible by 8")
    return a.reshape(a.shape[:-2] + (8 * n, L // 8))


def planes_to_chunks(planes: np.ndarray) -> np.ndarray:
    """[..., 8n, P] -> [..., n, 8P] (inverse of chunks_to_planes)."""
    a = np.asarray(planes)
    n8, P = a.shape[-2], a.shape[-1]
    if n8 % 8:
        raise ValueError(f"plane count {n8} not divisible by 8")
    return a.reshape(a.shape[:-2] + (n8 // 8, 8 * P))


# ----------------------------------------------------------------- oracle --

def region_xor_matmul_np(bitmat: np.ndarray, planes: np.ndarray) -> np.ndarray:
    """out[..., r, :] = XOR_{c: bitmat[r,c]=1} planes[..., c, :].

    bitmat [R, C] 0/1 uint8; planes [..., C, P] uint8.  NumPy oracle for
    the device kernel; also the scalar reference for the native AVX2
    region codec.
    """
    bm = np.asarray(bitmat, dtype=np.uint8)
    pl = np.asarray(planes, dtype=np.uint8)
    R, C = bm.shape
    if pl.shape[-2] != C:
        raise ValueError(f"planes have {pl.shape[-2]} rows, bitmat wants {C}")
    out = np.zeros(pl.shape[:-2] + (R, pl.shape[-1]), dtype=np.uint8)
    for r in range(R):
        cols = np.flatnonzero(bm[r])
        if len(cols):
            acc = pl[..., cols[0], :].copy()
            for c in cols[1:]:
                acc ^= pl[..., c, :]
            out[..., r, :] = acc
    return out


def bitsliced_symbols(chunks: np.ndarray) -> np.ndarray:
    """Extract the GF(2^8) symbol array a bit-sliced chunk set encodes.

    [n, L] uint8 chunks -> [n, 8*(L//8)] uint8 symbols: symbol (i, 8t+b)
    has bit p equal to bit b of byte t of plane p of chunk i.  Test-only
    helper proving the layout equivalence (the inverse bit transpose).
    """
    pl = chunks_to_planes(chunks)           # [8n, P]
    n = chunks.shape[-2]
    P = pl.shape[-1]
    pl = pl.reshape(n, 8, P)
    # bit b of byte t of plane p -> bit p of symbol 8t+b
    bits = (pl[:, :, None, :] >> np.arange(8, dtype=np.uint8)[None, None, :,
                                                              None]) & 1
    # bits[i, p, b, t] -> symbol[i, t, b] bit p
    sym = np.zeros((n, P, 8), dtype=np.uint8)
    for p in range(8):
        sym |= (bits[:, p] << p).transpose(0, 2, 1)
    return sym.reshape(n, 8 * P)


# ------------------------------------------------------- GF(2) matrix ops --

def gf2_matmul(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """(A @ B) mod 2 for 0/1 uint8 matrices."""
    return (np.asarray(A, dtype=np.int64) @
            np.asarray(B, dtype=np.int64) & 1).astype(np.uint8)


def gf2_inverse(M: np.ndarray) -> np.ndarray:
    """Invert a square 0/1 matrix over GF(2) (Gauss-Jordan).

    Raises ValueError if singular.  Decode-matrix construction for
    bitmatrix codes (jerasure_invert_bitmatrix role,
    src/erasure-code/jerasure/ErasureCodeJerasure.cc decode paths).
    """
    M = np.array(M, dtype=np.uint8) & 1
    n = M.shape[0]
    if M.shape != (n, n):
        raise ValueError("square matrix required")
    aug = np.concatenate([M, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = -1
        for r in range(col, n):
            if aug[r, col]:
                pivot = r
                break
        if pivot < 0:
            raise ValueError("singular matrix over GF(2)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        rows = np.flatnonzero(aug[:, col])
        rows = rows[rows != col]
        aug[rows] ^= aug[col]
    return aug[:, n:].copy()


def gf2_invertible(M: np.ndarray) -> bool:
    try:
        gf2_inverse(M)
        return True
    except ValueError:
        return False


def bitmatrix_masks(bitmat: np.ndarray) -> np.ndarray:
    """[R, C] 0/1 -> [R, C] int32 full-width masks (0 / -1) — the device
    operand layout of ops/xor_kernel.py (same orientation as the
    bit-matrix; the kernel takes static column slices)."""
    bm = np.asarray(bitmat, dtype=np.int32)
    return (-bm).astype(np.int32)
