"""Galois-field arithmetic for erasure coding — the host/NumPy reference layer.

The reference delegates GF math to the jerasure/gf-complete and ISA-L
libraries (empty submodules in this checkout — see SURVEY.md), so this module
re-derives the arithmetic from first principles:

  * GF(2^8)  — field tables for the AES-adjacent polynomial 0x11d used by both
    gf-complete (w=8) and ISA-L; all data-path codecs run in this field.
  * GF(2^16) — tables for polynomial 0x1100b (gf-complete w=16 default), used
    by wide reed_sol_van profiles (reference:
    src/erasure-code/jerasure/ErasureCodeJerasure.cc:450-474).
  * generic carry-less multiply for w=32 (poly 0x100400007) — matrix
    generation only.

Matrix machinery: GF matmul, Gaussian inversion, systematic Vandermonde
generator construction (semantics of jerasure's reed_sol_van coding matrix —
the systematic form of a Vandermonde code is unique, so building
``P = V_bot @ inv(V_top)`` reproduces the reference matrix without porting
its elementary-operation sequence), Cauchy constructions (jerasure
cauchy_orig/cauchy_good and ISA-L gf_gen_cauchy1 variants), and the
bit-matrix expansion that turns a GF(2^8) matrix into a GF(2) matrix of
8x8 blocks — the formulation the TPU kernel multiplies on the MXU
(see ceph_tpu/ec/gf_jax.py).

Everything here is NumPy on host: it is the correctness oracle and the
matrix-preparation path; the batched data path lives in the JAX plugin.
"""
from __future__ import annotations

import functools
from typing import Sequence, Tuple

import numpy as np

# Primitive polynomials (with the x^w term), per gf-complete defaults.
POLY8 = 0x11D
POLY16 = 0x1100B
POLY32 = 0x100400007


# ------------------------------------------------------------------ tables --

@functools.lru_cache(maxsize=None)
def _tables(w: int) -> Tuple[np.ndarray, np.ndarray]:
    """(exp, log) tables for GF(2^w), generator alpha=2."""
    if w == 8:
        poly, n = POLY8, 1 << 8
    elif w == 16:
        poly, n = POLY16, 1 << 16
    else:
        raise ValueError(f"no tables for w={w}")
    exp = np.zeros(2 * n, dtype=np.int64)
    log = np.zeros(n, dtype=np.int64)
    x = 1
    for i in range(n - 1):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & n:
            x ^= poly
    # duplicate so exp[(la+lb)] never needs a mod
    exp[n - 1:2 * (n - 1)] = exp[:n - 1]
    exp.setflags(write=False)
    log.setflags(write=False)
    return exp, log


def gf_mul(a, b, w: int = 8):
    """Element-wise GF(2^w) multiply (NumPy-broadcasting)."""
    exp, log = _tables(w)
    a = np.asarray(a, dtype=np.int64)
    b = np.asarray(b, dtype=np.int64)
    out = exp[log[a] + log[b]]
    return np.where((a == 0) | (b == 0), 0, out)


def gf_inv(a, w: int = 8):
    exp, log = _tables(w)
    a = np.asarray(a, dtype=np.int64)
    if np.any(a == 0):
        raise ZeroDivisionError("gf_inv(0)")
    order = (1 << w) - 1
    return exp[(order - log[a]) % order]


def gf_div(a, b, w: int = 8):
    b_inv = gf_inv(b, w)
    return gf_mul(a, b_inv, w)


def gf_pow(a: int, e: int, w: int = 8) -> int:
    """Scalar power; gf_pow(0, 0) == 1 by Vandermonde convention."""
    if e == 0:
        return 1
    if a == 0:
        return 0
    exp, log = _tables(w)
    order = (1 << w) - 1
    return int(exp[(int(log[a]) * e) % order])


def gf_mul_slow(a: int, b: int, w: int, poly: int) -> int:
    """Carry-less multiply + reduce — any width (used for w=32)."""
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a & (1 << w):
            a ^= poly
    return r


# ------------------------------------------------------------------ matmul --

def gf_matmul(A: np.ndarray, B: np.ndarray, w: int = 8) -> np.ndarray:
    """C = A @ B over GF(2^w); A is [m,k], B is [k,...] (uint arrays).

    Log-table formulation: products become exp[log a + log b]; the GF sum is
    XOR-reduce over the contraction axis.
    """
    exp, log = _tables(w)
    A = np.asarray(A, dtype=np.int64)
    B = np.asarray(B, dtype=np.int64)
    la = log[A]                                   # [m, k]
    lb = log[B]                                   # [k, N...]
    # explicit loop over k keeps memory bounded for wide B
    m, k = A.shape
    out = np.zeros((m,) + B.shape[1:], dtype=np.int64)
    for j in range(k):
        a = A[:, j]                               # [m]
        bj = B[j]                                 # [N...]
        pj = exp[la[:, j].reshape((m,) + (1,) * bj.ndim) + lb[j]]
        pj = np.where((a.reshape((m,) + (1,) * bj.ndim) == 0) | (bj == 0),
                      0, pj)
        out ^= pj
    return out.astype(np.uint8 if w == 8 else np.uint16)


def gf_matvec(A: np.ndarray, x: np.ndarray, w: int = 8) -> np.ndarray:
    return gf_matmul(A, x.reshape(len(x), 1), w)[:, 0]


def gf_gaussian_inverse(M: np.ndarray, w: int = 8) -> np.ndarray:
    """Invert a square GF(2^w) matrix by Gauss-Jordan elimination.

    Raises ValueError if singular.  Mirrors the role of jerasure's
    jerasure_invert_matrix (decode-matrix construction, reference:
    src/erasure-code/jerasure/ErasureCodeJerasure.cc:265-274 call sites).
    """
    M = np.array(M, dtype=np.int64)
    n = M.shape[0]
    if M.shape != (n, n):
        raise ValueError("square matrix required")
    inv = np.eye(n, dtype=np.int64)
    for col in range(n):
        pivot = -1
        for r in range(col, n):
            if M[r, col]:
                pivot = r
                break
        if pivot < 0:
            raise ValueError("singular matrix over GF")
        if pivot != col:
            M[[col, pivot]] = M[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        pinv = gf_inv(M[col, col], w)
        M[col] = gf_mul(M[col], pinv, w)
        inv[col] = gf_mul(inv[col], pinv, w)
        for r in range(n):
            if r != col and M[r, col]:
                f = M[r, col]
                M[r] ^= gf_mul(M[col], f, w)
                inv[r] ^= gf_mul(inv[col], f, w)
    return inv.astype(np.uint8 if w == 8 else np.uint16)


# -------------------------------------------------------- matrix generators --

def vandermonde_parity(k: int, m: int, w: int = 8) -> np.ndarray:
    """Systematic Vandermonde parity block P [m,k] — reed_sol_van semantics.

    Rows of the raw Vandermonde are [1, i, i^2, ..] for evaluation points
    i = 0..k+m-1; the unique column-reduction to a systematic generator is
    P = V_bot @ inv(V_top).  Any k rows of [I; P] are then invertible (MDS).
    Reference behavior: jerasure reed_sol_van technique
    (src/erasure-code/jerasure/ErasureCodeJerasure.h:81).
    """
    if k + m > (1 << w):
        raise ValueError(f"k+m={k + m} exceeds field size 2^{w}")
    V = np.zeros((k + m, k), dtype=np.int64)
    for i in range(k + m):
        for j in range(k):
            V[i, j] = gf_pow(i, j, w)
    v_top_inv = gf_gaussian_inverse(V[:k], w)
    return gf_matmul(V[k:], v_top_inv, w)


def cauchy_orig_parity(k: int, m: int, w: int = 8) -> np.ndarray:
    """jerasure cauchy_orig: P[i,j] = 1 / (i XOR (m+j)).

    (reference technique: src/erasure-code/jerasure/ErasureCodeJerasure.h:174)
    """
    if k + m > (1 << w):
        raise ValueError("k+m exceeds field size")
    P = np.zeros((m, k), dtype=np.int64)
    for i in range(m):
        for j in range(k):
            P[i, j] = int(gf_inv(i ^ (m + j), w))
    dtype = np.uint8 if w == 8 else np.uint16
    return P.astype(dtype)


def cauchy_good_parity(k: int, m: int, w: int = 8) -> np.ndarray:
    """cauchy_orig normalized so row 0 and column 0 are all ones.

    jerasure's 'good' variant additionally scales rows to minimize bitmatrix
    ones (a CPU XOR-scheduling optimization); scaling by invertible
    diagonals preserves the MDS property and the decode relation, and the
    TPU bit-plane matmul cost is ones-count independent, so only the
    normalization is kept.  (reference technique:
    src/erasure-code/jerasure/ErasureCodeJerasure.h:183)
    """
    P = cauchy_orig_parity(k, m, w).astype(np.int64)
    # scale each column so row 0 becomes 1
    P = gf_mul(P, gf_inv(P[0])[None, :], w).astype(np.int64)
    # scale each row so column 0 becomes 1
    P = gf_mul(P, gf_inv(P[:, 0])[:, None], w).astype(np.int64)
    dtype = np.uint8 if w == 8 else np.uint16
    return P.astype(dtype)


def isa_rs_parity(k: int, m: int, w: int = 8) -> np.ndarray:
    """ISA-L gf_gen_rs_matrix parity rows: row t = [gen_t^0 .. gen_t^{k-1}],
    gen_t = 2^t.  Matches the reference 'isa' plugin's Vandermonde technique
    (src/erasure-code/isa/ErasureCodeIsa.cc:385).  Not guaranteed MDS for
    large m; kept for parity with the reference's option surface.
    """
    P = np.zeros((m, k), dtype=np.int64)
    gen = 1
    for t in range(m):
        p = 1
        for j in range(k):
            P[t, j] = p
            p = int(gf_mul(p, gen, w))
        gen = int(gf_mul(gen, 2, w))
    return P.astype(np.uint8 if w == 8 else np.uint16)


def isa_cauchy_parity(k: int, m: int, w: int = 8) -> np.ndarray:
    """ISA-L gf_gen_cauchy1_matrix parity rows: P[i,j] = 1/((k+i) XOR j)
    (src/erasure-code/isa/ErasureCodeIsa.cc:387)."""
    if k + m > (1 << w):
        raise ValueError("k+m exceeds field size")
    P = np.zeros((m, k), dtype=np.int64)
    for i in range(m):
        for j in range(k):
            P[i, j] = int(gf_inv((k + i) ^ j, w))
    return P.astype(np.uint8 if w == 8 else np.uint16)


def generator_matrix(parity: np.ndarray) -> np.ndarray:
    """Full systematic generator [I_k; P] — (k+m, k)."""
    m, k = parity.shape
    return np.concatenate(
        [np.eye(k, dtype=parity.dtype), parity], axis=0)


# ------------------------------------------------------------- bit matrices --

@functools.lru_cache(maxsize=None)
def _gf8_const_bitmatrices() -> np.ndarray:
    """[256, 8, 8] uint8: B_c with y_bits = B_c @ x_bits (mod 2) == c*x.

    B_c[b, j] = bit b of (c * alpha^j') where alpha^j' = x^j, i.e. column j
    holds the bits of c * 2^j.  This is the jerasure bitmatrix block
    convention (GF(2^8) multiplication is GF(2)-linear in the operand bits).
    """
    out = np.zeros((256, 8, 8), dtype=np.uint8)
    for c in range(256):
        v = c
        for j in range(8):
            for b in range(8):
                out[c, b, j] = (v >> b) & 1
            v <<= 1
            if v & 0x100:
                v ^= POLY8
    out.setflags(write=False)
    return out


def gf8_bitmatrix(M: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) matrix [m,k] into its GF(2) bit-matrix [8m, 8k].

    Block (i,j) is the 8x8 multiplication matrix of M[i,j]; multiplying the
    bit-expanded data vector by this matrix (mod 2) computes the GF matmul.
    This is the operand the TPU kernel feeds the MXU.
    """
    M = np.asarray(M, dtype=np.uint8)
    m, k = M.shape
    blocks = _gf8_const_bitmatrices()[M]          # [m, k, 8, 8]
    return blocks.transpose(0, 2, 1, 3).reshape(8 * m, 8 * k)


def bytes_to_bits(data: np.ndarray) -> np.ndarray:
    """[k, N] uint8 -> [8k, N] uint8 of 0/1; row 8*i+b is bit b of row i."""
    k, n = data.shape
    bits = ((data[:, None, :] >> np.arange(8, dtype=np.uint8)[None, :, None])
            & 1)
    return bits.reshape(8 * k, n)


def bits_to_bytes(bits: np.ndarray) -> np.ndarray:
    """[8m, N] 0/1 -> [m, N] uint8 (inverse of bytes_to_bits)."""
    m8, n = bits.shape
    m = m8 // 8
    b = bits.reshape(m, 8, n).astype(np.uint8)
    return (b << np.arange(8, dtype=np.uint8)[None, :, None]).sum(
        axis=1, dtype=np.uint32).astype(np.uint8)


def gf8_bitmatmul(M: np.ndarray, data: np.ndarray) -> np.ndarray:
    """GF(2^8) matmul computed via the bit-plane formulation (NumPy oracle).

    Semantically identical to gf_matmul(M, data); exists to validate the
    formulation the TPU kernel uses.
    """
    bm = gf8_bitmatrix(M)
    dbits = bytes_to_bits(np.asarray(data, dtype=np.uint8))
    pbits = (bm.astype(np.uint32) @ dbits.astype(np.uint32)) & 1
    return bits_to_bytes(pbits.astype(np.uint8))
