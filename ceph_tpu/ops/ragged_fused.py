"""Fused ragged GF(2^8) encode + per-block crc32 — one traversal.

Mixed-size serving batches (S3Serve's zipf object profile) are RAGGED:
padding every object to the batch max before the EC matmul moves and
multiplies bytes that exist only to squarify the rectangle.  This
module stages a ragged batch the way Ragged Paged Attention stages
ragged sequences (PAPERS 2604.15464): a flat pool of fixed 4 KiB
blocks plus row-offset/length DESCRIPTORS, so the kernel's unit of
work is a block that really exists, not a rectangle row.

The fusion: the GF(2^8) bit-plane matmul (ops/gf_jax.py) and the crc32
GF(2) matmul (ops/crc32_gf2.py) both consume the SAME bit-unpacked
view of the staged bytes, so one dispatch computes parity AND the
per-4 KiB crc sub-words of every data row in a single traversal — and
the parity rows' sub-crcs come straight off the parity BIT planes
before they are even packed to bytes, a pass no unfused pipeline can
skip.  Those sub-crcs are exactly the `Csums` the wire tier folds via
crc32_combine and BlueStore adopts as blob csums, so a fused encode
leaves nothing for the host to scan but sub-block tails.

Correctness shape: GF(2^8) matmul is LANE-WISE over byte positions
(out[i, l] depends only on column l of the inputs), so per-block
staging with zero-padded tails yields parity bit-identical to the
padded-rectangle path after cropping — asserted against
:func:`encode_padded` by tests/test_ragged_fused.py, including 1-byte
and tail-block objects.  Device block crcs are used for FULL blocks
only; a tail's crc is a host scan of the valid prefix (counted at
``device_tail``, same convention as crc32_gf2.csums_many).

Dispatch: the 2-D data plane (parallel/data_plane.py) shards the block
pool over mesh rows when enabled; otherwise a single-device jit.  On
TPU the Pallas kernel (ops/gf_pallas.fused_ragged_matmul) keeps the 8x
bit expansion in VMEM; XLA everywhere else (and it is the bit-identity
path of record on CPU CI).
"""
from __future__ import annotations

import functools
import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..common import crcutil
from . import crc32_gf2, gf

TILE = crcutil.CSUM_BLOCK        # 4096: crc sub-word == staging block


class RaggedBatch:
    """A packed ragged batch: ``pool`` [G, k, TILE] uint8 (zero-padded
    tails) plus per-block descriptors ``desc`` [G, 2] int32 of
    (object index, valid byte count) — an object's blocks are
    contiguous in pool order, so the descriptor table is the whole
    page-table analogy: the kernel sees dense blocks, the unpack walks
    the table."""

    __slots__ = ("pool", "desc", "lengths", "k", "tile")

    def __init__(self, pool: np.ndarray, desc: np.ndarray,
                 lengths: List[int], k: int, tile: int):
        self.pool = pool
        self.desc = desc
        self.lengths = lengths
        self.k = k
        self.tile = tile

    def rect_bytes(self, m: int) -> int:
        """Bytes the padded-rectangle path moves for this batch:
        every object padded to the batch max, k data + m parity."""
        if not self.lengths:
            return 0
        return len(self.lengths) * (self.k + m) * max(self.lengths)

    def fused_bytes(self, m: int) -> int:
        """Bytes the fused path moves: only blocks that exist."""
        return int(self.pool.shape[0]) * (self.k + m) * self.tile

    def padding_avoided(self, m: int) -> int:
        """The headline delta: rectangle padding the descriptor
        layout never stages (>= 0 by construction — a block pool pads
        each object to a TILE multiple, never to the batch max)."""
        return max(0, self.rect_bytes(m) - self.fused_bytes(m))


def pack(shards: Sequence[np.ndarray], tile: int = TILE) -> RaggedBatch:
    """Stage ragged shard groups into the block pool.  ``shards`` is a
    sequence of [k, L_i] uint8 arrays with a common k and ragged L_i
    (>= 1 — even a 1-byte object owns one zero-padded block, because
    its parity still has to come out of the matmul)."""
    if not shards:
        raise ValueError("empty ragged batch")
    k = int(shards[0].shape[0])
    lengths: List[int] = []
    blocks: List[np.ndarray] = []
    desc: List[Tuple[int, int]] = []
    for i, s in enumerate(shards):
        a = np.ascontiguousarray(s, dtype=np.uint8)
        if a.ndim != 2 or a.shape[0] != k:
            raise ValueError(f"shard group {i}: want [k={k}, L] rows")
        L = int(a.shape[1])
        if L <= 0:
            raise ValueError(f"shard group {i}: empty object")
        lengths.append(L)
        n_blk = -(-L // tile)
        pad = n_blk * tile - L
        if pad:
            a = np.pad(a, ((0, 0), (0, pad)))
        for b in range(n_blk):
            blocks.append(a[:, b * tile:(b + 1) * tile])
            desc.append((i, min(tile, L - b * tile)))
    pool = np.stack(blocks, axis=0)
    return RaggedBatch(pool, np.asarray(desc, dtype=np.int32),
                       lengths, k, tile)


class RaggedResult:
    """Per-object outputs of one fused (or comparator) encode:
    ``parity[i]`` [m, L_i] uint8; ``data_csums[i]`` / ``parity_csums[i]``
    are the k (resp. m) per-row :class:`crcutil.Csums` — the trusted
    sub-crcs the wire/store tiers consume without rescanning."""

    __slots__ = ("parity", "data_csums", "parity_csums")

    def __init__(self, parity, data_csums, parity_csums):
        self.parity = parity
        self.data_csums = data_csums
        self.parity_csums = parity_csums


def _crc_a8(tile: int) -> Tuple[np.ndarray, int]:
    """crc32_gf2.crc_matrix reshaped for per-bit-plane contraction:
    A8 [8, tile, 32] int8 with A8[b, t] = A[8t+b] — the layout that
    lets a kernel contract bit plane b of a block row against one
    [tile, 32] slab (no in-kernel transposes)."""
    A, const = crc32_gf2.crc_matrix(tile)
    A8 = np.ascontiguousarray(
        A.reshape(tile, 8, 32).transpose(1, 0, 2).astype(np.int8))
    return A8, const


def fused_block_math(bitmat, crcA8, const: int, pool):
    """The one-traversal math, traceable (shared by the single-device
    jit, the data-plane shard_map body, and — in spirit — the Pallas
    kernel): pool [G, k, T] uint8 -> (parity [G, m, T] uint8,
    data block crcs [G, k] uint32, parity block crcs [G, m] uint32).

    One bit-unpack feeds BOTH contractions, and the parity crcs are
    contracted from the parity BIT planes before packing — the
    traversal the unfused pipeline pays twice (encode pass + crc
    scan) happens once."""
    import jax.numpy as jnp
    G, k, T = pool.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = ((pool[..., None] >> shifts) & jnp.uint8(1))   # [G, k, T, 8]
    # GF(2^8) leg: bit b of symbol row j at plane row 8j+b
    gf_bits = bits.transpose(0, 1, 3, 2).reshape(
        G, 8 * k, T).astype(jnp.int8)
    acc = jnp.einsum("rc,gct->grt", bitmat.astype(jnp.int8), gf_bits,
                     preferred_element_type=jnp.int32) & 1
    m = acc.shape[1] // 8
    pbits = acc.reshape(G, m, 8, T).astype(jnp.uint8)     # [G, m, 8, T]
    parity = (pbits << shifts[None, None, :, None]).sum(
        2, dtype=jnp.uint8)                               # [G, m, T]
    # crc leg: contract each row's bit plane b against A8[b] and
    # accumulate — data rows from the staged bits, parity rows from
    # the matmul's own bit planes (never re-unpacked)
    crcA8 = crcA8.astype(jnp.int8)
    dacc = jnp.einsum("gkbt,btc->gkc",
                      bits.transpose(0, 1, 3, 2).astype(jnp.int8),
                      crcA8, preferred_element_type=jnp.int32) & 1
    pacc = jnp.einsum("gjbt,btc->gjc", pbits.astype(jnp.int8),
                      crcA8, preferred_element_type=jnp.int32) & 1
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    dcrc = jnp.sum(dacc.astype(jnp.uint32) * weights, axis=-1,
                   dtype=jnp.uint32) ^ jnp.uint32(const)
    pcrc = jnp.sum(pacc.astype(jnp.uint32) * weights, axis=-1,
                   dtype=jnp.uint32) ^ jnp.uint32(const)
    return parity, dcrc, pcrc


@functools.lru_cache(maxsize=64)
def _jit_fused(tile: int):
    import jax
    import jax.numpy as jnp
    A8, const = _crc_a8(tile)
    A8_dev = jnp.asarray(A8)

    @jax.jit
    def fn(bitmat, pool):
        return fused_block_math(bitmat, A8_dev, const, pool)

    return fn


def _dispatch(bitmat_np: np.ndarray, batch: RaggedBatch,
              impl: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Route one pool through the best available engine: 2-D data
    plane when enabled, the Pallas VMEM kernel on real TPUs, the XLA
    jit otherwise.  All three are bit-identical (lane-wise math)."""
    import jax.numpy as jnp
    from ..parallel import data_plane
    pl = data_plane.plane() if impl in ("auto", "plane") else None
    if pl is not None:
        parity, dcrc, pcrc = pl.fused_ragged(bitmat_np, batch.pool,
                                             batch.tile)
    else:
        from . import gf_pallas
        if impl in ("auto", "pallas") and gf_pallas.available():
            A8, const = _crc_a8(batch.tile)
            parity, dbits, pbits_ = gf_pallas.fused_ragged_matmul(
                bitmat_np, A8, batch.pool)
            w = np.uint64(1) << np.arange(32, dtype=np.uint64)
            dcrc = ((np.asarray(dbits).astype(np.uint64) * w).sum(-1)
                    .astype(np.uint32) ^ np.uint32(const))
            pcrc = ((np.asarray(pbits_).astype(np.uint64) * w).sum(-1)
                    .astype(np.uint32) ^ np.uint32(const))
            return np.asarray(parity), dcrc, pcrc
        parity, dcrc, pcrc = _jit_fused(batch.tile)(
            jnp.asarray(bitmat_np, jnp.int8),
            jnp.asarray(batch.pool, jnp.uint8))
    return (np.asarray(parity), np.asarray(dcrc).astype(np.uint32),
            np.asarray(pcrc).astype(np.uint32))


def encode(A: np.ndarray, shards: Sequence[np.ndarray],
           impl: str = "auto") -> RaggedResult:
    """Fused ragged encode: parity AND trusted per-4 KiB sub-crcs for
    every data/parity row of every ragged object, one traversal.

    ``A`` [m, k] GF(2^8) parity matrix; ``shards[i]`` [k, L_i] uint8.
    Device crcs cover FULL blocks; tail prefixes are host-scanned
    (counted, ``device_tail``).  The staged pool bytes ride the
    ``device_crc_bytes``-style accounting via the returned Csums'
    consumers; the padding win is :meth:`RaggedBatch.padding_avoided`.
    """
    A = np.ascontiguousarray(A, dtype=np.uint8)
    m = int(A.shape[0])
    batch = pack(shards)
    bitmat = gf.gf8_bitmatrix(A)
    parity_pool, dcrc, pcrc = _dispatch(bitmat, batch, impl)
    tile = batch.tile
    # unpack the descriptor table back into per-object rows
    parities: List[np.ndarray] = []
    data_csums: List[List[crcutil.Csums]] = []
    parity_csums: List[List[crcutil.Csums]] = []
    g = 0
    for i, L in enumerate(batch.lengths):
        n_blk = -(-L // tile)
        blocks = slice(g, g + n_blk)
        par = parity_pool[blocks].transpose(1, 0, 2).reshape(
            m, n_blk * tile)[:, :L]
        parities.append(np.ascontiguousarray(par))
        n_full = L // tile
        tail = L - n_full * tile
        drows: List[crcutil.Csums] = []
        for j in range(batch.k):
            subs = [int(c) for c in dcrc[g:g + n_full, j]]
            if tail:
                subs.append(zlib.crc32(
                    shards[i][j, n_full * tile:L].tobytes()))
                crcutil.note_scan(tail, "device_tail")
            drows.append(crcutil.Csums(tile, subs, L))
        data_csums.append(drows)
        prows: List[crcutil.Csums] = []
        for j in range(m):
            subs = [int(c) for c in pcrc[g:g + n_full, j]]
            if tail:
                subs.append(zlib.crc32(par[j, n_full * tile:].tobytes()))
                crcutil.note_scan(tail, "device_tail")
            prows.append(crcutil.Csums(tile, subs, L))
        parity_csums.append(prows)
        g += n_blk
    return RaggedResult(parities, data_csums, parity_csums)


def encode_padded(A: np.ndarray, shards: Sequence[np.ndarray]
                  ) -> RaggedResult:
    """The unfused padded-rectangle comparator (and bit-identity
    oracle of record): pad every object to the batch max, run the
    plain gf_jax bit-plane matmul, then pay the SEPARATE host crc
    scan over every data and parity row (counted at ``unfused`` —
    exactly the double traversal the fused path deletes)."""
    import jax.numpy as jnp
    from . import gf_jax
    A = np.ascontiguousarray(A, dtype=np.uint8)
    m = int(A.shape[0])
    lens = [int(s.shape[1]) for s in shards]
    Lmax = max(lens)
    k = int(shards[0].shape[0])
    rect = np.zeros((len(shards), k, Lmax), dtype=np.uint8)
    for i, s in enumerate(shards):
        rect[i, :, :lens[i]] = s
    out = np.asarray(gf_jax.bitplane_matmul(
        jnp.asarray(gf.gf8_bitmatrix(A), jnp.int8),
        jnp.asarray(rect, jnp.uint8)))
    parities = [np.ascontiguousarray(out[i][:, :lens[i]])
                for i in range(len(shards))]
    data_csums = [[crcutil.Csums.scan(np.ascontiguousarray(s[j]),
                                      block=TILE, site="unfused")
                   for j in range(k)] for s in shards]
    parity_csums = [[crcutil.Csums.scan(p[j], block=TILE,
                                        site="unfused")
                     for j in range(m)] for p in parities]
    return RaggedResult(parities, data_csums, parity_csums)
