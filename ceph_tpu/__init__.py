"""ceph_tpu — a TPU-native storage-compute framework with the capabilities of Ceph.

Re-expresses Ceph's embarrassingly-parallel inner loops (CRUSH placement and
erasure-code stripe encode/decode) as jitted JAX/XLA/Pallas array programs, and
rebuilds the surrounding control plane (cluster map, EC profiles/registry,
placement pipeline, cluster simulator, CLI tools) TPU-first.

Reference under survey: fzakaria/ceph (Quincy), see SURVEY.md.
"""

__version__ = "0.1.0"
