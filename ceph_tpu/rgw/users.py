"""RGW user store — durable S3/Swift credentials and quotas.

The reference keeps users (access keys, secrets, display names,
quotas) in RADOS objects managed by radosgw-admin (src/rgw/rgw_user.cc,
rgw_admin.cc `user create/info/rm`).  Same shape here: one JSON row
object per user in the gateway's pool, plus an access-key → uid index
so SigV4 verification can resolve credentials in one read.  The
S3Frontend/SwiftFrontend consume ``auth_users()`` / ``swift_users()``
views of this store.
"""
from __future__ import annotations

import json
import secrets
from typing import Dict, List, Optional


class UserError(RuntimeError):
    pass


class UserStore:
    def __init__(self, ioctx):
        self.ioctx = ioctx

    # ----------------------------------------------------------- storage --
    def _uoid(self, uid: str) -> str:
        return f"rgw.user.{uid}"

    def _koid(self, access_key: str) -> str:
        return f"rgw.key.{access_key}"

    def _load(self, uid: str) -> dict:
        """Missing and corrupt are DIFFERENT errors: a torn/invalid
        record must not read as absent, or create() would silently
        clobber it and regenerate every credential."""
        try:
            blob = self.ioctx.read(self._uoid(uid))
        except KeyError:            # ObjectNotFound
            raise UserError(f"NoSuchUser: {uid}") from None
        try:
            return json.loads(bytes(blob).decode())
        except (ValueError, UnicodeDecodeError) as e:
            raise UserError(f"CorruptUser: {uid}: {e}") from None

    def _save(self, rec: dict) -> None:
        self.ioctx.write_full(self._uoid(rec["uid"]),
                              json.dumps(rec).encode())
        for k in rec["keys"]:
            self.ioctx.write_full(self._koid(k["access_key"]),
                                  rec["uid"].encode())

    # --------------------------------------------------------------- api --
    def create(self, uid: str, display_name: str = "",
               max_buckets: int = 1000) -> dict:
        exists = True
        try:
            self._load(uid)
        except UserError as e:
            if str(e).startswith("NoSuchUser"):
                exists = False
            else:
                raise               # corrupt record: surface, don't clobber
        if exists:
            raise UserError(f"UserAlreadyExists: {uid}")
        rec = {"uid": uid, "display_name": display_name or uid,
               "max_buckets": max_buckets, "suspended": False,
               "keys": [{"access_key": "AK" + secrets.token_hex(8).upper(),
                         "secret_key": secrets.token_hex(20)}],
               "swift_keys": [{"user": f"{uid}:swift",
                               "secret_key": secrets.token_hex(16)}]}
        self._save(rec)
        return rec

    def info(self, uid: str) -> dict:
        return self._load(uid)

    def rm(self, uid: str) -> None:
        rec = self._load(uid)
        for k in rec["keys"]:
            try:
                self.ioctx.remove(self._koid(k["access_key"]))
            except Exception:
                pass
        self.ioctx.remove(self._uoid(uid))

    def suspend(self, uid: str, suspended: bool = True) -> dict:
        rec = self._load(uid)
        rec["suspended"] = suspended
        self._save(rec)
        return rec

    def key_create(self, uid: str) -> dict:
        rec = self._load(uid)
        key = {"access_key": "AK" + secrets.token_hex(8).upper(),
               "secret_key": secrets.token_hex(20)}
        rec["keys"].append(key)
        self._save(rec)
        return key

    def list_users(self) -> List[str]:
        out = []
        for oid in self.ioctx.list_objects():
            if oid.startswith("rgw.user."):
                out.append(oid[len("rgw.user."):])
        return sorted(out)

    def lookup_access_key(self, access_key: str) -> Optional[dict]:
        try:
            uid = bytes(self.ioctx.read(self._koid(access_key))).decode()
        except KeyError:
            # unknown access key -> auth failure.  A TRANSIENT read
            # error propagates: spuriously denying a VALID key on a
            # degraded read is the CTL603 fabricated-absence class
            return None
        try:
            rec = self._load(uid)
        except UserError:
            return None
        if rec["suspended"]:
            return None
        return rec

    # ------------------------------------------------------ frontend views --
    def auth_users(self) -> Dict[str, dict]:
        """S3Frontend's ``users`` mapping: access_key -> secret/user."""
        out: Dict[str, dict] = {}
        for uid in self.list_users():
            rec = self._load(uid)
            if rec["suspended"]:
                continue
            for k in rec["keys"]:
                out[k["access_key"]] = {"secret": k["secret_key"],
                                        "user": uid}
        return out

    def swift_users(self) -> Dict[str, str]:
        """SwiftFrontend's ``users`` mapping: account:user -> key."""
        out: Dict[str, str] = {}
        for uid in self.list_users():
            rec = self._load(uid)
            if rec["suspended"]:
                continue
            for k in rec.get("swift_keys", []):
                out[k["user"]] = k["secret_key"]
        return out
