"""S3 SigV4-shaped request authentication (rgw_auth_s3 role).

The reference authenticates S3 requests by recomputing the AWS
Signature Version 4 over a canonical form of the request
(src/rgw/rgw_auth_s3.cc).  This module implements the same shape:

    canonical request = METHOD \n uri \n sorted(query) \n
                        canonical headers \n signed header names \n
                        sha256(payload)
    string to sign    = AWS4-HMAC-SHA256 \n amz-date \n scope \n
                        sha256(canonical request)
    signing key       = HMAC chain over (secret, date, region,
                        service, "aws4_request")
    Authorization: AWS4-HMAC-SHA256 Credential=<ak>/<scope>,
                   SignedHeaders=<names>, Signature=<hex>

Verification is constant-time on the signature; unknown access keys,
malformed headers and stale signatures map to the S3 error codes
(InvalidAccessKeyId / AccessDenied / SignatureDoesNotMatch).
"""
from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from typing import Dict, Optional, Tuple

ALGO = "AWS4-HMAC-SHA256"
REGION = "ceph-tpu"
SERVICE = "s3"


class S3AuthError(Exception):
    def __init__(self, code: str, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def signing_key(secret: str, date: str) -> bytes:
    k = _hmac(b"AWS4" + secret.encode(), date)
    k = _hmac(k, REGION)
    k = _hmac(k, SERVICE)
    return _hmac(k, "aws4_request")


def _canonical_query(query: str) -> str:
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    return "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}="
        f"{urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(pairs))


def canonical_request(method: str, path: str, query: str,
                      headers: Dict[str, str], signed: str,
                      payload_hash: str) -> str:
    names = signed.split(";")
    canon_headers = "".join(
        f"{n}:{' '.join(headers.get(n, '').split())}\n" for n in names)
    return "\n".join([method, urllib.parse.quote(path, safe="/-_.~"),
                      _canonical_query(query), canon_headers, signed,
                      payload_hash])


def string_to_sign(amz_date: str, scope: str, creq: str) -> str:
    return "\n".join([ALGO, amz_date, scope,
                      _sha256(creq.encode())])


MAX_SKEW = 900.0          # seconds: the AWS replay window


def _now_amz(now: Optional[float] = None) -> str:
    import time as _time
    t = _time.gmtime(_time.time() if now is None else now)
    return _time.strftime("%Y%m%dT%H%M%SZ", t)


def _amz_to_epoch(amz_date: str) -> float:
    import calendar
    import time as _time
    return calendar.timegm(_time.strptime(amz_date,
                                          "%Y%m%dT%H%M%SZ"))


def sign_request(method: str, path: str, query: str,
                 headers: Dict[str, str], payload: bytes,
                 access_key: str, secret_key: str,
                 amz_date: Optional[str] = None) -> Dict[str, str]:
    """Client side: returns the headers to add (Authorization,
    x-amz-date, x-amz-content-sha256).  ``headers`` must already hold
    'host'."""
    if amz_date is None:
        amz_date = _now_amz()
    date = amz_date[:8]
    payload_hash = _sha256(payload)
    hdrs = {k.lower(): v for k, v in headers.items()}
    hdrs["x-amz-date"] = amz_date
    hdrs["x-amz-content-sha256"] = payload_hash
    signed = ";".join(sorted(["host", "x-amz-date",
                              "x-amz-content-sha256"]))
    scope = f"{date}/{REGION}/{SERVICE}/aws4_request"
    creq = canonical_request(method, path, query, hdrs, signed,
                             payload_hash)
    sts = string_to_sign(amz_date, scope, creq)
    sig = hmac.new(signing_key(secret_key, date), sts.encode(),
                   hashlib.sha256).hexdigest()
    return {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "Authorization": (f"{ALGO} Credential={access_key}/{scope}, "
                          f"SignedHeaders={signed}, Signature={sig}"),
    }


def _parse_authorization(value: str
                         ) -> Tuple[str, str, str, str]:
    """-> (access_key, scope, signed_headers, signature)."""
    if not value.startswith(ALGO + " "):
        raise S3AuthError("AccessDenied",
                          "unsupported authorization scheme")
    fields = {}
    for part in value[len(ALGO):].split(","):
        part = part.strip()
        if "=" not in part:
            raise S3AuthError("AccessDenied", "malformed authorization")
        k, v = part.split("=", 1)
        fields[k] = v
    try:
        cred = fields["Credential"]
        ak, scope = cred.split("/", 1)
        return (ak, scope, fields["SignedHeaders"],
                fields["Signature"])
    except (KeyError, ValueError):
        raise S3AuthError("AccessDenied", "malformed authorization")


def verify_request(method: str, path: str, query: str,
                   headers: Dict[str, str], payload: bytes,
                   users: Dict[str, Dict[str, str]]) -> str:
    """Server side: -> authenticated user id, or raises S3AuthError.
    ``users``: access_key -> {"secret": ..., "user": ...}."""
    hdrs = {k.lower(): v for k, v in headers.items()}
    auth = hdrs.get("authorization")
    if not auth:
        raise S3AuthError("AccessDenied", "anonymous access denied")
    ak, scope, signed, signature = _parse_authorization(auth)
    ent = users.get(ak)
    if ent is None:
        raise S3AuthError("InvalidAccessKeyId",
                          f"unknown access key {ak}")
    amz_date = hdrs.get("x-amz-date", "")
    date = scope.split("/", 1)[0]
    if not amz_date.startswith(date):
        raise S3AuthError("SignatureDoesNotMatch",
                          "scope date != x-amz-date")
    # replay window: a captured request dies after MAX_SKEW seconds
    import time as _time
    try:
        signed_at = _amz_to_epoch(amz_date)
    except ValueError:
        raise S3AuthError("AccessDenied", "malformed x-amz-date")
    if abs(_time.time() - signed_at) > MAX_SKEW:
        raise S3AuthError("AccessDenied",
                          "request time too skewed (replay window)")
    payload_hash = hdrs.get("x-amz-content-sha256", "")
    if payload_hash != _sha256(payload):
        raise S3AuthError("SignatureDoesNotMatch",
                          "payload hash mismatch")
    creq = canonical_request(method, path, query, hdrs, signed,
                             payload_hash)
    sts = string_to_sign(amz_date, scope, creq)
    want = hmac.new(signing_key(ent["secret"], date), sts.encode(),
                    hashlib.sha256).hexdigest()
    if not hmac.compare_digest(signature, want):
        raise S3AuthError("SignatureDoesNotMatch",
                          "signature mismatch")
    return ent.get("user", ak)
