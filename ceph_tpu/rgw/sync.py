"""Multisite bucket sync — bilog replay between zones.

The RGW multisite role (rgw data sync: per-bucket index logs consumed
by the peer zone's sync agent) reduced to its core: every put/delete on
a bucket lands in its bilog (gateway.py); a BucketSyncAgent on the peer
side replays entries past its durable committed position, fetching
object payloads from the source zone and applying them locally.
Idempotent, incremental, restart-safe — the same consume/commit shape
as rbd-mirror over the shared Journaler.
"""
from __future__ import annotations

import json
from typing import Dict

from .gateway import Bucket, RGWError, RGWGateway


class BucketSyncAgent:
    def __init__(self, src: RGWGateway, dst: RGWGateway, bucket: str,
                 zone: str):
        """``zone`` names the DESTINATION and keys the committed
        position in the source pool — every destination zone must use
        a distinct name, or agents would consume each other's cursor
        and silently skip entries."""
        self.src_gw = src
        self.dst_gw = dst
        self.bucket = bucket
        self.zone = zone
        self.src = src.bucket(bucket)
        self._register_zone()

    def _zones_oid(self) -> str:
        return f"rgw.zones.{self.bucket}"

    def _register_zone(self) -> None:
        """Journal-client registration: trim must respect the SLOWEST
        registered zone, so every destination announces itself."""
        zones = self._zones()
        if self.zone not in zones:
            zones.append(self.zone)
            self.src_gw.ioctx.write_full(
                self._zones_oid(), json.dumps(sorted(zones)).encode())

    def _zones(self):
        # retry-through transient errors, default only on absence:
        # an "empty zone set" fabricated from a transient read error
        # would drop every peer zone from the next sync fan-out
        from .gateway import _read_json
        return _read_json(self.src_gw.ioctx, self._zones_oid(), [],
                          "zone set")

    def _dst_bucket(self) -> Bucket:
        try:
            return self.dst_gw.bucket(self.bucket)
        except RGWError:
            return self.dst_gw.create_bucket(self.bucket)

    # ------------------------------------------------------- positions --
    def _pos_oid(self) -> str:
        return f"rgw.sync.{self.bucket}.{self.zone}"

    def committed_position(self) -> int:
        try:
            return int(self.src_gw.ioctx.read(self._pos_oid()).decode())
        except (KeyError, ValueError):
            # absent (first sync) or corrupt marker -> replay from 0;
            # a TRANSIENT error propagates instead of silently forcing
            # a full re-replay (CTL603 bug class)
            return -1

    def _commit(self, seq: int) -> None:
        self.src_gw.ioctx.write_full(self._pos_oid(), str(seq).encode())

    # ----------------------------------------------------------- replay --
    def sync(self) -> Dict[str, int]:
        """One sync pass; returns {'puts': n, 'deletes': n}.  The
        position commits ONCE per pass and consumed journal objects
        are trimmed (the rbd-mirror consume/commit/trim shape)."""
        dst = self._dst_bucket()
        pos = self.committed_position()
        stats = {"puts": 0, "deletes": 0}
        last = pos
        for seq, payload in self.src.bilog.replay():
            if seq <= pos:
                continue
            ent = json.loads(payload.decode())
            key = ent["key"]
            if ent["op"] == "put":
                try:
                    data, meta = self.src.get_object(key)
                    dst.put_object(key, data,
                                   metadata=meta.get("meta") or None)
                    stats["puts"] += 1
                except RGWError:
                    pass          # logged-ahead put that never landed,
                    # or deleted again later in the log
            elif ent["op"] == "delete":
                try:
                    dst.delete_object(key)
                    stats["deletes"] += 1
                except RGWError:
                    pass          # never synced or already gone
            last = seq
        if last > pos:
            self._commit(last)
            # trim only what EVERY registered zone has consumed (the
            # min-commit rule of multi-client journals)
            mins = []
            for z in self._zones():
                try:
                    mins.append(int(self.src_gw.ioctx.read(
                        f"rgw.sync.{self.bucket}.{z}").decode()))
                except Exception:
                    mins.append(-1)       # registered, never synced
            if mins:
                self.src.bilog.trim_to(min(mins) + 1)
        return stats
