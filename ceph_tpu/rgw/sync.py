"""Multisite bucket sync — per-shard bilog replay between zones.

The RGW data-sync role (src/rgw/driver/rados/rgw_sync.cc,
rgw_data_sync.cc: per-(bucket, shard) index logs consumed by the peer
zone's sync agent) on this repo's seams:

  * MARKERS ARE PER (bucket, shard, generation).  One durable cursor
    object per (bucket, zone) holds {"gen": g, "shards": {shard:
    last_applied_seq}}; a crash/kill9 at ANY point resumes from it —
    there is no full-sync path in this agent at all (``stats
    ["full_syncs"]`` exists so gates can assert that structurally).
  * RESHARD IS A SYNCED CUTOVER, NOT A RESTART.  reshard_bucket
    end-marks the outgoing generation's bilogs in the bucket record
    (``log_gens``); the agent drains each retired generation's shards
    to those ends, bumps its cursor to the next generation, and
    continues on the new shard set.
  * CATCH-UP PIPELINES.  Each (generation, shard) drain is one job on
    the shared AioEngine, keyed (bucket, zone, gen, shard): ordering
    within a shard is FIFO-strict, while shards — and buckets, under
    PeriodSync's shared engine — fetch/apply concurrently.  Mutating
    applies go through the destination gateway's ioctx, so on the
    wire tier they ride the AsyncObjecter's (session, seq) stamps and
    a replayed apply is at-most-once at the daemon dup tables too.
  * AT-MOST-ONCE APPLIES.  The destination side keeps its own applied
    marker per (gen, shard); an entry at or below it is a counted
    ``replay_skip``, and the marker only advances AFTER the apply's
    write completed (advancing first is the acked-then-lost ordering
    bug lint CTL605 polices).
  * TRANSIENT IOErrors TAKE ExpBackoff and then SURFACE into the pass
    report with the marker unmoved — never the CTL603
    swallow-to-default class.
  * TRIM IS DRAIN-GATED.  Active-generation logs trim to the min
    cursor over every registered zone; retired generations are
    removed only once every zone drained past their end markers
    (gateway.retire_drained_bilogs).

Cross-zone traffic consults the ``net.partition`` faultpoint with
``zone.<name>`` entities, so the DR drill severs replication with the
same axis the daemons' netsplits use.
"""
from __future__ import annotations

import json
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ..common import faults
from ..common.backoff import ExpBackoff
from ..common.perf_counters import perf as _perf
from .gateway import (Bucket, RGWError, RGWGateway, _read_json,
                      read_sync_state, sync_state_oid, zones_oid)

# counters every agent carries; a gate may assert on any of them
_STAT_KEYS = ("puts", "deletes", "replay_skips", "origin_skips",
              "conflict_skips", "missing_src", "errors",
              "gen_cutovers", "double_applies", "full_syncs")


def make_sync_engine(workers: int = 4):
    """The shared fetch/apply pipeline (AioEngine): per-(bucket,
    zone, gen, shard) FIFO, everything else concurrent."""
    from ..cluster.async_objecter import AioEngine
    return AioEngine(workers=workers, name="geosync")


class BucketSyncAgent:
    def __init__(self, src: RGWGateway, dst: RGWGateway, bucket: str,
                 zone: str, src_zone: str = "src",
                 engine=None, lag_bucket: bool = True):
        """``zone`` names the DESTINATION and keys the committed
        cursor in the source pool — every destination zone must use
        a distinct name, or agents would consume each other's cursor
        and silently skip entries.  ``src_zone`` names the source
        (origin stamping + the destination-side applied markers);
        ``engine`` is an optional shared AioEngine — without one the
        shard drains run serially in the calling thread."""
        self.src_gw = src
        self.dst_gw = dst
        self.bucket = bucket
        self.zone = zone
        self.src_zone = src_zone
        self.engine = engine
        self.src = src.bucket(bucket)
        self.stats: Dict[str, int] = {k: 0 for k in _STAT_KEYS}
        self.last_errors: List[str] = []
        self._stats_lock = threading.Lock()
        self._applied: Dict[Tuple[int, int], int] = {}
        self._src_ent = f"zone.{src_zone}"
        self._dst_ent = f"zone.{zone}"
        self._lag = _perf(f"geosync.{src_zone}.{zone}") \
            if lag_bucket else None
        self._register_zone()

    # ----------------------------------------------------- registration --
    def _register_zone(self) -> None:
        """Journal-client registration: trim must respect the SLOWEST
        registered zone, so every destination announces itself."""
        zones = self._zones()
        if self.zone not in zones:
            zones.append(self.zone)
            self.src_gw.ioctx.write_full(
                zones_oid(self.bucket),
                json.dumps(sorted(zones)).encode())

    def _zones(self) -> List[str]:
        # retry-through transient errors, default only on absence:
        # an "empty zone set" fabricated from a transient read error
        # would drop every peer zone from the next sync fan-out
        return _read_json(self.src_gw.ioctx, zones_oid(self.bucket),
                          [], "zone set")

    def _dst_bucket(self) -> Bucket:
        try:
            return self.dst_gw.bucket(self.bucket)
        except RGWError:
            return self.dst_gw.create_bucket(self.bucket)

    # ----------------------------------------------------------- cursor --
    def _load_state(self) -> Optional[Dict[str, Any]]:
        return read_sync_state(self.src_gw.ioctx, self.bucket,
                               self.zone)

    def _save_state(self, state: Dict[str, Any]) -> None:
        self.src_gw.ioctx.write_full(
            sync_state_oid(self.bucket, self.zone),
            json.dumps(state).encode())

    def committed_position(self) -> int:
        """Legacy single-shard cursor view (gen-0 shard-0 marker);
        kept for pre-generation callers."""
        st = self._load_state()
        if st is None or int(st.get("gen", 0)) != 0:
            return -1
        return int(st.get("shards", {}).get("0", -1))

    # -------------------------------------------- dst applied markers --
    def _applied_oid(self, gen: int, shard: int) -> str:
        return (f"rgw.sync.applied.{self.bucket}."
                f"{self.src_zone}.g{gen}.{shard}")

    def _load_applied(self, gen: int, shard: int) -> int:
        got = self._applied.get((gen, shard))
        if got is None:
            got = int(_read_json(self.dst_gw.ioctx,
                                 self._applied_oid(gen, shard), -1,
                                 "applied marker"))
            self._applied[(gen, shard)] = got
        return got

    def _advance_applied(self, gen: int, shard: int,
                         seq: int) -> None:
        """Advance the destination-side applied marker — called ONLY
        after the apply's write resolved (CTL605: marker-first is the
        acked-then-lost ordering bug).  A non-monotonic advance means
        an apply ran twice past the dedup guard; it is counted, never
        silently absorbed."""
        cur = self._applied.get((gen, shard), -1)
        if seq <= cur:
            self._bump("double_applies")
            return
        self._applied[(gen, shard)] = seq
        self.dst_gw.ioctx.write_full(self._applied_oid(gen, shard),
                                     json.dumps(seq).encode())

    # ------------------------------------------------------------ stats --
    def _bump(self, key: str, by: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] = self.stats.get(key, 0) + by

    def lag_dump(self) -> Dict[str, Any]:
        """This agent's replication-lag histogram dump (entry mtime ->
        apply time), mergeable via mgr.cluster_stats.merge_histograms."""
        if self._lag is None:
            return {}
        h = self._lag.histogram("lag_s")
        return h.dump() if h is not None else {}

    # ------------------------------------------------------------ replay --
    def sync(self) -> Dict[str, int]:
        """One sync pass; returns {'puts': n, 'deletes': n} (richer
        counters accumulate on ``self.stats``, per-pass failures on
        ``self.last_errors``).  Cursors persist once per generation
        pump, AFTER the shard jobs' completions resolved; consumed
        journal objects trim under the min-commit rule."""
        self.last_errors = []
        dst = self._dst_bucket()
        ent = self.src_gw._read_buckets().get(self.bucket)
        if ent is None:
            raise RGWError(f"NoSuchBucket: {self.bucket}")
        cur_gen = int(ent.get("index_gen", 0))
        cur_shards = int(ent.get("num_shards", 1))
        history = {int(h["gen"]): h for h in ent.get("log_gens", [])}
        state = self._load_state()
        if state is None:
            # never synced: start at the OLDEST generation whose logs
            # still exist — a late-registering zone replays the whole
            # retained history instead of needing a full sync
            state = {"gen": min(list(history) + [cur_gen]),
                     "shards": {}}
        stats = {"puts": 0, "deletes": 0}
        # ---- generation cutover: drain retired gens to their ends --
        while int(state["gen"]) < cur_gen:
            g = int(state["gen"])
            h = history.get(g)
            if h is None:
                # retired before this zone registered: nothing left
                # to drain here (its entries are gone by the drain
                # gate's rules, i.e. no registered zone needed them)
                state = {"gen": self._next_gen(g, history, cur_gen),
                         "shards": {}}
                self._save_state(state)
                continue
            done = self._pump_gen(dst, state, g,
                                  int(h["num_shards"]),
                                  [int(e) for e in h["ends"]], stats)
            if not done:
                # blocked (partition / transient errors): the cursor
                # keeps this generation; the next pass RESUMES here —
                # never a restart
                self._trim(cur_gen, cur_shards)
                return stats
            self._bump("gen_cutovers")
            state = {"gen": self._next_gen(g, history, cur_gen),
                     "shards": {}}
            self._save_state(state)
        # ---- the active generation (no end bound) ------------------
        self._pump_gen(dst, state, cur_gen, cur_shards, None, stats)
        self._trim(cur_gen, cur_shards)
        return stats

    @staticmethod
    def _next_gen(gen: int, history: Dict[int, dict],
                  cur_gen: int) -> int:
        later = [g for g in list(history) + [cur_gen] if g > gen]
        return min(later) if later else cur_gen

    def _pump_gen(self, dst: Bucket, state: Dict[str, Any], gen: int,
                  nshards: int, ends: Optional[List[int]],
                  stats: Dict[str, int]) -> bool:
        """Drain one generation's shards (to ``ends`` when retired,
        to the live tails when active).  Returns True when every
        shard reached its end marker with no errors.  The cursor
        persists ONCE, after every shard job's completion resolved."""
        jobs: List[Tuple[int, Any]] = []
        for s in range(nshards):
            frm = int(state["shards"].get(str(s), -1))
            end = None if ends is None else ends[s]
            if end is not None and frm >= end:
                continue
            fn = (lambda s=s, frm=frm, end=end:
                  self._sync_shard(dst, gen, s, frm, end))
            if self.engine is not None:
                comp = self.engine.submit(
                    fn, key=(self.bucket, self.zone, gen, s))
            else:
                comp = _InlineResult(fn)
            jobs.append((s, comp))
        all_done = True
        for s, comp in jobs:
            try:
                res = comp.result()
            except (IOError, OSError) as e:  # engine-level failure
                res = {"last": int(state["shards"].get(str(s), -1)),
                       "puts": 0, "deletes": 0,
                       "error": f"{type(e).__name__}: {e}"}
            stats["puts"] += res["puts"]
            stats["deletes"] += res["deletes"]
            if res["error"] is not None:
                self._bump("errors")
                self.last_errors.append(
                    f"gen {gen} shard {s}: {res['error']}")
                all_done = False
            end = None if ends is None else ends[s]
            if end is not None and res["last"] < end:
                all_done = False
            if res["last"] > int(state["shards"].get(str(s), -1)):
                state["shards"][str(s)] = res["last"]
        # cursor commit AFTER the gather — every apply above is
        # resolved, so a crash here only costs re-skipped replays
        self._save_state(state)
        return all_done and ends is not None

    def _sync_shard(self, dst: Bucket, gen: int, shard: int,
                    frm: int, end: Optional[int]) -> Dict[str, Any]:
        """Replay one (gen, shard) bilog from ``frm`` (exclusive) to
        ``end`` (inclusive; None = live tail).  Never raises: the
        result carries how far it got plus the first surfaced error —
        partial progress must reach the cursor commit either way."""
        res: Dict[str, Any] = {"last": frm, "puts": 0, "deletes": 0,
                               "error": None}
        try:
            j = self.src.bilog_for_shard(shard, gen=gen)
            j._load_header()
            self._load_applied(gen, shard)
            for seq, payload in j.replay():
                if seq <= frm:
                    continue
                if end is not None and seq > end:
                    break
                if faults.partitioned(self._src_ent, self._dst_ent):
                    raise IOError(
                        f"net.partition: {self._src_ent} -> "
                        f"{self._dst_ent} severed")
                ent = json.loads(payload.decode())
                kind = self._apply_entry(dst, gen, shard, seq, ent)
                if kind is not None:
                    res[kind] += 1
                res["last"] = seq
        except (IOError, OSError) as e:
            res["error"] = f"{type(e).__name__}: {e}"
        return res

    def _apply_entry(self, dst: Bucket, gen: int, shard: int,
                     seq: int, ent: Dict[str, Any]
                     ) -> Optional[str]:
        """Apply one bilog entry with the at-most-once/LWW/origin
        rules; transient IOErrors take ExpBackoff then raise (the
        shard job surfaces them with the marker unmoved)."""
        key = ent["key"]
        if seq <= self._load_applied(gen, shard):
            self._bump("replay_skips")
            return None
        origin = ent.get("origin") or self.src_zone
        if origin == self.zone:
            # our own apply echoing back through the reverse agent:
            # the destination already has this write
            self._bump("origin_skips")
            self._advance_applied(gen, shard, seq)
            return None
        mtime = float(ent.get("mtime", 0.0))
        kind: Optional[str] = None
        backoff = ExpBackoff(base=0.02, cap=0.5,
                             seed=zlib.crc32(key.encode()) & 0xffff)
        last: Optional[Exception] = None
        for attempt in range(5):
            try:
                kind = self._apply_once(dst, ent, key, mtime, origin)
                break
            except RGWError as e:
                if "NoSuchKey" in str(e):
                    # logged-ahead put whose data never landed, or a
                    # version deleted later in the log: nothing to do
                    self._bump("missing_src")
                    kind = None
                    break
                last = e
            except (IOError, OSError) as e:
                last = e
            if attempt == 4:
                raise RGWError(f"apply {key!r} seq {seq} failed "
                               f"after retries: {last}")
            backoff.sleep(attempt)
        if kind is not None and self._lag is not None:
            import time as _time
            self._lag.hinc("lag_s", max(0.0, _time.time() - mtime))
        # marker advance strictly AFTER the apply write resolved
        self._advance_applied(gen, shard, seq)
        if kind is not None:
            self._bump(kind)
        return kind

    def _apply_once(self, dst: Bucket, ent: Dict[str, Any], key: str,
                    mtime: float, origin: str) -> Optional[str]:
        if ent["op"] == "put":
            data, meta = self.src.get_object(key)
            r = dst.apply_put(key, data, meta.get("meta") or None,
                              mtime=mtime, origin=origin)
            if r is None:
                self._bump("conflict_skips")
                return None
            return "puts"
        if dst.apply_delete(key, mtime=mtime, origin=origin):
            return "deletes"
        self._bump("conflict_skips")
        return None

    # -------------------------------------------------------------- trim --
    def _trim(self, cur_gen: int, cur_shards: int) -> None:
        """Min-commit trim of the ACTIVE generation's logs plus the
        drain-gated retirement sweep for old generations.  A zone
        whose cursor is unreadable keeps the logs (the old
        ``except Exception: -1`` swallow here was the CTL603 class —
        _read_json's taxonomy retries/raises instead)."""
        states = [read_sync_state(self.src_gw.ioctx, self.bucket, z)
                  for z in self._zones()]
        for s in range(cur_shards):
            mins = []
            for st in states:
                if st is None or int(st.get("gen", 0)) < cur_gen:
                    mins.append(-1)
                else:
                    mins.append(int(st.get("shards", {})
                                    .get(str(s), -1)))
            if mins and min(mins) >= 0:
                self.src.bilog_for_shard(s, gen=cur_gen).trim_to(
                    min(mins) + 1)
        self.src_gw.retire_drained_bilogs(self.bucket)


class _InlineResult:
    """Serial fallback when no engine is configured: run the job in
    the calling thread, quack like a completion."""

    def __init__(self, fn):
        self._v = fn()

    def result(self):
        return self._v
