"""Swift HTTP frontend for the RGW gateway slice.

The reference gateway speaks BOTH S3 and Swift (src/rgw/rgw_rest_swift.cc,
rgw_swift_auth.cc); this is the Swift object-API core over the same
RGWGateway/buckets the S3 frontend drives (a Swift container IS a
bucket, like the reference's shared bucket index):

    GET  /auth/v1.0                      TempAuth: X-Auth-User/X-Auth-Key
                                         -> X-Auth-Token + X-Storage-Url
    GET  /v1/<acct>                      list containers (text or ?format=json)
    PUT  /v1/<acct>/<container>          create container (201)
    DELETE /v1/<acct>/<container>        delete container (204; 409 nonempty)
    GET  /v1/<acct>/<container>          list objects (prefix/marker/limit/
                                         delimiter; text or ?format=json)
    PUT  /v1/<acct>/<container>/<obj>    put object (201 + ETag,
                                         X-Object-Meta-* stored)
    GET  /v1/<acct>/<container>/<obj>    object bytes + ETag + meta headers
    HEAD                                 metadata only
    DELETE /v1/<acct>/<container>/<obj>  delete object (204)

Swift returns errors as plain status codes (404/409/401), not XML —
kept faithful to the protocol rather than to the S3 sibling.
"""
from __future__ import annotations

import http.server
import json
import secrets
import threading
import urllib.parse
from typing import Dict, Optional, Tuple

from .gateway import RGWError, RGWGateway

_STATUS = {"NoSuchBucket": 404, "NoSuchKey": 404,
           "BucketAlreadyExists": 202,     # Swift PUT is idempotent: 202
           "BucketNotEmpty": 409, "InvalidBucketName": 400}


class SwiftFrontend:
    def __init__(self, gateway: RGWGateway, account: str = "AUTH_test",
                 users: Optional[Dict[str, str]] = None):
        """``users``: "account:user" -> key (the TempAuth shape).
        None disables auth (dev mode)."""
        self.gw = gateway
        self.account = account
        self.users = users
        self._tokens: Dict[str, str] = {}       # token -> user
        self._server: Optional[http.server.ThreadingHTTPServer] = None

    def issue_token(self, user: str) -> str:
        tok = "AUTH_tk" + secrets.token_hex(16)
        self._tokens[tok] = user
        return tok

    # --------------------------------------------------------------- ops --
    def start(self, port: int = 0) -> int:
        fe = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _split(self) -> Tuple[str, str, str, dict]:
                parsed = urllib.parse.urlparse(self.path)
                parts = [urllib.parse.unquote(p)
                         for p in parsed.path.strip("/").split("/")]
                q = {k: v[0] for k, v in urllib.parse.parse_qs(
                    parsed.query, keep_blank_values=True).items()}
                # /v1/<acct>[/<container>[/<obj...>]]
                acct = parts[1] if len(parts) > 1 else ""
                cont = parts[2] if len(parts) > 2 else ""
                obj = "/".join(parts[3:]) if len(parts) > 3 else ""
                return acct, cont, obj, q

            def _send(self, status: int, body: bytes = b"",
                      ctype: str = "text/plain; charset=utf-8",
                      head_only: bool = False, extra: dict = None):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                if not head_only and body:
                    self.wfile.write(body)

            def _fail(self, e: RGWError, head_only=False):
                code = str(e).split(":", 1)[0]
                self._send(_STATUS.get(code, 400), str(e).encode(),
                           head_only=head_only)

            def _authed(self, head_only=False) -> bool:
                if fe.users is None:
                    return True
                tok = self.headers.get("X-Auth-Token", "")
                if tok in fe._tokens:
                    return True
                self._send(401, b"Unauthorized", head_only=head_only)
                return False

            def _body(self) -> bytes:
                ln = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(ln) if ln else b""

            def _auth_v1(self) -> None:
                """GET /auth/v1.0 — TempAuth handshake."""
                user = self.headers.get("X-Auth-User", "")
                key = self.headers.get("X-Auth-Key", "")
                if fe.users is not None and \
                        fe.users.get(user) != key:
                    self._send(401, b"Unauthorized")
                    return
                tok = fe.issue_token(user)
                host, port_ = self.server.server_address
                self._send(200, extra={
                    "X-Auth-Token": tok,
                    "X-Storage-Token": tok,
                    "X-Storage-Url":
                        f"http://{host}:{port_}/v1/{fe.account}"})

            def do_GET(self, head_only=False):      # noqa: N802
                if self.path.startswith("/auth/"):
                    self._auth_v1()
                    return
                acct, cont, obj, q = self._split()
                if not self._authed(head_only=head_only):
                    return
                try:
                    if not cont:
                        names = fe.gw.list_buckets()
                        if q.get("format") == "json":
                            body = json.dumps(
                                [{"name": n} for n in names]).encode()
                            self._send(200, body, "application/json",
                                       head_only=head_only)
                        else:
                            self._send(200,
                                       ("\n".join(names) + "\n").encode()
                                       if names else b"",
                                       head_only=head_only)
                    elif not obj:
                        r = fe.gw.bucket(cont).list_objects(
                            prefix=q.get("prefix", ""),
                            marker=q.get("marker", ""),
                            max_keys=int(q.get("limit", 10000)),
                            delimiter=q.get("delimiter", ""))
                        if q.get("format") == "json":
                            body = json.dumps(
                                [{"name": c["key"], "bytes": c["size"],
                                  "hash": c["etag"]}
                                 for c in r["contents"]] +
                                [{"subdir": p}
                                 for p in r["common_prefixes"]]).encode()
                            self._send(200, body, "application/json",
                                       head_only=head_only)
                        else:
                            names = [c["key"] for c in r["contents"]] + \
                                list(r["common_prefixes"])
                            self._send(200,
                                       ("\n".join(names) + "\n").encode()
                                       if names else b"",
                                       head_only=head_only)
                    else:
                        data, ent = fe.gw.bucket(cont).get_object(obj)
                        extra = {"ETag": ent["etag"]}
                        for k, v in ent.get("meta", {}).items():
                            extra[f"X-Object-Meta-{k}"] = v
                        self._send(200, data,
                                   "application/octet-stream",
                                   head_only=head_only, extra=extra)
                except RGWError as e:
                    self._fail(e, head_only=head_only)

            def do_HEAD(self):                      # noqa: N802
                self.do_GET(head_only=True)

            def do_PUT(self):                       # noqa: N802
                acct, cont, obj, q = self._split()
                body = self._body()
                if not self._authed():
                    return
                try:
                    if not obj:
                        try:
                            fe.gw.create_bucket(cont)
                            self._send(201)
                        except RGWError as e:
                            if str(e).startswith("BucketAlreadyExists"):
                                self._send(202)     # idempotent PUT
                            else:
                                raise
                    else:
                        meta = {k[len("X-Object-Meta-"):]: v
                                for k, v in self.headers.items()
                                if k.lower().startswith("x-object-meta-")}
                        etag = fe.gw.bucket(cont).put_object(
                            obj, body, metadata=meta or None)
                        self._send(201, extra={"ETag": etag})
                except RGWError as e:
                    self._fail(e)

            def do_DELETE(self):                    # noqa: N802
                acct, cont, obj, q = self._split()
                if not self._authed():
                    return
                try:
                    if obj:
                        fe.gw.bucket(cont).delete_object(obj)
                    else:
                        fe.gw.delete_bucket(cont)
                    self._send(204)
                except RGWError as e:
                    self._fail(e)

            def log_message(self, *a):
                pass

        self._server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), Handler)
        threading.Thread(target=self._server.serve_forever,
                        daemon=True).start()
        return self._server.server_address[1]

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
