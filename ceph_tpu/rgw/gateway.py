"""RGW slice — S3-shaped object gateway over RADOS.

The thin S3-object slice VERDICT r2 asked for (missing #8): the
src/rgw/ roles reduced to the storage shape rather than the 191k-LoC
HTTP/multisite stack:

  * a bucket's KEY INDEX lives in one index object per bucket (the
    bucket-index-over-omap role, src/rgw/driver/rados bucket index
    shards) — ordered key -> {size, etag, mtime} entries, updated
    after the data object lands (index consistency: a crash between
    data and index leaves an orphan data object, never a dangling
    index entry);
  * object DATA is one RADOS object per S3 key under the bucket's
    data prefix ("rgw_data.<bucket>_<key>");
  * S3 list semantics: lexicographic, prefix + marker + max_keys with
    truncation flag, and delimiter-based common prefixes;
  * ETag = MD5 hex of the payload (S3 compatibility contract).

No HTTP frontend here — the gateway API is the seam a REST layer
would call (the RGWOp layer's interface).
"""
from __future__ import annotations

import hashlib
import json
import time
from typing import Dict, List, Optional, Tuple

from ..common.backoff import ExpBackoff

_BUCKETS_OID = "rgw.buckets"


class RGWError(IOError):
    pass


def _read_json(ioctx, oid: str, default, what: str):
    """Read+decode one JSON metadata object (bucket index, bucket
    directory, GC log) with the failure taxonomy these objects NEED:

      * object absent -> ``default`` (a fresh bucket/log);
      * TRANSIENT IOError (degraded EC read mid-recovery, injected
        EIO, connection cut) -> bounded retry with ExpBackoff, then
        RAISE.  The old ``except Exception: return {}`` here was a
        lost-object bug under load, not a flake: one transient read
        error made a full bucket index read as EMPTY — spurious
        NoSuchKey on a GET, and the next index WRITE would rebuild
        from {} and silently orphan every existing object;
      * corrupt JSON -> raise (serving {} for a damaged index is the
        same data loss with less evidence).
    """
    import zlib
    # stable digest, NOT hash(): str hashing is salted per process
    # and would make retry jitter irreproducible across runs
    backoff = ExpBackoff(base=0.02, cap=0.25,
                         seed=zlib.crc32(oid.encode()) & 0xffff)
    last: Optional[Exception] = None
    for attempt in range(4):
        try:
            return json.loads(ioctx.read(oid).decode())
        except KeyError:
            # ObjectNotFound subclasses KeyError in both client tiers:
            # genuinely absent metadata means a fresh bucket/log
            return default
        except (IOError, OSError) as e:
            last = e
            if attempt < 3:
                backoff.sleep(attempt)
    raise RGWError(f"{what} {oid!r} unreadable after retries: {last}")


class Bucket:
    def __init__(self, gw: "RGWGateway", name: str):
        self.gw = gw
        self.name = name
        self._bilog = None

    @property
    def bilog(self):
        """Bucket index log (the RGW bilog role): every put/delete is
        recorded for multisite sync (rgw/sync.py replays it)."""
        if self._bilog is None:
            from ..fs.journaler import Journaler
            self._bilog = Journaler(self.gw.ioctx,
                                    f"rgw.bilog.{self.name}")
        return self._bilog

    def _log_op(self, op: str, key: str) -> None:
        # reload the journal header first: another live handle of this
        # bucket may have appended since ours cached its sequence — a
        # stale seq would duplicate and sync would drop the entry
        self.bilog._load_header()
        self.bilog.append(json.dumps({"op": op, "key": key}).encode())

    # ------------------------------------------------------------- index --
    def _index_oid(self) -> str:
        return f"rgw.index.{self.name}"

    def _read_index(self) -> Dict[str, dict]:
        return _read_json(self.gw.ioctx, self._index_oid(), {},
                          "bucket index")

    def _write_index(self, idx: Dict[str, dict]) -> None:
        self.gw.ioctx.write_full(self._index_oid(),
                                 json.dumps(idx).encode())

    def _data_oid(self, key: str, gen: str = "") -> str:
        # '/' is forbidden in bucket names (create_bucket validates),
        # so this join is collision-free across (bucket, key) pairs.
        # ``gen`` is the per-write generation token: data oids are
        # UNIQUE per object version, so a superseded version's oid can
        # sit in the deferred-GC log while the SAME KEY is rewritten —
        # GC can never reclaim live data (the RGW tail-object
        # generation role).
        return f"rgw_data.{self.name}/{key}.{gen}" if gen \
            else f"rgw_data.{self.name}/{key}"

    # --------------------------------------------------------------- ops --
    def put_object(self, key: str, data: bytes,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        """-> ETag.  Data object first, index entry second."""
        import secrets as _secrets
        etag = hashlib.md5(data).hexdigest()
        gen = _secrets.token_hex(4)
        # bilog entry FIRST (the prepare-before-index-transaction
        # order): a crash between log and index leaves an entry whose
        # replay finds no object and skips — never a visible object
        # that multisite would silently miss
        self._log_op("put", key)
        self.gw.ioctx.write_full(self._data_oid(key, gen), data)
        idx = self._read_index()
        old = idx.get(key)
        idx[key] = {"size": len(data), "etag": etag, "gen": gen,
                    "mtime": time.time(), "meta": metadata or {}}
        self._write_index(idx)
        # the superseded version (plain or multipart) -> deferred GC
        if old:
            self.gw.gc_enqueue(self._version_oids(key, old))
        return etag

    def _version_oids(self, key: str, ent: dict) -> List[str]:
        """Every data oid one index-entry version owns."""
        mp = ent.get("mp")
        if mp:
            return [self._mp_part_oid(mp["uid"], p["n"])
                    for p in mp["parts"]]
        return [self._data_oid(key, ent.get("gen", ""))]

    def get_object(self, key: str) -> Tuple[bytes, dict]:
        ent = self._read_index().get(key)
        if ent is None:
            raise RGWError(f"NoSuchKey: {key}")
        mp = ent.get("mp")
        if mp:
            # multipart manifest: the object is striped across its
            # part objects (the RGW manifest role — completion never
            # copies bytes, rgw_op.h:1210 CompleteMultipart)
            chunks = []
            for p in mp["parts"]:
                raw = self.gw.ioctx.read(
                    self._mp_part_oid(mp["uid"], p["n"]))
                chunks.append(raw[:p["size"]])
            return b"".join(chunks), ent
        data = self.gw.ioctx.read(
            self._data_oid(key, ent.get("gen", "")))[:ent["size"]]
        return data, ent

    def head_object(self, key: str) -> dict:
        ent = self._read_index().get(key)
        if ent is None:
            raise RGWError(f"NoSuchKey: {key}")
        return dict(ent)

    def delete_object(self, key: str) -> None:
        idx = self._read_index()
        if key not in idx:
            raise RGWError(f"NoSuchKey: {key}")
        ent = idx[key]
        # index entry first, then data: a crash leaves an orphan data
        # object (GC-able), never a dangling index entry
        self._log_op("delete", key)       # log-ahead, like put
        del idx[key]
        self._write_index(idx)
        mp = ent.get("mp")
        if mp:
            # multipart tails go through the DEFERRED-delete GC log
            # (rgw_gc.cc role): the delete acks now, space reclaims
            # on the next gc_process pass
            self.gw.gc_enqueue(self._version_oids(key, ent))
            return
        try:
            self.gw.ioctx.remove(self._data_oid(key,
                                                ent.get("gen", "")))
        except Exception:
            pass

    # --------------------------------------------------------- multipart --
    # Reference: InitMultipart / UploadPart / CompleteMultipart ops
    # (src/rgw/rgw_op.h:1210-1212).  Parts are RADOS objects; completion
    # writes a MANIFEST into the index (striped mapping, no byte copy).

    def _mp_meta_oid(self, uid: str) -> str:
        return f"rgw.mp.{self.name}/{uid}"

    def _mp_part_oid(self, uid: str, n: int) -> str:
        return f"rgw_mp.{self.name}/{uid}.{n}"

    def _read_mp(self, uid: str) -> dict:
        meta = _read_json(self.gw.ioctx, self._mp_meta_oid(uid),
                          None, "multipart meta")
        if meta is None:
            raise RGWError(f"NoSuchUpload: {uid}")
        return meta

    def initiate_multipart(self, key: str) -> str:
        import secrets as _secrets
        uid = _secrets.token_hex(8)
        self.gw.ioctx.write_full(
            self._mp_meta_oid(uid),
            json.dumps({"key": key, "parts": {},
                        "started": time.time()}).encode())
        return uid

    def upload_part(self, uid: str, part_number: int,
                    data: bytes) -> str:
        if part_number < 1 or part_number > 10000:
            raise RGWError(f"InvalidPart: number {part_number}")
        etag = hashlib.md5(data).hexdigest()
        self.gw.ioctx.write_full(self._mp_part_oid(uid, part_number),
                                 data)
        with self.gw._mp_lock:     # concurrent parts: RMW must not
            meta = self._read_mp(uid)          # lose registrations
            meta["parts"][str(part_number)] = {"size": len(data),
                                               "etag": etag}
            self.gw.ioctx.write_full(self._mp_meta_oid(uid),
                                     json.dumps(meta).encode())
        return etag

    def complete_multipart(self, uid: str,
                           part_numbers: List[int]) -> str:
        """Stitch the listed parts (ascending) into the object as a
        manifest; superseded/unlisted parts go to GC.  ETag follows
        the S3 multipart convention: md5(part-md5s) + '-N'."""
        meta = self._read_mp(uid)
        key = meta["key"]
        nums = [int(x) for x in part_numbers]
        if len(set(nums)) != len(nums):
            raise RGWError("InvalidPart: duplicate part numbers")
        parts = []
        digest = hashlib.md5()
        size = 0
        for n in sorted(nums):
            p = meta["parts"].get(str(n))
            if p is None:
                raise RGWError(f"InvalidPart: {n} was never uploaded")
            parts.append({"n": n, "size": p["size"],
                          "etag": p["etag"]})
            digest.update(bytes.fromhex(p["etag"]))
            size += p["size"]
        if not parts:
            raise RGWError("InvalidPart: empty part list")
        etag = f"{digest.hexdigest()}-{len(parts)}"
        self._log_op("put", key)
        idx = self._read_index()
        old = idx.get(key)
        idx[key] = {"size": size, "etag": etag, "mtime": time.time(),
                    "meta": {},
                    "mp": {"uid": uid, "parts": parts}}
        self._write_index(idx)
        # unlisted parts + any overwritten previous object -> GC
        listed = {p["n"] for p in parts}
        orphans = [self._mp_part_oid(uid, int(n))
                   for n in meta["parts"] if int(n) not in listed]
        if old:
            orphans += self._version_oids(key, old)
        if orphans:
            self.gw.gc_enqueue(orphans)
        try:
            self.gw.ioctx.remove(self._mp_meta_oid(uid))
        except Exception:
            pass
        return etag

    def abort_multipart(self, uid: str) -> int:
        """Abandon an upload: every uploaded part becomes a deferred
        GC entry (AbortMultipart -> rgw_gc.cc defer_gc shape)."""
        meta = self._read_mp(uid)
        oids = [self._mp_part_oid(uid, int(n)) for n in meta["parts"]]
        self.gw.gc_enqueue(oids)
        try:
            self.gw.ioctx.remove(self._mp_meta_oid(uid))
        except Exception:
            pass
        return len(oids)

    def list_objects(self, prefix: str = "", marker: str = "",
                     max_keys: int = 1000, delimiter: str = ""
                     ) -> Dict[str, object]:
        """S3 ListObjects semantics: sorted keys after ``marker``
        matching ``prefix``; with ``delimiter``, roll common prefixes."""
        idx = self._read_index()
        keys = sorted(k for k in idx
                      if k.startswith(prefix) and k > marker)
        contents: List[dict] = []
        common: List[str] = []
        last_seen = ""           # S3 NextMarker = last key RETURNED
        for k in keys:
            if delimiter:
                rest = k[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter, 1)[0] + delimiter
                    if cp not in common:
                        if len(contents) + len(common) >= max_keys:
                            return {"contents": contents,
                                    "common_prefixes": common,
                                    "is_truncated": True,
                                    "next_marker": last_seen}
                        common.append(cp)
                    last_seen = k
                    continue
            if len(contents) + len(common) >= max_keys:
                return {"contents": contents, "common_prefixes": common,
                        "is_truncated": True, "next_marker": last_seen}
            contents.append({"key": k, **idx[k]})
            last_seen = k
        return {"contents": contents, "common_prefixes": common,
                "is_truncated": False, "next_marker": ""}


_GC_OID = "rgw.gc"


class RGWGateway:
    """Bucket directory + per-bucket handles (the RGWRados role)."""

    def __init__(self, ioctx):
        self.ioctx = ioctx
        import threading
        # serialize the shared-object read-modify-writes across the
        # frontend's request threads (gc log + per-upload multipart
        # meta; cross-PROCESS gateways would shard these like the
        # reference's gc/bucket-index objects)
        self._gc_lock = threading.Lock()
        self._mp_lock = threading.Lock()

    # ------------------------------------------------------------------ GC --
    # Deferred-delete log (src/rgw/rgw_gc.cc): deletions of tail/part
    # objects enqueue here and reclaim on the next gc_process() pass,
    # so client-visible deletes never wait on data removal and orphan
    # cleanup is centralized.

    def _read_gc(self) -> List[dict]:
        # same taxonomy as the bucket index: a transient read error
        # treated as "empty log" would let the next gc_enqueue
        # OVERWRITE pending entries — leaked data objects
        return _read_json(self.ioctx, _GC_OID, [], "gc log")

    def gc_enqueue(self, oids: List[str],
                   delay: float = 0.0) -> None:
        with self._gc_lock:
            entries = self._read_gc()
            due = time.time() + delay
            entries.extend({"oid": o, "due": due} for o in oids)
            self.ioctx.write_full(_GC_OID,
                                  json.dumps(entries).encode())

    def gc_list(self) -> List[dict]:
        return self._read_gc()

    def gc_process(self, now: Optional[float] = None) -> int:
        """Remove every due entry's object; returns objects removed.
        Entries whose object is already gone still clear (idempotent
        across a crash mid-pass)."""
        now = time.time() if now is None else now
        with self._gc_lock:
            entries = self._read_gc()
            keep, removed = [], 0
            for e in entries:
                if e["due"] > now:
                    keep.append(e)
                    continue
                try:
                    self.ioctx.remove(e["oid"])
                    removed += 1
                except Exception:
                    pass      # already gone: entry still clears
            self.ioctx.write_full(_GC_OID, json.dumps(keep).encode())
        return removed

    def _read_buckets(self) -> Dict[str, dict]:
        return _read_json(self.ioctx, _BUCKETS_OID, {},
                          "bucket directory")

    def _write_buckets(self, d: Dict[str, dict]) -> None:
        self.ioctx.write_full(_BUCKETS_OID, json.dumps(d).encode())

    def create_bucket(self, name: str) -> Bucket:
        if not name or "/" in name:
            raise RGWError(f"InvalidBucketName: {name!r}")
        d = self._read_buckets()
        if name in d:
            raise RGWError(f"BucketAlreadyExists: {name}")
        d[name] = {"created": time.time()}
        self._write_buckets(d)
        return Bucket(self, name)

    def bucket(self, name: str) -> Bucket:
        if name not in self._read_buckets():
            raise RGWError(f"NoSuchBucket: {name}")
        return Bucket(self, name)

    def list_buckets(self) -> List[str]:
        return sorted(self._read_buckets())

    def delete_bucket(self, name: str) -> None:
        d = self._read_buckets()
        if name not in d:
            raise RGWError(f"NoSuchBucket: {name}")
        b = Bucket(self, name)
        if b._read_index():
            raise RGWError(f"BucketNotEmpty: {name}")
        try:
            self.ioctx.remove(b._index_oid())
        except Exception:
            pass
        # drop the bilog chain + header so a recreated bucket starts
        # with a fresh log (sync position objects are per-zone and
        # owned by their agents)
        j = b.bilog
        for idx_no in range(j.first, j.active + 1):
            try:
                self.ioctx.remove(j._obj_oid(idx_no))
            except Exception:
                pass
        try:
            self.ioctx.remove(j._header_oid())
        except Exception:
            pass
        del d[name]
        self._write_buckets(d)
