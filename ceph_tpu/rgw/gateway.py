"""RGW slice — S3-shaped object gateway over RADOS.

The thin S3-object slice VERDICT r2 asked for (missing #8): the
src/rgw/ roles reduced to the storage shape rather than the 191k-LoC
HTTP/multisite stack:

  * a bucket's KEY INDEX lives in N SHARD objects keyed by key-hash
    (the bucket-index-shard role, src/rgw/driver/rados
    rgw_bucket_index_... / cls_rgw over omap): each shard holds the
    ordered key -> {size, etag, mtime} entries whose keys hash to it,
    updated after the data object lands (index consistency: a crash
    between data and index leaves an orphan data object, never a
    dangling index entry).  Legacy buckets (num_shards == 1, gen 0)
    keep the original one-object-per-bucket oid, so pre-shard pools
    read unchanged.  One hot bucket no longer serializes every
    writer on a single index object: per-request ops touch ONLY the
    key's shard, under a per-(bucket, shard) RMW lock;
  * LISTING is a shard-merge: every shard is read once and the
    results merge-sorted — identical output for every shard count;
  * online ``reshard`` copies the merged entries into a new
    generation of shard objects and commits the layout in the bucket
    directory record (the RGWBucketReshard role);
  * object DATA is one RADOS object per S3 key under the bucket's
    data prefix ("rgw_data.<bucket>_<key>");
  * S3 list semantics: lexicographic, prefix + marker + max_keys with
    truncation flag, and delimiter-based common prefixes;
  * ETag = MD5 hex of the payload (S3 compatibility contract).

No HTTP frontend here — the gateway API is the seam a REST layer
would call (the RGWOp layer's interface).
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..common import faults
from ..common.backoff import ExpBackoff

_BUCKETS_OID = "rgw.buckets"

# Declared next to its fire site (Bucket._log_op): the seeded
# lost-replication fault the DR drill's falsifiability leg arms — the
# data/index write lands but the bilog entry is silently dropped, so
# multisite sync never learns about the op.  A gate that stays green
# with this armed proves nothing.
faults.declare(
    "rgw.bilog_lost_entry",
    "drop one bucket-index-log append (the data/index write lands, "
    "the bilog entry never does) — the lost-replication seed the DR "
    "convergence gate must turn red on; ctx: bucket, key, shard")


class RGWError(IOError):
    pass


def _read_json(ioctx, oid: str, default, what: str):
    """Read+decode one JSON metadata object (bucket index, bucket
    directory, GC log) with the failure taxonomy these objects NEED:

      * object absent -> ``default`` (a fresh bucket/log);
      * TRANSIENT IOError (degraded EC read mid-recovery, injected
        EIO, connection cut) -> bounded retry with ExpBackoff, then
        RAISE.  The old ``except Exception: return {}`` here was a
        lost-object bug under load, not a flake: one transient read
        error made a full bucket index read as EMPTY — spurious
        NoSuchKey on a GET, and the next index WRITE would rebuild
        from {} and silently orphan every existing object;
      * corrupt JSON -> raise (serving {} for a damaged index is the
        same data loss with less evidence).
    """
    # stable digest, NOT hash(): str hashing is salted per process
    # and would make retry jitter irreproducible across runs
    backoff = ExpBackoff(base=0.02, cap=0.25,
                         seed=zlib.crc32(oid.encode()) & 0xffff)
    last: Optional[Exception] = None
    for attempt in range(4):
        try:
            return json.loads(ioctx.read(oid).decode())
        except KeyError:
            # ObjectNotFound subclasses KeyError in both client tiers:
            # genuinely absent metadata means a fresh bucket/log
            return default
        except (IOError, OSError) as e:
            last = e
            if attempt < 3:
                backoff.sleep(attempt)
    raise RGWError(f"{what} {oid!r} unreadable after retries: {last}")


# -------------------------------------------------- sync bookkeeping --
# The marker/zone object schema is shared between the gateway (drain
# gating on trim/retire/delete) and rgw/sync.py (the agents that own
# the markers), so it lives here next to the bilog naming it governs.

def zones_oid(bucket: str) -> str:
    return f"rgw.zones.{bucket}"


def sync_state_oid(bucket: str, zone: str) -> str:
    return f"rgw.sync.{bucket}.{zone}"


def read_sync_state(ioctx, bucket: str, zone: str):
    """One zone's persisted sync cursor: {"gen": g, "shards":
    {"<shard>": last_applied_seq}}.  Absent -> None (never synced);
    the pre-generation format (a bare int: shard 0's position) reads
    as a gen-0 single-shard cursor, so old pools resume, not restart.
    Transient read errors retry/raise via _read_json — fabricating
    "never synced" from a flake would re-replay a whole generation."""
    raw = _read_json(ioctx, sync_state_oid(bucket, zone), None,
                     "sync state")
    if raw is None:
        return None
    if isinstance(raw, (int, float)):
        return {"gen": 0, "shards": {"0": int(raw)}}
    return {"gen": int(raw.get("gen", 0)),
            "shards": {str(k): int(v)
                       for k, v in raw.get("shards", {}).items()}}


def zone_drained_past(state, gen: int, ends: List[int]) -> bool:
    """Has this zone consumed EVERY entry of bilog generation ``gen``
    (per-shard end seqs ``ends``)?  A later generation implies the
    cutover already drained this one; an earlier one (or no state at
    all) means entries this zone has not replicated still live here."""
    if state is None:
        return False
    zgen = int(state.get("gen", 0))
    if zgen != gen:
        return zgen > gen
    shards = state.get("shards", {})
    return all(int(shards.get(str(s), -1)) >= e
               for s, e in enumerate(ends))


class Bucket:
    # how long a handle trusts its cached shard layout before
    # re-reading the bucket directory record: the window in which a
    # CROSS-PROCESS ``reshard`` is invisible to a live writer (gens
    # make a stale write land in an unreferenced old-gen object — an
    # orphan, never a corrupted new-gen shard).  In-process handles
    # of one gateway share the reshard lock and never race at all.
    _LAYOUT_TTL_S = 1.0

    def __init__(self, gw: "RGWGateway", name: str,
                 layout: Optional[Dict[str, int]] = None):
        self.gw = gw
        self.name = name
        self._bilogs: Dict[int, object] = {}
        self._layout_cache = dict(layout) if layout else None
        self._layout_ts = time.monotonic() if layout else 0.0

    # ------------------------------------------------------------ layout --
    def _layout(self) -> Dict[str, int]:
        """{"num_shards": N, "index_gen": g} from the bucket
        directory record, TTL-cached (an online reshard bumps the
        gen; other handles pick the new layout up within the TTL)."""
        now = time.monotonic()
        if self._layout_cache is None or \
                now - self._layout_ts > self._LAYOUT_TTL_S:
            return self._refresh_layout()
        return self._layout_cache

    def _refresh_layout(self) -> Dict[str, int]:
        """Drop the TTL cache and re-read the bucket record NOW —
        the ECANCELED-refresh a real RGW client does when an index
        op lands on a resharded-away generation."""
        ent = self.gw._read_buckets().get(self.name) or {}
        self._layout_cache = {
            "num_shards": int(ent.get("num_shards", 1)),
            "index_gen": int(ent.get("index_gen", 0))}
        self._layout_ts = time.monotonic()
        return self._layout_cache

    def num_shards(self) -> int:
        return self._layout()["num_shards"]

    def _shard_for_key(self, key: str,
                       layout: Optional[Dict[str, int]] = None
                       ) -> int:
        # stable digest, NOT hash(): shard placement must agree
        # across processes and runs (str hashing is salted)
        lo = layout or self._layout()
        return zlib.crc32(key.encode()) % lo["num_shards"]

    def bilog_for_shard(self, shard: int, gen: Optional[int] = None):
        """Per-(generation, shard) bucket index log (the RGW
        bilog-per-shard role, generation-split like cls_rgw's
        bilog layout after reshard): every put/delete lands in its
        key's shard log OF THE CURRENT GENERATION.  A reshard starts
        a fresh set of logs (new gen) instead of interleaving two
        shard mappings in one stream — the old generation's logs stay
        put, end-marked, until every peer zone drains them.
        Generation 0 keeps the legacy un-suffixed/`.N` names so
        pre-generation pools replay unchanged."""
        if gen is None:
            gen = self._layout()["index_gen"]
        j = self._bilogs.get((gen, shard))
        if j is None:
            from ..fs.journaler import Journaler
            if gen == 0:
                suffix = "" if shard == 0 else f".{shard}"
            else:
                suffix = f".g{gen}.{shard}"
            j = self._bilogs[(gen, shard)] = Journaler(
                self.gw.ioctx, f"rgw.bilog.{self.name}{suffix}")
        return j

    @property
    def bilog(self):
        """Generation 0's shard-0 bilog — the whole log for legacy
        single-shard buckets (kept for pre-generation callers;
        rgw/sync.py walks every (gen, shard) log itself)."""
        return self.bilog_for_shard(0, gen=0)

    def _log_op(self, op: str, key: str, shard: int,
                gen: Optional[int] = None, **extra) -> None:
        """Append one bilog entry: {op, key, mtime} plus per-op extras
        (etag/size on puts; origin on sync applies, so the reverse
        agent can suppress the echo instead of ping-ponging writes).
        ``gen`` pins the log to the caller's layout snapshot — the
        shard NUMBER and the log GENERATION must come from the same
        layout or a TTL refresh mid-op could cross the streams."""
        if faults.fire("rgw.bilog_lost_entry", bucket=self.name,
                       key=key, shard=shard) is not None:
            return                     # the entry is silently LOST
        # reload the journal header first: another live handle of this
        # bucket may have appended since ours cached its sequence — a
        # stale seq would duplicate and sync would drop the entry
        j = self.bilog_for_shard(shard, gen=gen)
        j._load_header()
        ent = {"op": op, "key": key, "mtime": time.time()}
        ent.update(extra)
        j.append(json.dumps(ent).encode())

    # ------------------------------------------------------------- index --
    def _index_shard_oid(self, shard: int,
                         layout: Optional[Dict[str, int]] = None
                         ) -> str:
        lo = layout or self._layout()
        if lo["num_shards"] == 1 and lo["index_gen"] == 0:
            # legacy single-object layout: pre-shard pools unchanged
            return f"rgw.index.{self.name}"
        return f"rgw.index.{self.name}.g{lo['index_gen']}.{shard}"

    def _read_index_shard(self, shard: int,
                          layout: Optional[Dict[str, int]] = None
                          ) -> Dict[str, dict]:
        return _read_json(self.gw.ioctx,
                          self._index_shard_oid(shard, layout), {},
                          f"bucket index shard {shard}")

    def _write_index_shard(self, shard: int, idx: Dict[str, dict],
                           layout: Optional[Dict[str, int]] = None
                           ) -> None:
        self.gw.ioctx.write_full(self._index_shard_oid(shard, layout),
                                 json.dumps(idx).encode())

    def _read_index(self) -> Dict[str, dict]:
        """The WHOLE index, merged across shards — the listing /
        reshard / admin surface, never a per-request path (lint
        CTL901 polices exactly that)."""
        lo = dict(self._layout())
        merged: Dict[str, dict] = {}
        for s in range(lo["num_shards"]):
            merged.update(self._read_index_shard(s, layout=lo))
        return merged

    def shard_entry_counts(self) -> List[int]:
        """Per-shard entry counts (`radosgw-admin bucket limit
        check`'s fill view)."""
        lo = dict(self._layout())
        return [len(self._read_index_shard(s, layout=lo))
                for s in range(lo["num_shards"])]

    # -------------------------------------------------------------- data --
    def _read_data(self, oid: str, what: str) -> bytes:
        """Data-object read with the bounded poll-budget retry the
        metadata reads already had (_read_json's taxonomy): the
        degraded-read window right after an OSD SIGKILL surfaces as
        TRANSIENT IOErrors while the map catches up — retry through
        it, then raise.  Genuine absence (KeyError) propagates: an
        indexed key whose data object is gone is an inconsistency
        the caller must see, not retry."""
        backoff = ExpBackoff(base=0.05, cap=0.5,
                             seed=zlib.crc32(oid.encode()) & 0xffff)
        last: Optional[Exception] = None
        for attempt in range(5):
            try:
                return self.gw.ioctx.read(oid)
            except KeyError:
                raise
            except (IOError, OSError) as e:
                last = e
                if attempt < 4:
                    backoff.sleep(attempt)
        raise RGWError(f"{what} {oid!r} unreadable after retries: "
                       f"{last}")

    def _data_oid(self, key: str, gen: str = "") -> str:
        # '/' is forbidden in bucket names (create_bucket validates),
        # so this join is collision-free across (bucket, key) pairs.
        # ``gen`` is the per-write generation token: data oids are
        # UNIQUE per object version, so a superseded version's oid can
        # sit in the deferred-GC log while the SAME KEY is rewritten —
        # GC can never reclaim live data (the RGW tail-object
        # generation role).
        return f"rgw_data.{self.name}/{key}.{gen}" if gen \
            else f"rgw_data.{self.name}/{key}"

    # --------------------------------------------------------------- ops --
    def put_object(self, key: str, data: bytes,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        """-> ETag.  Data object first, index entry second.  Only the
        KEY'S shard is read-modify-written, under that shard's lock —
        writers to a hot bucket serialize per shard, not per bucket."""
        import secrets as _secrets
        etag = hashlib.md5(data).hexdigest()
        gen = _secrets.token_hex(4)
        # ONE layout snapshot for the whole op: the shard NUMBER and
        # the oid GENERATION must come from the same layout, or a
        # TTL refresh mid-op could write the key into the wrong
        # new-gen shard (a stale snapshot only ever writes a dead
        # old-gen oid — an orphan, never corruption)
        lo = dict(self._layout())
        shard = self._shard_for_key(key, lo)
        # bilog entry FIRST (the prepare-before-index-transaction
        # order): a crash between log and index leaves an entry whose
        # replay finds no object and skips — never a visible object
        # that multisite would silently miss
        with self.gw._index_lock(self.name, shard):
            self._log_op("put", key, shard, gen=lo["index_gen"],
                         etag=etag, size=len(data))
            self.gw.ioctx.write_full(self._data_oid(key, gen), data)
            idx = self._read_index_shard(shard, layout=lo)
            old = idx.get(key)
            idx[key] = {"size": len(data), "etag": etag, "gen": gen,
                        "mtime": time.time(), "meta": metadata or {}}
            self._write_index_shard(shard, idx, layout=lo)
        # the superseded version (plain or multipart) -> deferred GC
        if old:
            self.gw.gc_enqueue(self._version_oids(key, old))
        return etag

    def apply_put(self, key: str, data: bytes,
                  metadata: Optional[Dict[str, str]], mtime: float,
                  origin: str) -> Optional[str]:
        """Sync-agent apply of a replicated put — put_object with the
        three cross-zone differences: the index entry keeps the
        SOURCE's mtime (last-writer-wins across zones compares source
        timestamps, not apply times), the bilog entry carries the
        ORIGIN zone (the reverse-direction agent suppresses the echo
        instead of ping-ponging the write back), and a strictly NEWER
        local entry wins (the post-failover overwrite case).  Returns
        the ETag, or None when the local entry won."""
        import secrets as _secrets
        etag = hashlib.md5(data).hexdigest()
        gen = _secrets.token_hex(4)
        lo = dict(self._layout())
        shard = self._shard_for_key(key, lo)
        with self.gw._index_lock(self.name, shard):
            idx = self._read_index_shard(shard, layout=lo)
            old = idx.get(key)
            if old and float(old.get("mtime", 0.0)) > mtime:
                return None            # local write is newer: keep it
            self._log_op("put", key, shard, gen=lo["index_gen"],
                         etag=etag, size=len(data), mtime=mtime,
                         origin=origin)
            self.gw.ioctx.write_full(self._data_oid(key, gen), data)
            idx[key] = {"size": len(data), "etag": etag, "gen": gen,
                        "mtime": mtime, "meta": metadata or {}}
            self._write_index_shard(shard, idx, layout=lo)
        if old:
            self.gw.gc_enqueue(self._version_oids(key, old))
        return etag

    def apply_delete(self, key: str, mtime: float,
                     origin: str) -> bool:
        """Sync-agent apply of a replicated delete (same LWW/origin
        contract as apply_put).  Returns False when there was nothing
        to delete or a newer local entry won."""
        lo = dict(self._layout())
        shard = self._shard_for_key(key, lo)
        with self.gw._index_lock(self.name, shard):
            idx = self._read_index_shard(shard, layout=lo)
            ent = idx.get(key)
            if ent is None or float(ent.get("mtime", 0.0)) > mtime:
                return False
            self._log_op("delete", key, shard, gen=lo["index_gen"],
                         mtime=mtime, origin=origin)
            del idx[key]
            self._write_index_shard(shard, idx, layout=lo)
        mp = ent.get("mp")
        if mp:
            self.gw.gc_enqueue(self._version_oids(key, ent))
            return True
        try:
            self.gw.ioctx.remove(self._data_oid(key,
                                                ent.get("gen", "")))
        except Exception:
            pass
        return True

    def _version_oids(self, key: str, ent: dict) -> List[str]:
        """Every data oid one index-entry version owns."""
        mp = ent.get("mp")
        if mp:
            return [self._mp_part_oid(mp["uid"], p["n"])
                    for p in mp["parts"]]
        return [self._data_oid(key, ent.get("gen", ""))]

    def get_object(self, key: str) -> Tuple[bytes, dict]:
        lo = dict(self._layout())
        ent = self._read_index_shard(
            self._shard_for_key(key, lo), layout=lo).get(key)
        if ent is None:
            # a miss through a TTL-stale handle reads a resharded-away
            # generation's (removed) index shard — refresh and retry
            # once before declaring absence, like the reference
            # client's ECANCELED + layout-refresh loop
            lo2 = dict(self._refresh_layout())
            if lo2 != lo:
                ent = self._read_index_shard(
                    self._shard_for_key(key, lo2),
                    layout=lo2).get(key)
        if ent is None:
            raise RGWError(f"NoSuchKey: {key}")
        mp = ent.get("mp")
        if mp:
            # multipart manifest: the object is striped across its
            # part objects (the RGW manifest role — completion never
            # copies bytes, rgw_op.h:1210 CompleteMultipart)
            chunks = []
            for p in mp["parts"]:
                raw = self._read_data(
                    self._mp_part_oid(mp["uid"], p["n"]),
                    "multipart part")
                chunks.append(raw[:p["size"]])
            return b"".join(chunks), ent
        data = self._read_data(
            self._data_oid(key, ent.get("gen", "")),
            "object data")[:ent["size"]]
        return data, ent

    def head_object(self, key: str) -> dict:
        lo = dict(self._layout())
        ent = self._read_index_shard(
            self._shard_for_key(key, lo), layout=lo).get(key)
        if ent is None:
            lo2 = dict(self._refresh_layout())
            if lo2 != lo:
                ent = self._read_index_shard(
                    self._shard_for_key(key, lo2),
                    layout=lo2).get(key)
        if ent is None:
            raise RGWError(f"NoSuchKey: {key}")
        return dict(ent)

    def delete_object(self, key: str) -> None:
        lo = dict(self._layout())
        shard = self._shard_for_key(key, lo)
        with self.gw._index_lock(self.name, shard):
            idx = self._read_index_shard(shard, layout=lo)
            if key not in idx:
                raise RGWError(f"NoSuchKey: {key}")
            ent = idx[key]
            # index entry first, then data: a crash leaves an orphan
            # data object (GC-able), never a dangling index entry
            self._log_op("delete", key, shard,   # log-ahead, like put
                         gen=lo["index_gen"])
            del idx[key]
            self._write_index_shard(shard, idx, layout=lo)
        mp = ent.get("mp")
        if mp:
            # multipart tails go through the DEFERRED-delete GC log
            # (rgw_gc.cc role): the delete acks now, space reclaims
            # on the next gc_process pass
            self.gw.gc_enqueue(self._version_oids(key, ent))
            return
        try:
            self.gw.ioctx.remove(self._data_oid(key,
                                                ent.get("gen", "")))
        except Exception:
            pass

    # --------------------------------------------------------- multipart --
    # Reference: InitMultipart / UploadPart / CompleteMultipart ops
    # (src/rgw/rgw_op.h:1210-1212).  Parts are RADOS objects; completion
    # writes a MANIFEST into the index (striped mapping, no byte copy).

    def _mp_meta_oid(self, uid: str) -> str:
        return f"rgw.mp.{self.name}/{uid}"

    def _mp_part_oid(self, uid: str, n: int) -> str:
        return f"rgw_mp.{self.name}/{uid}.{n}"

    def _read_mp(self, uid: str) -> dict:
        meta = _read_json(self.gw.ioctx, self._mp_meta_oid(uid),
                          None, "multipart meta")
        if meta is None:
            raise RGWError(f"NoSuchUpload: {uid}")
        return meta

    def initiate_multipart(self, key: str) -> str:
        import secrets as _secrets
        uid = _secrets.token_hex(8)
        self.gw.ioctx.write_full(
            self._mp_meta_oid(uid),
            json.dumps({"key": key, "parts": {},
                        "started": time.time()}).encode())
        return uid

    def upload_part(self, uid: str, part_number: int,
                    data: bytes) -> str:
        if part_number < 1 or part_number > 10000:
            raise RGWError(f"InvalidPart: number {part_number}")
        etag = hashlib.md5(data).hexdigest()
        self.gw.ioctx.write_full(self._mp_part_oid(uid, part_number),
                                 data)
        with self.gw._mp_lock:     # concurrent parts: RMW must not
            meta = self._read_mp(uid)          # lose registrations
            meta["parts"][str(part_number)] = {"size": len(data),
                                               "etag": etag}
            self.gw.ioctx.write_full(self._mp_meta_oid(uid),
                                     json.dumps(meta).encode())
        return etag

    def complete_multipart(self, uid: str,
                           part_numbers: List[int]) -> str:
        """Stitch the listed parts (ascending) into the object as a
        manifest; superseded/unlisted parts go to GC.  ETag follows
        the S3 multipart convention: md5(part-md5s) + '-N'."""
        meta = self._read_mp(uid)
        key = meta["key"]
        nums = [int(x) for x in part_numbers]
        if len(set(nums)) != len(nums):
            raise RGWError("InvalidPart: duplicate part numbers")
        parts = []
        digest = hashlib.md5()
        size = 0
        for n in sorted(nums):
            p = meta["parts"].get(str(n))
            if p is None:
                raise RGWError(f"InvalidPart: {n} was never uploaded")
            parts.append({"n": n, "size": p["size"],
                          "etag": p["etag"]})
            digest.update(bytes.fromhex(p["etag"]))
            size += p["size"]
        if not parts:
            raise RGWError("InvalidPart: empty part list")
        etag = f"{digest.hexdigest()}-{len(parts)}"
        lo = dict(self._layout())
        shard = self._shard_for_key(key, lo)
        with self.gw._index_lock(self.name, shard):
            self._log_op("put", key, shard, gen=lo["index_gen"],
                         etag=etag, size=size)
            idx = self._read_index_shard(shard, layout=lo)
            old = idx.get(key)
            idx[key] = {"size": size, "etag": etag,
                        "mtime": time.time(), "meta": {},
                        "mp": {"uid": uid, "parts": parts}}
            self._write_index_shard(shard, idx, layout=lo)
        # unlisted parts + any overwritten previous object -> GC
        listed = {p["n"] for p in parts}
        orphans = [self._mp_part_oid(uid, int(n))
                   for n in meta["parts"] if int(n) not in listed]
        if old:
            orphans += self._version_oids(key, old)
        if orphans:
            self.gw.gc_enqueue(orphans)
        try:
            self.gw.ioctx.remove(self._mp_meta_oid(uid))
        except Exception:
            pass
        return etag

    def abort_multipart(self, uid: str) -> int:
        """Abandon an upload: every uploaded part becomes a deferred
        GC entry (AbortMultipart -> rgw_gc.cc defer_gc shape)."""
        meta = self._read_mp(uid)
        oids = [self._mp_part_oid(uid, int(n)) for n in meta["parts"]]
        self.gw.gc_enqueue(oids)
        try:
            self.gw.ioctx.remove(self._mp_meta_oid(uid))
        except Exception:
            pass
        return len(oids)

    def list_objects(self, prefix: str = "", marker: str = "",
                     max_keys: int = 1000, delimiter: str = ""
                     ) -> Dict[str, object]:
        """S3 ListObjects semantics: sorted keys after ``marker``
        matching ``prefix``; with ``delimiter``, roll common prefixes."""
        idx = self._read_index()
        keys = sorted(k for k in idx
                      if k.startswith(prefix) and k > marker)
        contents: List[dict] = []
        common: List[str] = []
        last_seen = ""           # S3 NextMarker = last key RETURNED
        for k in keys:
            if delimiter:
                rest = k[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter, 1)[0] + delimiter
                    if cp not in common:
                        if len(contents) + len(common) >= max_keys:
                            return {"contents": contents,
                                    "common_prefixes": common,
                                    "is_truncated": True,
                                    "next_marker": last_seen}
                        common.append(cp)
                    last_seen = k
                    continue
            if len(contents) + len(common) >= max_keys:
                return {"contents": contents, "common_prefixes": common,
                        "is_truncated": True, "next_marker": last_seen}
            contents.append({"key": k, **idx[k]})
            last_seen = k
        return {"contents": contents, "common_prefixes": common,
                "is_truncated": False, "next_marker": ""}


_GC_OID = "rgw.gc"


class RGWGateway:
    """Bucket directory + per-bucket handles (the RGWRados role)."""

    def __init__(self, ioctx):
        self.ioctx = ioctx
        # serialize the shared-object read-modify-writes across the
        # frontend's request threads (gc log + per-upload multipart
        # meta; cross-PROCESS gateways would shard these like the
        # reference's gc/bucket-index objects)
        self._gc_lock = threading.Lock()
        self._mp_lock = threading.Lock()
        # per-(bucket, shard) index RMW locks: writers to ONE bucket
        # serialize per SHARD, so an N-shard hot bucket admits N
        # concurrent index writers (the whole point of sharding) —
        # and a reshard excludes every writer by taking all of them.
        # Pruned on delete_bucket so bucket churn cannot grow the
        # table forever
        self._index_locks: Dict[Tuple[str, int], threading.Lock] = {}
        self._index_locks_guard = threading.Lock()

    def _index_lock(self, bucket: str, shard: int):
        with self._index_locks_guard:
            lk = self._index_locks.get((bucket, shard))
            if lk is None:
                lk = self._index_locks[(bucket, shard)] = \
                    threading.Lock()
            return lk

    def _drop_index_locks(self, bucket: str) -> None:
        with self._index_locks_guard:
            for key in [k for k in self._index_locks
                        if k[0] == bucket]:
                del self._index_locks[key]

    # ------------------------------------------------------------------ GC --
    # Deferred-delete log (src/rgw/rgw_gc.cc): deletions of tail/part
    # objects enqueue here and reclaim on the next gc_process() pass,
    # so client-visible deletes never wait on data removal and orphan
    # cleanup is centralized.

    def _read_gc(self) -> List[dict]:
        # same taxonomy as the bucket index: a transient read error
        # treated as "empty log" would let the next gc_enqueue
        # OVERWRITE pending entries — leaked data objects
        return _read_json(self.ioctx, _GC_OID, [], "gc log")

    def gc_enqueue(self, oids: List[str],
                   delay: float = 0.0) -> None:
        with self._gc_lock:
            entries = self._read_gc()
            due = time.time() + delay
            entries.extend({"oid": o, "due": due} for o in oids)
            self.ioctx.write_full(_GC_OID,
                                  json.dumps(entries).encode())

    def gc_list(self) -> List[dict]:
        return self._read_gc()

    def gc_process(self, now: Optional[float] = None) -> int:
        """Remove every due entry's object; returns objects removed.
        Entries whose object is already gone still clear (idempotent
        across a crash mid-pass)."""
        now = time.time() if now is None else now
        with self._gc_lock:
            entries = self._read_gc()
            keep, removed = [], 0
            for e in entries:
                if e["due"] > now:
                    keep.append(e)
                    continue
                try:
                    self.ioctx.remove(e["oid"])
                    removed += 1
                except Exception:
                    pass      # already gone: entry still clears
            self.ioctx.write_full(_GC_OID, json.dumps(keep).encode())
        return removed

    def _read_buckets(self) -> Dict[str, dict]:
        return _read_json(self.ioctx, _BUCKETS_OID, {},
                          "bucket directory")

    def _write_buckets(self, d: Dict[str, dict]) -> None:
        self.ioctx.write_full(_BUCKETS_OID, json.dumps(d).encode())

    def create_bucket(self, name: str,
                      num_shards: int = 1) -> Bucket:
        if not name or "/" in name:
            raise RGWError(f"InvalidBucketName: {name!r}")
        if num_shards < 1:
            raise RGWError(f"InvalidArgument: num_shards "
                           f"{num_shards}")
        d = self._read_buckets()
        if name in d:
            raise RGWError(f"BucketAlreadyExists: {name}")
        # max_shards tracks the LARGEST layout this bucket ever had:
        # per-shard bilogs are keyed by shard number and survive a
        # shrink reshard, so deletion must sweep up to the high-water
        # mark, not the current count
        d[name] = {"created": time.time(),
                   "num_shards": int(num_shards), "index_gen": 0,
                   "max_shards": int(num_shards)}
        self._write_buckets(d)
        return Bucket(self, name,
                      layout={"num_shards": int(num_shards),
                              "index_gen": 0})

    def bucket(self, name: str) -> Bucket:
        ent = self._read_buckets().get(name)
        if ent is None:
            raise RGWError(f"NoSuchBucket: {name}")
        return Bucket(self, name, layout={
            "num_shards": int(ent.get("num_shards", 1)),
            "index_gen": int(ent.get("index_gen", 0))})

    def reshard_bucket(self, name: str,
                       num_shards: int) -> Dict[str, int]:
        """Online bucket reshard (the RGWBucketReshard role): copy
        the merged entries into a NEW generation of shard objects,
        commit the layout in the bucket directory, then drop the old
        generation.  In-process writers are excluded by holding every
        old-shard lock for the copy; cross-process handles land on
        the new layout within the layout TTL (their in-window writes
        go to unreferenced old-gen objects — orphans for GC, never
        corrupted new-gen shards)."""
        if num_shards < 1:
            raise RGWError(f"InvalidArgument: num_shards "
                           f"{num_shards}")
        d = self._read_buckets()
        ent = d.get(name)
        if ent is None:
            raise RGWError(f"NoSuchBucket: {name}")
        old_layout = {"num_shards": int(ent.get("num_shards", 1)),
                      "index_gen": int(ent.get("index_gen", 0))}
        b = Bucket(self, name, layout=old_layout)
        locks = [self._index_lock(name, s)
                 for s in range(old_layout["num_shards"])]
        for lk in locks:
            lk.acquire()
        try:
            merged = b._read_index()
            new_gen = old_layout["index_gen"] + 1
            new_layout = {"num_shards": int(num_shards),
                          "index_gen": new_gen}
            nb = Bucket(self, name, layout=new_layout)
            shards: List[Dict[str, dict]] = [
                {} for _ in range(num_shards)]
            for key, e in merged.items():
                shards[nb._shard_for_key(key)][key] = e
            for s, idx in enumerate(shards):
                nb._write_index_shard(s, idx)
            # END-MARK the outgoing generation's bilogs: under the
            # shard locks no writer can append, so each log's current
            # tail seq is its final entry.  The cutover record is
            # what lets a sync agent DRAIN the old generation to
            # these ends and switch — instead of a full-sync restart
            ends = []
            for s in range(old_layout["num_shards"]):
                j = b.bilog_for_shard(s, gen=old_layout["index_gen"])
                j._load_header()
                ends.append(j.seq - 1)
            # commit the layout AFTER the new shards exist: a crash
            # mid-copy leaves the old generation authoritative
            d = self._read_buckets()
            prev = d.get(name) or {}
            new_layout["max_shards"] = max(
                int(prev.get("max_shards",
                             old_layout["num_shards"])),
                int(num_shards))
            new_layout["log_gens"] = list(prev.get("log_gens", [])) + [
                {"gen": old_layout["index_gen"],
                 "num_shards": old_layout["num_shards"],
                 "ends": ends}]
            d[name] = dict(prev, **new_layout)
            self._write_buckets(d)
            # old generation -> gone (absent old-gen reads were never
            # possible: the record now names the new gen)
            for s in range(old_layout["num_shards"]):
                try:
                    self.ioctx.remove(
                        b._index_shard_oid(s, layout=old_layout))
                except Exception:
                    pass
            return {"bucket": name, "entries": len(merged),
                    "old_num_shards": old_layout["num_shards"],
                    "num_shards": int(num_shards),
                    "index_gen": new_gen}
        finally:
            for lk in locks:
                lk.release()

    def bucket_limit_check(self, max_entries_per_shard: int = 1000
                           ) -> List[Dict[str, object]]:
        """`radosgw-admin bucket limit check`: per-bucket per-shard
        entry counts with a fill verdict — OK under the warn line,
        WARN past 90% of ``max_entries_per_shard``, OVER past it (a
        hot shard is the reshard signal)."""
        out: List[Dict[str, object]] = []
        warn_at = 0.9 * max_entries_per_shard
        for name in self.list_buckets():
            counts = self.bucket(name).shard_entry_counts()
            hottest = max(counts) if counts else 0
            status = "OK"
            if hottest > max_entries_per_shard:
                status = "OVER"
            elif hottest >= warn_at:
                status = "WARN"
            out.append({"bucket": name, "num_shards": len(counts),
                        "shard_entries": counts,
                        "max_shard_entries": hottest,
                        "fill_status": status})
        return out

    def list_buckets(self) -> List[str]:
        return sorted(self._read_buckets())

    # --------------------------------------------- bilog retirement --
    # Old-generation bilogs are the ONLY copy of ops a peer zone has
    # not replicated yet: removing one before every registered zone
    # drained past its end markers is the lost-replication bug class.
    # Trim/retire is therefore drain-gated everywhere — the sync
    # agents call retire_drained_bilogs() after their passes, and
    # delete_bucket refuses while undrained entries remain.

    def _remove_bilog(self, b: Bucket, gen: int, shard: int) -> None:
        j = b.bilog_for_shard(shard, gen=gen)
        j._load_header()
        for idx_no in range(j.first, j.active + 1):
            try:
                self.ioctx.remove(j._obj_oid(idx_no))
            except Exception:
                pass
        try:
            self.ioctx.remove(j._header_oid())
        except Exception:
            pass

    def _gen_drained(self, name: str, gen: int, ends: List[int],
                     zones: Optional[List[str]] = None) -> bool:
        """True when every registered peer zone's sync cursor is past
        generation ``gen``'s end markers (no zones -> vacuously
        drained: nothing replicates this bucket)."""
        if zones is None:
            zones = _read_json(self.ioctx, zones_oid(name), [],
                               "zone set")
        return all(zone_drained_past(
            read_sync_state(self.ioctx, name, z), gen, ends)
            for z in zones)

    def retire_drained_bilogs(self, name: str) -> int:
        """Remove retired-generation bilogs every registered zone has
        drained past (and drop them from the bucket record's gen
        history); returns generations retired.  Undrained generations
        stay — they are replayable history, not garbage."""
        d = self._read_buckets()
        ent = d.get(name)
        if ent is None or not ent.get("log_gens"):
            return 0
        zones = _read_json(self.ioctx, zones_oid(name), [],
                           "zone set")
        b = Bucket(self, name,
                   layout={"num_shards": int(ent.get("num_shards", 1)),
                           "index_gen": int(ent.get("index_gen", 0))})
        keep, retired = [], 0
        for h in ent["log_gens"]:
            g = int(h["gen"])
            ends = [int(e) for e in h["ends"]]
            if self._gen_drained(name, g, ends, zones):
                for s in range(int(h["num_shards"])):
                    self._remove_bilog(b, g, s)
                retired += 1
            else:
                keep.append(h)
        if retired:
            d = self._read_buckets()
            cur = d.get(name)
            if cur is not None:
                cur["log_gens"] = keep
                self._write_buckets(d)
        return retired

    def delete_bucket(self, name: str, force: bool = False) -> None:
        d = self._read_buckets()
        if name not in d:
            raise RGWError(f"NoSuchBucket: {name}")
        ent = d[name]
        b = self.bucket(name)
        if b._read_index():
            raise RGWError(f"BucketNotEmpty: {name}")
        cur_gen = int(ent.get("index_gen", 0))
        cur_n = int(ent.get("num_shards", 1))
        zones = _read_json(self.ioctx, zones_oid(name), [],
                           "zone set")
        # every generation's logs, with the ACTIVE one end-marked at
        # its current tails (the bucket is empty, so its remaining
        # entries are the deletes peers still need to replicate)
        gens = [(int(h["gen"]), int(h["num_shards"]),
                 [int(e) for e in h["ends"]])
                for h in ent.get("log_gens", [])]
        cur_ends = []
        for s in range(cur_n):
            j = b.bilog_for_shard(s, gen=cur_gen)
            j._load_header()
            cur_ends.append(j.seq - 1)
        gens.append((cur_gen, cur_n, cur_ends))
        if zones and not force:
            for g, _n, ends in gens:
                if not self._gen_drained(name, g, ends, zones):
                    raise RGWError(
                        f"BucketNotDrained: {name} bilog gen {g} has "
                        f"entries no peer zone has synced yet — pump "
                        f"sync first, or force=True to accept the "
                        f"lost replication")
        for s in range(b.num_shards()):
            try:
                self.ioctx.remove(b._index_shard_oid(s))
            except Exception:
                pass
        for g, n, _ends in gens:
            for s in range(n):
                self._remove_bilog(b, g, s)
        # legacy sweep to the HIGH-WATER shard count: pre-generation
        # pools left shrink-reshard bilogs under plain gen-0 names
        max_shards = max(int(ent.get("max_shards", cur_n)), cur_n)
        for s in range(max_shards):
            self._remove_bilog(b, 0, s)
        # sync bookkeeping goes with the bucket (the drain gate above
        # already proved the markers were consumed or force waived)
        for z in zones:
            try:
                self.ioctx.remove(sync_state_oid(name, z))
            except Exception:
                pass
        try:
            self.ioctx.remove(zones_oid(name))
        except Exception:
            pass
        del d[name]
        self._write_buckets(d)
        self._drop_index_locks(name)
