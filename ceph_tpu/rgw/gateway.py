"""RGW slice — S3-shaped object gateway over RADOS.

The thin S3-object slice VERDICT r2 asked for (missing #8): the
src/rgw/ roles reduced to the storage shape rather than the 191k-LoC
HTTP/multisite stack:

  * a bucket's KEY INDEX lives in one index object per bucket (the
    bucket-index-over-omap role, src/rgw/driver/rados bucket index
    shards) — ordered key -> {size, etag, mtime} entries, updated
    after the data object lands (index consistency: a crash between
    data and index leaves an orphan data object, never a dangling
    index entry);
  * object DATA is one RADOS object per S3 key under the bucket's
    data prefix ("rgw_data.<bucket>_<key>");
  * S3 list semantics: lexicographic, prefix + marker + max_keys with
    truncation flag, and delimiter-based common prefixes;
  * ETag = MD5 hex of the payload (S3 compatibility contract).

No HTTP frontend here — the gateway API is the seam a REST layer
would call (the RGWOp layer's interface).
"""
from __future__ import annotations

import hashlib
import json
import time
from typing import Dict, List, Optional, Tuple

_BUCKETS_OID = "rgw.buckets"


class RGWError(IOError):
    pass


class Bucket:
    def __init__(self, gw: "RGWGateway", name: str):
        self.gw = gw
        self.name = name
        self._bilog = None

    @property
    def bilog(self):
        """Bucket index log (the RGW bilog role): every put/delete is
        recorded for multisite sync (rgw/sync.py replays it)."""
        if self._bilog is None:
            from ..fs.journaler import Journaler
            self._bilog = Journaler(self.gw.ioctx,
                                    f"rgw.bilog.{self.name}")
        return self._bilog

    def _log_op(self, op: str, key: str) -> None:
        # reload the journal header first: another live handle of this
        # bucket may have appended since ours cached its sequence — a
        # stale seq would duplicate and sync would drop the entry
        self.bilog._load_header()
        self.bilog.append(json.dumps({"op": op, "key": key}).encode())

    # ------------------------------------------------------------- index --
    def _index_oid(self) -> str:
        return f"rgw.index.{self.name}"

    def _read_index(self) -> Dict[str, dict]:
        try:
            return json.loads(self.gw.ioctx.read(self._index_oid())
                              .decode())
        except Exception:
            return {}

    def _write_index(self, idx: Dict[str, dict]) -> None:
        self.gw.ioctx.write_full(self._index_oid(),
                                 json.dumps(idx).encode())

    def _data_oid(self, key: str) -> str:
        # '/' is forbidden in bucket names (create_bucket validates),
        # so this join is collision-free across (bucket, key) pairs
        return f"rgw_data.{self.name}/{key}"

    # --------------------------------------------------------------- ops --
    def put_object(self, key: str, data: bytes,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        """-> ETag.  Data object first, index entry second."""
        etag = hashlib.md5(data).hexdigest()
        # bilog entry FIRST (the prepare-before-index-transaction
        # order): a crash between log and index leaves an entry whose
        # replay finds no object and skips — never a visible object
        # that multisite would silently miss
        self._log_op("put", key)
        self.gw.ioctx.write_full(self._data_oid(key), data)
        idx = self._read_index()
        idx[key] = {"size": len(data), "etag": etag,
                    "mtime": time.time(), "meta": metadata or {}}
        self._write_index(idx)
        return etag

    def get_object(self, key: str) -> Tuple[bytes, dict]:
        ent = self._read_index().get(key)
        if ent is None:
            raise RGWError(f"NoSuchKey: {key}")
        data = self.gw.ioctx.read(self._data_oid(key))[:ent["size"]]
        return data, ent

    def head_object(self, key: str) -> dict:
        ent = self._read_index().get(key)
        if ent is None:
            raise RGWError(f"NoSuchKey: {key}")
        return dict(ent)

    def delete_object(self, key: str) -> None:
        idx = self._read_index()
        if key not in idx:
            raise RGWError(f"NoSuchKey: {key}")
        # index entry first, then data: a crash leaves an orphan data
        # object (GC-able), never a dangling index entry
        self._log_op("delete", key)       # log-ahead, like put
        del idx[key]
        self._write_index(idx)
        try:
            self.gw.ioctx.remove(self._data_oid(key))
        except Exception:
            pass

    def list_objects(self, prefix: str = "", marker: str = "",
                     max_keys: int = 1000, delimiter: str = ""
                     ) -> Dict[str, object]:
        """S3 ListObjects semantics: sorted keys after ``marker``
        matching ``prefix``; with ``delimiter``, roll common prefixes."""
        idx = self._read_index()
        keys = sorted(k for k in idx
                      if k.startswith(prefix) and k > marker)
        contents: List[dict] = []
        common: List[str] = []
        last_seen = ""           # S3 NextMarker = last key RETURNED
        for k in keys:
            if delimiter:
                rest = k[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter, 1)[0] + delimiter
                    if cp not in common:
                        if len(contents) + len(common) >= max_keys:
                            return {"contents": contents,
                                    "common_prefixes": common,
                                    "is_truncated": True,
                                    "next_marker": last_seen}
                        common.append(cp)
                    last_seen = k
                    continue
            if len(contents) + len(common) >= max_keys:
                return {"contents": contents, "common_prefixes": common,
                        "is_truncated": True, "next_marker": last_seen}
            contents.append({"key": k, **idx[k]})
            last_seen = k
        return {"contents": contents, "common_prefixes": common,
                "is_truncated": False, "next_marker": ""}


class RGWGateway:
    """Bucket directory + per-bucket handles (the RGWRados role)."""

    def __init__(self, ioctx):
        self.ioctx = ioctx

    def _read_buckets(self) -> Dict[str, dict]:
        try:
            return json.loads(self.ioctx.read(_BUCKETS_OID).decode())
        except Exception:
            return {}

    def _write_buckets(self, d: Dict[str, dict]) -> None:
        self.ioctx.write_full(_BUCKETS_OID, json.dumps(d).encode())

    def create_bucket(self, name: str) -> Bucket:
        if not name or "/" in name:
            raise RGWError(f"InvalidBucketName: {name!r}")
        d = self._read_buckets()
        if name in d:
            raise RGWError(f"BucketAlreadyExists: {name}")
        d[name] = {"created": time.time()}
        self._write_buckets(d)
        return Bucket(self, name)

    def bucket(self, name: str) -> Bucket:
        if name not in self._read_buckets():
            raise RGWError(f"NoSuchBucket: {name}")
        return Bucket(self, name)

    def list_buckets(self) -> List[str]:
        return sorted(self._read_buckets())

    def delete_bucket(self, name: str) -> None:
        d = self._read_buckets()
        if name not in d:
            raise RGWError(f"NoSuchBucket: {name}")
        b = Bucket(self, name)
        if b._read_index():
            raise RGWError(f"BucketNotEmpty: {name}")
        try:
            self.ioctx.remove(b._index_oid())
        except Exception:
            pass
        # drop the bilog chain + header so a recreated bucket starts
        # with a fresh log (sync position objects are per-zone and
        # owned by their agents)
        j = b.bilog
        for idx_no in range(j.first, j.active + 1):
            try:
                self.ioctx.remove(j._obj_oid(idx_no))
            except Exception:
                pass
        try:
            self.ioctx.remove(j._header_oid())
        except Exception:
            pass
        del d[name]
        self._write_buckets(d)
