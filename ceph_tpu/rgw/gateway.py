"""RGW slice — S3-shaped object gateway over RADOS.

The thin S3-object slice VERDICT r2 asked for (missing #8): the
src/rgw/ roles reduced to the storage shape rather than the 191k-LoC
HTTP/multisite stack:

  * a bucket's KEY INDEX lives in N SHARD objects keyed by key-hash
    (the bucket-index-shard role, src/rgw/driver/rados
    rgw_bucket_index_... / cls_rgw over omap): each shard holds the
    ordered key -> {size, etag, mtime} entries whose keys hash to it,
    updated after the data object lands (index consistency: a crash
    between data and index leaves an orphan data object, never a
    dangling index entry).  Legacy buckets (num_shards == 1, gen 0)
    keep the original one-object-per-bucket oid, so pre-shard pools
    read unchanged.  One hot bucket no longer serializes every
    writer on a single index object: per-request ops touch ONLY the
    key's shard, under a per-(bucket, shard) RMW lock;
  * LISTING is a shard-merge: every shard is read once and the
    results merge-sorted — identical output for every shard count;
  * online ``reshard`` copies the merged entries into a new
    generation of shard objects and commits the layout in the bucket
    directory record (the RGWBucketReshard role);
  * object DATA is one RADOS object per S3 key under the bucket's
    data prefix ("rgw_data.<bucket>_<key>");
  * S3 list semantics: lexicographic, prefix + marker + max_keys with
    truncation flag, and delimiter-based common prefixes;
  * ETag = MD5 hex of the payload (S3 compatibility contract).

No HTTP frontend here — the gateway API is the seam a REST layer
would call (the RGWOp layer's interface).
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..common.backoff import ExpBackoff

_BUCKETS_OID = "rgw.buckets"


class RGWError(IOError):
    pass


def _read_json(ioctx, oid: str, default, what: str):
    """Read+decode one JSON metadata object (bucket index, bucket
    directory, GC log) with the failure taxonomy these objects NEED:

      * object absent -> ``default`` (a fresh bucket/log);
      * TRANSIENT IOError (degraded EC read mid-recovery, injected
        EIO, connection cut) -> bounded retry with ExpBackoff, then
        RAISE.  The old ``except Exception: return {}`` here was a
        lost-object bug under load, not a flake: one transient read
        error made a full bucket index read as EMPTY — spurious
        NoSuchKey on a GET, and the next index WRITE would rebuild
        from {} and silently orphan every existing object;
      * corrupt JSON -> raise (serving {} for a damaged index is the
        same data loss with less evidence).
    """
    # stable digest, NOT hash(): str hashing is salted per process
    # and would make retry jitter irreproducible across runs
    backoff = ExpBackoff(base=0.02, cap=0.25,
                         seed=zlib.crc32(oid.encode()) & 0xffff)
    last: Optional[Exception] = None
    for attempt in range(4):
        try:
            return json.loads(ioctx.read(oid).decode())
        except KeyError:
            # ObjectNotFound subclasses KeyError in both client tiers:
            # genuinely absent metadata means a fresh bucket/log
            return default
        except (IOError, OSError) as e:
            last = e
            if attempt < 3:
                backoff.sleep(attempt)
    raise RGWError(f"{what} {oid!r} unreadable after retries: {last}")


class Bucket:
    # how long a handle trusts its cached shard layout before
    # re-reading the bucket directory record: the window in which a
    # CROSS-PROCESS ``reshard`` is invisible to a live writer (gens
    # make a stale write land in an unreferenced old-gen object — an
    # orphan, never a corrupted new-gen shard).  In-process handles
    # of one gateway share the reshard lock and never race at all.
    _LAYOUT_TTL_S = 1.0

    def __init__(self, gw: "RGWGateway", name: str,
                 layout: Optional[Dict[str, int]] = None):
        self.gw = gw
        self.name = name
        self._bilogs: Dict[int, object] = {}
        self._layout_cache = dict(layout) if layout else None
        self._layout_ts = time.monotonic() if layout else 0.0

    # ------------------------------------------------------------ layout --
    def _layout(self) -> Dict[str, int]:
        """{"num_shards": N, "index_gen": g} from the bucket
        directory record, TTL-cached (an online reshard bumps the
        gen; other handles pick the new layout up within the TTL)."""
        now = time.monotonic()
        if self._layout_cache is None or \
                now - self._layout_ts > self._LAYOUT_TTL_S:
            ent = self.gw._read_buckets().get(self.name) or {}
            self._layout_cache = {
                "num_shards": int(ent.get("num_shards", 1)),
                "index_gen": int(ent.get("index_gen", 0))}
            self._layout_ts = now
        return self._layout_cache

    def num_shards(self) -> int:
        return self._layout()["num_shards"]

    def _shard_for_key(self, key: str,
                       layout: Optional[Dict[str, int]] = None
                       ) -> int:
        # stable digest, NOT hash(): shard placement must agree
        # across processes and runs (str hashing is salted)
        lo = layout or self._layout()
        return zlib.crc32(key.encode()) % lo["num_shards"]

    def bilog_for_shard(self, shard: int):
        """Per-shard bucket index log (the RGW bilog-per-shard role):
        every put/delete lands in its key's shard log.  Shard 0 keeps
        the legacy un-suffixed name so multisite sync (rgw/sync.py)
        replays single-shard buckets unchanged."""
        j = self._bilogs.get(shard)
        if j is None:
            from ..fs.journaler import Journaler
            suffix = "" if shard == 0 else f".{shard}"
            j = self._bilogs[shard] = Journaler(
                self.gw.ioctx, f"rgw.bilog.{self.name}{suffix}")
        return j

    @property
    def bilog(self):
        """Shard 0's bilog — the whole log for single-shard buckets
        (what rgw/sync.py replays; resharded buckets need a
        full-sync restart, as the reference's bilog reshard does)."""
        return self.bilog_for_shard(0)

    def _log_op(self, op: str, key: str, shard: int) -> None:
        # reload the journal header first: another live handle of this
        # bucket may have appended since ours cached its sequence — a
        # stale seq would duplicate and sync would drop the entry
        j = self.bilog_for_shard(shard)
        j._load_header()
        j.append(json.dumps({"op": op, "key": key}).encode())

    # ------------------------------------------------------------- index --
    def _index_shard_oid(self, shard: int,
                         layout: Optional[Dict[str, int]] = None
                         ) -> str:
        lo = layout or self._layout()
        if lo["num_shards"] == 1 and lo["index_gen"] == 0:
            # legacy single-object layout: pre-shard pools unchanged
            return f"rgw.index.{self.name}"
        return f"rgw.index.{self.name}.g{lo['index_gen']}.{shard}"

    def _read_index_shard(self, shard: int,
                          layout: Optional[Dict[str, int]] = None
                          ) -> Dict[str, dict]:
        return _read_json(self.gw.ioctx,
                          self._index_shard_oid(shard, layout), {},
                          f"bucket index shard {shard}")

    def _write_index_shard(self, shard: int, idx: Dict[str, dict],
                           layout: Optional[Dict[str, int]] = None
                           ) -> None:
        self.gw.ioctx.write_full(self._index_shard_oid(shard, layout),
                                 json.dumps(idx).encode())

    def _read_index(self) -> Dict[str, dict]:
        """The WHOLE index, merged across shards — the listing /
        reshard / admin surface, never a per-request path (lint
        CTL901 polices exactly that)."""
        lo = dict(self._layout())
        merged: Dict[str, dict] = {}
        for s in range(lo["num_shards"]):
            merged.update(self._read_index_shard(s, layout=lo))
        return merged

    def shard_entry_counts(self) -> List[int]:
        """Per-shard entry counts (`radosgw-admin bucket limit
        check`'s fill view)."""
        lo = dict(self._layout())
        return [len(self._read_index_shard(s, layout=lo))
                for s in range(lo["num_shards"])]

    # -------------------------------------------------------------- data --
    def _read_data(self, oid: str, what: str) -> bytes:
        """Data-object read with the bounded poll-budget retry the
        metadata reads already had (_read_json's taxonomy): the
        degraded-read window right after an OSD SIGKILL surfaces as
        TRANSIENT IOErrors while the map catches up — retry through
        it, then raise.  Genuine absence (KeyError) propagates: an
        indexed key whose data object is gone is an inconsistency
        the caller must see, not retry."""
        backoff = ExpBackoff(base=0.05, cap=0.5,
                             seed=zlib.crc32(oid.encode()) & 0xffff)
        last: Optional[Exception] = None
        for attempt in range(5):
            try:
                return self.gw.ioctx.read(oid)
            except KeyError:
                raise
            except (IOError, OSError) as e:
                last = e
                if attempt < 4:
                    backoff.sleep(attempt)
        raise RGWError(f"{what} {oid!r} unreadable after retries: "
                       f"{last}")

    def _data_oid(self, key: str, gen: str = "") -> str:
        # '/' is forbidden in bucket names (create_bucket validates),
        # so this join is collision-free across (bucket, key) pairs.
        # ``gen`` is the per-write generation token: data oids are
        # UNIQUE per object version, so a superseded version's oid can
        # sit in the deferred-GC log while the SAME KEY is rewritten —
        # GC can never reclaim live data (the RGW tail-object
        # generation role).
        return f"rgw_data.{self.name}/{key}.{gen}" if gen \
            else f"rgw_data.{self.name}/{key}"

    # --------------------------------------------------------------- ops --
    def put_object(self, key: str, data: bytes,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        """-> ETag.  Data object first, index entry second.  Only the
        KEY'S shard is read-modify-written, under that shard's lock —
        writers to a hot bucket serialize per shard, not per bucket."""
        import secrets as _secrets
        etag = hashlib.md5(data).hexdigest()
        gen = _secrets.token_hex(4)
        # ONE layout snapshot for the whole op: the shard NUMBER and
        # the oid GENERATION must come from the same layout, or a
        # TTL refresh mid-op could write the key into the wrong
        # new-gen shard (a stale snapshot only ever writes a dead
        # old-gen oid — an orphan, never corruption)
        lo = dict(self._layout())
        shard = self._shard_for_key(key, lo)
        # bilog entry FIRST (the prepare-before-index-transaction
        # order): a crash between log and index leaves an entry whose
        # replay finds no object and skips — never a visible object
        # that multisite would silently miss
        with self.gw._index_lock(self.name, shard):
            self._log_op("put", key, shard)
            self.gw.ioctx.write_full(self._data_oid(key, gen), data)
            idx = self._read_index_shard(shard, layout=lo)
            old = idx.get(key)
            idx[key] = {"size": len(data), "etag": etag, "gen": gen,
                        "mtime": time.time(), "meta": metadata or {}}
            self._write_index_shard(shard, idx, layout=lo)
        # the superseded version (plain or multipart) -> deferred GC
        if old:
            self.gw.gc_enqueue(self._version_oids(key, old))
        return etag

    def _version_oids(self, key: str, ent: dict) -> List[str]:
        """Every data oid one index-entry version owns."""
        mp = ent.get("mp")
        if mp:
            return [self._mp_part_oid(mp["uid"], p["n"])
                    for p in mp["parts"]]
        return [self._data_oid(key, ent.get("gen", ""))]

    def get_object(self, key: str) -> Tuple[bytes, dict]:
        lo = dict(self._layout())
        ent = self._read_index_shard(
            self._shard_for_key(key, lo), layout=lo).get(key)
        if ent is None:
            raise RGWError(f"NoSuchKey: {key}")
        mp = ent.get("mp")
        if mp:
            # multipart manifest: the object is striped across its
            # part objects (the RGW manifest role — completion never
            # copies bytes, rgw_op.h:1210 CompleteMultipart)
            chunks = []
            for p in mp["parts"]:
                raw = self._read_data(
                    self._mp_part_oid(mp["uid"], p["n"]),
                    "multipart part")
                chunks.append(raw[:p["size"]])
            return b"".join(chunks), ent
        data = self._read_data(
            self._data_oid(key, ent.get("gen", "")),
            "object data")[:ent["size"]]
        return data, ent

    def head_object(self, key: str) -> dict:
        lo = dict(self._layout())
        ent = self._read_index_shard(
            self._shard_for_key(key, lo), layout=lo).get(key)
        if ent is None:
            raise RGWError(f"NoSuchKey: {key}")
        return dict(ent)

    def delete_object(self, key: str) -> None:
        lo = dict(self._layout())
        shard = self._shard_for_key(key, lo)
        with self.gw._index_lock(self.name, shard):
            idx = self._read_index_shard(shard, layout=lo)
            if key not in idx:
                raise RGWError(f"NoSuchKey: {key}")
            ent = idx[key]
            # index entry first, then data: a crash leaves an orphan
            # data object (GC-able), never a dangling index entry
            self._log_op("delete", key, shard)   # log-ahead, like put
            del idx[key]
            self._write_index_shard(shard, idx, layout=lo)
        mp = ent.get("mp")
        if mp:
            # multipart tails go through the DEFERRED-delete GC log
            # (rgw_gc.cc role): the delete acks now, space reclaims
            # on the next gc_process pass
            self.gw.gc_enqueue(self._version_oids(key, ent))
            return
        try:
            self.gw.ioctx.remove(self._data_oid(key,
                                                ent.get("gen", "")))
        except Exception:
            pass

    # --------------------------------------------------------- multipart --
    # Reference: InitMultipart / UploadPart / CompleteMultipart ops
    # (src/rgw/rgw_op.h:1210-1212).  Parts are RADOS objects; completion
    # writes a MANIFEST into the index (striped mapping, no byte copy).

    def _mp_meta_oid(self, uid: str) -> str:
        return f"rgw.mp.{self.name}/{uid}"

    def _mp_part_oid(self, uid: str, n: int) -> str:
        return f"rgw_mp.{self.name}/{uid}.{n}"

    def _read_mp(self, uid: str) -> dict:
        meta = _read_json(self.gw.ioctx, self._mp_meta_oid(uid),
                          None, "multipart meta")
        if meta is None:
            raise RGWError(f"NoSuchUpload: {uid}")
        return meta

    def initiate_multipart(self, key: str) -> str:
        import secrets as _secrets
        uid = _secrets.token_hex(8)
        self.gw.ioctx.write_full(
            self._mp_meta_oid(uid),
            json.dumps({"key": key, "parts": {},
                        "started": time.time()}).encode())
        return uid

    def upload_part(self, uid: str, part_number: int,
                    data: bytes) -> str:
        if part_number < 1 or part_number > 10000:
            raise RGWError(f"InvalidPart: number {part_number}")
        etag = hashlib.md5(data).hexdigest()
        self.gw.ioctx.write_full(self._mp_part_oid(uid, part_number),
                                 data)
        with self.gw._mp_lock:     # concurrent parts: RMW must not
            meta = self._read_mp(uid)          # lose registrations
            meta["parts"][str(part_number)] = {"size": len(data),
                                               "etag": etag}
            self.gw.ioctx.write_full(self._mp_meta_oid(uid),
                                     json.dumps(meta).encode())
        return etag

    def complete_multipart(self, uid: str,
                           part_numbers: List[int]) -> str:
        """Stitch the listed parts (ascending) into the object as a
        manifest; superseded/unlisted parts go to GC.  ETag follows
        the S3 multipart convention: md5(part-md5s) + '-N'."""
        meta = self._read_mp(uid)
        key = meta["key"]
        nums = [int(x) for x in part_numbers]
        if len(set(nums)) != len(nums):
            raise RGWError("InvalidPart: duplicate part numbers")
        parts = []
        digest = hashlib.md5()
        size = 0
        for n in sorted(nums):
            p = meta["parts"].get(str(n))
            if p is None:
                raise RGWError(f"InvalidPart: {n} was never uploaded")
            parts.append({"n": n, "size": p["size"],
                          "etag": p["etag"]})
            digest.update(bytes.fromhex(p["etag"]))
            size += p["size"]
        if not parts:
            raise RGWError("InvalidPart: empty part list")
        etag = f"{digest.hexdigest()}-{len(parts)}"
        lo = dict(self._layout())
        shard = self._shard_for_key(key, lo)
        with self.gw._index_lock(self.name, shard):
            self._log_op("put", key, shard)
            idx = self._read_index_shard(shard, layout=lo)
            old = idx.get(key)
            idx[key] = {"size": size, "etag": etag,
                        "mtime": time.time(), "meta": {},
                        "mp": {"uid": uid, "parts": parts}}
            self._write_index_shard(shard, idx, layout=lo)
        # unlisted parts + any overwritten previous object -> GC
        listed = {p["n"] for p in parts}
        orphans = [self._mp_part_oid(uid, int(n))
                   for n in meta["parts"] if int(n) not in listed]
        if old:
            orphans += self._version_oids(key, old)
        if orphans:
            self.gw.gc_enqueue(orphans)
        try:
            self.gw.ioctx.remove(self._mp_meta_oid(uid))
        except Exception:
            pass
        return etag

    def abort_multipart(self, uid: str) -> int:
        """Abandon an upload: every uploaded part becomes a deferred
        GC entry (AbortMultipart -> rgw_gc.cc defer_gc shape)."""
        meta = self._read_mp(uid)
        oids = [self._mp_part_oid(uid, int(n)) for n in meta["parts"]]
        self.gw.gc_enqueue(oids)
        try:
            self.gw.ioctx.remove(self._mp_meta_oid(uid))
        except Exception:
            pass
        return len(oids)

    def list_objects(self, prefix: str = "", marker: str = "",
                     max_keys: int = 1000, delimiter: str = ""
                     ) -> Dict[str, object]:
        """S3 ListObjects semantics: sorted keys after ``marker``
        matching ``prefix``; with ``delimiter``, roll common prefixes."""
        idx = self._read_index()
        keys = sorted(k for k in idx
                      if k.startswith(prefix) and k > marker)
        contents: List[dict] = []
        common: List[str] = []
        last_seen = ""           # S3 NextMarker = last key RETURNED
        for k in keys:
            if delimiter:
                rest = k[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter, 1)[0] + delimiter
                    if cp not in common:
                        if len(contents) + len(common) >= max_keys:
                            return {"contents": contents,
                                    "common_prefixes": common,
                                    "is_truncated": True,
                                    "next_marker": last_seen}
                        common.append(cp)
                    last_seen = k
                    continue
            if len(contents) + len(common) >= max_keys:
                return {"contents": contents, "common_prefixes": common,
                        "is_truncated": True, "next_marker": last_seen}
            contents.append({"key": k, **idx[k]})
            last_seen = k
        return {"contents": contents, "common_prefixes": common,
                "is_truncated": False, "next_marker": ""}


_GC_OID = "rgw.gc"


class RGWGateway:
    """Bucket directory + per-bucket handles (the RGWRados role)."""

    def __init__(self, ioctx):
        self.ioctx = ioctx
        # serialize the shared-object read-modify-writes across the
        # frontend's request threads (gc log + per-upload multipart
        # meta; cross-PROCESS gateways would shard these like the
        # reference's gc/bucket-index objects)
        self._gc_lock = threading.Lock()
        self._mp_lock = threading.Lock()
        # per-(bucket, shard) index RMW locks: writers to ONE bucket
        # serialize per SHARD, so an N-shard hot bucket admits N
        # concurrent index writers (the whole point of sharding) —
        # and a reshard excludes every writer by taking all of them.
        # Pruned on delete_bucket so bucket churn cannot grow the
        # table forever
        self._index_locks: Dict[Tuple[str, int], threading.Lock] = {}
        self._index_locks_guard = threading.Lock()

    def _index_lock(self, bucket: str, shard: int):
        with self._index_locks_guard:
            lk = self._index_locks.get((bucket, shard))
            if lk is None:
                lk = self._index_locks[(bucket, shard)] = \
                    threading.Lock()
            return lk

    def _drop_index_locks(self, bucket: str) -> None:
        with self._index_locks_guard:
            for key in [k for k in self._index_locks
                        if k[0] == bucket]:
                del self._index_locks[key]

    # ------------------------------------------------------------------ GC --
    # Deferred-delete log (src/rgw/rgw_gc.cc): deletions of tail/part
    # objects enqueue here and reclaim on the next gc_process() pass,
    # so client-visible deletes never wait on data removal and orphan
    # cleanup is centralized.

    def _read_gc(self) -> List[dict]:
        # same taxonomy as the bucket index: a transient read error
        # treated as "empty log" would let the next gc_enqueue
        # OVERWRITE pending entries — leaked data objects
        return _read_json(self.ioctx, _GC_OID, [], "gc log")

    def gc_enqueue(self, oids: List[str],
                   delay: float = 0.0) -> None:
        with self._gc_lock:
            entries = self._read_gc()
            due = time.time() + delay
            entries.extend({"oid": o, "due": due} for o in oids)
            self.ioctx.write_full(_GC_OID,
                                  json.dumps(entries).encode())

    def gc_list(self) -> List[dict]:
        return self._read_gc()

    def gc_process(self, now: Optional[float] = None) -> int:
        """Remove every due entry's object; returns objects removed.
        Entries whose object is already gone still clear (idempotent
        across a crash mid-pass)."""
        now = time.time() if now is None else now
        with self._gc_lock:
            entries = self._read_gc()
            keep, removed = [], 0
            for e in entries:
                if e["due"] > now:
                    keep.append(e)
                    continue
                try:
                    self.ioctx.remove(e["oid"])
                    removed += 1
                except Exception:
                    pass      # already gone: entry still clears
            self.ioctx.write_full(_GC_OID, json.dumps(keep).encode())
        return removed

    def _read_buckets(self) -> Dict[str, dict]:
        return _read_json(self.ioctx, _BUCKETS_OID, {},
                          "bucket directory")

    def _write_buckets(self, d: Dict[str, dict]) -> None:
        self.ioctx.write_full(_BUCKETS_OID, json.dumps(d).encode())

    def create_bucket(self, name: str,
                      num_shards: int = 1) -> Bucket:
        if not name or "/" in name:
            raise RGWError(f"InvalidBucketName: {name!r}")
        if num_shards < 1:
            raise RGWError(f"InvalidArgument: num_shards "
                           f"{num_shards}")
        d = self._read_buckets()
        if name in d:
            raise RGWError(f"BucketAlreadyExists: {name}")
        # max_shards tracks the LARGEST layout this bucket ever had:
        # per-shard bilogs are keyed by shard number and survive a
        # shrink reshard, so deletion must sweep up to the high-water
        # mark, not the current count
        d[name] = {"created": time.time(),
                   "num_shards": int(num_shards), "index_gen": 0,
                   "max_shards": int(num_shards)}
        self._write_buckets(d)
        return Bucket(self, name,
                      layout={"num_shards": int(num_shards),
                              "index_gen": 0})

    def bucket(self, name: str) -> Bucket:
        ent = self._read_buckets().get(name)
        if ent is None:
            raise RGWError(f"NoSuchBucket: {name}")
        return Bucket(self, name, layout={
            "num_shards": int(ent.get("num_shards", 1)),
            "index_gen": int(ent.get("index_gen", 0))})

    def reshard_bucket(self, name: str,
                       num_shards: int) -> Dict[str, int]:
        """Online bucket reshard (the RGWBucketReshard role): copy
        the merged entries into a NEW generation of shard objects,
        commit the layout in the bucket directory, then drop the old
        generation.  In-process writers are excluded by holding every
        old-shard lock for the copy; cross-process handles land on
        the new layout within the layout TTL (their in-window writes
        go to unreferenced old-gen objects — orphans for GC, never
        corrupted new-gen shards)."""
        if num_shards < 1:
            raise RGWError(f"InvalidArgument: num_shards "
                           f"{num_shards}")
        d = self._read_buckets()
        ent = d.get(name)
        if ent is None:
            raise RGWError(f"NoSuchBucket: {name}")
        old_layout = {"num_shards": int(ent.get("num_shards", 1)),
                      "index_gen": int(ent.get("index_gen", 0))}
        b = Bucket(self, name, layout=old_layout)
        locks = [self._index_lock(name, s)
                 for s in range(old_layout["num_shards"])]
        for lk in locks:
            lk.acquire()
        try:
            merged = b._read_index()
            new_gen = old_layout["index_gen"] + 1
            new_layout = {"num_shards": int(num_shards),
                          "index_gen": new_gen}
            nb = Bucket(self, name, layout=new_layout)
            shards: List[Dict[str, dict]] = [
                {} for _ in range(num_shards)]
            for key, e in merged.items():
                shards[nb._shard_for_key(key)][key] = e
            for s, idx in enumerate(shards):
                nb._write_index_shard(s, idx)
            # commit the layout AFTER the new shards exist: a crash
            # mid-copy leaves the old generation authoritative
            d = self._read_buckets()
            prev = d.get(name) or {}
            new_layout["max_shards"] = max(
                int(prev.get("max_shards",
                             old_layout["num_shards"])),
                int(num_shards))
            d[name] = dict(prev, **new_layout)
            self._write_buckets(d)
            # old generation -> gone (absent old-gen reads were never
            # possible: the record now names the new gen)
            for s in range(old_layout["num_shards"]):
                try:
                    self.ioctx.remove(
                        b._index_shard_oid(s, layout=old_layout))
                except Exception:
                    pass
            return {"bucket": name, "entries": len(merged),
                    "old_num_shards": old_layout["num_shards"],
                    "num_shards": int(num_shards),
                    "index_gen": new_gen}
        finally:
            for lk in locks:
                lk.release()

    def bucket_limit_check(self, max_entries_per_shard: int = 1000
                           ) -> List[Dict[str, object]]:
        """`radosgw-admin bucket limit check`: per-bucket per-shard
        entry counts with a fill verdict — OK under the warn line,
        WARN past 90% of ``max_entries_per_shard``, OVER past it (a
        hot shard is the reshard signal)."""
        out: List[Dict[str, object]] = []
        warn_at = 0.9 * max_entries_per_shard
        for name in self.list_buckets():
            counts = self.bucket(name).shard_entry_counts()
            hottest = max(counts) if counts else 0
            status = "OK"
            if hottest > max_entries_per_shard:
                status = "OVER"
            elif hottest >= warn_at:
                status = "WARN"
            out.append({"bucket": name, "num_shards": len(counts),
                        "shard_entries": counts,
                        "max_shard_entries": hottest,
                        "fill_status": status})
        return out

    def list_buckets(self) -> List[str]:
        return sorted(self._read_buckets())

    def delete_bucket(self, name: str) -> None:
        d = self._read_buckets()
        if name not in d:
            raise RGWError(f"NoSuchBucket: {name}")
        b = self.bucket(name)
        if b._read_index():
            raise RGWError(f"BucketNotEmpty: {name}")
        for s in range(b.num_shards()):
            try:
                self.ioctx.remove(b._index_shard_oid(s))
            except Exception:
                pass
        # drop every shard's bilog chain + header so a recreated
        # bucket starts with fresh logs (sync position objects are
        # per-zone and owned by their agents).  Sweep to the
        # HIGH-WATER shard count: bilogs are keyed by shard number
        # and a shrink reshard leaves the higher shards' logs behind
        max_shards = max(int(d[name].get("max_shards",
                                         b.num_shards())),
                         b.num_shards())
        for s in range(max_shards):
            j = b.bilog_for_shard(s)
            for idx_no in range(j.first, j.active + 1):
                try:
                    self.ioctx.remove(j._obj_oid(idx_no))
                except Exception:
                    pass
            try:
                self.ioctx.remove(j._header_oid())
            except Exception:
                pass
        del d[name]
        self._write_buckets(d)
        self._drop_index_locks(name)
