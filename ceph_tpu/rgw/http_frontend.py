"""S3 HTTP frontend for the RGW gateway slice.

The REST layer of src/rgw (beast frontend + RGWOp handlers) reduced to
the S3 object-API core so stock HTTP clients can drive the gateway:

    PUT    /<bucket>                 create bucket
    DELETE /<bucket>                 delete bucket (must be empty)
    GET    /                         ListAllMyBucketsResult XML
    GET    /<bucket>?prefix&marker&max-keys&delimiter
                                     ListBucketResult XML
    PUT    /<bucket>/<key>           put object (ETag header returned)
    GET    /<bucket>/<key>           object bytes (+ ETag)
    HEAD   /<bucket>/<key>           metadata only
    DELETE /<bucket>/<key>           delete object

Errors use the S3 XML error envelope with the gateway's error codes
(NoSuchBucket, NoSuchKey, BucketAlreadyExists, BucketNotEmpty).
"""
from __future__ import annotations

import http.server
import threading
import urllib.parse
from typing import Optional, Tuple
from xml.sax.saxutils import escape

from .gateway import RGWError, RGWGateway


def _err_xml(code: str, message: str) -> bytes:
    return (f"<?xml version='1.0'?><Error><Code>{escape(code)}</Code>"
            f"<Message>{escape(message)}</Message></Error>").encode()


_STATUS = {"NoSuchBucket": 404, "NoSuchKey": 404,
           "BucketAlreadyExists": 409, "BucketNotEmpty": 409,
           "InvalidBucketName": 400, "NoSuchUpload": 404,
           "InvalidPart": 400, "AccessDenied": 403,
           "InvalidAccessKeyId": 403, "SignatureDoesNotMatch": 403}


class S3Frontend:
    def __init__(self, gateway: RGWGateway,
                 users: Optional[dict] = None):
        """``users``: access_key -> {"secret":…, "user":…}.  When set,
        every request must carry a valid SigV4-shaped signature
        (rgw_auth_s3 role); None = auth disabled (dev mode, like
        rgw_auth anonymous)."""
        self.gw = gateway
        self.users = users
        self._server: Optional[http.server.ThreadingHTTPServer] = None

    # --------------------------------------------------------------- ops --
    def start(self, port: int = 0) -> int:
        fe = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _split(self) -> Tuple[str, str, dict]:
                parsed = urllib.parse.urlparse(self.path)
                parts = parsed.path.strip("/").split("/", 1)
                bucket = urllib.parse.unquote(parts[0]) if parts[0] else ""
                key = urllib.parse.unquote(parts[1]) \
                    if len(parts) > 1 else ""
                q = {k: v[0] for k, v in
                     urllib.parse.parse_qs(
                         parsed.query,
                         keep_blank_values=True).items()}
                return bucket, key, q

            def _send(self, status: int, body: bytes = b"",
                      ctype: str = "application/xml", etag: str = None,
                      head_only: bool = False, extra: dict = None):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                if etag:
                    self.send_header("ETag", f'"{etag}"')
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                if not head_only and body:
                    self.wfile.write(body)

            def _fail(self, e: Exception, head_only=False):
                code = str(e).split(":", 1)[0]
                self._send(_STATUS.get(code, 400),
                           _err_xml(code, str(e)), head_only=head_only)

            def _authenticate(self, body: bytes,
                              head_only: bool = False) -> bool:
                """SigV4 verification against the frontend's user set
                (True = proceed).  Anonymous requests are refused when
                auth is enabled.  The verified uid is bound as this
                request thread's TENANT on the gateway's cluster
                handle, so every RADOS op this request issues
                dispatches under the tenant's own dmClock class (the
                S3-auth -> objecter -> op-dispatch QoS plumbing)."""
                from .auth_s3 import S3AuthError, verify_request
                rc = getattr(fe.gw.ioctx, "_rc", None)
                if rc is not None and hasattr(rc, "set_tenant"):
                    # clear any binding a previous request left on
                    # this pooled server thread
                    rc.set_tenant(None, thread_only=True)
                if fe.users is None:
                    return True
                parsed = urllib.parse.urlparse(self.path)
                try:
                    uid = verify_request(self.command, parsed.path,
                                         parsed.query,
                                         dict(self.headers.items()),
                                         body, fe.users)
                    if rc is not None and hasattr(rc, "set_tenant"):
                        rc.set_tenant(uid, thread_only=True)
                    return True
                except S3AuthError as e:
                    self._fail(e, head_only=head_only)
                    return False

            def _body(self) -> bytes:
                ln = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(ln) if ln else b""

            def do_PUT(self):             # noqa: N802
                bucket, key, q = self._split()
                body = self._body()
                if not self._authenticate(body):
                    return
                try:
                    if not key:
                        fe.gw.create_bucket(bucket)
                        self._send(200)
                    elif "uploadId" in q:
                        etag = fe.gw.bucket(bucket).upload_part(
                            q["uploadId"], int(q.get("partNumber", 0)),
                            body)
                        self._send(200, etag=etag)
                    else:
                        meta = {k[11:]: v for k, v in
                                self.headers.items()
                                if k.lower().startswith("x-amz-meta-")}
                        etag = fe.gw.bucket(bucket).put_object(
                            key, body, metadata=meta or None)
                        self._send(200, etag=etag)
                except RGWError as e:
                    self._fail(e)

            def do_POST(self):            # noqa: N802
                bucket, key, q = self._split()
                body = self._body()
                if not self._authenticate(body):
                    return
                try:
                    if "uploads" in q:
                        uid = fe.gw.bucket(bucket).initiate_multipart(
                            key)
                        xml = ("<?xml version='1.0'?>"
                               "<InitiateMultipartUploadResult>"
                               f"<Bucket>{escape(bucket)}</Bucket>"
                               f"<Key>{escape(key)}</Key>"
                               f"<UploadId>{uid}</UploadId>"
                               "</InitiateMultipartUploadResult>")
                        self._send(200, xml.encode())
                    elif "uploadId" in q:
                        import re
                        nums = [int(n) for n in re.findall(
                            r"<PartNumber>(\d+)</PartNumber>",
                            body.decode(errors="replace"))]
                        etag = fe.gw.bucket(bucket).complete_multipart(
                            q["uploadId"], nums)
                        xml = ("<?xml version='1.0'?>"
                               "<CompleteMultipartUploadResult>"
                               f"<Key>{escape(key)}</Key>"
                               f"<ETag>&quot;{etag}&quot;</ETag>"
                               "</CompleteMultipartUploadResult>")
                        self._send(200, xml.encode(), etag=etag)
                    else:
                        self._send(400, _err_xml(
                            "InvalidRequest", "unsupported POST"))
                except RGWError as e:
                    self._fail(e)

            def do_GET(self, head_only=False):    # noqa: N802
                bucket, key, q = self._split()
                if not self._authenticate(b"", head_only=head_only):
                    return
                try:
                    if not bucket:
                        names = fe.gw.list_buckets()
                        xml = ("<?xml version='1.0'?>"
                               "<ListAllMyBucketsResult><Buckets>" +
                               "".join(f"<Bucket><Name>{escape(n)}"
                                       "</Name></Bucket>"
                                       for n in names) +
                               "</Buckets></ListAllMyBucketsResult>")
                        self._send(200, xml.encode(),
                                   head_only=head_only)
                    elif not key:
                        r = fe.gw.bucket(bucket).list_objects(
                            prefix=q.get("prefix", ""),
                            marker=q.get("marker", ""),
                            max_keys=int(q.get("max-keys", 1000)),
                            delimiter=q.get("delimiter", ""))
                        xml = ["<?xml version='1.0'?><ListBucketResult>",
                               f"<Name>{escape(bucket)}</Name>",
                               "<IsTruncated>" +
                               str(r["is_truncated"]).lower() +
                               "</IsTruncated>"]
                        if r["next_marker"]:
                            xml.append("<NextMarker>" +
                                       escape(r["next_marker"]) +
                                       "</NextMarker>")
                        for c in r["contents"]:
                            xml.append(
                                f"<Contents><Key>{escape(c['key'])}"
                                f"</Key><Size>{c['size']}</Size>"
                                f"<ETag>&quot;{c['etag']}&quot;</ETag>"
                                "</Contents>")
                        for cp in r["common_prefixes"]:
                            xml.append("<CommonPrefixes><Prefix>" +
                                       escape(cp) +
                                       "</Prefix></CommonPrefixes>")
                        xml.append("</ListBucketResult>")
                        self._send(200, "".join(xml).encode(),
                                   head_only=head_only)
                    else:
                        data, ent = fe.gw.bucket(bucket).get_object(key)
                        extra = {f"x-amz-meta-{k}": v for k, v in
                                 ent.get("meta", {}).items()}
                        self._send(200, data,
                                   ctype="application/octet-stream",
                                   etag=ent["etag"],
                                   head_only=head_only, extra=extra)
                except RGWError as e:
                    self._fail(e, head_only=head_only)

            def do_HEAD(self):            # noqa: N802
                self.do_GET(head_only=True)

            def do_DELETE(self):          # noqa: N802
                bucket, key, q = self._split()
                if not self._authenticate(b""):
                    return
                try:
                    if key and "uploadId" in q:
                        fe.gw.bucket(bucket).abort_multipart(
                            q["uploadId"])
                        self._send(204)
                    elif key:
                        fe.gw.bucket(bucket).delete_object(key)
                        self._send(204)
                    else:
                        fe.gw.delete_bucket(bucket)
                        self._send(204)
                except RGWError as e:
                    self._fail(e)

            def log_message(self, *a):
                pass

        self._server = http.server.ThreadingHTTPServer(("127.0.0.1",
                                                        port), Handler)
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        return self._server.server_address[1]

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
