"""Object gateway layer (src/rgw/ role)."""
from .gateway import Bucket, RGWError, RGWGateway  # noqa: F401
from .sync import BucketSyncAgent, make_sync_engine  # noqa: F401
from .users import UserError, UserStore  # noqa: F401
from .zone import (Period, PeriodSync, Realm, RealmError,  # noqa: F401
                   Zone, ZoneGroup)
