"""Object gateway layer (src/rgw/ role)."""
from .gateway import Bucket, RGWError, RGWGateway  # noqa: F401
