"""S3Serve — the multi-tenant S3 serving subsystem (ROADMAP item 3).

The millions-of-users serving benchmark, scaled to fit any box: many
concurrent S3 clients per tenant drive the RGW gateway over LIVE OSD
daemons through the AsyncObjecter wire core, with seeded zipfian key
popularity and a mixed GET/PUT/DELETE/multipart op profile per
tenant.  Three contracts distinguish this from a load generator:

  * **SLOs are a GATE, not a report**: per-tenant p99/p999 latency is
    read from the mon's cluster-wide bucket-merged histograms (the
    PR-10 ClusterStats merge — the harness ships its per-tenant op
    histograms up the same report_perf path every daemon uses) and
    the run EXITS NONZERO on a breach, with a per-tenant breach
    report.  Falsifiable by construction: a deliberately starved
    config (``--starve``) must fail.
  * **per-tenant QoS, end to end**: each tenant's identity starts as
    an S3 SigV4 verification (auth_s3), binds to the tenant's
    cluster handle (RemoteCluster.set_tenant), rides every wire
    request the async objecter submits, and lands the op in the
    tenant's OWN dmClock class inside each OSD
    (osd_mclock_scheduler_client_* / the spec's qos_tenants table).
    The gate asserts the reserved tenant kept its completed-op share
    — a noisy tenant must not push a reserved tenant below its
    r floor.
  * **chaos composes**: ``--chaos`` runs the SAME workload while a
    seeded scheduler composes all three thrashers' fault shapes —
    OSD kill/revive, ``net.partition`` netsplits armed over the
    daemons' admin sockets, and power-loss browns (device.power_loss
    + WAL tail tear + reboot, the PR-9 pipeline).  The gate relaxes
    the latency SLOs by ``chaos_slo_factor`` but adds a HARD
    invariant: zero acked-write loss (every single-writer key reads
    back with its acked ETag after heal).

Hot buckets don't serialize: the bucket is created with N index
shards (gateway.py), so concurrent writers RMW distinct shard
objects under distinct locks.

``ceph serve`` (tools/ceph_cli.py) builds a self-contained vstart
cluster, runs the harness, prints the per-tenant report, and exits
with the gate's verdict — the operator-facing serving benchmark.
"""
from __future__ import annotations

import bisect
import hashlib
import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..common.perf_counters import perf as _perf

_PERF_GROUP = "s3.serve"


# --------------------------------------------------------------- zipf --

class ZipfKeys:
    """Seeded zipfian key-popularity sampler.

    Rank r (0-based) is drawn with weight 1/(r+1)**theta — the
    classic zipf law web-object popularity follows (theta ~0.99 in
    the CDN literature; PAPERS 1709.05365 characterizes online-EC
    under exactly this shape).  Deterministic: the same (n, theta,
    seed) produces the identical index sequence, which is what makes
    a serving soak a regression test instead of an anecdote.
    """

    def __init__(self, n: int, theta: float = 0.99, seed: int = 0):
        if n < 1:
            raise ValueError(f"need n >= 1 keys, got {n}")
        self.n = int(n)
        self.theta = float(theta)
        self._rng = random.Random(seed)
        cum: List[float] = []
        total = 0.0
        for r in range(self.n):
            total += 1.0 / ((r + 1) ** self.theta)
            cum.append(total)
        self._cum = cum
        self._total = total

    def next_index(self) -> int:
        """The next key rank: 0 is the hottest key."""
        x = self._rng.random() * self._total
        return bisect.bisect_left(self._cum, x)


# ------------------------------------------------------------- config --

@dataclass
class TenantSpec:
    """One tenant's load + QoS + SLO contract."""
    name: str
    clients: int = 4                  # concurrent closed-loop workers
    ops: int = 120                    # this tenant's op budget
    # op mix (fractions; multipart is initiate+parts+complete)
    get_frac: float = 0.55
    put_frac: float = 0.30
    delete_frac: float = 0.10
    multipart_frac: float = 0.05
    object_kib: int = 4
    n_keys: int = 48                  # tenant keyspace size
    zipf_theta: float = 0.99
    # dmClock class parameters shipped to every OSD (qos_tenants)
    qos_res: float = 0.2
    qos_wgt: float = 1.0
    qos_lim: float = 0.0              # 0 = unlimited
    # ---- the gate ----
    slo_p99_s: float = 5.0
    slo_p999_s: float = 10.0
    min_share: float = 0.0            # completed-op share floor
    max_error_frac: float = 0.0       # failed ops / attempted


@dataclass
class ServeConfig:
    seed: int = 0
    n_osds: int = 4
    osds_per_host: int = 1            # must divide n_osds (the crush
    # map materializes hosts*per_host OSD slots; a slot with no
    # daemon would draw placements)
    pg_num: int = 16
    index_shards: int = 8             # bucket index shards
    bucket: str = "serve"
    tenants: List[TenantSpec] = field(default_factory=list)
    # ---- chaos composition ----
    chaos: bool = False
    chaos_events: int = 3             # >= one of each kind
    chaos_hold_s: float = 1.5         # partition/kill hold per event
    chaos_slo_factor: float = 20.0    # latency SLO relaxation
    # transient op-failure budget under chaos (a GET inside a kill/
    # cut window can exhaust its bounded retries — that is a
    # degraded-window error, not data loss; loss stays a HARD zero)
    chaos_error_budget: float = 0.10
    hb_interval: float = 0.25
    wait_ticks: int = 240             # bounded state polls (0.25 s)


def default_tenants(starve: bool = False) -> List[TenantSpec]:
    """The stock 3-tenant profile: a RESERVED tenant (gold) with an
    r floor and a completed-op share SLO, a plain tenant (silver),
    and a NOISY tenant (bronze) with a big weight, no reservation
    and a larger budget.  ``starve=True`` builds the falsifiability
    config: gold loses its reservation and almost all weight and
    runs one client against a tripled noisy fleet, while its share
    floor stays — the gate MUST fail it."""
    if starve:
        return [
            TenantSpec("gold", clients=1, ops=60,
                       qos_res=0.0, qos_wgt=0.01,
                       min_share=0.25, slo_p99_s=5.0),
            TenantSpec("bronze", clients=12, ops=360,
                       qos_res=0.0, qos_wgt=8.0),
        ]
    return [
        TenantSpec("gold", clients=4, ops=120,
                   qos_res=0.4, qos_wgt=2.0,
                   min_share=0.10, slo_p99_s=5.0),
        TenantSpec("silver", clients=4, ops=120,
                   qos_res=0.2, qos_wgt=1.0),
        TenantSpec("bronze", clients=8, ops=200,
                   qos_res=0.0, qos_wgt=8.0),
    ]


def draw_op(t: TenantSpec, widx: int, rng: random.Random,
            zipf: ZipfKeys) -> Tuple[str, str]:
    """One seeded (op, key) draw — THE schedule the workers run, as
    a pure function so determinism is testable against the exact
    production draw: zipfian rank over the tenant keyspace, then the
    tenant's op mix.  Mutations clamp the rank into worker
    ``widx``'s slice (rank % clients == widx), so every key has ONE
    writer and acked-write oracles are exact under concurrency."""
    rank = zipf.next_index()
    x = rng.random()
    if x < t.get_frac:
        op = "get"
    elif x < t.get_frac + t.put_frac:
        op = "put"
    elif x < t.get_frac + t.put_frac + t.delete_frac:
        op = "delete"
    else:
        op = "multipart"
    if op != "get":
        rank = rank - rank % t.clients + widx
        if rank >= t.n_keys:
            # keyspace edge: wrap to the slice's FIRST member, never
            # modulo (a plain % n_keys would break the rank-mod-
            # clients congruence and hand the key a second writer)
            rank = widx
    if op == "multipart":
        return op, f"{t.name}-mp{rank:05d}"
    return op, f"{t.name}-k{rank:05d}"


def worker_rngs(seed: int, t: TenantSpec, widx: int
                ) -> Tuple[random.Random, ZipfKeys]:
    """The (op rng, zipf sampler) pair worker ``widx`` of tenant
    ``t`` runs under — seeded from (run seed, tenant, worker), so a
    run's whole op schedule is a pure function of the seed."""
    return (random.Random((seed, t.name, widx, "ops").__repr__()),
            ZipfKeys(t.n_keys, t.zipf_theta,
                     seed=f"{seed}/{t.name}/{widx}"))


# ---------------------------------------------------------------- gate --

def evaluate_gate(per_tenant: Dict[str, Dict[str, Any]],
                  tenants: Sequence[TenantSpec],
                  slo_factor: float = 1.0,
                  data_loss: Optional[List[str]] = None,
                  error_budget: Optional[float] = None
                  ) -> List[Dict[str, Any]]:
    """The SLO/QoS gate, pure and unit-testable: per-tenant measured
    {p99_s, p999_s, share, ops, errors, attempted} against each
    tenant's contract (latency bounds scaled by ``slo_factor`` — the
    chaos relaxation; ``error_budget`` likewise floors the per-tenant
    error allowance for degraded windows, while data loss stays a
    hard zero).  Returns the breach list; empty = green."""
    breaches: List[Dict[str, Any]] = []
    for t in tenants:
        m = per_tenant.get(t.name) or {}
        p99 = m.get("p99_s")
        p999 = m.get("p999_s")
        if p99 is not None and p99 > t.slo_p99_s * slo_factor:
            breaches.append({
                "tenant": t.name, "metric": "p99_s",
                "got": p99, "bound": t.slo_p99_s * slo_factor})
        if p999 is not None and p999 > t.slo_p999_s * slo_factor:
            breaches.append({
                "tenant": t.name, "metric": "p999_s",
                "got": p999, "bound": t.slo_p999_s * slo_factor})
        if t.min_share > 0.0:
            share = float(m.get("share") or 0.0)
            if share < t.min_share:
                breaches.append({
                    "tenant": t.name, "metric": "share",
                    "got": round(share, 4), "bound": t.min_share})
        attempted = int(m.get("attempted") or 0)
        if attempted:
            bound = t.max_error_frac
            if error_budget is not None:
                bound = max(bound, error_budget)
            frac = float(m.get("errors") or 0) / attempted
            if frac > bound:
                breaches.append({
                    "tenant": t.name, "metric": "error_frac",
                    "got": round(frac, 4), "bound": bound})
    for loss in (data_loss or []):
        breaches.append({"tenant": "*", "metric": "data_loss",
                         "got": loss, "bound": "zero acked-write "
                                               "loss"})
    return breaches


# -------------------------------------------------------------- harness --

class S3ServeHarness:
    """One serving run over a LIVE vstart cluster directory.

    The cluster must already be running (``serve_main`` builds its
    own; tests may reuse a fixture cluster).  Tenant QoS classes are
    loaded by the daemons from the cluster spec's ``qos_tenants``
    table at boot — ``write_qos_spec`` amends the spec before daemon
    start."""

    def __init__(self, cluster_dir: str, cfg: ServeConfig,
                 vstart=None):
        self.dir = cluster_dir
        self.cfg = cfg
        self.v = vstart                # Vstart handle (chaos needs it)
        self.tenants = cfg.tenants or default_tenants()
        self._stop = threading.Event()
        # chaos runs gate the measurement window on the SCHEDULE
        # completing, not just the op budgets: every composed fault
        # shape must fire under live traffic
        self._chaos_done = threading.Event()
        if not cfg.chaos:
            self._chaos_done.set()
        self._counts_lock = threading.Lock()
        # tenant -> {"ops": completed, "errors": n, "attempted": n}
        self.counts: Dict[str, Dict[str, int]] = {
            t.name: {"ops": 0, "errors": 0, "attempted": 0}
            for t in self.tenants}
        # single-writer oracle: (tenant, key) -> acked etag (puts by
        # worker w touch only key ranks where rank % clients == w, so
        # each key has exactly one writer and the oracle is exact)
        self._oracle_lock = threading.Lock()
        self.oracle: Dict[Tuple[str, str], str] = {}
        self.failures: List[str] = []
        self.chaos_log: List[Tuple] = []
        self._rcs: List[Any] = []

    # ------------------------------------------------------------ setup --
    @staticmethod
    def write_qos_spec(cluster_dir: str,
                       tenants: Sequence[TenantSpec]) -> None:
        """Amend cluster.json with the tenants' dmClock classes —
        run BEFORE daemon start (daemons load the table at boot)."""
        path = os.path.join(cluster_dir, "cluster.json")
        spec = json.load(open(path))
        spec["qos_tenants"] = {
            t.name: {"res": t.qos_res, "wgt": t.qos_wgt,
                     "lim": t.qos_lim} for t in tenants}
        json.dump(spec, open(path, "w"))

    def _make_tenant_client(self, t: TenantSpec, users) -> Any:
        """One authenticated cluster handle per tenant: create the
        S3 user, run a real SigV4 sign/verify round (auth_s3 — the
        identity is what the SIGNATURE proves, not a caller claim),
        and bind the verified uid as the handle's tenant."""
        from ..client.remote import RemoteCluster
        from .auth_s3 import sign_request, verify_request
        from .users import UserError
        try:
            rec = users.create(t.name)
        except UserError as e:
            if not str(e).startswith("UserAlreadyExists"):
                raise
            # back-to-back runs on one cluster (the chaos seeds, a
            # re-entered bench): the tenant keeps its credentials
            rec = users.info(t.name)
        ak = rec["keys"][0]["access_key"]
        sk = rec["keys"][0]["secret_key"]
        headers = {"host": "s3.serve"}
        headers.update(sign_request(
            "GET", "/", "", dict(headers), b"", ak, sk))
        uid = verify_request("GET", "/", "", headers, b"",
                             {ak: {"secret": sk, "user": t.name}})
        rc = RemoteCluster(self.dir)
        rc.set_tenant(uid)
        self._rcs.append(rc)
        return rc

    # ------------------------------------------------------------ worker --
    def _blob(self, rng: random.Random, n: int) -> bytes:
        return random.Random(rng.getrandbits(32)).randbytes(n)

    def _worker(self, t: TenantSpec, widx: int, bucket) -> None:
        """One closed-loop S3 client: seeded op draws over a zipfian
        tenant keyspace until the tenant's op budget (or the run)
        ends.  Mutations stay inside this worker's key slice
        (single-writer oracle); GETs roam the whole tenant keyspace
        and verify payload-vs-ETag integrity."""
        from .gateway import RGWError
        cfg = self.cfg
        rng, zipf = worker_rngs(cfg.seed, t, widx)
        pc = _perf(_PERF_GROUP)
        nbytes = t.object_kib << 10
        while not self._stop.is_set():
            with self._counts_lock:
                c = self.counts[t.name]
                if c["ops"] >= t.ops and \
                        self._chaos_done.is_set():
                    # budget burned: the first tenant to finish ends
                    # the measurement window for everyone (shares
                    # compare the same wall interval).  Under chaos
                    # the budget is a FLOOR — traffic keeps flowing
                    # until the whole fault schedule has run
                    self._stop.set()
                    break
                c["attempted"] += 1
            op, key = draw_op(t, widx, rng, zipf)
            t0 = time.perf_counter()
            ok = True
            try:
                if op == "get":
                    try:
                        data, ent = bucket.get_object(key)
                    except RGWError as e:
                        if not str(e).startswith("NoSuchKey"):
                            raise
                        # a key never written (or deleted): a
                        # legitimate miss, not an error
                    else:
                        if "mp" not in ent and ent["etag"] != \
                                hashlib.md5(data).hexdigest():
                            ok = False
                            self.failures.append(
                                f"{key}: payload/ETag mismatch")
                elif op == "put":
                    data = self._blob(rng, nbytes)
                    etag = bucket.put_object(key, data)
                    with self._oracle_lock:
                        self.oracle[(t.name, key)] = etag
                elif op == "delete":
                    try:
                        bucket.delete_object(key)
                    except RGWError as e:
                        if not str(e).startswith("NoSuchKey"):
                            raise
                    with self._oracle_lock:
                        self.oracle.pop((t.name, key), None)
                else:
                    uid = bucket.initiate_multipart(key)
                    parts = []
                    for n in (1, 2):
                        bucket.upload_part(
                            uid, n, self._blob(rng, nbytes // 2))
                        parts.append(n)
                    bucket.complete_multipart(uid, parts)
            except Exception as e:                 # noqa: CTL603 —
                # the soak's whole point: an op failure is COUNTED
                # and gated (max_error_frac), never silently retried
                # into a green report
                ok = False
                if op in ("put", "delete"):
                    # a mutation that FAILED after possibly
                    # committing its index entry (e.g. put's GC
                    # enqueue raising after the index write) leaves
                    # the key's state AMBIGUOUS — it made no ack, so
                    # it claims nothing: drop it from the oracle
                    # rather than let a stale etag read as loss
                    with self._oracle_lock:
                        self.oracle.pop((t.name, key), None)
                self.failures.append(
                    f"{t.name}/{op} {key}: {type(e).__name__}: {e}")
            dt = time.perf_counter() - t0
            pc.hinc(f"tenant.{t.name}.op_s", dt)
            pc.hinc(f"tenant.{t.name}.{op}_s", dt)
            pc.inc(f"tenant.{t.name}.{op}_ops")
            with self._counts_lock:
                c = self.counts[t.name]
                c["ops"] += 1
                if not ok:
                    c["errors"] += 1

    # ------------------------------------------------------------- chaos --
    def _asok(self, osd: int) -> str:
        return os.path.join(self.dir, f"osd.{osd}.asok")

    def _wait(self, fn, desc: str) -> bool:
        for _ in range(self.cfg.wait_ticks):
            try:
                if fn():
                    return True
            except (OSError, IOError):
                pass
            time.sleep(0.25)
        self.failures.append(f"wait-for-state timed out: {desc}")
        return False

    def _arm_all(self, req: Dict[str, Any]) -> int:
        """fault_injection over every OSD asok; -> how many answered
        (a dead daemon's socket is skipped, exactly like the
        operator's sweep)."""
        from ..common.admin import admin_request
        n = 0
        for o in range(self.cfg.n_osds):
            try:
                admin_request(self._asok(o), req)
                n += 1
            except (OSError, IOError):
                continue
        return n

    def _chaos_driver(self, rc, rng: random.Random) -> None:
        """The composed thrasher: while the serving load runs, one
        seeded schedule interleaves all three fault shapes — the
        first scenario that runs kill + netsplit + powercycle under
        real traffic.  Every event heals before the next starts (the
        workload must survive each shape, not an unbounded pileup)."""
        from ..common.admin import admin_request
        from ..cluster.crashdev import tear_wal_tail
        cfg = self.cfg
        kinds = ["kill", "netsplit", "powercycle"]
        extra = [kinds[rng.randrange(3)]
                 for _ in range(max(0, cfg.chaos_events - 3))]
        schedule = kinds + extra
        rng.shuffle(schedule)
        for i, kind in enumerate(schedule):
            victim = rng.randrange(cfg.n_osds)
            self.chaos_log.append((kind, victim))
            if kind == "kill":
                self.v.kill9(f"osd.{victim}")
                time.sleep(cfg.chaos_hold_s)
                self.v.start_osd(victim,
                                 hb_interval=cfg.hb_interval)
                self._wait(lambda: self.v.alive(f"osd.{victim}"),
                           f"osd.{victim} revived")
            elif kind == "netsplit":
                minority = [f"osd.{victim}"]
                majority = ["mon", "mon.0", "client",
                            "client.admin"] + [
                    f"osd.{o}" for o in range(cfg.n_osds)
                    if o != victim]
                self._arm_all({
                    "prefix": "fault_injection", "action": "arm",
                    "name": "net.partition",
                    "params": {"groups": [minority, majority],
                               "oneway": False}})
                time.sleep(cfg.chaos_hold_s)
                self._arm_all({
                    "prefix": "fault_injection", "action": "disarm",
                    "name": "net.partition"})
            else:                                  # powercycle
                try:
                    admin_request(self._asok(victim), {
                        "prefix": "fault_injection", "action": "arm",
                        "name": "device.power_loss",
                        "mode": "one_in", "n": 2,
                        "seed": cfg.seed * 100 + i,
                        "params": {"exit": True}})
                except (OSError, IOError):
                    pass
                deadline = time.monotonic() + cfg.chaos_hold_s * 4
                while time.monotonic() < deadline and \
                        self.v.alive(f"osd.{victim}"):
                    time.sleep(0.1)
                if self.v.alive(f"osd.{victim}"):
                    # traffic never hit the victim's store barrier:
                    # SIGKILL keeps the soak moving
                    self.v.kill9(f"osd.{victim}")
                tear_wal_tail(
                    os.path.join(self.dir, f"osd.{victim}.store"),
                    rng)
                self.v.start_osd(victim,
                                 hb_interval=cfg.hb_interval)
                self._wait(lambda: self.v.alive(f"osd.{victim}"),
                           f"osd.{victim} rebooted")
            try:
                rc.refresh_map()
            except (OSError, IOError):
                pass
        self._chaos_done.set()

    # --------------------------------------------------------------- run --
    def run(self) -> Dict[str, Any]:
        from ..client.remote import RemoteCluster
        from ..client.remote_ioctx import RemoteIoCtx
        from .gateway import RGWGateway
        from .users import UserStore
        cfg = self.cfg
        if cfg.chaos and self.v is None:
            raise ValueError("chaos runs need the Vstart handle that "
                             "owns the daemons (kill/revive uses its "
                             "process registry)")
        _perf(_PERF_GROUP).reset()
        rc_admin = RemoteCluster(self.dir)
        self._rcs.append(rc_admin)
        io_admin = RemoteIoCtx(rc_admin, "rep")
        users = UserStore(io_admin)
        gw_admin = RGWGateway(io_admin)
        # one BUCKET per tenant (the S3 tenancy shape), each with N
        # index shards, served through the tenant's OWN authenticated
        # cluster handle: every RADOS op a tenant's workers issue
        # carries that tenant's identity.  A tenant's concurrent
        # writers arbitrate through its gateway's per-shard locks;
        # index RMW across gateway PROCESSES is outside the client-
        # side-RMW contract (RemoteIoCtx's documented caveat — the
        # reference serializes shard updates server-side in cls_rgw)
        buckets: Dict[str, Any] = {}
        for t in self.tenants:
            gw_admin.create_bucket(f"{cfg.bucket}-{t.name}",
                                   num_shards=cfg.index_shards)
            rc = self._make_tenant_client(t, users)
            buckets[t.name] = RGWGateway(
                RemoteIoCtx(rc, "rep")).bucket(
                f"{cfg.bucket}-{t.name}")
        t_start = time.perf_counter()
        threads: List[threading.Thread] = []
        for t in self.tenants:
            for w in range(t.clients):
                th = threading.Thread(
                    target=self._worker,
                    args=(t, w, buckets[t.name]),
                    name=f"serve-{t.name}-{w}", daemon=True)
                th.start()
                threads.append(th)
        chaos_th = None
        if cfg.chaos:
            chaos_th = threading.Thread(
                target=self._chaos_driver,
                args=(rc_admin, random.Random(cfg.seed)),
                name="serve-chaos", daemon=True)
            chaos_th.start()
        for th in threads:
            th.join()
        self._stop.set()
        if chaos_th is not None:
            chaos_th.join()
        wall_s = time.perf_counter() - t_start
        data_loss: List[str] = []
        if cfg.chaos:
            data_loss = self._heal_and_verify(rc_admin, buckets)
        report = self._report(rc_admin, wall_s, data_loss)
        for rc in self._rcs:
            try:
                rc.close()
            except Exception:
                pass
        return report

    def _heal_and_verify(self, rc, buckets) -> List[str]:
        """Settle after chaos: disarm everything, everyone up,
        recover, then the zero-acked-write-loss readback — every
        single-writer oracle key must GET with its acked ETag."""
        self._arm_all({"prefix": "fault_injection",
                       "action": "disarm"})
        self._wait(lambda: rc.status()["n_up"] == self.cfg.n_osds,
                   "all OSDs up at settle")
        try:
            rc.refresh_map()
            rc.recover_pool(1)
        except (OSError, IOError) as e:
            self.failures.append(f"settle recovery failed: {e}")
        loss: List[str] = []
        from .gateway import RGWError
        with self._oracle_lock:
            oracle = dict(self.oracle)
        for (tname, key), etag in sorted(oracle.items()):
            try:
                data, ent = buckets[tname].get_object(key)
            except (RGWError, IOError, OSError) as e:
                loss.append(f"{tname}/{key}: unreadable after heal "
                            f"({e})")
                continue
            if ent["etag"] != etag:
                loss.append(f"{tname}/{key}: acked write lost "
                            f"(etag {ent['etag']} != acked {etag})")
        return loss

    def _sched_shares(self, rc) -> Dict[str, Any]:
        """Per-tenant dmClock dequeue counts summed across the live
        OSDs (`status` -> scheduler stats): the daemon-side evidence
        that tenant classes really dispatched — and in what shares."""
        from ..msg.scheduler import TENANT_PREFIX
        per_class: Dict[str, int] = {}
        for o in range(self.cfg.n_osds):
            try:
                st = rc.osd_call(o, {"cmd": "status"})
            except (OSError, IOError):
                continue
            for klass, n in (st.get("scheduler") or {}).get(
                    "dequeued", {}).items():
                per_class[klass] = per_class.get(klass, 0) + int(n)
        tenant_total = sum(n for k, n in per_class.items()
                           if k.startswith(TENANT_PREFIX))
        shares = {}
        for k, n in sorted(per_class.items()):
            if k.startswith(TENANT_PREFIX) and tenant_total:
                shares[k[len(TENANT_PREFIX):]] = round(
                    n / tenant_total, 4)
        return {"dequeued": per_class, "tenant_shares": shares}

    def _report(self, rc, wall_s: float,
                data_loss: List[str]) -> Dict[str, Any]:
        cfg = self.cfg
        # ship this process's per-tenant histograms up the SAME
        # report_perf path every daemon uses, then read the SLO
        # numbers back from the mon's bucket-merged cluster view —
        # the PR-10 histogram merge is the single source of truth
        try:
            rc.mon_call({"cmd": "report_perf", "report": {
                "perf": _perf().dump_typed(), "util": {},
                "ts": time.time()}})
            quant = rc.mon_call({"cmd": "cluster_stats"})["quantiles"]
        except (OSError, IOError) as e:
            self.failures.append(f"cluster_stats unreadable: {e}")
            quant = {}
        total_ops = sum(c["ops"] for c in self.counts.values()) or 1
        per_tenant: Dict[str, Dict[str, Any]] = {}
        for t in self.tenants:
            q = quant.get(f"{_PERF_GROUP}.tenant.{t.name}.op_s") or {}
            c = self.counts[t.name]
            per_tenant[t.name] = {
                "ops": c["ops"],
                "attempted": c["attempted"],
                "errors": c["errors"],
                "ops_s": round(c["ops"] / wall_s, 2) if wall_s
                else 0.0,
                "share": round(c["ops"] / total_ops, 4),
                "p50_s": q.get("p50"), "p99_s": q.get("p99"),
                "p999_s": q.get("p999"),
                "samples": q.get("count", 0),
            }
        slo_factor = cfg.chaos_slo_factor if cfg.chaos else 1.0
        breaches = evaluate_gate(
            per_tenant, self.tenants, slo_factor=slo_factor,
            data_loss=data_loss,
            error_budget=cfg.chaos_error_budget if cfg.chaos
            else None)
        sched = self._sched_shares(rc)
        return {
            "seed": cfg.seed,
            "chaos": cfg.chaos,
            "chaos_log": [list(e) for e in self.chaos_log],
            "index_shards": cfg.index_shards,
            "wall_s": round(wall_s, 3),
            "total_ops": total_ops,
            "ops_s": round(total_ops / wall_s, 2) if wall_s else 0.0,
            "tenants": per_tenant,
            "scheduler": sched,
            "slo_factor": slo_factor,
            "breaches": breaches,
            "data_loss": data_loss,
            "op_failures": self.failures[:20],
            "ok": not breaches,
        }


# ------------------------------------------------------------ ceph serve --

def serve_main(argv: Optional[Sequence[str]] = None,
               out=None) -> int:
    """`ceph serve [--seed N --chaos --starve --json ...]`: build a
    self-contained vstart cluster (like `ceph thrash --powercycle`),
    run the serving workload, print the per-tenant report, exit with
    the SLO/QoS gate's verdict (nonzero on any breach)."""
    import argparse
    import sys
    out = out or sys.stdout
    argv = list(argv or [])
    if "--dr" in argv:
        # `ceph serve --dr`: the two-zone disaster-recovery drill
        # (sever -> failover -> heal -> convergence gate) — same
        # serving-shaped workload, different harness
        from ..cluster.dr_drill import drill_main
        argv.remove("--dr")
        return drill_main(argv, out=out)
    ap = argparse.ArgumentParser(
        prog="ceph serve",
        description="multi-tenant S3 serving workload with an "
                    "enforced SLO/QoS gate (S3Serve)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--osds", type=int, default=4)
    ap.add_argument("--shards", type=int, default=8,
                    help="bucket index shards")
    ap.add_argument("--ops-scale", type=float, default=1.0,
                    help="scale every tenant's op budget")
    ap.add_argument("--clients-scale", type=float, default=1.0,
                    help="scale every tenant's worker count (drive "
                         "hundreds of concurrent clients)")
    ap.add_argument("--starve", action="store_true",
                    help="the falsifiability config: the reserved "
                         "tenant loses its reservation and weight — "
                         "the gate MUST exit nonzero with a breach "
                         "report")
    ap.add_argument("--chaos", action="store_true",
                    help="compose kill + netsplit + powercycle under "
                         "the serving load (SLO-relaxed, zero "
                         "acked-write loss enforced)")
    ap.add_argument("--json", action="store_true")
    ns = ap.parse_args(argv)
    tenants = default_tenants(starve=ns.starve)
    for t in tenants:
        t.ops = max(10, int(t.ops * ns.ops_scale))
        t.clients = max(1, int(t.clients * ns.clients_scale))
    cfg = ServeConfig(seed=ns.seed, n_osds=ns.osds,
                      index_shards=ns.shards, tenants=tenants,
                      chaos=ns.chaos)
    report = run_serve(cfg)
    if ns.json:
        out.write(json.dumps(report, indent=2, sort_keys=True,
                             default=str) + "\n")
    else:
        out.write(
            f"serve seed={report['seed']} shards="
            f"{report['index_shards']} chaos={report['chaos']}: "
            f"{report['total_ops']} ops in {report['wall_s']}s "
            f"({report['ops_s']} op/s)\n")
        for name, m in sorted(report["tenants"].items()):
            out.write(
                f"  {name}: {m['ops']} ops ({m['ops_s']} op/s, "
                f"share {m['share']}), p50={m['p50_s']} "
                f"p99={m['p99_s']} p999={m['p999_s']} "
                f"errors={m['errors']}\n")
        if report["scheduler"]["tenant_shares"]:
            out.write(f"  dmClock tenant dispatch shares: "
                      f"{report['scheduler']['tenant_shares']}\n")
        for b in report["breaches"]:
            out.write(f"BREACH: tenant {b['tenant']} {b['metric']} "
                      f"= {b['got']} (bound {b['bound']})\n")
        out.write("SLO gate: " +
                  ("PASS\n" if report["ok"] else "FAIL\n"))
    return 0 if report["ok"] else 1


def run_serve(cfg: ServeConfig, cluster_dir: Optional[str] = None,
              vstart=None) -> Dict[str, Any]:
    """Build (or reuse) a cluster and run one harness pass.  With
    ``cluster_dir`` the caller owns the daemons, must have written
    the qos spec before starting them, and must pass its own Vstart
    handle for chaos runs (kill/revive needs the process registry)."""
    from ..tools.vstart import Vstart, build_cluster_dir
    tenants = cfg.tenants or default_tenants()
    cfg.tenants = tenants
    if cluster_dir is not None:
        h = S3ServeHarness(cluster_dir, cfg, vstart=vstart)
        return h.run()
    import shutil
    import tempfile
    tmp = tempfile.mkdtemp(prefix="ceph-serve-")
    d = os.path.join(tmp, "cluster")
    try:
        build_cluster_dir(
            d, n_osds=cfg.n_osds, osds_per_host=cfg.osds_per_host,
            fsync=cfg.chaos,
            pools=[{"id": 1, "name": "rep", "type": 1, "size": 3,
                    "pg_num": cfg.pg_num, "crush_rule": 0}],
            qos_tenants={t.name: {"res": t.qos_res,
                                  "wgt": t.qos_wgt,
                                  "lim": t.qos_lim}
                         for t in tenants})
        v = Vstart(d)
        v.start(cfg.n_osds, hb_interval=cfg.hb_interval)
        try:
            h = S3ServeHarness(d, cfg, vstart=v)
            return h.run()
        finally:
            v.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":      # pragma: no cover
    raise SystemExit(serve_main())
