"""Realm / zonegroup / zone / period — multisite configuration.

The COVERAGE gap "no zone/period configuration".  Reference roles:
src/rgw/rgw_zone.h (RGWRealm / RGWZoneGroup / RGWZoneParams),
src/rgw/rgw_period.cc (RGWPeriod: immutable config snapshots with a
commit flow; the realm points at its current period).  Re-derived on
this repo's seams rather than ported:

  * a REALM is the top-level namespace; it owns a staging config and a
    pointer to the current committed period, all durable in an admin
    ioctx ("rgw.realm.<name>", "rgw.period.<realm>.<id>");
  * a PERIOD is an immutable snapshot {id, epoch, zonegroups} produced
    by ``commit_period`` — in-place epoch bumps happen only for
    non-topology changes (endpoint edits), topology changes (zones
    added/removed, master moved) mint a NEW period id whose
    predecessor field chains the history, like the reference's
    period_update --commit;
  * SYNC IS DRIVEN BY THE PERIOD MAP: ``PeriodSync`` reads the
    committed period, pairs the master zone with every peer in each
    zonegroup, and runs the existing bilog BucketSyncAgents — this
    replaces ad-hoc zone registration as the source of truth for who
    replicates what (sync.py's agents stay the data plane).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .gateway import RGWGateway
from .sync import BucketSyncAgent, make_sync_engine


class RealmError(RuntimeError):
    pass


@dataclass
class Zone:
    name: str
    endpoints: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"name": self.name, "endpoints": list(self.endpoints)}


@dataclass
class ZoneGroup:
    name: str
    master_zone: str = ""
    zones: Dict[str, Zone] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"name": self.name, "master_zone": self.master_zone,
                "zones": {n: z.to_dict() for n, z in self.zones.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "ZoneGroup":
        return cls(d["name"], d["master_zone"],
                   {n: Zone(z["name"], list(z["endpoints"]))
                    for n, z in d["zones"].items()})


@dataclass
class Period:
    """Immutable committed config snapshot (RGWPeriod)."""
    period_id: str
    epoch: int
    realm: str
    predecessor: str
    master_zonegroup: str
    zonegroups: Dict[str, ZoneGroup]

    def to_dict(self) -> dict:
        return {"period_id": self.period_id, "epoch": self.epoch,
                "realm": self.realm, "predecessor": self.predecessor,
                "master_zonegroup": self.master_zonegroup,
                "zonegroups": {n: g.to_dict()
                               for n, g in self.zonegroups.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "Period":
        return cls(d["period_id"], d["epoch"], d["realm"],
                   d["predecessor"], d["master_zonegroup"],
                   {n: ZoneGroup.from_dict(g)
                    for n, g in d["zonegroups"].items()})

    def all_zones(self) -> List[str]:
        return sorted(z for g in self.zonegroups.values()
                      for z in g.zones)


class Realm:
    """Durable realm: staging config + committed period chain."""

    def __init__(self, ioctx, name: str):
        self.ioctx = ioctx
        self.name = name
        self._load_or_create()

    # ----------------------------------------------------------- storage --
    def _oid(self) -> str:
        return f"rgw.realm.{self.name}"

    def _period_oid(self, period_id: str) -> str:
        return f"rgw.period.{self.name}.{period_id}"

    def _load_or_create(self) -> None:
        try:
            blob = self.ioctx.read(self._oid())
        except KeyError:
            # ObjectNotFound only — a transient read failure must not
            # reset a durable realm (clobbering the period pointer)
            blob = None
        if blob is None:
            self.current_period_id = ""
            self._period_seq = 0
            self.staging: Dict[str, ZoneGroup] = {}
            self.staging_master = ""
            self._save()
            return
        d = json.loads(bytes(blob).decode())
        self.current_period_id = d["current_period"]
        self._period_seq = d["period_seq"]
        self.staging = {n: ZoneGroup.from_dict(g)
                        for n, g in d["staging"].items()}
        self.staging_master = d["staging_master"]

    def _save(self) -> None:
        self.ioctx.write_full(self._oid(), json.dumps(
            {"current_period": self.current_period_id,
             "period_seq": self._period_seq,
             "staging": {n: g.to_dict()
                         for n, g in self.staging.items()},
             "staging_master": self.staging_master}).encode())

    # ----------------------------------------------------------- staging --
    def create_zonegroup(self, name: str,
                         master: bool = False) -> ZoneGroup:
        if name in self.staging:
            raise RealmError(f"zonegroup exists: {name}")
        g = ZoneGroup(name)
        self.staging[name] = g
        if master or not self.staging_master:
            self.staging_master = name
        self._save()
        return g

    def create_zone(self, zonegroup: str, name: str,
                    endpoints: Optional[List[str]] = None,
                    master: bool = False) -> Zone:
        g = self.staging.get(zonegroup)
        if g is None:
            raise RealmError(f"no zonegroup {zonegroup}")
        if any(name in gg.zones for gg in self.staging.values()):
            raise RealmError(f"zone exists: {name}")
        z = Zone(name, endpoints or [])
        g.zones[name] = z
        if master or not g.master_zone:
            g.master_zone = name
        self._save()
        return z

    def remove_zone(self, zonegroup: str, name: str) -> None:
        g = self.staging.get(zonegroup)
        if g is None or name not in g.zones:
            raise RealmError(f"no zone {name} in {zonegroup}")
        del g.zones[name]
        if g.master_zone == name:
            g.master_zone = min(g.zones) if g.zones else ""
        self._save()

    def set_endpoints(self, zonegroup: str, zone: str,
                      endpoints: List[str]) -> None:
        g = self.staging.get(zonegroup)
        if g is None or zone not in g.zones:
            raise RealmError(f"no zone {zone} in {zonegroup}")
        g.zones[zone].endpoints = list(endpoints)
        self._save()

    # ------------------------------------------------------------ commit --
    def current_period(self) -> Optional[Period]:
        if not self.current_period_id:
            return None
        blob = self.ioctx.read(self._period_oid(self.current_period_id))
        return Period.from_dict(json.loads(bytes(blob).decode()))

    def _topology(self, zonegroups: Dict[str, ZoneGroup],
                  master: str) -> list:
        return [master] + sorted(
            (n, g.master_zone, tuple(sorted(g.zones)))
            for n, g in zonegroups.items())

    def commit_period(self) -> Period:
        """period_update --commit: mint the staging config.  Topology
        changes start a new period (id chains to the predecessor);
        endpoint-only changes bump the current period's epoch."""
        if not self.staging or not self.staging_master:
            raise RealmError("staging is empty: nothing to commit")
        cur = self.current_period()
        same_topology = cur is not None and \
            self._topology(cur.zonegroups, cur.master_zonegroup) == \
            self._topology(self.staging, self.staging_master)
        if same_topology:
            period = Period(
                cur.period_id, cur.epoch + 1, self.name,
                cur.predecessor, self.staging_master,
                {n: ZoneGroup.from_dict(g.to_dict())
                 for n, g in self.staging.items()})
        else:
            self._period_seq += 1
            period = Period(
                f"{self.name}.{self._period_seq}", 1, self.name,
                cur.period_id if cur else "", self.staging_master,
                {n: ZoneGroup.from_dict(g.to_dict())
                 for n, g in self.staging.items()})
        self.ioctx.write_full(self._period_oid(period.period_id),
                              json.dumps(period.to_dict()).encode())
        self.current_period_id = period.period_id
        self._save()
        return period

    def period_history(self) -> List[str]:
        """Current-first chain of period ids (the period predecessor
        walk the reference exposes via `period list`)."""
        out = []
        pid = self.current_period_id
        while pid:
            out.append(pid)
            blob = self.ioctx.read(self._period_oid(pid))
            pid = Period.from_dict(
                json.loads(bytes(blob).decode())).predecessor
        return out


class PeriodSync:
    """Drive bilog sync agents from the committed period map: within
    each zonegroup, every non-master zone pulls every master-zone
    bucket (the rgw data-sync fan-out shape, with sync.py's bilog
    agents as the data plane)."""

    def __init__(self, realm: Realm, gateways: Dict[str, RGWGateway],
                 engine_workers: int = 4):
        self.realm = realm
        self.gateways = gateways
        self._agents: Dict[tuple, BucketSyncAgent] = {}
        # one shared fetch/apply pipeline for every agent: shard
        # drains across buckets AND zone pairs run concurrently,
        # FIFO-ordered only within one (bucket, zone, gen, shard)
        self._engine = None
        self._engine_workers = int(engine_workers)

    def engine(self):
        if self._engine is None and self._engine_workers > 0:
            self._engine = make_sync_engine(self._engine_workers)
        return self._engine

    def close(self) -> None:
        if self._engine is not None:
            self._engine.close()
            self._engine = None

    def _pairs(self) -> List[tuple]:
        period = self.realm.current_period()
        if period is None:
            raise RealmError("no committed period: commit one first")
        pairs = []
        for g in period.zonegroups.values():
            if g.master_zone not in self.gateways:
                continue
            for zname in g.zones:
                if zname != g.master_zone and zname in self.gateways:
                    pairs.append((g.master_zone, zname))
        return pairs

    def sync_all(self) -> Dict[tuple, Dict[str, int]]:
        """One pump over every (master bucket × peer zone); returns
        {(bucket, dst_zone): {"puts": n, "deletes": n}}."""
        applied: Dict[tuple, Dict[str, int]] = {}
        for src_zone, dst_zone in self._pairs():
            src_gw = self.gateways[src_zone]
            dst_gw = self.gateways[dst_zone]
            for bucket in src_gw.list_buckets():
                key = (bucket, dst_zone)
                agent = self._agents.get(key)
                if agent is None:
                    agent = BucketSyncAgent(src_gw, dst_gw, bucket,
                                            zone=dst_zone,
                                            src_zone=src_zone,
                                            engine=self.engine())
                    self._agents[key] = agent
                applied[key] = agent.sync()
        return applied
