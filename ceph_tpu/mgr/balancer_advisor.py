"""Balancer dry-run advisor — `ceph balancer eval` / `propose`.

Role of the reference mgr balancer module's EVAL side
(src/pybind/mgr/balancer/module.py: ``plan``/``eval`` score a map and
build a plan WITHOUT executing it; ``execute`` is a separate verb).
This PR ships only the advisory half: score the CURRENT mapping from
the ClusterScope signals the mon already holds — per-PG heat (pool
HitSet role) times per-OSD store utilization — propose concrete
``pg_upmap_items`` moves, and VALIDATE each proposal by re-scoring
the same heat history under the proposed mapping.  Nothing in this
module may touch the osdmap: the wire handler asserts the epoch is
unchanged around every call, and accepting a proposal is a future
PR's explicit verb.

Scoring: each eligible OSD's load is the summed decayed heat of the
PGs currently mapped to it, scaled by ``1 + utilization`` (a byte-
full OSD hurts more at equal heat — the utilization-history term).
The imbalance score is the RMS deviation of per-OSD load from the
crush-weight-proportional target, normalized by the mean load, so 0
means perfectly proportional and the number is comparable across
cluster sizes.  A proposal is kept only if the re-scored imbalance
under the virtual move strictly drops.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..cluster.balancer import (osd_ancestors, osd_crush_weights,
                                rule_failure_domain)
from ..placement.crush_map import ITEM_NONE


def imbalance_score(loads: Dict[int, float],
                    shares: Dict[int, float]) -> float:
    """Normalized RMS deviation of per-OSD load vs the weight-
    proportional target.  ``shares`` maps osd -> effective weight
    fraction (sums to 1 over eligible OSDs)."""
    if not loads:
        return 0.0
    total = sum(loads.values())
    if total <= 0:
        return 0.0
    mean = total / len(loads)
    acc = 0.0
    for osd, load in loads.items():
        target = total * shares.get(osd, 0.0)
        acc += (load - target) ** 2
    return round(math.sqrt(acc / len(loads)) / mean, 6)


def _eligible(om) -> Tuple[np.ndarray, Dict[int, float]]:
    """Effective weights (crush x in x up) and the share map over
    eligible OSDs — the same eligibility calc_pg_upmaps uses."""
    cw = osd_crush_weights(om.crush)
    n = len(cw)
    eff = cw * (om.osd_weight[:n] / 0x10000) * om.osd_up[:n] * \
        om.osd_exists[:n]
    s = eff.sum()
    shares = {int(i): float(eff[i] / s)
              for i in np.nonzero(eff > 0)[0]} if s > 0 else {}
    return eff, shares


def _pg_rows(cs, pool: Optional[int]) -> List[Dict[str, Any]]:
    rows = cs.pg_heat(pool=pool)
    return [r for r in rows if r.get("heat", 0.0) > 0.0]


def _util_by_osd(cs) -> Dict[int, float]:
    out: Dict[int, float] = {}
    for row in cs.osd_df():
        d = row.get("daemon", "")
        if d.startswith("osd."):
            out[int(d[4:])] = float(row.get("utilization", 0.0))
    return out


def _loads(pg_map: Dict[Tuple[int, int], Tuple[List[int], float]],
           util: Dict[int, float],
           shares: Dict[int, float]) -> Dict[int, float]:
    """Per-OSD combined load: summed heat of mapped PGs, scaled by
    1 + utilization.  Every eligible OSD appears (zero-load OSDs are
    exactly the underfull candidates)."""
    loads = {osd: 0.0 for osd in shares}
    for (_pool, _pg), (up, heat) in pg_map.items():
        per = heat / max(1, len([o for o in up if o != ITEM_NONE]))
        for osd in up:
            if osd != ITEM_NONE and osd in loads:
                loads[osd] += per
    for osd in loads:
        loads[osd] *= 1.0 + util.get(osd, 0.0)
    return loads


def evaluate(om, cs, max_moves: int = 8,
             pool: Optional[int] = None) -> Dict[str, Any]:
    """Score the current mapping and propose upmap moves as a
    REPORT.  ``om`` is never mutated (the caller asserts the epoch);
    proposals are validated by re-scoring the heat history under the
    virtual mapping and kept only when the score strictly drops."""
    pool = None if pool is None else int(pool)
    eff, shares = _eligible(om)
    rows = _pg_rows(cs, pool)
    util = _util_by_osd(cs)
    # pg -> (current up set, merged decayed heat)
    pg_map: Dict[Tuple[int, int], Tuple[List[int], float]] = {}
    domains: Dict[int, np.ndarray] = {}
    for r in rows:
        pid, pg = (int(x) for x in r["pgid"].split(".", 1))
        p = om.pools.get(pid)
        if p is None:
            continue
        up, _pri, _act, _apri = om.pg_to_up_acting_osds(pid, pg)
        if not up:
            continue
        pg_map[(pid, pg)] = (list(up), float(r["heat"]))
        if pid not in domains:
            domains[pid] = osd_ancestors(
                om.crush, rule_failure_domain(om.crush, p.crush_rule))
    loads = _loads(pg_map, util, shares)
    score_before = imbalance_score(loads, shares)
    out: Dict[str, Any] = {
        "epoch": om.epoch,
        "score_before": score_before,
        "score_after": score_before,
        "proposals": [],
        "osd_load": {f"osd.{o}": round(v, 6)
                     for o, v in sorted(loads.items())},
        "pgs_considered": len(pg_map),
    }
    if not pg_map or not shares:
        return out
    # greedy dry-run: repeatedly move the hottest PG off the most
    # overloaded OSD onto the most underloaded valid candidate,
    # applying each move VIRTUALLY (pg_map copy, never the osdmap)
    virt = {k: (list(up), heat) for k, (up, heat) in pg_map.items()}
    cur = dict(loads)
    cur_score = score_before
    total = sum(cur.values())
    targets = {o: total * shares.get(o, 0.0) for o in cur}
    proposals: List[Dict[str, Any]] = []
    for _ in range(max(0, int(max_moves))):
        over = sorted(cur, key=lambda o: targets[o] - cur[o])
        best = None
        for src in over[:2]:                    # most overloaded first
            if cur[src] <= targets[src]:
                break
            # hottest PG currently touching src, not already upmapped
            cands = sorted(
                ((heat, k, up) for k, (up, heat) in virt.items()
                 if src in up and k not in om.pg_upmap_items
                 and k not in om.pg_upmap
                 and not any(k == p["key"] for p in proposals)),
                key=lambda t: -t[0])
            for heat, k, up in cands[:8]:
                dom = domains[k[0]]
                pg_doms = {dom[o] for o in up
                           if o != ITEM_NONE and o != src
                           and o < len(dom)}
                for dst in sorted(cur, key=lambda o: cur[o] -
                                  targets[o]):
                    if dst == src or dst in up:
                        continue
                    if dst < len(dom) and dom[dst] != ITEM_NONE \
                            and dom[dst] in pg_doms:
                        continue            # would collapse domains
                    # virtual apply + re-score
                    share = (heat *
                             (1.0 + util.get(src, 0.0)) /
                             max(1, len([o for o in up
                                         if o != ITEM_NONE])))
                    trial = dict(cur)
                    trial[src] -= share
                    trial[dst] += heat * (1.0 + util.get(dst, 0.0)) \
                        / max(1, len([o for o in up
                                      if o != ITEM_NONE]))
                    s = imbalance_score(trial, shares)
                    if s < cur_score:
                        best = (s, k, up, src, dst, heat, trial)
                    break                   # only the best candidate
                if best is not None:
                    break
            if best is not None:
                break
        if best is None:
            break
        s, k, up, src, dst, heat, trial = best
        cur = trial
        cur_score = s
        virt[k] = ([dst if o == src else o for o in up], heat)
        proposals.append({
            "key": k,
            "pgid": f"{k[0]}.{k[1]}",
            "pool": k[0],
            "from": int(src),
            "to": int(dst),
            "heat": round(heat, 6),
            "score_after": s,
        })
    # validation sweep: rebuild loads FROM SCRATCH under the proposed
    # mapping (not the incremental trail) and re-score — the number
    # the report promises is the recomputed one
    final_loads = _loads(virt, util, shares)
    score_after = imbalance_score(final_loads, shares)
    if proposals and score_after >= score_before:
        # the incremental trail lied (rounding, overlapping moves):
        # an advisor must not promise a non-improvement
        proposals = []
        score_after = score_before
    for p in proposals:
        p.pop("key", None)
    out["proposals"] = proposals
    out["score_after"] = score_after if proposals else score_before
    out["moves"] = len(proposals)
    return out
