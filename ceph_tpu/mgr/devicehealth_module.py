"""Device-health mgr module (src/pybind/mgr/devicehealth role).

The reference scrapes SMART metrics per device, stores them in a
health pool, and predicts life expectancy; failing devices raise
health warnings and can be preemptively drained.  This cluster model
has no SMART source, so the scrape substitutes the observable health
signals the stores DO expose — up/down flaps, scrub-found
inconsistencies (checksum failures are exactly what a dying disk
produces), and usage — while keeping the reference's surface: metric
history per device, ``life_expectancy``, a health check for devices
predicted to fail, and ``maybe_mark_out`` (the mark-out-ahead-of-
failure behavior behind devicehealth's self_heal option).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from .module_host import MgrModule

# life-expectancy buckets (the reference expresses this as a date
# range; buckets keep the semantics without wall-clock coupling)
GOOD, WARNING, FAILING = "good", "warning", "failing"


class DeviceHealthModule(MgrModule):
    NAME = "devicehealth"
    HISTORY = 16                # scrapes retained per device
    FLAP_WARN = 2               # down-transitions before WARNING
    ERROR_FAIL = 1              # scrub errors before FAILING

    def __init__(self, host):
        super().__init__(host)
        # osd id -> ring of scrapes {ts, up, in, errors, objects}
        self.metrics: Dict[int, List[Dict[str, Any]]] = {}
        self._last_up: Dict[int, bool] = {}
        self.flaps: Dict[int, int] = {}
        self.errors: Dict[int, int] = {}
        self.self_heal = False
        self.marked_out: List[int] = []

    # ------------------------------------------------------------ scrape --
    def record_scrub_errors(self, osd_id: int, n: int = 1) -> None:
        """Scrub found inconsistent/unreadable shards on this OSD —
        the strongest dying-media signal this model observes (the
        SMART reallocated-sector analog)."""
        self.errors[osd_id] = self.errors.get(osd_id, 0) + n

    def scrape(self, now: Optional[float] = None) -> None:
        osd = self.get("osd_stats")
        ts = time.time() if now is None else now
        for i, up in enumerate(osd["up"]):
            if self._last_up.get(i, True) and not up:
                self.flaps[i] = self.flaps.get(i, 0) + 1
            self._last_up[i] = bool(up)
            ring = self.metrics.setdefault(i, [])
            ring.append({"ts": ts, "up": bool(up),
                         "in": bool(osd["in"][i]),
                         "errors": self.errors.get(i, 0),
                         "flaps": self.flaps.get(i, 0)})
            del ring[:-self.HISTORY]

    # ---------------------------------------------------------- verdicts --
    def life_expectancy(self, osd_id: int) -> str:
        if self.errors.get(osd_id, 0) >= self.ERROR_FAIL:
            return FAILING
        if self.flaps.get(osd_id, 0) >= self.FLAP_WARN:
            return WARNING
        return GOOD

    def checks(self) -> Dict[str, Dict]:
        """Health checks (DEVICE_HEALTH / DEVICE_HEALTH_IN_USE roles)."""
        failing = [i for i in self.metrics
                   if self.life_expectancy(i) == FAILING]
        warning = [i for i in self.metrics
                   if self.life_expectancy(i) == WARNING]
        out: Dict[str, Dict] = {}
        if failing:
            out["DEVICE_HEALTH_TOOMANY" if len(failing) > 1
                else "DEVICE_HEALTH"] = {
                "severity": "error",
                "message": f"{len(failing)} device(s) predicted to "
                           f"fail: {sorted(failing)}"}
        if warning:
            out.setdefault("DEVICE_HEALTH_WARN", {
                "severity": "warning",
                "message": f"{len(warning)} device(s) degrading: "
                           f"{sorted(warning)}"})
        return out

    def maybe_mark_out(self) -> List[int]:
        """self_heal: mark failing devices out so data re-replicates
        BEFORE the device dies (devicehealth mark_out_threshold)."""
        if not self.self_heal:
            return []
        m = self.get("osd_map")
        newly = []
        for i in list(self.metrics):
            if self.life_expectancy(i) == FAILING and \
                    i not in self.marked_out and \
                    int(m.osd_weight[i]) > 0:
                self.host.mark_osd_out(i)
                self.marked_out.append(i)
                newly.append(i)
        return newly

    # -------------------------------------------------------------- serve --
    def serve_tick(self) -> None:
        self.scrape()
        self.maybe_mark_out()


def register(host) -> None:
    host.register(DeviceHealthModule.NAME, DeviceHealthModule)
