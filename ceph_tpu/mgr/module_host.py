"""Mgr module host — the ActivePyModules/mgr_module role.

The reference mgr embeds CPython and hosts modules (balancer,
pg_autoscaler, prometheus, ...) behind a stable module API
(src/mgr/ActivePyModules.cc, src/pybind/mgr/mgr_module.py): each module
sees cluster state (maps, pg dump, perf counters, pool stats, config)
and can command the mon.  Here the host is native Python from the
start; the module contract is the same shape:

  * ``MgrModule.serve_tick()`` — one pass of the module's periodic work
    (the serve() loop body; the host drives ticks so tests and the
    daemon can pump deterministically).
  * ``self.get("osd_map") / get("pg_dump") / get("pool_stats")`` —
    cluster state queries (MgrModule.get role).
  * ``self.set_pool_pg_num(...)`` etc. — mon commands via the host.

Modules register by name; enable/disable matches ``ceph mgr module
enable`` semantics.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class MgrModule:
    """Base module (mgr_module.MgrModule role)."""

    NAME = "module"

    def __init__(self, host: "MgrModuleHost"):
        self.host = host

    # ------------------------------------------------------------ queries --
    def get(self, what: str) -> Any:
        return self.host.get(what)

    # ------------------------------------------------------------- actions --
    def set_pool_pg_num(self, pool_id: int, pg_num: int) -> None:
        self.host.set_pool_pg_num(pool_id, pg_num)

    # -------------------------------------------------------------- serve --
    def serve_tick(self) -> None:        # pragma: no cover - abstract-ish
        pass


class MgrModuleHost:
    """Hosts modules over a live cluster (sim + monitor)."""

    def __init__(self, sim, mon=None):
        self.sim = sim
        self.mon = mon
        self._available: Dict[str, Callable[["MgrModuleHost"], MgrModule]] = {}
        self.modules: Dict[str, MgrModule] = {}

    # ----------------------------------------------------------- registry --
    def register(self, name: str,
                 factory: Callable[["MgrModuleHost"], MgrModule]) -> None:
        self._available[name] = factory

    def enable(self, name: str) -> MgrModule:
        if name not in self._available:
            raise KeyError(f"no mgr module {name!r}")
        if name not in self.modules:
            self.modules[name] = self._available[name](self)
        return self.modules[name]

    def disable(self, name: str) -> None:
        self.modules.pop(name, None)

    def enabled(self) -> List[str]:
        return sorted(self.modules)

    def tick(self) -> None:
        """One serve pass of every enabled module."""
        for m in list(self.modules.values()):
            m.serve_tick()

    # ------------------------------------------------------ state queries --
    def get(self, what: str) -> Any:
        m = self.sim.osdmap
        if what == "osd_map":
            return m
        if what == "osd_stats":
            n = m.max_osd
            return {
                "up": [bool(v) for v in m.osd_up[:n]],
                "in": [int(w) > 0 for w in m.osd_weight[:n]],
                "weight": [int(w) for w in m.osd_weight[:n]],
            }
        if what == "pg_dump":
            out = {}
            for pid, pool in m.pools.items():
                up, prim = m.map_pgs_batch(pid)
                out[pid] = {"up": up, "primary": prim}
            return out
        if what == "pool_stats":
            stats: Dict[int, Dict[str, int]] = {}
            for (pid, _name), info in self.sim.objects.items():
                s = stats.setdefault(pid, {"objects": 0, "bytes": 0})
                s["objects"] += 1
                s["bytes"] += info.size
            for pid in m.pools:
                stats.setdefault(pid, {"objects": 0, "bytes": 0})
            return stats
        if what == "pg_counts_per_osd":
            return self.sim.osdmap.pg_counts_per_osd()
        if what == "cluster_stats":
            # the ClusterTelemetry aggregator (None without a mon:
            # modules degrade to the per-process view)
            return None if self.mon is None \
                else getattr(self.mon, "cluster_stats", None)
        raise KeyError(f"unknown query {what!r}")

    # ------------------------------------------------------- mon commands --
    def mark_osd_out(self, osd: int) -> None:
        """Mark an OSD out (weight 0) — with a mon, as a committed
        incremental; standalone, directly on the sim's map (the
        `ceph osd out` / devicehealth self-heal path)."""
        if self.mon is not None:
            inc = self.mon.next_incremental()
            inc.new_weight[osd] = 0
            if not self.mon.commit_incremental(inc):
                raise RuntimeError(f"osd.{osd} mark-out lost quorum")
            return
        self.sim.osdmap.mark_out(osd)      # bumps the epoch itself

    def set_pool_pg_num(self, pool_id: int, pg_num: int) -> None:
        """Commit a pg_num change.  With a mon: consensus + durable
        incremental FIRST (no quorum -> RuntimeError, nothing moves),
        then the PG-split data movement reshards objects from the old
        geometry.  Without a mon: the sim reshards and bumps the epoch
        itself."""
        old = self.sim.osdmap.pools[pool_id].pg_num
        if self.mon is not None:
            inc = self.mon.next_incremental()
            inc.new_pool_pg_num[pool_id] = pg_num
            if not self.mon.commit_incremental(inc):
                raise RuntimeError(
                    f"pg_num change for pool {pool_id} lost quorum")
            if hasattr(self.sim, "reshard_pool"):
                self.sim.reshard_pool(pool_id, pg_num,
                                      bump_epoch=False, old_pg_num=old)
            return
        if hasattr(self.sim, "reshard_pool"):
            self.sim.reshard_pool(pool_id, pg_num)
            return
        pool = self.sim.osdmap.pools[pool_id]
        pool.pg_num = pg_num
        pool.pgp_num = pg_num
        self.sim.osdmap.bump_epoch()
