"""ClusterStats — mgr-style cluster-wide stats aggregation.

Role of the reference's PGMap (src/mon/PGMap.cc: per-OSD/per-pool
stat ingestion from MOSDPGStats reports, the `ceph -s` io line and
`ceph df` / `ceph osd df` renderings) combined with the mgr
prometheus module's cluster scrape (src/pybind/mgr/prometheus:
per-daemon labeled families from every daemon's perf counters).

Each daemon ships, on its existing heartbeat/reporter path, a report:

    {"perf": <PerfCountersCollection.dump_typed()>,     # typed values
     "util": {"bytes": .., "total_bytes": .., "objects": ..,
              "pools": {pid: {"objects": n, "bytes": b}}},
     "ts": <wall clock>}

and the aggregator (leader-mon-local, like the SLOW_OPS rollup):

  * merges log2 ``PerfHistogram`` dumps BUCKET-WISE across daemons
    and reads cluster p50/p99/p999 off the merged distribution —
    exact within one bucket's resolution, which is the histogram's
    own resolution (averaging per-daemon quantiles would be wrong);
  * computes io RATES (ops/s, bytes/s, per pool and per daemon) from
    deltas between consecutive reports of the monotonic ``osd.io``
    counters — the `ceph -s` "io:" line;
  * aggregates utilization for `ceph df` / `ceph osd df`;
  * renders ONE cluster-wide Prometheus scrape with per-daemon
    ``ceph_daemon`` labels plus merged ``ceph_cluster_*`` families —
    the per-process-only prometheus_module view, cluster-shaped.

Stale reporters age out (a daemon that stopped reporting must not
pin week-old rates into `ceph -s` forever).
"""
from __future__ import annotations

import math
import re
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..common.perf_counters import (COUNTER, GAUGE, HISTOGRAM,
                                    TIME_AVG)
from ..common.perf_counters import perf as _perf
from ..cluster.pg_heat import merge_heat, osd_heat_rollup
from .metrics_history import RATE_COUNTERS, MetricsHistory

QUANTILES = (0.5, 0.99, 0.999)
STALE_S = 600.0          # reporter aging (the SLOW_OPS window)


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _le_key(le) -> float:
    return math.inf if le == "+Inf" else float(le)


def merge_histograms(dumps: Iterable[Dict[str, Any]]
                     ) -> Dict[str, Any]:
    """Bucket-wise merge of PerfHistogram dumps ({count, sum,
    buckets: [[le, n], ...]}, non-cumulative, le ascending): buckets
    with the SAME le bound add their counts — all producers share the
    log2 bucket geometry, so identical bounds mean identical value
    ranges and the merged histogram is exactly the histogram of the
    pooled samples (no resolution loss beyond each sample's own
    bucket)."""
    counts: Dict[float, int] = {}
    labels: Dict[float, Any] = {}
    total = 0
    sm = 0.0
    for d in dumps:
        if not d:
            continue
        total += int(d.get("count", 0))
        sm += float(d.get("sum", 0.0))
        for le, n in d.get("buckets", []):
            k = _le_key(le)
            counts[k] = counts.get(k, 0) + int(n)
            labels[k] = le
    buckets = [[labels[k], counts[k]] for k in sorted(counts)]
    return {"count": total, "sum": round(sm, 9), "buckets": buckets}


def quantile(dump: Dict[str, Any], q: float) -> Optional[float]:
    """Read one quantile off a (merged) histogram dump: the le upper
    bound of the bucket where the cumulative count crosses q*total —
    exact to one bucket's resolution.  The +Inf bucket answers with
    the last finite bound (prometheus histogram_quantile's rule)."""
    total = int(dump.get("count", 0))
    if total <= 0:
        return None
    target = q * total
    cum = 0
    last_finite = None
    for le, n in dump.get("buckets", []):
        if le != "+Inf":
            last_finite = float(le)
        cum += int(n)
        if cum >= target:
            return float(le) if le != "+Inf" else last_finite
    return last_finite


_Q_LABEL = {0.5: "p50", 0.99: "p99", 0.999: "p999"}


def quantiles(dump: Dict[str, Any],
              qs: Tuple[float, ...] = QUANTILES) -> Dict[str, Any]:
    return {_Q_LABEL.get(q, f"q{q}"): quantile(dump, q) for q in qs}


class ClusterStats:
    """The aggregator: per-daemon latest reports + previous-report
    deltas for rates.  Thread-safe (wire handler threads ingest while
    admin/scrape threads read)."""

    def __init__(self, stale_s: float = STALE_S):
        self._lock = threading.Lock()
        self.stale_s = float(stale_s)
        # daemon -> {"ts", "perf", "util"} (latest)
        self._latest: Dict[str, Dict[str, Any]] = {}
        # daemon -> {"ts", flat io counters} (previous, for deltas)
        self._prev_io: Dict[str, Tuple[float, Dict[str, float]]] = {}
        # daemon -> computed {key: rate/s}
        self._rates: Dict[str, Dict[str, float]] = {}
        self.reports_ingested = 0
        # ClusterScope: bounded per-reporter delivery rings (the
        # mgr MetricCollector / PGMap-history role)
        self.history = MetricsHistory(stale_s=self.stale_s)
        # daemon -> latest PGHeatTracker.dump() (pool-HitSet role)
        self._heat: Dict[str, Dict[str, Any]] = {}
        # monotonic-counter resets observed across reporters (a
        # daemon restart zeroes its counters; the rate layer clamps
        # the negative delta and counts it here + stats.counter_resets)
        self.counter_resets = 0

    # ------------------------------------------------------------ ingest --
    @staticmethod
    def _flat_io(perf: Dict[str, Any]) -> Dict[str, float]:
        """Monotonic io counters a rate can be derived from — the
        ``osd.io`` group daemons count CLIENT-facing ops into (the
        only group whose keys the rate sums below understand)."""
        out: Dict[str, float] = {}
        for group in ("osd.io",):
            for key, tv in (perf.get(group) or {}).items():
                typ, val = tv[0], tv[1]
                if typ == COUNTER and isinstance(val, (int, float)):
                    out[key] = float(val)
        return out

    def ingest(self, daemon: str, report: Dict[str, Any]) -> None:
        ts = float(report.get("ts") or time.time())
        perf = report.get("perf") or {}
        util = report.get("util") or {}
        heat = report.get("heat")
        with self._lock:
            self.reports_ingested += 1
            prev = self._prev_io.get(daemon)
            flat = self._flat_io(perf)
            if prev is not None:
                pts, pflat = prev
                dt = ts - pts
                # counter-reset robustness: a restarted daemon's
                # monotonic counters went backwards — the rate clamps
                # to zero (max() below) and the reset is COUNTED, so
                # a restart reads as "reset, rate 0", not garbage
                if any(v < pflat.get(k, 0.0)
                       for k, v in flat.items() if k in pflat):
                    self.counter_resets += 1
                    _perf("stats").inc("counter_resets")
                if dt > 0:
                    self._rates[daemon] = {
                        k: max(0.0, (v - pflat.get(k, 0.0)) / dt)
                        for k, v in flat.items()}
            self._prev_io[daemon] = (ts, flat)
            self._latest[daemon] = {"ts": ts, "perf": perf,
                                    "util": util,
                                    "host": report.get("host")}
            if heat:
                self._heat[daemon] = heat
        # retain the delivery in the history ring (its own lock; the
        # ring does its own per-reporter reset detection so history
        # rate series clamp identically)
        self.history.record(daemon, ts, perf)

    def _live(self) -> Dict[str, Dict[str, Any]]:
        """Latest reports younger than the staleness window (caller
        holds the lock)."""
        now = time.time()
        return {d: r for d, r in self._latest.items()
                if now - r["ts"] <= self.stale_s}

    def daemons(self) -> List[str]:
        with self._lock:
            return sorted(self._live())

    # ----------------------------------------------------------- merging --
    def _histogram_families(self, live) -> Dict[str, Dict[str, Any]]:
        """{group.key: {"merged": dump, "per_daemon": {d: dump}}}
        across every daemon's typed perf dump."""
        fams: Dict[str, Dict[str, Any]] = {}
        for daemon, rep in live.items():
            for group, counters in (rep["perf"] or {}).items():
                for key, tv in counters.items():
                    if tv[0] != HISTOGRAM:
                        continue
                    fam = fams.setdefault(f"{group}.{key}",
                                          {"per_daemon": {}})
                    fam["per_daemon"][daemon] = tv[1]
        for fam in fams.values():
            fam["merged"] = merge_histograms(
                fam["per_daemon"].values())
            fam["quantiles"] = quantiles(fam["merged"])
        return fams

    def merged_quantiles(self) -> Dict[str, Dict[str, Any]]:
        """{group.key: {p5: .., p99: .., p999: .., count: ..}} —
        cluster percentiles off the bucket-wise merged histograms
        (the SLO surface ROADMAP item 4 consumes)."""
        with self._lock:
            fams = self._histogram_families(self._live())
        return {name: dict(fam["quantiles"],
                           count=fam["merged"]["count"])
                for name, fam in fams.items()}

    # -------------------------------------------------------------- io --
    def io_rates(self) -> Dict[str, Any]:
        """Cluster + per-pool + per-daemon io rates (the `ceph -s`
        io: line), from monotonic counter deltas between consecutive
        daemon reports."""
        with self._lock:
            live = set(self._live())
            rates = {d: dict(r) for d, r in self._rates.items()
                     if d in live}
        cluster = {"rd_ops": 0.0, "wr_ops": 0.0,
                   "rd_bytes": 0.0, "wr_bytes": 0.0}
        pools: Dict[int, Dict[str, float]] = {}
        for _d, r in rates.items():
            for k, v in r.items():
                if k in cluster:
                    cluster[k] += v
                elif k.startswith("pool."):
                    _, pid, metric = k.split(".", 2)
                    p = pools.setdefault(int(pid), {})
                    p[metric] = p.get(metric, 0.0) + v
        return {"cluster": {k: round(v, 3)
                            for k, v in cluster.items()},
                "pools": {pid: {k: round(v, 3)
                                for k, v in p.items()}
                          for pid, p in sorted(pools.items())},
                "daemons": {d: {k: round(v, 3)
                                for k, v in r.items()
                                if not k.startswith("pool.")}
                            for d, r in sorted(rates.items())}}

    # -------------------------------------------------------------- heat --
    def _live_heat(self) -> Dict[str, Dict[str, Any]]:
        """Heat dumps of non-stale reporters (caller holds no lock)."""
        with self._lock:
            live = set(self._live())
            return {d: h for d, h in self._heat.items() if d in live}

    def pg_heat(self, pool: Optional[int] = None,
                top: Optional[int] = None) -> List[Dict[str, Any]]:
        """`ceph pg heat [--pool P] [--top N]`: per-PG client-io heat
        rows merged across every reporting OSD, hottest first."""
        return merge_heat(self._live_heat(), pool=pool, top=top)

    def osd_heat(self, check: bool = True) -> Dict[str, Any]:
        """Per-OSD heat rollup.  ``check`` asserts the raw totals
        agree with the same daemon's reported ``osd.io`` counters —
        heat and io counters are incremented at the SAME call sites,
        so a mismatch means an attribution bug, and the rollup says
        so rather than letting the two surfaces silently diverge.
        (>= because the io counters may have advanced between the
        heat snapshot and the perf dump inside one report.)"""
        rollup = osd_heat_rollup(self._live_heat())
        if check:
            with self._lock:
                live = self._live()
            for daemon, row in rollup.items():
                io = (live.get(daemon) or {}).get("perf") or {}
                flat = self._flat_io(io)
                if not flat:
                    continue
                for f in ("rd_ops", "wr_ops", "rd_bytes", "wr_bytes"):
                    got, want = row.get(f"tot_{f}", 0.0), \
                        flat.get(f, 0.0)
                    if got > want + 0.5:
                        raise AssertionError(
                            f"{daemon}: heat rollup {f}={got} "
                            f"exceeds osd.io counter {want} — "
                            f"per-PG attribution double-counted")
        return rollup

    # ---------------------------------------------------------- df views --
    def osd_df(self) -> List[Dict[str, Any]]:
        """Per-OSD utilization rows (`ceph osd df`) — OSD reporters
        only (clients report perf too, but they own no store)."""
        with self._lock:
            live = self._live()
        rows = []
        for daemon, rep in sorted(live.items()):
            if not daemon.startswith("osd."):
                continue
            u = rep["util"] or {}
            total = int(u.get("total_bytes") or 0)
            used = int(u.get("bytes") or 0)
            rows.append({
                "daemon": daemon,
                "bytes_used": used,
                "bytes_total": total,
                "utilization": round(used / total, 6)
                if total else 0.0,
                "objects": int(u.get("objects") or 0),
                # recent-rate trend columns off the history rings
                # (the `ceph osd df` sparkline; "-" until 2 samples)
                "wr_trend": self.history.sparkline(
                    daemon, "osd.io.wr_ops"),
                "rd_trend": self.history.sparkline(
                    daemon, "osd.io.rd_ops")})
        return rows

    def df(self) -> Dict[str, Any]:
        """Pool + cluster usage (`ceph df`): shard/replica objects
        and bytes summed across the daemons that hold them (RAW
        usage, the STORED/USED distinction the reference draws)."""
        with self._lock:
            live = self._live()
        pools: Dict[int, Dict[str, int]] = {}
        total_used = total_bytes = total_objects = 0
        for daemon, rep in live.items():
            if not daemon.startswith("osd."):
                continue          # only store owners count toward RAW
            u = rep["util"] or {}
            total_used += int(u.get("bytes") or 0)
            total_bytes += int(u.get("total_bytes") or 0)
            total_objects += int(u.get("objects") or 0)
            for pid, p in (u.get("pools") or {}).items():
                row = pools.setdefault(int(pid),
                                       {"objects": 0, "bytes": 0})
                row["objects"] += int(p.get("objects") or 0)
                row["bytes"] += int(p.get("bytes") or 0)
        return {"total_bytes": total_bytes,
                "total_used_bytes": total_used,
                "total_objects": total_objects,
                "pools": dict(sorted(pools.items()))}

    # -------------------------------------------------------- mesh plane --
    _CHIP_KEY = re.compile(r"^(r(\d+)c(\d+)|shard(\d+))\.(.+)$")

    def mesh_rollup(self) -> Dict[str, Any]:
        """Per-(host, chip) data-plane counter rollup — the MeshPlane2D
        cluster view.  Each reporter's ``dataplane`` perf group is
        scanned for per-chip keys and attributed to the host label its
        report carried (``host0`` when absent — single-process plane).
        A reporter writing BOTH the 2-D coordinate keys and the 1-D
        ``shard<i>`` aliases contributes the coordinate namespace only
        (the alias is the same value under another name — summing both
        would double-count); ``totals`` sums every (host, chip) cell,
        so a 2-process plane's totals equal the single-process run's
        (per-cell accounting is locality-gated at the source)."""
        with self._lock:
            live = self._live()
        hosts: Dict[str, Dict[str, Dict[str, float]]] = {}
        totals: Dict[str, float] = {}
        rows = cols = 0
        for daemon, rep in live.items():
            grp = (rep["perf"] or {}).get("dataplane") or {}
            chips: Dict[str, Dict[str, float]] = {}
            coords = False
            for key, tv in grp.items():
                m = self._CHIP_KEY.match(key)
                if not m or tv[0] != COUNTER:
                    continue
                if m.group(2) is not None:
                    coords = True
            for key, tv in grp.items():
                m = self._CHIP_KEY.match(key)
                if not m or tv[0] != COUNTER \
                        or not isinstance(tv[1], (int, float)):
                    continue
                is_coord = m.group(2) is not None
                if coords != is_coord:
                    continue          # skip the alias namespace
                if is_coord:
                    rows = max(rows, int(m.group(2)) + 1)
                    cols = max(cols, int(m.group(3)) + 1)
                chips.setdefault(m.group(1), {})[m.group(5)] = \
                    float(tv[1])
            if not chips:
                continue
            host = str(rep.get("host") or "host0")
            hrow = hosts.setdefault(host, {})
            for chip, counters in chips.items():
                cell = hrow.setdefault(chip, {})
                for k, v in counters.items():
                    cell[k] = cell.get(k, 0.0) + v
                    totals[k] = totals.get(k, 0.0) + v
        n_chips = sum(len(h) for h in hosts.values())
        return {"hosts": hosts, "totals": totals,
                "n_hosts": len(hosts), "n_chips": n_chips,
                "shape": [rows, cols] if rows else None}

    # ------------------------------------------------------------- dump --
    def dump(self) -> Dict[str, Any]:
        return {"daemons": self.daemons(),
                "reports_ingested": self.reports_ingested,
                "counter_resets": self.counter_resets,
                "quantiles": self.merged_quantiles(),
                "io": self.io_rates(),
                "df": self.df(),
                "osd_df": self.osd_df(),
                "mesh": self.mesh_rollup(),
                "history": self.history.dump()}

    # -------------------------------------------------------- prometheus --
    @staticmethod
    def _safe(name: str) -> str:
        return name.replace(".", "_").replace("-", "_")

    @staticmethod
    def _hist_lines(lines: List[str], name: str, labels: str,
                    dump: Dict[str, Any]) -> None:
        cum = 0
        saw_inf = False
        for le, n in dump.get("buckets", []):
            cum += int(n)
            saw_inf = saw_inf or le == "+Inf"
            le_s = le if le == "+Inf" else repr(float(le))
            sep = "," if labels else ""
            lines.append(f'{name}_bucket{{{labels}{sep}le="{le_s}"}} '
                         f'{cum}')
        if not saw_inf:
            sep = "," if labels else ""
            lines.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} '
                         f'{dump.get("count", 0)}')
        lab = f"{{{labels}}}" if labels else ""
        lines.append(f'{name}_sum{lab} {dump.get("sum", 0.0)}')
        lines.append(f'{name}_count{lab} {dump.get("count", 0)}')

    def render_prometheus(self) -> str:
        """The single cluster-wide scrape: every daemon's counters
        with a ``ceph_daemon`` label, merged ``ceph_cluster_*``
        histogram families, merged quantile gauges, and per-OSD
        utilization."""
        with self._lock:
            live = {d: {"perf": dict(r["perf"] or {}),
                        "util": dict(r["util"] or {})}
                    for d, r in self._live().items()}
            fams = self._histogram_families(live)
        lines: List[str] = []
        # per-daemon families use their own ceph_daemon_* namespace:
        # the per-process exporter already emits UNLABELED
        # ceph_tpu_* families for this process's counters, and one
        # scrape body must never carry two # TYPE lines for one
        # family name (a real Prometheus parser rejects the whole
        # scrape)
        # scalar families, per daemon (gauges/counters/time_avgs).
        # Per-pool io counters ("pool.<pid>.<metric>" keys) render as
        # ONE family per metric with a pool label — ids belong in
        # labels, not metric names, or no PromQL query can aggregate
        # across pools
        scalars: Dict[str, List[Tuple[str, str, Any]]] = {}
        for daemon, rep in sorted(live.items()):
            for group, counters in sorted(rep["perf"].items()):
                for key, tv in sorted(counters.items()):
                    typ, val = tv[0], tv[1]
                    labels = f'ceph_daemon="{_esc(daemon)}"'
                    if key.startswith("pool.") and \
                            key.count(".") >= 2:
                        _p, pid, metric = key.split(".", 2)
                        key = f"pool_{metric}"
                        labels += f',pool="{_esc(pid)}"'
                    name = self._safe(f"ceph_daemon_{group}_{key}")
                    if typ == HISTOGRAM:
                        continue                 # rendered below
                    if typ == TIME_AVG:
                        val = (val or {}).get("avgtime", 0.0)
                        typ = GAUGE
                    if isinstance(val, bool) or \
                            not isinstance(val, (int, float)):
                        continue
                    scalars.setdefault(name, []).append(
                        (labels, "gauge" if typ == GAUGE
                         else "counter", val))
        for name, samples in sorted(scalars.items()):
            lines.append(f"# HELP {name} per-daemon perf counter")
            lines.append(f"# TYPE {name} {samples[0][1]}")
            for labels, _typ, val in samples:
                lines.append(f"{name}{{{labels}}} {val}")
        # histogram families: per-daemon labeled + cluster-merged
        for fname, fam in sorted(fams.items()):
            name = self._safe(f"ceph_daemon_{fname}")
            lines.append(f"# HELP {name} per-daemon histogram")
            lines.append(f"# TYPE {name} histogram")
            for daemon, dump in sorted(fam["per_daemon"].items()):
                self._hist_lines(lines, name,
                                 f'ceph_daemon="{_esc(daemon)}"',
                                 dump)
            cname = self._safe(f"ceph_cluster_{fname}")
            lines.append(f"# HELP {cname} bucket-wise merged "
                         f"cluster histogram")
            lines.append(f"# TYPE {cname} histogram")
            self._hist_lines(lines, cname, "", fam["merged"])
            qname = cname + "_quantile"
            lines.append(f"# HELP {qname} merged cluster quantiles "
                         f"(one log2 bucket resolution)")
            lines.append(f"# TYPE {qname} gauge")
            for q in QUANTILES:
                v = quantile(fam["merged"], q)
                if v is not None:
                    lines.append(f'{qname}{{quantile="{q}"}} {v}')
        # utilization (`ceph osd df` as a scrape family)
        rows = self.osd_df()
        if rows:
            lines.append("# HELP ceph_osd_utilization used/total "
                         "store bytes per OSD")
            lines.append("# TYPE ceph_osd_utilization gauge")
            for r in rows:
                lines.append(
                    f'ceph_osd_utilization{{ceph_daemon='
                    f'"{_esc(r["daemon"])}"}} {r["utilization"]}')
        # io rates (the `ceph -s` io line as gauges)
        io = self.io_rates()
        lines.append("# HELP ceph_cluster_io_rate cluster io rates "
                     "from counter deltas")
        lines.append("# TYPE ceph_cluster_io_rate gauge")
        for k, v in sorted(io["cluster"].items()):
            lines.append(f'ceph_cluster_io_rate{{metric="{k}"}} {v}')
        # short/long window rates off the history rings: the latest
        # interval vs the whole retained window, per daemon per
        # headline counter (reset intervals clamp to zero inside)
        hist = self.history
        rate_lines: List[str] = []
        for daemon in hist.reporters():
            for group, key in RATE_COUNTERS:
                counter = f"{group}.{key}"
                short = hist.window_rate(daemon, counter, window=2)
                long = hist.window_rate(daemon, counter,
                                        window=1 << 30)
                for win, v in (("short", short), ("long", long)):
                    if v is not None:
                        rate_lines.append(
                            f'ceph_history_rate{{ceph_daemon='
                            f'"{_esc(daemon)}",counter='
                            f'"{_esc(counter)}",window="{win}"}} '
                            f'{v}')
        if rate_lines:
            lines.append("# HELP ceph_history_rate windowed counter "
                         "rates from the metrics-history rings "
                         "(reset-clamped)")
            lines.append("# TYPE ceph_history_rate gauge")
            lines.extend(rate_lines)
        # cumulative reset count (alerting on restart storms)
        lines.append("# HELP ceph_cluster_counter_resets monotonic "
                     "counter resets observed (daemon restarts)")
        lines.append("# TYPE ceph_cluster_counter_resets counter")
        lines.append(f"ceph_cluster_counter_resets "
                     f"{self.counter_resets}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._latest.clear()
            self._prev_io.clear()
            self._rates.clear()
            self._heat.clear()
            self.reports_ingested = 0
            self.counter_resets = 0
        self.history.reset()
