"""Dashboard mgr module (src/pybind/mgr/dashboard role, API slice).

The reference dashboard is a full web UI; its load-bearing layer is
the REST API the UI consumes.  This module serves that JSON API over
HTTP — `/api/health`, `/api/osds`, `/api/pools`, `/api/summary`,
`/api/pgs` (per-PG placement + degraded/undersized state rollup, the
PG page), `/api/perf` (the live perf-counter collection, the daemon
perf panel), `/api/crush` (the `ceph osd tree` view), `/api/config`
(`config show` with per-option provenance) — plus a minimal index
page, so the cluster is observable from a browser/curl without the
prometheus scraper.  Read-only by design: mutations go through the
mon quorum paths (`ceph` CLI / cephadm), not the dashboard.
"""
from __future__ import annotations

import http.server
import json
import threading
from typing import Optional

from .module_host import MgrModule


class DashboardModule(MgrModule):
    NAME = "dashboard"

    def __init__(self, host):
        super().__init__(host)
        self._server: Optional[http.server.ThreadingHTTPServer] = None

    # --------------------------------------------------------------- api --
    def api_health(self) -> dict:
        osd = self.get("osd_stats")
        n_down = sum(1 for v in osd["up"] if not v)
        return {"status": "HEALTH_WARN" if n_down else "HEALTH_OK",
                "checks": ([{"type": "OSD_DOWN",
                             "message": f"{n_down} osds down"}]
                           if n_down else [])}

    def api_osds(self) -> list:
        osd = self.get("osd_stats")
        return [{"id": i, "up": bool(osd["up"][i]),
                 "in": bool(osd["in"][i]),
                 "weight": int(osd["weight"][i])}
                for i in range(len(osd["up"]))]

    def api_pools(self) -> list:
        m = self.get("osd_map")
        stats = self.get("pool_stats")
        out = []
        for pid, pool in sorted(m.pools.items()):
            s = stats.get(pid, {"objects": 0, "bytes": 0})
            out.append({"id": pid, "name": pool.name,
                        "type": int(pool.type),
                        "pg_num": int(pool.pg_num),
                        "size": int(pool.size),
                        "objects": s["objects"],
                        "bytes": s["bytes"]})
        return out

    def api_summary(self) -> dict:
        m = self.get("osd_map")
        return {"epoch": int(m.epoch), "health": self.api_health(),
                "n_osds": int(m.max_osd),
                "n_pools": len(m.pools),
                "mgr_modules": self.host.enabled()}

    def api_pgs(self) -> dict:
        """Per-PG placement + state rollup (the dashboard PG page /
        `ceph pg dump` summary).  The map pipeline filters down OSDs
        to ITEM_NONE holes, so a hole means a mapped member is
        down/unmappable — Ceph's compound `active+undersized+degraded`
        (fewer copies than size exist until recovery re-homes)."""
        from ..placement.crush_map import ITEM_NONE
        dump = self.get("pg_dump")
        pools = {}
        states = {"active+clean": 0, "active+undersized+degraded": 0,
                  "down": 0}
        for pid, d in sorted(dump.items()):
            rows = []
            for pg, ups in enumerate(d["up"]):
                # positions are SHARD slots for EC pools: holes stay
                # in place as null (like `ceph pg dump`'s NONE), so a
                # consumer can tell WHICH shard is missing
                ups = [int(o) for o in ups]
                n_live = sum(1 for o in ups if o != ITEM_NONE)
                if n_live == 0:
                    state = "down"        # no copy mapped anywhere
                elif n_live == len(ups):
                    state = "active+clean"
                else:
                    state = "active+undersized+degraded"
                states[state] += 1
                rows.append({"pg": f"{pid}.{pg}",
                             "up": [None if o == ITEM_NONE else o
                                    for o in ups],
                             "primary": int(d["primary"][pg]),
                             "state": state})
            pools[str(pid)] = rows
        return {"states": states, "pgs": pools}

    def api_perf(self) -> dict:
        """The live perf-counter collection (`perf dump` over HTTP —
        encode/decode dispatch+byte counters, mapper lanes, tier
        promote/flush/evict ops, ...)."""
        from ..common.perf_counters import perf
        return perf().dump()

    def api_crush(self) -> dict:
        """The CRUSH hierarchy (`ceph osd tree` rows + raw text)."""
        m = self.get("osd_map")
        from ..placement.treedump import tree_dump
        text = tree_dump(m.crush)
        return {"tree": text.splitlines()}

    def api_config(self) -> dict:
        """`config show`: every option's value + provenance layer."""
        from ..common.options import config
        return config().dump()

    # -------------------------------------------------------------- http --
    def start_http(self, port: int = 0) -> int:
        mod = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):             # noqa: N802
                routes = {"/api/health": mod.api_health,
                          "/api/osds": mod.api_osds,
                          "/api/pools": mod.api_pools,
                          "/api/summary": mod.api_summary,
                          "/api/pgs": mod.api_pgs,
                          "/api/perf": mod.api_perf,
                          "/api/crush": mod.api_crush,
                          "/api/config": mod.api_config}
                path = self.path.rstrip("/") or "/"
                if path in routes:
                    body = json.dumps(routes[path]()).encode()
                    ctype = "application/json"
                elif path == "/":
                    body = (b"<html><body><h1>ceph_tpu dashboard"
                            b"</h1><ul>" +
                            b"".join(f'<li><a href="{r}">{r}</a></li>'
                                     .encode() for r in routes) +
                            b"</ul></body></html>")
                    ctype = "text/html"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), Handler)
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        return self._server.server_address[1]

    def stop_http(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


def register(host) -> None:
    host.register(DashboardModule.NAME, DashboardModule)
