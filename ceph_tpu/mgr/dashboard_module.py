"""Dashboard mgr module (src/pybind/mgr/dashboard role, API slice).

The reference dashboard is a full web UI; its load-bearing layer is
the REST API the UI consumes (health, OSDs, pools, usage).  This
module serves that JSON API over HTTP — `/api/health`, `/api/osds`,
`/api/pools`, `/api/summary` — plus a minimal index page, so the
cluster is observable from a browser/curl without the prometheus
scraper.
"""
from __future__ import annotations

import http.server
import json
import threading
from typing import Optional

from .module_host import MgrModule


class DashboardModule(MgrModule):
    NAME = "dashboard"

    def __init__(self, host):
        super().__init__(host)
        self._server: Optional[http.server.ThreadingHTTPServer] = None

    # --------------------------------------------------------------- api --
    def api_health(self) -> dict:
        osd = self.get("osd_stats")
        n_down = sum(1 for v in osd["up"] if not v)
        return {"status": "HEALTH_WARN" if n_down else "HEALTH_OK",
                "checks": ([{"type": "OSD_DOWN",
                             "message": f"{n_down} osds down"}]
                           if n_down else [])}

    def api_osds(self) -> list:
        osd = self.get("osd_stats")
        return [{"id": i, "up": bool(osd["up"][i]),
                 "in": bool(osd["in"][i]),
                 "weight": int(osd["weight"][i])}
                for i in range(len(osd["up"]))]

    def api_pools(self) -> list:
        m = self.get("osd_map")
        stats = self.get("pool_stats")
        out = []
        for pid, pool in sorted(m.pools.items()):
            s = stats.get(pid, {"objects": 0, "bytes": 0})
            out.append({"id": pid, "name": pool.name,
                        "type": int(pool.type),
                        "pg_num": int(pool.pg_num),
                        "size": int(pool.size),
                        "objects": s["objects"],
                        "bytes": s["bytes"]})
        return out

    def api_summary(self) -> dict:
        m = self.get("osd_map")
        return {"epoch": int(m.epoch), "health": self.api_health(),
                "n_osds": int(m.max_osd),
                "n_pools": len(m.pools),
                "mgr_modules": self.host.enabled()}

    # -------------------------------------------------------------- http --
    def start_http(self, port: int = 0) -> int:
        mod = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):             # noqa: N802
                routes = {"/api/health": mod.api_health,
                          "/api/osds": mod.api_osds,
                          "/api/pools": mod.api_pools,
                          "/api/summary": mod.api_summary}
                path = self.path.rstrip("/") or "/"
                if path in routes:
                    body = json.dumps(routes[path]()).encode()
                    ctype = "application/json"
                elif path == "/":
                    body = (b"<html><body><h1>ceph_tpu dashboard"
                            b"</h1><ul>" +
                            b"".join(f'<li><a href="{r}">{r}</a></li>'
                                     .encode() for r in routes) +
                            b"</ul></body></html>")
                    ctype = "text/html"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), Handler)
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        return self._server.server_address[1]

    def stop_http(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


def register(host) -> None:
    host.register(DashboardModule.NAME, DashboardModule)
