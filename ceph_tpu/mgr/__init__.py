"""Manager layer: module host + standard modules (src/mgr/ +
src/pybind/mgr/ roles)."""
from .module_host import MgrModule, MgrModuleHost  # noqa: F401
