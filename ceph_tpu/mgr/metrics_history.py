"""MetricsHistory — the leader mon's time-series memory.

Role of the reference's mgr ``MetricCollector`` (src/mgr/MetricCollector.h:
bounded per-entity metric ring the mgr modules query) combined with the
``PGMap`` delta history (src/mon/PGMap.cc: per-interval stat deltas the
`ceph -s` io line and `ceph osd df` trends read).  Every ``report_perf``
delivery already reaches the leader mon's ClusterStats; this module
retains a bounded ring of those deliveries per reporter so the cluster
finally has *memory* — a `ceph -s` stops being a point-in-time snapshot.

Design:

  * per reporter, a multi-resolution ring: level 0 holds the newest
    ``metrics_history_samples`` raw deliveries; when it overflows, the
    two OLDEST raw samples merge into one level-1 sample, and so on up
    to ``metrics_history_levels`` — log2 downsampling, so retained wall
    coverage grows exponentially while memory stays bounded at
    levels x samples entries per reporter;
  * a merge keeps the NEWER sample of the pair (counters are monotonic
    cumulative values, so deltas TELESCOPE: dropping an interior sample
    fuses two adjacent intervals into one whose delta is exactly their
    sum — downsampling conserves counter sums, the property the tests
    pin);
  * rates derive from consecutive-sample deltas with RESET CLAMPING: a
    daemon restart zeroes its monotonic counters, and a negative delta
    must read as "reset, rate unknown -> 0", never as a huge negative
    or garbage-positive rate.  Resets are counted per reporter and
    surfaced (``stats.counter_resets``);
  * reporters age out after ``stale_s`` (the ClusterStats STALE_S
    window): a daemon that stopped reporting drops from history
    queries rather than pinning week-old series into the CLI.

Only COUNTER-typed keys of the ``HISTORY_GROUPS`` perf groups are
retained — rate derivation is only meaningful over monotonic counters,
which is exactly what lint CTL702 closes statically: every counter
listed in ``RATE_COUNTERS`` must be inc-typed at its declaration site
(a ``set()`` anywhere in the tree on one of these keys is a lint
error, because a gauge fed into the delta pipeline produces garbage
rates silently).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ..common.perf_counters import COUNTER

# perf groups whose COUNTER-typed keys the history ring retains (the
# rate layer's input universe)
HISTORY_GROUPS = ("osd.io", "jit")

# (group, key) pairs the rate/query layer surfaces as headline series
# (CLI defaults, Prometheus short/long-window gauges).  Lint CTL702
# statically verifies each is ONLY ever updated via .inc() — a
# rate-queried counter must be monotonic at its declaration site.
RATE_COUNTERS = (
    ("osd.io", "rd_ops"),
    ("osd.io", "wr_ops"),
    ("osd.io", "rd_bytes"),
    ("osd.io", "wr_bytes"),
    ("jit", "compiles"),
)

DEFAULT_SAMPLES = 64
DEFAULT_LEVELS = 6


def _configured(name: str, default: int) -> int:
    try:
        from ..common.options import config
        return int(config().get(name))
    except Exception:
        return default


class _Ring:
    """One reporter's multi-resolution sample ring.

    ``levels[0]`` is the raw ring (newest deliveries, full
    resolution); ``levels[i]`` holds samples whose implied interval
    fuses 2^i raw deliveries.  Each level is a list of (ts, counters)
    tuples, oldest first, bounded at ``samples`` entries.
    """

    __slots__ = ("levels", "samples", "resets")

    def __init__(self, samples: int, n_levels: int):
        self.samples = max(2, int(samples))
        self.levels: List[List[Tuple[float, Dict[str, float]]]] = \
            [[] for _ in range(max(1, int(n_levels)))]
        self.resets = 0

    def push(self, ts: float, flat: Dict[str, float]) -> None:
        self.levels[0].append((ts, flat))
        # cascade: an overflowing level folds its two oldest samples
        # into the next level by KEEPING THE NEWER one (cumulative
        # counters: the survivor's value already includes the dropped
        # sample's, so the fused interval's delta is the exact sum of
        # the two raw deltas — sums conserve through downsampling)
        for lvl in range(len(self.levels)):
            ring = self.levels[lvl]
            while len(ring) > self.samples:
                if lvl + 1 < len(self.levels):
                    ring.pop(0)      # fused into the survivor's window
                    self.levels[lvl + 1].append(ring.pop(0))
                else:
                    ring.pop(0)      # deepest level: plain oldest-drop

    def series(self) -> List[Tuple[float, Dict[str, float]]]:
        """All retained samples, oldest first (coarse levels precede
        the raw ring — a level-i sample always predates every
        level-(i-1) sample by construction of the cascade)."""
        out: List[Tuple[float, Dict[str, float]]] = []
        for ring in reversed(self.levels):
            out.extend(ring)
        return out

    def newest_ts(self) -> float:
        return self.levels[0][-1][0] if self.levels[0] else 0.0

    def sample_count(self) -> int:
        return sum(len(r) for r in self.levels)


class MetricsHistory:
    """Bounded per-reporter delivery rings + range-query/rate layer.

    Owned by the leader mon's ClusterStats; ``record()`` is called
    from ``ClusterStats.ingest`` under the aggregator's report flow,
    ``query()`` serves the ``cluster_stats {"history": ...}`` wire
    sub-command (`ceph telemetry history`), and the window-rate
    helpers feed the Prometheus short/long gauges and the `ceph osd
    df` sparkline column."""

    def __init__(self, samples: Optional[int] = None,
                 levels: Optional[int] = None,
                 stale_s: float = 600.0):
        self._lock = threading.Lock()
        self.samples = samples if samples is not None else \
            _configured("metrics_history_samples", DEFAULT_SAMPLES)
        self.levels = levels if levels is not None else \
            _configured("metrics_history_levels", DEFAULT_LEVELS)
        self.stale_s = float(stale_s)
        self._rings: Dict[str, _Ring] = {}
        self.counter_resets = 0          # cumulative, all reporters

    # ------------------------------------------------------------ ingest --
    @staticmethod
    def flatten(perf: Dict[str, Any]) -> Dict[str, float]:
        """COUNTER-typed keys of the HISTORY_GROUPS as
        ``group.key`` -> value (the retained sample payload)."""
        out: Dict[str, float] = {}
        for group in HISTORY_GROUPS:
            for key, tv in (perf.get(group) or {}).items():
                if tv[0] == COUNTER and isinstance(tv[1], (int, float)):
                    out[f"{group}.{key}"] = float(tv[1])
        return out

    def record(self, reporter: str, ts: float,
               perf: Dict[str, Any]) -> int:
        """Retain one delivery; returns the number of counter RESETS
        detected against the reporter's previous sample (any retained
        counter that went backwards — a daemon restart zeroed it)."""
        flat = self.flatten(perf)
        if not flat:
            return 0
        with self._lock:
            ring = self._rings.get(reporter)
            if ring is None:
                ring = self._rings[reporter] = _Ring(self.samples,
                                                     self.levels)
            resets = 0
            if ring.levels[0]:
                _pts, pflat = ring.levels[0][-1]
                resets = sum(1 for k, v in flat.items()
                             if k in pflat and v < pflat[k])
            if resets:
                ring.resets += 1
                self.counter_resets += 1
            ring.push(ts, flat)
            return resets

    def prune(self, now: float) -> None:
        """Drop reporters whose newest delivery aged past stale_s
        (the 600 s reporter window — dead daemons leave history)."""
        with self._lock:
            for r in [r for r, ring in self._rings.items()
                      if now - ring.newest_ts() > self.stale_s]:
                del self._rings[r]

    # ------------------------------------------------------------- query --
    def reporters(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def _series_locked(self, reporter: str, counter: str,
                       since: Optional[float],
                       until: Optional[float]
                       ) -> List[Tuple[float, float]]:
        ring = self._rings.get(reporter)
        if ring is None:
            return []
        out = []
        for ts, flat in ring.series():
            if counter not in flat:
                continue
            if since is not None and ts < since:
                continue
            if until is not None and ts > until:
                continue
            out.append((ts, flat[counter]))
        return out

    @staticmethod
    def _rates(samples: List[Tuple[float, float]]
               ) -> List[Tuple[float, float]]:
        """Per-interval rates with reset clamping: a negative delta
        (daemon restart) reads as rate 0.0 at that timestamp, never a
        garbage value."""
        rates = []
        for (pts, pv), (ts, v) in zip(samples, samples[1:]):
            dt = ts - pts
            if dt <= 0:
                continue
            delta = v - pv
            rates.append((ts, 0.0 if delta < 0
                          else round(delta / dt, 6)))
        return rates

    def query(self, counter: str, daemon: Optional[str] = None,
              since: Optional[float] = None,
              until: Optional[float] = None,
              now: Optional[float] = None) -> Dict[str, Any]:
        """Range query: ``counter`` is a ``group.key`` name
        (``osd.io.wr_ops``); ``daemon`` narrows to one reporter, else
        every live reporter answers.  -> {"counter", "series":
        {daemon: {"samples": [[ts, value]...], "rates": [[ts,
        rate]...], "resets": n}}, "counter_resets": total}."""
        import time as _time
        if now is None:
            now = _time.time()
        self.prune(now)
        with self._lock:
            names = [daemon] if daemon else sorted(self._rings)
            series: Dict[str, Any] = {}
            for name in names:
                samples = self._series_locked(name, counter,
                                              since, until)
                if not samples:
                    continue
                ring = self._rings[name]
                series[name] = {
                    "samples": [[round(ts, 6), v]
                                for ts, v in samples],
                    "rates": [[round(ts, 6), r]
                              for ts, r in self._rates(samples)],
                    "resets": ring.resets,
                }
            return {"counter": counter, "series": series,
                    "counter_resets": self.counter_resets}

    # ------------------------------------------------------ window rates --
    def window_rate(self, reporter: str, counter: str,
                    window: int = 2) -> Optional[float]:
        """Rate over the newest ``window`` retained samples (2 =
        latest interval, the "short" Prometheus gauge; a large window
        spans the whole retained ring, the "long" gauge).  Reset
        intervals clamp to zero inside the window."""
        with self._lock:
            samples = self._series_locked(reporter, counter,
                                          None, None)
        if len(samples) < 2:
            return None
        samples = samples[-max(2, window):]
        total = 0.0
        dt = samples[-1][0] - samples[0][0]
        if dt <= 0:
            return None
        for (pts, pv), (_ts, v) in zip(samples, samples[1:]):
            d = v - pv
            if d > 0:
                total += d
        return round(total / dt, 6)

    def sparkline(self, reporter: str, counter: str,
                  width: int = 12) -> str:
        """Unicode sparkline of the newest ``width`` per-interval
        rates (the `ceph osd df` trend column); "-" when fewer than
        two samples exist."""
        with self._lock:
            samples = self._series_locked(reporter, counter,
                                          None, None)
        rates = [r for _ts, r in self._rates(samples)][-width:]
        if not rates:
            return "-"
        blocks = "▁▂▃▄▅▆▇█"
        top = max(rates)
        if top <= 0:
            return blocks[0] * len(rates)
        return "".join(
            blocks[min(len(blocks) - 1,
                       int(r / top * (len(blocks) - 1) + 0.5))]
            for r in rates)

    def dump(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "samples_per_level": self.samples,
                "levels": self.levels,
                "counter_resets": self.counter_resets,
                "reporters": {
                    r: {"samples": ring.sample_count(),
                        "resets": ring.resets,
                        "newest_ts": round(ring.newest_ts(), 6)}
                    for r, ring in sorted(self._rings.items())},
            }

    def reset(self) -> None:
        with self._lock:
            self._rings.clear()
            self.counter_resets = 0
