"""Balancer mgr module — wraps the upmap optimizer as a module
(src/pybind/mgr/balancer/module.py calling OSDMap::calc_pg_upmaps)."""
from __future__ import annotations

from ..cluster.balancer import BalanceResult, calc_pg_upmaps
from .module_host import MgrModule


class BalancerModule(MgrModule):
    NAME = "balancer"

    def __init__(self, host):
        super().__init__(host)
        self.mode = "upmap"
        self.last_result: BalanceResult | None = None

    def optimize(self, **kw) -> BalanceResult:
        self.last_result = calc_pg_upmaps(self.get("osd_map"), **kw)
        return self.last_result

    def eval(self, cluster_stats, **kw) -> dict:
        """Dry-run advisor (`ceph balancer eval`): score the current
        mapping from heat x utilization and return proposed moves as
        a report — calc_pg_upmaps MUTATES the map, this never does."""
        from .balancer_advisor import evaluate
        return evaluate(self.get("osd_map"), cluster_stats, **kw)

    def serve_tick(self) -> None:
        self.optimize()


def register(host) -> None:
    host.register(BalancerModule.NAME, BalancerModule)
