"""Telemetry mgr module (src/pybind/mgr/telemetry role).

Builds the anonymized cluster report the reference phones home:
cluster shape (osd/pool/pg counts), usage, health, and crash-free
uptime — WITHOUT identifying payloads (no object names, no keys).
This environment has zero egress, so "send" appends the report to a
local spool with a monotonically increasing report id (the judge of
honesty here: the reference module also spools and retries locally
when the endpoint is unreachable).  Reports require explicit opt-in
(``on()``), matching the reference's license/opt-in gate.
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from .module_host import MgrModule


class TelemetryModule(MgrModule):
    NAME = "telemetry"
    INTERVAL_TICKS = 4          # reference sends every 24h; ticks here

    def __init__(self, host):
        super().__init__(host)
        self.enabled = False    # opt-in gate (telemetry on)
        self.spool: List[Dict[str, Any]] = []
        self._seq = 0
        self._ticks = 0

    # -------------------------------------------------------------- gate --
    def on(self) -> None:
        self.enabled = True

    def off(self) -> None:
        self.enabled = False

    # ------------------------------------------------------------ report --
    def compile_report(self, now: Optional[float] = None) -> Dict:
        """The anonymized snapshot (telemetry module's report shape,
        reduced to what this cluster model exposes)."""
        m = self.get("osd_map")
        osd = self.get("osd_stats")
        pstats = self.get("pool_stats")
        n_up = sum(1 for v in osd["up"] if v)
        n_in = sum(1 for v in osd["in"] if v)
        pools = []
        for pid, pool in sorted(m.pools.items()):
            s = pstats.get(pid, {"objects": 0, "bytes": 0})
            pools.append({
                "pool_id": pid,
                "type": int(pool.type),
                "pg_num": int(pool.pg_num),
                "size": int(getattr(pool, "size", 0)),
                "objects": s["objects"],
                "bytes": s["bytes"],
            })
        return {
            "ts": time.time() if now is None else now,
            "osd": {"count": int(m.max_osd), "up": n_up, "in": n_in},
            "pools": pools,
            "total_objects": sum(p["objects"] for p in pools),
            "total_bytes": sum(p["bytes"] for p in pools),
            "health": "HEALTH_OK" if n_up == int(m.max_osd)
                      else "HEALTH_WARN",
        }

    def send(self, now: Optional[float] = None) -> int:
        """Spool one report; returns its report id."""
        if not self.enabled:
            raise RuntimeError(
                "telemetry is off: explicit opt-in required "
                "(`telemetry on`)")
        self._seq += 1
        report = {"report_id": self._seq,
                  **self.compile_report(now)}
        self.spool.append(report)
        return self._seq

    def last_report(self) -> Optional[Dict]:
        return self.spool[-1] if self.spool else None

    def show(self) -> str:
        """`ceph telemetry show` — what WOULD be sent."""
        return json.dumps(self.compile_report(), indent=2,
                          sort_keys=True)

    # -------------------------------------------------------------- serve --
    def serve_tick(self) -> None:
        if not self.enabled:
            return
        self._ticks += 1
        if self._ticks % self.INTERVAL_TICKS == 0:
            self.send()


def register(host) -> None:
    host.register(TelemetryModule.NAME, TelemetryModule)
