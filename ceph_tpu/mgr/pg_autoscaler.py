"""pg_autoscaler mgr module (src/pybind/mgr/pg_autoscaler role).

The reference autoscaler computes, per pool, a target PG count from
the pool's share of cluster usage and the per-OSD PG budget, rounds to
a power of two, and only acts when the actual count is off by more
than a 3x threshold (pg_autoscale_mode=on) — small drifts are left
alone to avoid data movement churn.  Same math here:

  target_raw = usage_share * osd_count * mon_target_pg_per_osd / size
  target     = next power of two >= target_raw (>= pool minimum)
  act if max(target, actual) / min(target, actual) >= threshold

Usage share uses the pool's logical bytes over total logical bytes
(capacity-based estimation is a refinement the sim's stores don't
model); empty clusters fall back to an even split.
"""
from __future__ import annotations

from typing import Dict, List

from .module_host import MgrModule

MON_TARGET_PG_PER_OSD = 100      # reference default option
MIN_PG = 4
THRESHOLD = 3.0


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


class PgAutoscaler(MgrModule):
    NAME = "pg_autoscaler"

    def __init__(self, host):
        super().__init__(host)
        # mode "on" is safe: set_pool_pg_num reshards the pool's
        # objects to their new PGs before the map change commits
        # (ClusterSim.reshard_pool, the PG-split data movement);
        # default remains "warn" per the reference's conservative
        # pg_autoscale_mode posture — operators opt in
        self.mode = "warn"           # on | warn (off = module disabled)
        self.last_recommendations: List[Dict] = []

    # ------------------------------------------------------------ policy --
    def recommendations(self) -> List[Dict]:
        m = self.get("osd_map")
        stats = self.get("pool_stats")
        osd = self.get("osd_stats")
        n_osds = max(1, sum(1 for v in osd["in"] if v))
        total_bytes = sum(s["bytes"] for s in stats.values())
        out = []
        for pid, pool in sorted(m.pools.items()):
            share = (stats.get(pid, {}).get("bytes", 0) / total_bytes
                     if total_bytes else 1.0 / max(1, len(m.pools)))
            raw = share * n_osds * MON_TARGET_PG_PER_OSD / max(1,
                                                               pool.size)
            target = max(MIN_PG, _next_pow2(max(1, round(raw))))
            actual = pool.pg_num
            ratio = max(target, actual) / max(1, min(target, actual))
            out.append({
                "pool_id": pid, "pool_name": pool.name,
                "actual_pg_num": actual, "target_pg_num": target,
                "usage_share": round(share, 4),
                "would_adjust": ratio >= THRESHOLD,
            })
        self.last_recommendations = out
        return out

    def serve_tick(self) -> None:
        for rec in self.recommendations():
            if rec["would_adjust"] and self.mode == "on":
                self.set_pool_pg_num(rec["pool_id"],
                                     rec["target_pg_num"])


def register(host) -> None:
    host.register(PgAutoscaler.NAME, PgAutoscaler)
