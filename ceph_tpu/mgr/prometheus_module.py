"""Prometheus exporter mgr module (src/pybind/mgr/prometheus role).

Renders cluster state and the process perf counters in the Prometheus
text exposition format (the scrape payload), optionally served over
HTTP.  Metric names mirror the reference exporter's families:
ceph_osd_up / ceph_osd_in / ceph_osd_weight, ceph_pg_total,
ceph_pool_objects / ceph_pool_bytes, ceph_health_status, plus every
ceph_tpu perf counter as ceph_tpu_<group>_<name>.
"""
from __future__ import annotations

import http.server
import threading
from typing import List, Optional

from ..common.perf_counters import (COUNTER, GAUGE, HISTOGRAM, TIME_AVG,
                                    perf as _perf)
from .module_host import MgrModule


def _esc(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_le(bound) -> str:
    """Prometheus le label: trim float noise, keep +Inf literal."""
    if isinstance(bound, str):
        return bound
    return repr(float(bound))


class PrometheusModule(MgrModule):
    NAME = "prometheus"

    def __init__(self, host):
        super().__init__(host)
        self._server: Optional[http.server.ThreadingHTTPServer] = None

    # ------------------------------------------------------------ render --
    def render(self) -> str:
        lines: List[str] = []

        def metric(name, help_, type_, samples):
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {type_}")
            for labels, value in samples:
                if labels:
                    lab = ",".join(f'{k}="{_esc(str(v))}"'
                                   for k, v in labels.items())
                    lines.append(f"{name}{{{lab}}} {value}")
                else:
                    lines.append(f"{name} {value}")

        osd = self.get("osd_stats")
        n = len(osd["up"])
        metric("ceph_osd_up", "OSD up state", "gauge",
               [({"ceph_daemon": f"osd.{i}"}, int(osd["up"][i]))
                for i in range(n)])
        metric("ceph_osd_in", "OSD in state", "gauge",
               [({"ceph_daemon": f"osd.{i}"}, int(osd["in"][i]))
                for i in range(n)])
        metric("ceph_osd_weight", "OSD crush weight (16.16 fixed)",
               "gauge",
               [({"ceph_daemon": f"osd.{i}"}, osd["weight"][i])
                for i in range(n)])
        m = self.get("osd_map")
        metric("ceph_pg_total", "PGs per pool", "gauge",
               [({"pool_id": pid}, pool.pg_num)
                for pid, pool in sorted(m.pools.items())])
        pstats = self.get("pool_stats")
        metric("ceph_pool_objects", "objects per pool", "gauge",
               [({"pool_id": pid}, s["objects"])
                for pid, s in sorted(pstats.items())])
        metric("ceph_pool_bytes", "logical bytes per pool", "gauge",
               [({"pool_id": pid}, s["bytes"])
                for pid, s in sorted(pstats.items())])
        n_down = sum(1 for v in osd["up"] if not v)
        metric("ceph_health_status",
               "0=HEALTH_OK 1=HEALTH_WARN 2=HEALTH_ERR", "gauge",
               [({}, 1 if n_down else 0)])
        # process perf counters (the exporter's daemon-perf families),
        # rendered by DECLARED type: counters stay counters, gauges
        # gauges, TIME_AVG surfaces its long-run average as a gauge,
        # and histograms become full `_bucket`/`_sum`/`_count` families
        # (cumulative buckets; the +Inf bucket equals `_count`)
        for group, counters in sorted(_perf().dump_typed().items()):
            for cname, (typ, value) in sorted(counters.items()):
                safe = f"ceph_tpu_{group}_{cname}".replace(".", "_") \
                    .replace("-", "_")
                help_ = f"perf counter {group}.{cname}"
                if typ == HISTOGRAM:
                    self._render_histogram(lines, safe, help_, value)
                elif typ == TIME_AVG:
                    metric(safe, help_ + " (long-run avg seconds)",
                           "gauge", [({}, value["avgtime"])])
                elif isinstance(value, (int, float)) and \
                        not isinstance(value, bool):
                    metric(safe, help_,
                           "gauge" if typ == GAUGE else "counter",
                           [({}, value)])
        # cluster section (ClusterTelemetry): when a mon with a
        # ClusterStats aggregator is attached, ONE scrape also serves
        # every reporting daemon's families under per-daemon labels
        # plus the bucket-wise merged ceph_cluster_* histograms and
        # quantile gauges — the reference mgr's cluster-wide
        # prometheus view replacing the per-process-only one
        try:
            cs = self.get("cluster_stats")
        except KeyError:
            cs = None
        if cs is not None and cs.daemons():
            lines.append(cs.render_prometheus().rstrip("\n"))
        return "\n".join(lines) + "\n"

    @staticmethod
    def _render_histogram(lines: List[str], name: str, help_: str,
                          dumped) -> None:
        """One Prometheus histogram family from a PerfHistogram dump
        ({count, sum, buckets: [[le, n], ...]} with non-cumulative
        counts; le ascending, '+Inf' last when populated)."""
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} histogram")
        cum = 0
        saw_inf = False
        for le, n in dumped["buckets"]:
            cum += n
            saw_inf = saw_inf or le == "+Inf"
            lines.append(
                f'{name}_bucket{{le="{_fmt_le(le)}"}} {cum}')
        if not saw_inf:
            lines.append(f'{name}_bucket{{le="+Inf"}} '
                         f'{dumped["count"]}')
        lines.append(f'{name}_sum {dumped["sum"]}')
        lines.append(f'{name}_count {dumped["count"]}')

    # -------------------------------------------------------------- http --
    def start_http(self, port: int = 0) -> int:
        """Serve /metrics; returns the bound port."""
        mod = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):             # noqa: N802 (stdlib API)
                if self.path.rstrip("/") in ("", "/metrics",
                                             "/metrics/"):
                    body = mod.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

            def log_message(self, *a):     # silent
                pass

        self._server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), Handler)
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        return self._server.server_address[1]

    def stop_http(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()     # release the listening fd
            self._server = None


def register(host) -> None:
    host.register(PrometheusModule.NAME, PrometheusModule)
