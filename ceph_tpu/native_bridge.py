"""ctypes bridge to the native C++ runtime (native/libceph_tpu_native.so).

Two surfaces:

  * ``NativeMapper`` — the compiled C++ CRUSH interpreter
    (native/crush_native.cpp), the fast host-side mapper.  It is the
    honest scalar-CPU baseline for the batched TPU mapper and the
    low-latency fallback for maps outside the vectorized subset (the
    role of crush_do_rule behind CrushWrapper::do_rule,
    src/crush/CrushWrapper.h:1581).
  * ``gf_matmul_regions`` — the SIMD GF(2^8) region codec
    (native/gf_native.cpp), the role ISA-L's ec_encode_data plays in the
    reference (src/erasure-code/isa/ErasureCodeIsa.cc:129) and the
    honest local CPU throughput baseline for the TPU EC kernels.

The shared object is (re)built on demand with `make -C native`; loading
is lazy so pure-Python paths never require a toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

from .placement import lntable
from .placement.crush_map import (
    BUCKET_LIST, BUCKET_STRAW, BUCKET_TREE, ITEM_NONE, CrushMap)

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SO = os.path.join(_NATIVE_DIR, "libceph_tpu_native.so")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None

_I32P = ctypes.POINTER(ctypes.c_int32)
_I64P = ctypes.POINTER(ctypes.c_int64)
_U8P = ctypes.POINTER(ctypes.c_uint8)
_U32P = ctypes.POINTER(ctypes.c_uint32)


class NativeUnavailable(RuntimeError):
    """The native library could not be built or loaded."""


def ensure_built(force: bool = False) -> str:
    """Build the shared object if missing or stale; returns its path."""
    srcs = [os.path.join(_NATIVE_DIR, f)
            for f in ("crush_native.cpp", "gf_native.cpp",
                      "msgqueue.cpp", "allocator_native.cpp", "Makefile")]
    stale = (not os.path.exists(_SO) or
             any(os.path.getmtime(s) > os.path.getmtime(_SO)
                 for s in srcs if os.path.exists(s)))
    if force or stale:
        proc = subprocess.run(["make", "-C", _NATIVE_DIR],
                              capture_output=True, text=True, timeout=300)
        if proc.returncode != 0:
            raise NativeUnavailable(
                f"native build failed:\n{proc.stdout}\n{proc.stderr}")
    return _SO


def _i32p(a: Optional[np.ndarray]):
    if a is None:
        return None
    return a.ctypes.data_as(_I32P)


def lib() -> ctypes.CDLL:
    global _LIB
    with _LOCK:
        if _LIB is None:
            try:
                so = ensure_built()
                _LIB = ctypes.CDLL(so)
            except OSError as e:
                raise NativeUnavailable(str(e)) from e
            _LIB.ceph_tpu_do_rule_batch.restype = ctypes.c_int
            _LIB.ceph_tpu_do_rule_batch.argtypes = [
                ctypes.c_int32, ctypes.c_int32,          # n_buckets, max_size
                _I32P, _I32P, _I32P, _I32P, _I32P,       # items..algs
                _I32P, _I32P, _I32P, _I32P,              # aux tables
                _I64P, ctypes.c_int32,                   # ln_table, max_dev
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,  # tunables
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                _I32P, ctypes.c_int32,                   # steps, n_steps
                _I32P, _I32P, ctypes.c_int32,            # choose_args
                _U32P, ctypes.c_int64, ctypes.c_int32,   # xs, n, result_max
                _I32P, _I32P]                            # weights, results
            _LIB.ceph_tpu_gf_matmul_regions.restype = ctypes.c_int
            _LIB.ceph_tpu_gf_matmul_regions.argtypes = [
                _U8P, ctypes.c_int32, ctypes.c_int32, _U8P, _U8P,
                ctypes.c_int64]
            _LIB.ceph_tpu_gf_region_mul_xor.restype = None
            _LIB.ceph_tpu_gf_region_mul_xor.argtypes = [
                _U8P, _U8P, ctypes.c_uint8, ctypes.c_int64]
            _LIB.ceph_tpu_gf2_xor_regions.restype = ctypes.c_int
            _LIB.ceph_tpu_gf2_xor_regions.argtypes = [
                _U8P, ctypes.c_int32, ctypes.c_int32, _U8P, _U8P,
                ctypes.c_int64]
            _U64P = ctypes.POINTER(ctypes.c_uint64)
            _LIB.ceph_tpu_alloc_init.restype = None
            _LIB.ceph_tpu_alloc_init.argtypes = [_U64P, ctypes.c_int64]
            _LIB.ceph_tpu_alloc_count_free.restype = ctypes.c_int64
            _LIB.ceph_tpu_alloc_count_free.argtypes = [
                _U64P, ctypes.c_int64]
            _LIB.ceph_tpu_alloc_mark.restype = ctypes.c_int
            _LIB.ceph_tpu_alloc_mark.argtypes = [
                _U64P, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64]
            _LIB.ceph_tpu_alloc_release.restype = ctypes.c_int
            _LIB.ceph_tpu_alloc_release.argtypes = [
                _U64P, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64]
            _LIB.ceph_tpu_alloc_runs.restype = ctypes.c_int
            _LIB.ceph_tpu_alloc_runs.argtypes = [
                _U64P, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                _I64P, ctypes.c_int]
            _LIB.ceph_tpu_has_avx2.restype = ctypes.c_int
            _LIB.ceph_tpu_hash2.restype = ctypes.c_uint32
            _LIB.ceph_tpu_hash2.argtypes = [ctypes.c_uint32, ctypes.c_uint32]
            _LIB.ceph_tpu_hash3.restype = ctypes.c_uint32
            _LIB.ceph_tpu_hash3.argtypes = [ctypes.c_uint32, ctypes.c_uint32,
                                            ctypes.c_uint32]
        return _LIB


def has_avx2() -> bool:
    return bool(lib().ceph_tpu_has_avx2())


# ------------------------------------------------------------------ CRUSH ---

class NativeMapper:
    """Flatten a CrushMap into the dense MapView arrays once, then run
    batched do_rule sweeps through the C++ interpreter."""

    def __init__(self, cmap: CrushMap, choose_args_key: object = None):
        lib()   # fail fast if unbuildable
        self.cmap = cmap
        B = cmap.max_buckets
        # node_weights stride in the C ABI is 2*max_size: widen max_size so
        # every TREE bucket's num_nodes (which can exceed 2*size for
        # non-power-of-two sizes) still fits.
        S = max((b.size for b in cmap.buckets if b is not None), default=1)
        for b in cmap.buckets:
            if b is not None and b.alg == BUCKET_TREE and b.num_nodes:
                S = max(S, (b.num_nodes + 1) // 2)
        S = max(S, 1)
        self.items = np.zeros((B, S), dtype=np.int32)
        self.weights = np.zeros((B, S), dtype=np.int32)
        self.sizes = np.zeros(B, dtype=np.int32)
        self.types = np.zeros(B, dtype=np.int32)
        self.algs = np.zeros(B, dtype=np.int32)
        self.sum_weights = np.zeros((B, S), dtype=np.int32)
        self.straws = np.zeros((B, S), dtype=np.int32)
        self.node_weights = np.zeros((B, 2 * S), dtype=np.int32)
        self.num_nodes = np.zeros(B, dtype=np.int32)
        for i, b in enumerate(cmap.buckets):
            if b is None:
                continue
            n = b.size
            self.items[i, :n] = b.items
            if b.weights:
                w = ([b.weights[0]] * n if len(b.weights) == 1 and n > 1
                     else b.weights[:n])
                self.weights[i, :len(w)] = w
            self.sizes[i] = n
            self.types[i] = b.type
            self.algs[i] = b.alg
            # derived tables are u32 (wrapped in finalize_derived);
            # reinterpret as i32 for the C ABI, which zero-extends back
            if b.alg == BUCKET_LIST and b.sum_weights:
                self.sum_weights[i, :n] = np.asarray(
                    b.sum_weights, dtype=np.uint32).view(np.int32)
            if b.alg == BUCKET_STRAW and b.straws:
                self.straws[i, :n] = np.asarray(
                    b.straws, dtype=np.uint32).view(np.int32)
            if b.alg == BUCKET_TREE and b.node_weights:
                self.node_weights[i, :len(b.node_weights)] = np.asarray(
                    b.node_weights, dtype=np.uint32).view(np.int32)
                self.num_nodes[i] = b.num_nodes
        self.max_size = S
        self.ln_table = np.ascontiguousarray(
            lntable.crush_ln_lut(), dtype=np.int64)
        # choose_args → flattened [B, P, S] weight sets / [B, S] ids
        self.arg_weight_sets: Optional[np.ndarray] = None
        self.arg_ids: Optional[np.ndarray] = None
        self.n_positions = 0
        if choose_args_key is not None:
            args = cmap.choose_args.get(choose_args_key)
            if args:
                P = max((len(a.weight_set) for a in args
                         if a is not None and a.weight_set), default=0)
                if P:
                    ws = np.zeros((B, P, S), dtype=np.int32)
                    for i, a in enumerate(args[:B]):
                        src = (a.weight_set if a is not None and a.weight_set
                               else None)
                        for p in range(P):
                            row = (src[min(p, len(src) - 1)] if src
                                   else (cmap.buckets[i].weights
                                         if cmap.buckets[i] else []))
                            ws[i, p, :len(row)] = row
                    self.arg_weight_sets = ws
                    self.n_positions = P
                if any(a is not None and a.ids for a in args):
                    ids = np.array(self.items, copy=True)
                    for i, a in enumerate(args[:B]):
                        if a is not None and a.ids:
                            ids[i, :len(a.ids)] = a.ids
                    self.arg_ids = ids

    def map_batch(self, ruleno: int, xs, result_max: int,
                  weights: Sequence[int]) -> np.ndarray:
        rule = self.cmap.rules[ruleno]
        if rule is None:
            raise ValueError(f"no rule {ruleno}")
        steps = np.asarray([list(s) for s in rule.steps],
                           dtype=np.int32).reshape(-1)
        xs = np.ascontiguousarray(np.asarray(xs, dtype=np.uint32))
        dev_w = np.zeros(self.cmap.max_devices, dtype=np.int32)
        w_in = np.asarray(list(weights), dtype=np.int64)
        dev_w[:len(w_in)] = np.clip(w_in, 0, 0x10000)
        results = np.empty((len(xs), result_max), dtype=np.int32)
        t = self.cmap.tunables
        rc = lib().ceph_tpu_do_rule_batch(
            np.int32(self.cmap.max_buckets), np.int32(self.max_size),
            _i32p(self.items), _i32p(self.weights), _i32p(self.sizes),
            _i32p(self.types), _i32p(self.algs), _i32p(self.sum_weights),
            _i32p(self.straws), _i32p(self.node_weights),
            _i32p(self.num_nodes), self.ln_table.ctypes.data_as(_I64P),
            np.int32(self.cmap.max_devices),
            np.int32(t.choose_local_tries),
            np.int32(t.choose_local_fallback_tries),
            np.int32(t.choose_total_tries),
            np.int32(t.chooseleaf_descend_once),
            np.int32(t.chooseleaf_vary_r),
            np.int32(t.chooseleaf_stable),
            _i32p(steps), np.int32(len(rule.steps)),
            _i32p(self.arg_weight_sets), _i32p(self.arg_ids),
            np.int32(self.n_positions),
            xs.ctypes.data_as(_U32P), np.int64(len(xs)),
            np.int32(result_max), _i32p(dev_w),
            results.ctypes.data_as(_I32P))
        if rc != 0:
            raise RuntimeError(f"native do_rule_batch rc={rc}")
        return results


# --------------------------------------------------------------------- GF ---

def gf_matmul_regions(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """out[m, chunk] = matrix[m, k] ∘ data[k, chunk] over GF(2^8)."""
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    m, k = matrix.shape
    assert data.shape[0] == k, (matrix.shape, data.shape)
    chunk = data.shape[1]
    out = np.empty((m, chunk), dtype=np.uint8)
    lib().ceph_tpu_gf_matmul_regions(
        matrix.ctypes.data_as(_U8P), np.int32(m), np.int32(k),
        data.ctypes.data_as(_U8P), out.ctypes.data_as(_U8P),
        np.int64(chunk))
    return out


def gf_matmul_regions_batch(matrix: np.ndarray,
                            data: np.ndarray) -> np.ndarray:
    """Batched: data [B, k, chunk] → [B, m, chunk]."""
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    B, k, chunk = data.shape
    m = matrix.shape[0]
    out = np.empty((B, m, chunk), dtype=np.uint8)
    fn = lib().ceph_tpu_gf_matmul_regions
    mp = matrix.ctypes.data_as(_U8P)
    for i in range(B):
        fn(mp, np.int32(m), np.int32(k), data[i].ctypes.data_as(_U8P),
           out[i].ctypes.data_as(_U8P), np.int64(chunk))
    return out


def region_mul_xor(dst: np.ndarray, src: np.ndarray, c: int) -> None:
    """dst ^= c * src in place (GF(2^8))."""
    assert dst.dtype == np.uint8 and src.dtype == np.uint8
    assert dst.flags.c_contiguous and src.flags.c_contiguous
    lib().ceph_tpu_gf_region_mul_xor(
        dst.ctypes.data_as(_U8P), src.ctypes.data_as(_U8P),
        np.uint8(c), np.int64(dst.size))


def gf2_xor_regions(bitmat: np.ndarray, planes: np.ndarray) -> np.ndarray:
    """AVX2 bit-sliced codec: out[R, P] planes = bitmat [R, C] ∘
    planes [C, P] over GF(2) (region XOR — jerasure schedule role)."""
    bitmat = np.ascontiguousarray(bitmat, dtype=np.uint8)
    planes = np.ascontiguousarray(planes, dtype=np.uint8)
    R, C = bitmat.shape
    if planes.shape[0] != C:
        raise ValueError(
            f"bitmat {bitmat.shape} needs {C} planes, got {planes.shape}")
    P = planes.shape[1]
    out = np.empty((R, P), dtype=np.uint8)
    lib().ceph_tpu_gf2_xor_regions(
        bitmat.ctypes.data_as(_U8P), np.int32(R), np.int32(C),
        planes.ctypes.data_as(_U8P), out.ctypes.data_as(_U8P), np.int64(P))
    return out


# ---------------------------------------------------------------- allocator --

_U64PTR = ctypes.POINTER(ctypes.c_uint64)


class AllocatorError(RuntimeError):
    pass


class BitmapAllocator:
    """Block-space allocator over a numpy uint64 bitmap (the BlueStore
    Allocator family role — src/os/bluestore/BitmapAllocator.h).  The
    bitmap itself is plain numpy so the owning store can rebuild it from
    object metadata at mount (the post-Pacific BlueStore NCB freelist
    stance: no persisted freelist, recover allocations from onodes).

    A pure-numpy fallback keeps the store importable without a
    toolchain; the native path is the default.
    """

    def __init__(self, n_blocks: int, use_native: bool = True):
        if n_blocks <= 0:
            raise ValueError("n_blocks must be positive")
        self.n_blocks = int(n_blocks)
        self._words = np.zeros((self.n_blocks + 63) // 64, dtype=np.uint64)
        self._native = False
        if use_native:
            try:
                lib().ceph_tpu_alloc_init(
                    self._words.ctypes.data_as(_U64PTR),
                    np.int64(self.n_blocks))
                self._native = True
            except NativeUnavailable:
                pass
        if not self._native:
            rem = self.n_blocks % 64
            if rem:
                self._words[-1] = np.uint64(
                    (0xFFFFFFFFFFFFFFFF << rem) & 0xFFFFFFFFFFFFFFFF)

    @property
    def free_blocks(self) -> int:
        if self._native:
            return int(lib().ceph_tpu_alloc_count_free(
                self._words.ctypes.data_as(_U64PTR),
                np.int64(self.n_blocks)))
        used = int(np.unpackbits(
            self._words.view(np.uint8)).sum())
        return self._words.size * 64 - used

    def _bits(self) -> np.ndarray:
        """Bit array [n_words*64], little-endian bit order per word."""
        by = self._words.view(np.uint8)
        return np.unpackbits(by, bitorder="little")

    def allocate(self, want: int, hint: int = 0):
        """Allocate `want` blocks; returns list of (start, len) runs.
        Raises AllocatorError when space is insufficient (no partial
        allocation escapes)."""
        if want <= 0:
            return []
        max_runs = max(16, min(4096, int(want)))
        if self._native:
            out = np.empty(2 * max_runs, dtype=np.int64)
            rc = lib().ceph_tpu_alloc_runs(
                self._words.ctypes.data_as(_U64PTR),
                np.int64(self.n_blocks), np.int64(want), np.int64(hint),
                out.ctypes.data_as(_I64P), np.int32(max_runs))
            if rc >= 0:
                return [(int(out[2 * i]), int(out[2 * i + 1]))
                        for i in range(rc)]
            if self.free_blocks < want:
                raise AllocatorError(
                    f"cannot allocate {want} blocks "
                    f"({self.free_blocks} free)")
            # enough space but the run table overflowed (severe
            # fragmentation): the vectorized path below has no run cap
        # numpy fallback: greedy first-fit over free runs
        bits = self._bits()[:self.n_blocks]
        free_idx = np.flatnonzero(bits == 0)
        if len(free_idx) < want:
            raise AllocatorError(
                f"cannot allocate {want} blocks ({len(free_idx)} free)")
        order = np.concatenate([free_idx[free_idx >= hint],
                                free_idx[free_idx < hint]])
        take = np.sort(order[:want])
        runs = []
        run_start = prev = int(take[0])
        for b in take[1:]:
            b = int(b)
            if b == prev + 1:
                prev = b
                continue
            runs.append((run_start, prev - run_start + 1))
            run_start = prev = b
        runs.append((run_start, prev - run_start + 1))
        for s, ln in runs:
            self.mark(s, ln)
        return runs

    def mark(self, start: int, length: int) -> None:
        """Mark [start, start+len) allocated; AllocatorError on overlap
        (mount-time rebuild uses this to detect double-allocation)."""
        if self._native:
            rc = lib().ceph_tpu_alloc_mark(
                self._words.ctypes.data_as(_U64PTR),
                np.int64(self.n_blocks), np.int64(start),
                np.int64(length))
            if rc != 0:
                raise AllocatorError(
                    f"mark [{start},+{length}): overlap/out-of-range")
            return
        if start < 0 or length <= 0 or start + length > self.n_blocks:
            raise AllocatorError(f"mark [{start},+{length}): out of range")
        for b in range(start, start + length):
            w, bit = b // 64, b % 64
            m = np.uint64(1 << bit)
            if self._words[w] & m:
                raise AllocatorError(f"mark {b}: already allocated")
            self._words[w] |= m

    def release(self, start: int, length: int) -> None:
        if self._native:
            rc = lib().ceph_tpu_alloc_release(
                self._words.ctypes.data_as(_U64PTR),
                np.int64(self.n_blocks), np.int64(start),
                np.int64(length))
            if rc != 0:
                raise AllocatorError(
                    f"release [{start},+{length}): double free/range")
            return
        if start < 0 or length <= 0 or start + length > self.n_blocks:
            raise AllocatorError(
                f"release [{start},+{length}): out of range")
        for b in range(start, start + length):
            w, bit = b // 64, b % 64
            m = np.uint64(1 << bit)
            if not (self._words[w] & m):
                raise AllocatorError(f"release {b}: double free")
            self._words[w] &= ~m


def gf2_xor_regions_batch(bitmat: np.ndarray,
                          planes: np.ndarray) -> np.ndarray:
    """Batched bit-sliced codec: planes [B, C, P] → [B, R, P]."""
    bitmat = np.ascontiguousarray(bitmat, dtype=np.uint8)
    planes = np.ascontiguousarray(planes, dtype=np.uint8)
    B, C, P = planes.shape
    R = bitmat.shape[0]
    if bitmat.shape[1] != C:
        raise ValueError(
            f"bitmat {bitmat.shape} needs {bitmat.shape[1]} planes, "
            f"got {C}")
    out = np.empty((B, R, P), dtype=np.uint8)
    fn = lib().ceph_tpu_gf2_xor_regions
    bp = bitmat.ctypes.data_as(_U8P)
    for i in range(B):
        fn(bp, np.int32(R), np.int32(C), planes[i].ctypes.data_as(_U8P),
           out[i].ctypes.data_as(_U8P), np.int64(P))
    return out
