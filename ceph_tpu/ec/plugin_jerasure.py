"""The 'jerasure' codec family — baseline RS/Cauchy techniques.

Re-creates the technique surface of the reference jerasure plugin
(src/erasure-code/jerasure/ErasureCodeJerasure.h:81-240) from first
principles (the GF libraries are empty submodules in the reference
checkout; ceph_tpu.ops.gf re-derives the math):

  * reed_sol_van    — systematic Vandermonde RS, w in {8, 16}
  * reed_sol_r6_op  — RAID-6 P/Q (m == 2; rows [1..1], [1,2,4,...])
  * cauchy_orig     — Cauchy generator 1/(i ^ (m+j))
  * cauchy_good     — normalized Cauchy

The bitmatrix techniques run on the GF(2) plane layout:

  * liberation     — RAID-6 minimal-density bitmatrix (m=2, prime w)
  * blaum_roth     — RAID-6 ring construction (m=2, w+1 prime)
  * liber8tion     — RAID-6 search-built bitmatrix (m=2, w=8)

(constructions in ec/bitmatrix_raid6.py; data path is the masked
region-XOR kernel over packet planes, the layout jerasure's schedules
use — src/erasure-code/jerasure/ErasureCodeJerasure.cc:162,274.)
"""
from __future__ import annotations

import numpy as np

from ..ops import gf
from .bitmatrix_codec import BitmatrixCodec
from .bitmatrix_raid6 import (blaum_roth_bitmatrix, liber8tion_bitmatrix,
                              liberation_bitmatrix)
from .interface import ErasureCodeError, ErasureCodeProfile
from .matrix_codec import MatrixCodec

TECHNIQUES = ("reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good",
              "liberation", "blaum_roth", "liber8tion")

DEFAULT_K = 2
DEFAULT_M = 1
DEFAULT_W = 8


class ErasureCodeJerasure(MatrixCodec):
    def init(self, profile: ErasureCodeProfile) -> None:
        technique = profile.get("technique", "reed_sol_van")
        k = self.profile_int(profile, "k", DEFAULT_K, minimum=1)
        m = self.profile_int(profile, "m", DEFAULT_M, minimum=1)
        w = self.profile_int(profile, "w", DEFAULT_W)

        if technique == "reed_sol_van":
            if w not in (8, 16):
                raise ErasureCodeError(
                    f"reed_sol_van supports w in (8, 16), got {w}")
            try:
                parity = gf.vandermonde_parity(k, m, w)
            except ValueError as e:
                raise ErasureCodeError(str(e)) from e
        elif technique == "reed_sol_r6_op":
            if m != 2:
                raise ErasureCodeError("reed_sol_r6_op requires m=2")
            if w not in (8, 16):
                raise ErasureCodeError("reed_sol_r6_op supports w in (8,16)")
            parity = np.zeros((2, k), dtype=np.int64)
            parity[0] = 1
            for j in range(k):
                parity[1, j] = gf.gf_pow(2, j, w)
            parity = parity.astype(np.uint8 if w == 8 else np.uint16)
        elif technique == "cauchy_orig":
            if w != 8:
                raise ErasureCodeError("cauchy_orig implemented for w=8")
            try:
                parity = gf.cauchy_orig_parity(k, m, w)
            except ValueError as e:
                raise ErasureCodeError(str(e)) from e
        elif technique == "cauchy_good":
            if w != 8:
                raise ErasureCodeError("cauchy_good implemented for w=8")
            try:
                parity = gf.cauchy_good_parity(k, m, w)
            except ValueError as e:
                raise ErasureCodeError(str(e)) from e
        else:  # pragma: no cover - _factory validates technique names
            raise ErasureCodeError(f"not a matrix technique: {technique}")
        self.set_matrix(parity, w)
        self._profile = dict(profile)
        self._profile.setdefault("plugin", "jerasure")
        self._profile["technique"] = technique
        self._profile.update(k=str(k), m=str(m), w=str(w))


BITMATRIX_TECHNIQUES = ("liberation", "blaum_roth", "liber8tion")
# per-technique default w, matching jerasure's common usage
_BITMATRIX_DEFAULT_W = {"liberation": 7, "blaum_roth": 6, "liber8tion": 8}


class ErasureCodeJerasureBitmatrix(BitmatrixCodec):
    """The three RAID-6 bitmatrix techniques (m forced to 2)."""

    def init(self, profile: ErasureCodeProfile) -> None:
        technique = profile["technique"]
        k = self.profile_int(profile, "k", DEFAULT_K, minimum=1)
        m = self.profile_int(profile, "m", 2)
        w = self.profile_int(profile, "w",
                             _BITMATRIX_DEFAULT_W[technique])
        if m != 2:
            raise ErasureCodeError(f"{technique} requires m=2, got {m}")
        try:
            if technique == "liberation":
                bm = liberation_bitmatrix(k, w)
            elif technique == "blaum_roth":
                bm = blaum_roth_bitmatrix(k, w)
            else:
                bm = liber8tion_bitmatrix(k, w)
        except ValueError as e:
            raise ErasureCodeError(str(e)) from e
        self.set_bitmatrix(bm, k, m, w)
        self._profile = dict(profile)
        self._profile.setdefault("plugin", "jerasure")
        self._profile["technique"] = technique
        self._profile.update(k=str(k), m=str(m), w=str(w))


def _factory(profile: ErasureCodeProfile):
    """Single validation point for the technique whitelist; bitmatrix
    techniques dispatch to the GF(2) codec class."""
    technique = profile.get("technique", "reed_sol_van")
    if technique not in TECHNIQUES:
        raise ErasureCodeError(
            f"technique={technique!r} not in {TECHNIQUES}")
    codec = (ErasureCodeJerasureBitmatrix()
             if technique in BITMATRIX_TECHNIQUES else ErasureCodeJerasure())
    codec.init(dict(profile, technique=technique))
    return codec


def register(registry) -> None:
    registry.add("jerasure", _factory)
