"""The 'jerasure' codec family — baseline RS/Cauchy techniques.

Re-creates the technique surface of the reference jerasure plugin
(src/erasure-code/jerasure/ErasureCodeJerasure.h:81-240) from first
principles (the GF libraries are empty submodules in the reference
checkout; ceph_tpu.ops.gf re-derives the math):

  * reed_sol_van    — systematic Vandermonde RS, w in {8, 16}
  * reed_sol_r6_op  — RAID-6 P/Q (m == 2; rows [1..1], [1,2,4,...])
  * cauchy_orig     — Cauchy generator 1/(i ^ (m+j))
  * cauchy_good     — normalized Cauchy

The bitmatrix-only techniques (liberation, blaum_roth, liber8tion) are
CPU XOR-schedule optimizations of the same code space; they are not yet
implemented here and fail loudly at init.
"""
from __future__ import annotations

import numpy as np

from ..ops import gf
from .interface import ErasureCodeError, ErasureCodeProfile
from .matrix_codec import MatrixCodec

TECHNIQUES = ("reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good",
              "liberation", "blaum_roth", "liber8tion")

DEFAULT_K = 2
DEFAULT_M = 1
DEFAULT_W = 8


class ErasureCodeJerasure(MatrixCodec):
    def init(self, profile: ErasureCodeProfile) -> None:
        technique = profile.get("technique", "reed_sol_van")
        if technique not in TECHNIQUES:
            raise ErasureCodeError(
                f"technique={technique!r} not in {TECHNIQUES}")
        k = self.profile_int(profile, "k", DEFAULT_K, minimum=1)
        m = self.profile_int(profile, "m", DEFAULT_M, minimum=1)
        w = self.profile_int(profile, "w", DEFAULT_W)

        if technique == "reed_sol_van":
            if w not in (8, 16):
                raise ErasureCodeError(
                    f"reed_sol_van supports w in (8, 16), got {w}")
            try:
                parity = gf.vandermonde_parity(k, m, w)
            except ValueError as e:
                raise ErasureCodeError(str(e)) from e
        elif technique == "reed_sol_r6_op":
            if m != 2:
                raise ErasureCodeError("reed_sol_r6_op requires m=2")
            if w not in (8, 16):
                raise ErasureCodeError("reed_sol_r6_op supports w in (8,16)")
            parity = np.zeros((2, k), dtype=np.int64)
            parity[0] = 1
            for j in range(k):
                parity[1, j] = gf.gf_pow(2, j, w)
            parity = parity.astype(np.uint8 if w == 8 else np.uint16)
        elif technique == "cauchy_orig":
            if w != 8:
                raise ErasureCodeError("cauchy_orig implemented for w=8")
            try:
                parity = gf.cauchy_orig_parity(k, m, w)
            except ValueError as e:
                raise ErasureCodeError(str(e)) from e
        elif technique == "cauchy_good":
            if w != 8:
                raise ErasureCodeError("cauchy_good implemented for w=8")
            try:
                parity = gf.cauchy_good_parity(k, m, w)
            except ValueError as e:
                raise ErasureCodeError(str(e)) from e
        else:
            raise ErasureCodeError(
                f"technique {technique!r} is a CPU bitmatrix XOR-schedule "
                "variant not yet provided by this backend")
        self.set_matrix(parity, w)
        self._profile = dict(profile)
        self._profile.setdefault("plugin", "jerasure")
        self._profile["technique"] = technique
        self._profile.update(k=str(k), m=str(m), w=str(w))


def _factory(profile: ErasureCodeProfile):
    codec = ErasureCodeJerasure()
    codec.init(profile)
    return codec


def register(registry) -> None:
    registry.add("jerasure", _factory)
