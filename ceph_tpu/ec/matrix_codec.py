"""Systematic GF(2^w) matrix codec — shared core of the RS/Cauchy plugins.

Encode is ``parity = P @ data`` over GF(2^w); decode inverts the k x k
sub-generator selected by the surviving chunks and multiplies once more.
This is the math both reference codec families reduce to (jerasure
jerasure_matrix_encode/decode, ISA-L ec_encode_data with precomputed
gftbls); the inverted matrices are LRU-cached per erasure signature like
the reference ISA table cache.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

from ..ops import gf
from .base import ErasureCodeBase
from .interface import ErasureCodeError
from .table_cache import DecodeTableCache


class MatrixCodec(ErasureCodeBase):
    """Holds parity matrix P [m,k] over GF(2^w); w in {8, 16}."""

    def __init__(self) -> None:
        super().__init__()
        self.w = 8
        self.parity: np.ndarray | None = None
        from ..common.options import config
        self._cache = DecodeTableCache(
            capacity=int(config().get("ec_table_cache_size")))

    # -------------------------------------------------------------- setup --
    def set_matrix(self, parity: np.ndarray, w: int = 8) -> None:
        self.parity = np.asarray(
            parity, dtype=np.uint8 if w == 8 else np.uint16)
        self.m, self.k = self.parity.shape
        self.w = w

    def generator(self) -> np.ndarray:
        return gf.generator_matrix(self.parity)

    # ---------------------------------------------------------- data path --
    def _as_symbols(self, arr: np.ndarray) -> np.ndarray:
        """View uint8 chunk bytes as GF symbols (uint16 pairs for w=16)."""
        if self.w == 8:
            return arr
        if arr.shape[-1] % 2:
            raise ErasureCodeError("w=16 requires even chunk size")
        return np.ascontiguousarray(arr).view(np.uint16)

    @staticmethod
    def _as_bytes(arr: np.ndarray) -> np.ndarray:
        return arr if arr.dtype == np.uint8 else \
            np.ascontiguousarray(arr).view(np.uint8)

    def encode_chunks(self, data_chunks: np.ndarray) -> np.ndarray:
        data = np.asarray(data_chunks, dtype=np.uint8)
        if data.shape[0] != self.k:
            raise ErasureCodeError(
                f"expected {self.k} data chunks, got {data.shape[0]}")
        out = gf.gf_matmul(self.parity, self._as_symbols(data), self.w)
        return self._as_bytes(out)

    def decode_matrix(self, available_ids: Sequence[int],
                      erased_ids: Sequence[int]) -> Tuple[np.ndarray, list]:
        """[len(erased), k] recovery matrix R with erased = R @ avail[:k],
        plus the k available ids actually used.  Cached per signature."""
        avail = sorted(set(available_ids))[:self.k]
        if len(avail) < self.k:
            raise ErasureCodeError(
                f"need {self.k} chunks, have {len(set(available_ids))}")
        key = (tuple(avail), tuple(sorted(erased_ids)))
        hit = self._cache.get(key)
        if hit is not None:
            return hit, avail
        G = self.generator()
        try:
            inv = gf.gf_gaussian_inverse(G[avail], self.w)
        except ValueError as e:
            raise ErasureCodeError(
                f"singular sub-generator for chunks {avail}") from e
        R = gf.gf_matmul(G[sorted(erased_ids)], inv, self.w)
        self._cache.put(key, R)
        return R, avail

    def decode_chunks(self, available_ids: Sequence[int],
                      chunks: np.ndarray, erased_ids: Sequence[int]
                      ) -> np.ndarray:
        erased = sorted(erased_ids)
        if not erased:
            return np.zeros((0,) + tuple(chunks.shape[1:]), dtype=np.uint8)
        R, used = self.decode_matrix(available_ids, erased)
        order = list(available_ids)
        rows = np.stack([np.asarray(chunks[order.index(c)], dtype=np.uint8)
                         for c in used])
        out = gf.gf_matmul(R, self._as_symbols(rows), self.w)
        return self._as_bytes(out)
