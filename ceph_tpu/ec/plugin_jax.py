"""The 'jax' codec — TPU-batched erasure coding (north-star loop #2).

Same profile surface as the jerasure/isa RS techniques, but the data path
runs as single compiled calls batched over stripes, with matrix
preparation and the erasure-signature cache on host.  Single-stripe
calls reuse the same kernel with batch 1, so every ErasureCodeInterface
entry point is served by the device path.

Two chunk layouts, selected by the ``layout`` profile key:

  * ``layout=bytes`` (default): classic byte-symbol layout — chunk byte
    t is one GF(2^8) symbol; parity bytes match jerasure/ISA-L matrix
    techniques.  Data path: the XLA/Pallas bit-plane MXU matmul
    (ceph_tpu.ops.gf_jax / gf_pallas).
  * ``layout=bitsliced``: jerasure-packet layout — each chunk is 8
    plane regions and one GF(2^8) symbol is bit-sliced across them
    (exactly how the reference's bitmatrix/schedule techniques lay out
    chunks: jerasure_schedule_encode packets,
    src/erasure-code/jerasure/ErasureCodeJerasure.cc:162,274).  The
    data path is the flagship masked-XOR region kernel
    (ceph_tpu.ops.xor_kernel): no bit unpacking, 32 GF(2) lanes per
    int32 ALU op, ~70% of HBM roofline on v5e.  Parity BYTES differ
    from layout=bytes (as cauchy_good differs from reed_sol_van in the
    reference) but the code is the same MDS RS code per symbols.

Matches the BASELINE north star: ErasureCodeInterface::encode_chunks /
decode_chunks as batched GF(2) programs compiled for the TPU, behind
the registry seam (reference: src/erasure-code/ErasureCodeInterface.h:370,
:411; src/erasure-code/ErasureCodePlugin.cc:86).
"""
from __future__ import annotations

import numpy as np

from ..common.perf_counters import perf as _perf
from ..ops import gf, gf_jax
from .interface import ErasureCodeError, ErasureCodeProfile
from .matrix_codec import MatrixCodec

DEFAULT_K = 8
DEFAULT_M = 3

TECHNIQUES = ("reed_sol_van", "cauchy", "cauchy_good", "isa_rs")
LAYOUTS = ("bytes", "bitsliced")


def _pallas_ok() -> bool:
    from ..ops import gf_pallas
    return gf_pallas.available()


def _data_plane():
    """The sharded cluster data plane, or None (parallel_data_plane
    off / single-device host).  Resolved per dispatch so a runtime
    config flip takes effect immediately."""
    from ..parallel.data_plane import plane
    return plane()


class ErasureCodeJax(MatrixCodec):
    """RS/Cauchy codec whose stripe math executes on the accelerator."""

    def init(self, profile: ErasureCodeProfile) -> None:
        technique = profile.get("technique", "reed_sol_van")
        k = self.profile_int(profile, "k", DEFAULT_K, minimum=1)
        m = self.profile_int(profile, "m", DEFAULT_M, minimum=1)
        w = self.profile_int(profile, "w", 8)
        if w != 8:
            raise ErasureCodeError("jax codec runs in GF(2^8); w must be 8")
        if k + m > 256:
            raise ErasureCodeError("k+m must be <= 256 for w=8")
        if technique == "reed_sol_van":
            parity = gf.vandermonde_parity(k, m)
        elif technique == "cauchy":
            parity = gf.isa_cauchy_parity(k, m)
        elif technique == "cauchy_good":
            parity = gf.cauchy_good_parity(k, m)
        elif technique == "isa_rs":
            parity = gf.isa_rs_parity(k, m)
        else:
            raise ErasureCodeError(
                f"technique={technique!r} not in {TECHNIQUES}")
        layout = profile.get("layout", "bytes")
        if layout not in LAYOUTS:
            raise ErasureCodeError(f"layout={layout!r} not in {LAYOUTS}")
        self.layout = layout
        self.set_matrix(parity, 8)
        self._pc = _perf("ec.jax")       # cached group handle (hot path)
        self._profile = dict(profile)
        self._profile.setdefault("plugin", "jax")
        self._profile["technique"] = technique
        self._profile["layout"] = layout
        self._profile.update(k=str(k), m=str(m))

    # ----------------------------------------------------------- encode ---
    def encode_chunks(self, data_chunks: np.ndarray) -> np.ndarray:
        out = self.encode_chunks_device(data_chunks)
        return np.asarray(out)

    def encode_chunks_batch(self, data: np.ndarray) -> np.ndarray:
        return np.asarray(self.encode_chunks_device(data))

    def _matmul(self, matrix, data):
        """Backend select: XLA lowering or the Pallas VMEM-unpack
        kernel (ec_kernel option: auto = pallas on TPU, xla elsewhere;
        both bit-identical — see ops/gf_pallas.py)."""
        from ..common.options import config
        mode = config().get("ec_kernel")
        if mode == "pallas" or (mode == "auto" and _pallas_ok()):
            from ..ops import gf_pallas
            return gf_pallas.bitplane_matmul(
                gf_jax.matrix_to_device(matrix), data)
        return gf_jax.gf8_matmul(matrix, data)

    def _plane_matmul(self, gf_matrix, data):
        """Bitsliced path: [..., n, L] chunks -> [..., rows, L] chunks
        via the masked-XOR region kernel (reshape-only layout moves)."""
        import jax.numpy as jnp
        from ..ops import xor_kernel
        masks = xor_kernel.masks_to_device(gf.gf8_bitmatrix(gf_matrix))
        d = jnp.asarray(data)
        n, L = d.shape[-2], d.shape[-1]
        if L % 32:
            raise ErasureCodeError(
                f"bitsliced layout needs chunk size % 32 == 0, got {L}")
        planes = d.reshape(d.shape[:-2] + (8 * n, L // 8))
        out = xor_kernel.xor_matmul(masks, planes)
        r = out.shape[-2] // 8
        return out.reshape(out.shape[:-2] + (r, L))

    def encode_chunks_device(self, data):
        """[..., k, L] -> [..., m, L]; stays on device (jax.Array out)."""
        if data.shape[-2] != self.k:
            raise ErasureCodeError(
                f"expected {self.k} data chunks, got {data.shape[-2]}")
        pc = self._pc
        pc.inc("encode_dispatches")
        pc.inc("encode_bytes", int(np.prod(data.shape)))
        if self.layout == "bitsliced":
            return self._plane_matmul(self.parity, data)
        return self._matmul(self.parity, data)

    # ------------------------------------------------ word-domain (i32) ---
    # The bitsliced at-rest format IS int32 plane words (32 GF(2)
    # lanes per word).  These entry points take/return [.., n, W]
    # int32 (W = chunk_bytes/4) and never touch a u8<->i32 bitcast:
    # region boundaries are word-aligned (chunk % 32 == 0), so the
    # plane view is a pure word-domain reshape.  This matters: XLA
    # materializes the [.., W, 4]-minor u8 bitcast intermediate with
    # ~5x tile padding (5 GiB temp per 1 GiB encoded; un-compilable at
    # 3 GiB) — the words-native path has no such temp and is how the
    # cluster's device data plane runs (cluster/device_store.py).

    def encode_words_device(self, words):
        """[.., k, W] int32 -> [.., m, W] int32, on device."""
        from ..ops import xor_kernel
        if self.layout != "bitsliced":
            raise ErasureCodeError(
                "word-domain encode requires layout=bitsliced")
        if words.shape[-2] != self.k:
            raise ErasureCodeError(
                f"expected {self.k} data chunks, got {words.shape[-2]}")
        W = words.shape[-1]
        if (W * 4) % 32:
            raise ErasureCodeError(
                f"bitsliced layout needs chunk size % 32 == 0, "
                f"got {W * 4}")
        masks = xor_kernel.masks_to_device(gf.gf8_bitmatrix(self.parity))
        planes = words.reshape(words.shape[:-2] +
                               (8 * self.k, W // 8))
        pc = self._pc
        pc.inc("encode_dispatches")
        pc.inc("encode_bytes", 4 * int(np.prod(words.shape)))
        dp = _data_plane()
        if dp is not None:
            # sharded data plane: stripes split across the mesh, the
            # same masked-XOR contraction per chip (bit-identical)
            out = dp.xor_matmul_w32(masks, planes, kind="put")
        else:
            out = xor_kernel.xor_matmul_w32(masks, planes)
        return out.reshape(words.shape[:-2] + (self.m, W))

    def decode_words_device(self, available_ids, words, erased_ids):
        """words [.., n_avail, W] int32 for one erasure signature ->
        [.., n_erased, W] int32 on device (recovery matrix is a
        dynamic operand: new signatures do NOT recompile)."""
        from ..ops import xor_kernel
        if self.layout != "bitsliced":
            raise ErasureCodeError(
                "word-domain decode requires layout=bitsliced")
        erased = sorted(erased_ids)
        if not erased:
            import jax.numpy as jnp
            return jnp.zeros(words.shape[:-2] + (0, words.shape[-1]),
                             dtype=words.dtype)
        W = words.shape[-1]
        if (W * 4) % 32:
            raise ErasureCodeError(
                f"bitsliced layout needs chunk size % 32 == 0, "
                f"got {W * 4}")
        pc = self._pc
        pc.inc("decode_dispatches")
        pc.inc("decode_bytes", 4 * int(np.prod(words.shape)))
        R, dev = self._select_rows(available_ids, erased, words)
        masks = xor_kernel.masks_to_device(gf.gf8_bitmatrix(R))
        planes = dev.reshape(dev.shape[:-2] +
                             (8 * dev.shape[-2], W // 8))
        dp = _data_plane()
        if dp is not None:
            # one sharded dispatch per signature group: the lost
            # stripes split across the mesh, accounting psums back
            out = dp.xor_matmul_w32(masks, planes, kind="decode")
        else:
            out = xor_kernel.xor_matmul_w32(masks, planes)
        return out.reshape(dev.shape[:-2] + (len(erased), W))

    def _select_rows(self, available_ids, erased, chunks):
        """Decode matrix + the used-row subset of ``chunks`` (shared
        by both decode domains).  Static per-row slices, NOT a
        fancy-index gather: a gather lowers to ~0.1 G elem/s serial
        loops on TPU — measured 60x slower than the encode matmul it
        feeds."""
        import jax.numpy as jnp
        R, used = self.decode_matrix(available_ids, erased)
        order = list(available_ids)
        sel = [order.index(c) for c in used]
        dev = jnp.asarray(chunks)
        if sel != list(range(len(order))):
            dev = jnp.stack([dev[..., i, :] for i in sel], axis=-2)
        return R, dev

    # ----------------------------------------------------------- decode ---
    def decode_chunks(self, available_ids, chunks, erased_ids):
        return np.asarray(
            self.decode_chunks_device(available_ids, chunks, erased_ids))

    def decode_chunks_batch(self, available_ids, chunks, erased_ids):
        return np.asarray(
            self.decode_chunks_device(available_ids, chunks, erased_ids))

    def decode_chunks_device(self, available_ids, chunks, erased_ids):
        """chunks [..., n_avail, L] for one erasure signature shared by the
        whole batch -> [..., n_erased, L] on device.  The recovery matrix
        is a dynamic operand, so new signatures do NOT recompile."""
        erased = sorted(erased_ids)
        if not erased:
            return np.zeros(
                tuple(chunks.shape[:-2]) + (0, chunks.shape[-1]),
                dtype=np.uint8)
        pc = self._pc
        pc.inc("decode_dispatches")
        pc.inc("decode_bytes", int(np.prod(chunks.shape)))
        pc.set("decode_cache_hits", self._cache.hits)
        pc.set("decode_cache_misses", self._cache.misses)
        R, rows = self._select_rows(available_ids, erased, chunks)
        if self.layout == "bitsliced":
            return self._plane_matmul(R, rows)
        return self._matmul(R, rows)


def _factory(profile: ErasureCodeProfile):
    codec = ErasureCodeJax()
    codec.init(profile)
    return codec


def register(registry) -> None:
    registry.add("jax", _factory)
