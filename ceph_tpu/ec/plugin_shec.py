"""The 'shec' codec — Shingled Erasure Code.

Re-creates the behavior of the reference SHEC plugin
(src/erasure-code/shec/ErasureCodeShec.cc): the generator is a
reed_sol_van parity matrix with each parity row masked down to a cyclic
shingle window of the data chunks (shec_reedsolomon_coding_matrix,
ErasureCodeShec.cc:514-531: row rr keeps columns outside
[start, end) where end = rr*k/m %k, start = (rr+c)*k/m %k), trading extra
storage (c, the durability estimator) for cheaper single-failure repair:
a lost chunk is rebuilt from one parity's window instead of k chunks.

SHEC is deliberately not MDS, so decode selects an invertible row subset
by greedy rank-revealing elimination over all available rows (the role of
shec_make_decoding_matrix, ErasureCodeShec.cc:535), and
minimum_to_decode searches for the smallest parity window covering the
erasures (the multiple-solution search, ErasureCodeShec.cc:113).

Constraints mirror the reference parse(): k <= 12, k+m <= 20, m <= k,
0 < c <= m (ErasureCodeShec.cc:300-341).

The 'multiple' technique's (m1,c1) row-group split is re-derived as an
exhaustive search minimizing the average single-failure repair width; it
is a valid SHEC layout though the split choice may differ from the
reference's heuristic for some (k,m,c).
"""
from __future__ import annotations

from typing import List, Sequence, Set, Tuple

import numpy as np

from ..ops import gf
from .interface import ErasureCodeError, ErasureCodeProfile, SubChunkPlan
from .matrix_codec import MatrixCodec

DEFAULT_K, DEFAULT_M, DEFAULT_C = 4, 3, 2


def _shingle_mask(parity: np.ndarray, rows: range, m_grp: int,
                  c_grp: int, k: int) -> None:
    """Zero columns in the cyclic window [start, end) per group row."""
    if m_grp <= 0:
        return
    for gi, rr in enumerate(rows):
        end = ((gi * k) // m_grp) % k
        start = (((gi + c_grp) * k) // m_grp) % k
        cc = start
        while cc != end:
            parity[rr, cc] = 0
            cc = (cc + 1) % k


def shec_parity(k: int, m: int, c: int, technique: str = "multiple"
                ) -> np.ndarray:
    base = gf.vandermonde_parity(k, m)
    parity = base.astype(np.int64)
    if technique == "single" or m == 1 or c == m:
        _shingle_mask(parity, range(m), m, c, k)
        return parity.astype(np.uint8)
    # 'multiple': split rows into two shingle groups (m1,c1)+(m2,c2),
    # minimizing average repair width over single data failures
    best = None
    for m1 in range(0, m + 1):
        for c1 in range(0, c + 1):
            m2, c2 = m - m1, c - c1
            if (m1 == 0) != (c1 == 0):
                continue
            if (m2 == 0) != (c2 == 0):
                continue
            if m1 and c1 > m1 or m2 and c2 > m2:
                continue
            cand = base.astype(np.int64).copy()
            _shingle_mask(cand, range(m1), m1, c1, k)
            _shingle_mask(cand, range(m1, m), m2, c2, k)
            if np.any((cand != 0).sum(axis=1) == 0):
                continue
            # every data chunk must be covered by some parity
            if np.any((cand != 0).sum(axis=0) == 0):
                continue
            width = min((cand[j] != 0).sum() for j in range(m))
            score = ((cand != 0).sum(), width)
            if best is None or score < best[0]:
                best = (score, cand)
    if best is None:
        raise ErasureCodeError(f"no valid shec layout for k={k} m={m} c={c}")
    return best[1].astype(np.uint8)


class ErasureCodeShec(MatrixCodec):
    def init(self, profile: ErasureCodeProfile) -> None:
        technique = profile.get("technique", "multiple")
        if technique not in ("single", "multiple"):
            raise ErasureCodeError(
                f"shec technique must be single|multiple, got {technique!r}")
        k = self.profile_int(profile, "k", DEFAULT_K, minimum=1)
        m = self.profile_int(profile, "m", DEFAULT_M, minimum=1)
        c = self.profile_int(profile, "c", DEFAULT_C, minimum=1)
        # reference bounds (ErasureCodeShec.cc:300-341)
        if k > 12:
            raise ErasureCodeError(f"shec k={k} must be <= 12")
        if k + m > 20:
            raise ErasureCodeError(f"shec k+m={k + m} must be <= 20")
        if m > k:
            raise ErasureCodeError(f"shec m={m} must be <= k={k}")
        if c > m:
            raise ErasureCodeError(f"shec c={c} must be <= m={m}")
        self.c = c
        self.set_matrix(shec_parity(k, m, c, technique), 8)
        self._profile = dict(profile)
        self._profile.setdefault("plugin", "shec")
        self._profile["technique"] = technique
        self._profile.update(k=str(k), m=str(m), c=str(c))

    # ----------------------------------------------- row-space solution --
    def _pick_rows(self, available: Sequence[int], erased: Sequence[int]
                   ) -> List[int]:
        """Greedy rank-revealing choice of k independent available rows."""
        G = self.generator().astype(np.int64)
        chosen: List[int] = []
        basis = np.zeros((0, self.k), dtype=np.int64)
        for c_id in sorted(available):
            cand = np.concatenate([basis, G[c_id][None, :]])
            rank = _gf_rank(cand)
            if rank > basis.shape[0]:
                basis = _gf_row_reduce(cand)[:rank]
                chosen.append(c_id)
            if len(chosen) == self.k:
                return chosen
        raise ErasureCodeError(
            f"shec: available rows {sorted(available)} do not span; "
            f"cannot rebuild {sorted(erased)}")

    def decode_matrix(self, available_ids, erased_ids):
        """R with erased = R @ available — unlike the MDS base, the
        available set may be SMALLER than k (a local shingle window): the
        erased rows just have to lie in the span of the available rows
        (the role of shec_make_decoding_matrix)."""
        avail = sorted(set(available_ids))
        erased = sorted(erased_ids)
        key = (tuple(avail), tuple(erased))
        hit = self._cache.get(key)
        if hit is not None:
            return hit, avail
        G = self.generator().astype(np.int64)
        R = _gf_solve_rowspace(G[avail], G[erased])
        if R is None:
            raise ErasureCodeError(
                f"shec: cannot express chunks {erased} from {avail}")
        self._cache.put(key, R)
        return R, avail

    def decode_chunks(self, available_ids, chunks, erased_ids):
        erased = sorted(erased_ids)
        if not erased:
            return np.zeros((0,) + tuple(chunks.shape[1:]), dtype=np.uint8)
        R, used = self.decode_matrix(available_ids, erased)
        order = list(available_ids)
        rows = np.stack([np.asarray(chunks[order.index(c)], dtype=np.uint8)
                         for c in used])
        return gf.gf_matmul(R, rows, self.w).astype(np.uint8)

    # ------------------------------------------------- minimum_to_decode --
    def minimum_to_decode(self, want_to_read: Set[int],
                          available: Set[int]) -> SubChunkPlan:
        if want_to_read <= available:
            return {c: [(0, 1)] for c in want_to_read}
        erased = want_to_read - available
        P = self.parity.astype(np.int64)
        if len(erased) == 1:
            (e,) = erased
            best: Tuple[int, Set[int]] | None = None
            if e < self.k:
                for j in range(self.m):
                    if P[j, e] and (self.k + j) in available:
                        need = {cc for cc in range(self.k)
                                if P[j, cc] and cc != e}
                        if need <= available:
                            sol = need | {self.k + j}
                            if best is None or len(sol) < len(best[1]):
                                best = (j, sol)
            else:
                j = e - self.k
                need = {cc for cc in range(self.k) if P[j, cc]}
                if need <= available:
                    best = (j, need)
            if best is not None:
                return {c: [(0, 1)] for c in best[1]}
        # general: the rank-revealing row choice
        rows = self._pick_rows(sorted(available), sorted(erased))
        return {c: [(0, 1)] for c in rows}


def _gf_solve_rowspace(A: np.ndarray, T: np.ndarray):
    """Find R with T = R @ A over GF(2^8), or None if T is outside A's
    row space.  Gaussian elimination over A's columns, with an identity
    block tracking the combination coefficients."""
    n, k = A.shape
    aug = np.concatenate(
        [A.astype(np.int64), np.eye(n, dtype=np.int64)], axis=1)
    pivots = []        # (row, col) with col < k
    r = 0
    for col in range(k):
        pivot = None
        for i in range(r, n):
            if aug[i, col]:
                pivot = i
                break
        if pivot is None:
            continue
        aug[[r, pivot]] = aug[[pivot, r]]
        aug[r] = gf.gf_mul(aug[r], gf.gf_inv(aug[r, col]))
        for i in range(n):
            if i != r and aug[i, col]:
                aug[i] ^= gf.gf_mul(aug[r], aug[i, col])
        pivots.append((r, col))
        r += 1
        if r == n:
            break
    R = np.zeros((T.shape[0], n), dtype=np.int64)
    for ti in range(T.shape[0]):
        residual = T[ti].astype(np.int64).copy()
        coeffs = np.zeros(n, dtype=np.int64)
        for row, col in pivots:
            if residual[col]:
                f = residual[col]          # pivot normalized to 1
                residual ^= gf.gf_mul(aug[row, :k], f)
                coeffs ^= gf.gf_mul(aug[row, k:], f)
        if residual.any():
            return None
        R[ti] = coeffs
    return R.astype(np.uint8)


def _gf_row_reduce(M: np.ndarray) -> np.ndarray:
    M = M.astype(np.int64).copy()
    rows, cols = M.shape
    r = 0
    for col in range(cols):
        pivot = None
        for i in range(r, rows):
            if M[i, col]:
                pivot = i
                break
        if pivot is None:
            continue
        M[[r, pivot]] = M[[pivot, r]]
        M[r] = gf.gf_mul(M[r], gf.gf_inv(M[r, col]))
        for i in range(rows):
            if i != r and M[i, col]:
                M[i] ^= gf.gf_mul(M[r], M[i, col])
        r += 1
        if r == rows:
            break
    return M


def _gf_rank(M: np.ndarray) -> int:
    R = _gf_row_reduce(M)
    return int((R.any(axis=1)).sum())


def _factory(profile: ErasureCodeProfile):
    codec = ErasureCodeShec()
    codec.init(profile)
    return codec


def register(registry) -> None:
    registry.add("shec", _factory)
