"""Generic GF(2) bitmatrix codec over packet/plane chunk layout.

The codec space of jerasure's schedule techniques: a [m*w, k*w] 0/1
parity bitmatrix acts on chunks divided into w plane regions
(ops/gf2.py layout).  Encode and decode are masked region XOR — on
device via ops/xor_kernel.py, on host via the native AVX2 region codec
or the NumPy oracle.  Decode matrices are GF(2) inversions of the
surviving generator rows, LRU-cached per erasure signature (the ISA
table-cache role).

Reference roles: jerasure_schedule_encode / jerasure_schedule_decode_lazy
(src/erasure-code/jerasure/ErasureCodeJerasure.cc:162,274),
jerasure bitmatrix decode construction (ErasureCodeJerasure.cc decode).
"""
from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

from ..ops import gf2
from .base import ErasureCodeBase
from .interface import ErasureCodeError
from .table_cache import DecodeTableCache


class BitmatrixCodec(ErasureCodeBase):
    """Holds a parity bitmatrix B [m*w, k*w]; chunks carry w planes."""

    def __init__(self) -> None:
        super().__init__()
        self.w = 8
        self.bitmatrix: np.ndarray | None = None
        from ..common.options import config
        self._cache = DecodeTableCache(
            capacity=int(config().get("ec_table_cache_size")))

    # -------------------------------------------------------------- setup --
    def set_bitmatrix(self, bm: np.ndarray, k: int, m: int, w: int) -> None:
        bm = np.asarray(bm, dtype=np.uint8) & 1
        if bm.shape != (m * w, k * w):
            raise ErasureCodeError(
                f"bitmatrix shape {bm.shape} != ({m * w}, {k * w})")
        self.bitmatrix = bm
        self.k, self.m, self.w = k, m, w

    def generator_bitmatrix(self) -> np.ndarray:
        """[(k+m)w, kw]: identity rows for data planes, then parity."""
        kw = self.k * self.w
        return np.concatenate(
            [np.eye(kw, dtype=np.uint8), self.bitmatrix], axis=0)

    def get_chunk_size(self, stripe_width: int) -> int:
        """Chunks must split into w planes whose byte count is 32-bit
        aligned for the packed-word kernels."""
        align = self.k * self.w * 4
        padded = -(-stripe_width // align) * align
        return padded // self.k

    # ---------------------------------------------------------- data path --
    def _planes(self, chunks: np.ndarray, n: int) -> np.ndarray:
        a = np.asarray(chunks, dtype=np.uint8)
        L = a.shape[-1]
        if L % (self.w * 4):
            raise ErasureCodeError(
                f"chunk size {L} not divisible by {self.w * 4}")
        return a.reshape(a.shape[:-2] + (n * self.w, L // self.w))

    def _chunks(self, planes: np.ndarray, L: int) -> np.ndarray:
        n = planes.shape[-2] // self.w
        return planes.reshape(planes.shape[:-2] + (n, L))

    _native_ok: bool | None = None   # probed once per process

    def _combine_host(self, bitmat: np.ndarray,
                      planes: np.ndarray) -> np.ndarray:
        cls = BitmatrixCodec
        if cls._native_ok is None:
            try:
                from .. import native_bridge as nb
                nb.lib()
                cls._native_ok = True
            except Exception:       # no toolchain: NumPy oracle path
                cls._native_ok = False
        if cls._native_ok:
            from .. import native_bridge as nb
            if planes.ndim == 2:
                return nb.gf2_xor_regions(bitmat, planes)
            flat = planes.reshape((-1,) + planes.shape[-2:])
            out = nb.gf2_xor_regions_batch(bitmat, flat)
            return out.reshape(planes.shape[:-2] + out.shape[-2:])
        return gf2.region_xor_matmul_np(bitmat, planes)

    def encode_chunks(self, data_chunks: np.ndarray) -> np.ndarray:
        data = np.asarray(data_chunks, dtype=np.uint8)
        if data.shape[-2] != self.k:
            raise ErasureCodeError(
                f"expected {self.k} data chunks, got {data.shape[-2]}")
        L = data.shape[-1]
        out = self._combine_host(self.bitmatrix, self._planes(data, self.k))
        return self._chunks(out, L)

    def encode_chunks_batch(self, data: np.ndarray) -> np.ndarray:
        return np.asarray(self.encode_chunks_device(data))

    def encode_chunks_device(self, data):
        """Batched device path: [..., k, L] -> [..., m, L] jax.Array."""
        import jax.numpy as jnp
        from ..ops import xor_kernel
        d = jnp.asarray(np.asarray(data, dtype=np.uint8))
        if d.shape[-2] != self.k:
            raise ErasureCodeError(
                f"expected {self.k} data chunks, got {d.shape[-2]}")
        L = d.shape[-1]
        if L % (self.w * 4):
            raise ErasureCodeError(
                f"chunk size {L} not divisible by {self.w * 4}")
        planes = d.reshape(d.shape[:-2] + (self.k * self.w, L // self.w))
        out = xor_kernel.xor_matmul(
            xor_kernel.masks_to_device(self.bitmatrix), planes)
        return out.reshape(out.shape[:-2] + (self.m, L))

    # -------------------------------------------------------------- decode --
    def decode_bitmatrix(self, available_ids: Sequence[int],
                         erased_ids: Sequence[int]
                         ) -> Tuple[np.ndarray, list]:
        """[e*w, k*w] GF(2) recovery bitmatrix R with
        erased_planes = R @ planes(avail_used), plus the used ids."""
        avail = sorted(set(available_ids))[:self.k]
        if len(avail) < self.k:
            raise ErasureCodeError(
                f"need {self.k} chunks, have {len(set(available_ids))}")
        key = (tuple(avail), tuple(sorted(erased_ids)))
        hit = self._cache.get(key)
        if hit is not None:
            return hit, avail
        G = self.generator_bitmatrix()
        w = self.w
        rows = np.concatenate(
            [np.arange(c * w, (c + 1) * w) for c in avail])
        try:
            inv = gf2.gf2_inverse(G[rows])
        except ValueError as e:
            raise ErasureCodeError(
                f"singular GF(2) sub-generator for chunks {avail}") from e
        er_rows = np.concatenate(
            [np.arange(c * w, (c + 1) * w) for c in sorted(erased_ids)])
        R = gf2.gf2_matmul(G[er_rows], inv)
        self._cache.put(key, R)
        return R, avail

    def decode_chunks(self, available_ids: Sequence[int],
                      chunks: np.ndarray, erased_ids: Sequence[int]
                      ) -> np.ndarray:
        erased = sorted(erased_ids)
        if not erased:
            return np.zeros((0,) + tuple(np.asarray(chunks).shape[1:]),
                            dtype=np.uint8)
        R, used = self.decode_bitmatrix(available_ids, erased)
        order = list(available_ids)
        rows = np.stack([np.asarray(chunks[order.index(c)], dtype=np.uint8)
                         for c in used])
        L = rows.shape[-1]
        out = self._combine_host(R, self._planes(rows, self.k))
        return self._chunks(out, L)

    def decode_chunks_batch(self, available_ids, chunks, erased_ids):
        import numpy as _np
        return _np.asarray(self.decode_chunks_device(
            available_ids, chunks, erased_ids))

    def decode_chunks_device(self, available_ids, chunks, erased_ids):
        """Batched device decode for one shared signature; the recovery
        bitmatrix is a mask operand, so new signatures don't recompile."""
        import jax.numpy as jnp
        from ..ops import xor_kernel
        erased = sorted(erased_ids)
        if not erased:
            return np.zeros(tuple(np.asarray(chunks).shape[:-2]) +
                            (0, np.asarray(chunks).shape[-1]),
                            dtype=np.uint8)
        R, used = self.decode_bitmatrix(available_ids, erased)
        order = list(available_ids)
        sel = [order.index(c) for c in used]
        dev = jnp.asarray(np.asarray(chunks, dtype=np.uint8))
        if sel != list(range(len(order))):
            dev = jnp.stack([dev[..., i, :] for i in sel], axis=-2)
        L = dev.shape[-1]
        planes = dev.reshape(dev.shape[:-2] + (self.k * self.w,
                                               L // self.w))
        out = xor_kernel.xor_matmul(xor_kernel.masks_to_device(R), planes)
        return out.reshape(out.shape[:-2] + (len(erased), L))
