"""The 'clay' codec — Coupled-LAYer MSR regenerating code.

Re-creates the behavior of the reference CLAY plugin
(src/erasure-code/clay/ErasureCodeClay.{h,cc}; Clay codes, FAST'18):
an (k, m, d) code whose chunks split into q^t sub-chunks
(q = d-k+1, t = (k+m+nu)/q, nu pads the node grid,
ErasureCodeClay.cc:271-296) arranged on a q x t node grid.  Stored
("coupled") sub-chunks relate to an uncoupled MDS layer through 2x2
pairwise transforms (the PFT, a k=2/m=2 scalar codec): node (x,y) in
plane z pairs with node (z_y, y) in the reflected plane z_sw
(ErasureCodeClay.cc:781-871).  Encode/decode walk planes in
intersection-score order, converting between coupled and uncoupled
symbols and MDS-decoding each plane (decode_layered,
ErasureCodeClay.cc:647-712).

Single-failure repair reads only the q^(t-1) "dot" planes of the lost
node from d helpers — the minimum-bandwidth property
(minimum_to_repair/get_repair_subchunks, ErasureCodeClay.cc:325-377;
repair_one_lost_chunk, :462-645).

Sub-chunk payloads are numpy arrays [sub_chunk_no, sc_size]; the MDS and
PFT layers default to the batched 'jax' codec.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Set, Tuple

import numpy as np

from .base import CHUNK_ALIGN, ErasureCodeBase
from .interface import ErasureCodeError, ErasureCodeProfile, SubChunkPlan

DEFAULT_K, DEFAULT_M = 4, 2


class ErasureCodeClay(ErasureCodeBase):
    def init(self, profile: ErasureCodeProfile) -> None:
        from .registry import ErasureCodePluginRegistry
        reg = ErasureCodePluginRegistry.instance()
        k = self.profile_int(profile, "k", DEFAULT_K, minimum=2)
        m = self.profile_int(profile, "m", DEFAULT_M, minimum=1)
        d = self.profile_int(profile, "d", k + m - 1)
        if not (k + 1 <= d + 1 and k <= d <= k + m - 1):
            raise ErasureCodeError(
                f"clay requires k <= d <= k+m-1, got k={k} m={m} d={d}")
        scalar = profile.get("scalar_mds", "jax")
        if scalar not in ("jax", "jerasure", "isa"):
            raise ErasureCodeError(
                f"clay scalar_mds must be jax|jerasure|isa, got {scalar!r}")
        self.k, self.m, self.d = k, m, d
        self.q = d - k + 1
        self.nu = (self.q - (k + m) % self.q) % self.q
        if k + m + self.nu > 254:
            raise ErasureCodeError("clay k+m+nu must be <= 254")
        self.t = (k + m + self.nu) // self.q
        self.sub_chunk_no = self.q ** self.t
        technique = profile.get("technique", "reed_sol_van")
        self.mds = reg.factory(scalar, {
            "k": str(k + self.nu), "m": str(m), "technique": technique})
        self.pft = reg.factory(scalar, {
            "k": "2", "m": "2", "technique": technique})
        self._profile = dict(profile)
        self._profile.setdefault("plugin", "clay")
        self._profile.update(k=str(k), m=str(m), d=str(d))

    # ------------------------------------------------------------ layout --
    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_no

    def get_chunk_size(self, stripe_width: int) -> int:
        align = self.k * self.sub_chunk_no * CHUNK_ALIGN
        padded = -(-stripe_width // align) * align
        return padded // self.k

    def _plane_vector(self, z: int) -> List[int]:
        zv = [0] * self.t
        for i in range(self.t):
            zv[self.t - 1 - i] = z % self.q
            z //= self.q
        return zv

    def _pair(self, x: int, y: int, z: int, zv: List[int]) -> Tuple[int, int]:
        """(node_sw, z_sw): the coupled partner of (x,y) in plane z."""
        node_sw = y * self.q + zv[y]
        z_sw = z + (x - zv[y]) * self.q ** (self.t - 1 - y)
        return node_sw, z_sw

    # --------------------------------------------------------- PFT solve --
    def _pft_solve(self, known: Dict[int, np.ndarray],
                   want: List[int]) -> List[np.ndarray]:
        """Solve the 2x2 pairwise transform: positions 0,1 = coupled pair
        (data), 2,3 = uncoupled pair (parity of the k=2 scalar code)."""
        avail = sorted(known)
        out = self.pft.decode_chunks(
            avail, np.stack([known[i] for i in avail]), sorted(want))
        order = {w: i for i, w in enumerate(sorted(want))}
        return [out[order[w]] for w in want]

    @staticmethod
    def _canon(x: int, x_sw: int) -> Tuple[int, int, int, int]:
        """Canonical PFT position order (i0..i3): position 0 belongs to
        the larger-x member (the i-swap at ErasureCodeClay.cc:789-794)."""
        if x_sw > x:
            return 1, 0, 3, 2
        return 0, 1, 2, 3

    # ------------------------------------------------------------ encode --
    def encode_chunks(self, data_chunks: np.ndarray) -> np.ndarray:
        data = np.asarray(data_chunks, dtype=np.uint8)
        if data.shape[0] != self.k:
            raise ErasureCodeError(
                f"expected {self.k} data chunks, got {data.shape[0]}")
        chunk = data.shape[1]
        if chunk % self.sub_chunk_no:
            raise ErasureCodeError(
                f"chunk size {chunk} not divisible by sub_chunk_no "
                f"{self.sub_chunk_no} (use get_chunk_size)")
        sc = chunk // self.sub_chunk_no
        nodes: Dict[int, np.ndarray] = {}
        for i in range(self.k):
            nodes[i] = data[i].reshape(self.sub_chunk_no, sc).copy()
        for i in range(self.k, self.k + self.nu):
            nodes[i] = np.zeros((self.sub_chunk_no, sc), dtype=np.uint8)
        parity_nodes = list(range(self.k + self.nu, self.q * self.t))
        for i in parity_nodes:
            nodes[i] = np.zeros((self.sub_chunk_no, sc), dtype=np.uint8)
        self._decode_layered(set(parity_nodes), nodes, sc)
        return np.stack([nodes[i].reshape(chunk) for i in parity_nodes])

    # ------------------------------------------------------------ decode --
    def decode_chunks(self, available_ids: Sequence[int],
                      chunks: np.ndarray, erased_ids: Sequence[int]
                      ) -> np.ndarray:
        chunk = chunks.shape[-1]
        if chunk % self.sub_chunk_no:
            raise ErasureCodeError("chunk size not divisible by sub chunks")
        sc = chunk // self.sub_chunk_no
        to_node = lambda i: i if i < self.k else i + self.nu
        nodes: Dict[int, np.ndarray] = {}
        for idx, cid in enumerate(available_ids):
            nodes[to_node(cid)] = np.asarray(
                chunks[idx], dtype=np.uint8).reshape(
                    self.sub_chunk_no, sc).copy()
        for i in range(self.k, self.k + self.nu):
            nodes[i] = np.zeros((self.sub_chunk_no, sc), dtype=np.uint8)
        erased_nodes = {to_node(i) for i in erased_ids}
        if len(erased_nodes) > self.m:
            raise ErasureCodeError(
                f"clay cannot recover {len(erased_nodes)} > m={self.m}")
        for i in erased_nodes:
            nodes[i] = np.zeros((self.sub_chunk_no, sc), dtype=np.uint8)
        # any remaining unknown nodes (not provided, not wanted) also count
        for i in range(self.q * self.t):
            if i not in nodes:
                erased_nodes.add(i)
                nodes[i] = np.zeros((self.sub_chunk_no, sc), dtype=np.uint8)
        if len(erased_nodes) > self.m:
            raise ErasureCodeError(
                f"need at least {self.q * self.t - self.nu - self.m} chunks")
        self._decode_layered(set(erased_nodes), nodes, sc)
        return np.stack([nodes[to_node(i)].reshape(chunk)
                         for i in sorted(erased_ids)])

    # --------------------------------------------------- layered decoder --
    def _decode_layered(self, erased: Set[int], nodes: Dict[int, np.ndarray],
                        sc: int) -> None:
        """(decode_layered, ErasureCodeClay.cc:647-712)"""
        q, t = self.q, self.t
        # pad erasures to exactly m with unused parity-region nodes
        i = self.k + self.nu
        while len(erased) < self.m and i < q * t:
            erased.add(i)
            i += 1
        if len(erased) != self.m:
            raise ErasureCodeError("clay: erasure count exceeds m")
        U = {n: np.zeros_like(nodes[n]) for n in range(q * t)}
        order = np.zeros(self.sub_chunk_no, dtype=np.int64)
        zvs = [self._plane_vector(z) for z in range(self.sub_chunk_no)]
        for z in range(self.sub_chunk_no):
            order[z] = sum(1 for n in erased if n % q == zvs[z][n // q])
        max_iscore = len({n // q for n in erased})
        for iscore in range(max_iscore + 1):
            planes = [z for z in range(self.sub_chunk_no)
                      if order[z] == iscore]
            for z in planes:
                self._decode_erasures(erased, z, zvs[z], nodes, U)
            for z in planes:
                zv = zvs[z]
                for n in sorted(erased):
                    x, y = n % q, n // q
                    node_sw, z_sw = self._pair(x, y, z, zv)
                    if zv[y] != x:
                        i0, i1, i2, i3 = self._canon(x, zv[y])
                        if node_sw not in erased:
                            # type-1: pair survives
                            (c_xy,) = self._pft_solve(
                                {i1: nodes[node_sw][z_sw],
                                 i2: U[n][z]}, [i0])
                            nodes[n][z] = c_xy
                        elif zv[y] < x:
                            # both pair members erased: one joint solve
                            c0, c1 = self._pft_solve(
                                {2: U[n][z], 3: U[node_sw][z_sw]}, [0, 1])
                            nodes[n][z] = c0
                            nodes[node_sw][z_sw] = c1
                    else:
                        nodes[n][z] = U[n][z]

    def _decode_erasures(self, erased: Set[int], z: int, zv: List[int],
                         nodes: Dict[int, np.ndarray],
                         U: Dict[int, np.ndarray]) -> None:
        """(decode_erasures, ErasureCodeClay.cc:714-741)"""
        q, t = self.q, self.t
        for x in range(q):
            for y in range(t):
                n = y * q + x
                if n in erased:
                    continue
                node_sw, z_sw = self._pair(x, y, z, zv)
                if zv[y] == x:
                    U[n][z] = nodes[n][z]
                elif zv[y] < x or node_sw in erased:
                    i0, i1, i2, i3 = self._canon(x, zv[y])
                    u_xy, u_sw = self._pft_solve(
                        {i0: nodes[n][z], i1: nodes[node_sw][z_sw]},
                        [i2, i3])
                    U[n][z] = u_xy
                    U[node_sw][z_sw] = u_sw
        self._decode_uncoupled(erased, z, U)

    def _decode_uncoupled(self, erased: Set[int], z: int,
                          U: Dict[int, np.ndarray]) -> None:
        """Per-plane MDS decode across nodes (ErasureCodeClay.cc:743-761)."""
        avail = [n for n in range(self.q * self.t) if n not in erased]
        rebuilt = self.mds.decode_chunks(
            avail, np.stack([U[n][z] for n in avail]), sorted(erased))
        for i, n in enumerate(sorted(erased)):
            U[n][z] = rebuilt[i]

    # ------------------------------------------------------- repair path --
    def is_repair(self, want_to_read: Set[int],
                  available: Set[int]) -> bool:
        """(ErasureCodeClay.cc:304-323)"""
        if want_to_read <= available:
            return False
        if len(want_to_read) != 1:
            return False
        (i,) = want_to_read
        lost = i if i < self.k else i + self.nu
        for x in range(self.q):
            node = (lost // self.q) * self.q + x
            node = node if node < self.k else node - self.nu
            if node != i and 0 <= node < self.k + self.m and \
                    node not in available:
                return False
        return len(available) >= self.d

    def get_repair_subchunks(self, lost_node: int) -> List[Tuple[int, int]]:
        """Sub-chunk (offset, count) ranges helpers must read
        (ErasureCodeClay.cc:363-377)."""
        y, x = lost_node // self.q, lost_node % self.q
        seq = self.q ** (self.t - 1 - y)
        out = []
        index = x * seq
        for _ in range(self.q ** y):
            out.append((index, seq))
            index += self.q * seq
        return out

    def minimum_to_decode(self, want_to_read: Set[int],
                          available: Set[int]) -> SubChunkPlan:
        if self.is_repair(want_to_read, available):
            (i,) = want_to_read
            lost = i if i < self.k else i + self.nu
            ranges = self.get_repair_subchunks(lost)
            plan: SubChunkPlan = {}
            for j in range(self.q):
                if j == lost % self.q:
                    continue
                rep = (lost // self.q) * self.q + j
                rep = rep if rep < self.k else rep - self.nu
                if 0 <= rep < self.k + self.m and rep in available:
                    plan[rep] = list(ranges)
            for c in sorted(available):
                if len(plan) >= self.d:
                    break
                plan.setdefault(c, list(ranges))
            if len(plan) != self.d:
                raise ErasureCodeError("clay repair needs d helpers")
            return plan
        return super().minimum_to_decode(want_to_read, available)

    def repair(self, want_id: int, helper_data: Dict[int, np.ndarray],
               chunk_size: int) -> np.ndarray:
        """Minimum-bandwidth single-chunk repair: helpers supply ONLY the
        repair sub-chunk ranges (repair_one_lost_chunk,
        ErasureCodeClay.cc:462-645)."""
        q, t = self.q, self.t
        if chunk_size % self.sub_chunk_no:
            raise ErasureCodeError("chunk_size not divisible by sub chunks")
        sc = chunk_size // self.sub_chunk_no
        repair_subchunks = self.sub_chunk_no // q
        lost = want_id if want_id < self.k else want_id + self.nu
        ranges = self.get_repair_subchunks(lost)
        repair_planes = [z for (off, cnt) in ranges
                         for z in range(off, off + cnt)]
        plane_ind = {z: i for i, z in enumerate(repair_planes)}
        to_node = lambda i: i if i < self.k else i + self.nu

        helpers: Dict[int, np.ndarray] = {}
        for cid, buf in helper_data.items():
            buf = np.asarray(buf, dtype=np.uint8).reshape(
                repair_subchunks, sc)
            helpers[to_node(cid)] = buf
        for i in range(self.k, self.k + self.nu):
            helpers[i] = np.zeros((repair_subchunks, sc), dtype=np.uint8)
        aloof = {n for n in range(q * t)
                 if n != lost and n not in helpers}
        recovered = np.zeros((self.sub_chunk_no, sc), dtype=np.uint8)
        U = {n: np.zeros((self.sub_chunk_no, sc), dtype=np.uint8)
             for n in range(q * t)}
        # erasures for the per-plane MDS: the lost node's whole column +
        # aloof nodes
        erasures = {lost - lost % q + i for i in range(q)} | aloof
        if len(erasures) > self.m:
            raise ErasureCodeError("clay repair: too many unknown nodes")
        zero = np.zeros(sc, dtype=np.uint8)

        def plane_order(z):
            zv = self._plane_vector(z)
            return sum(1 for n in ({lost} | aloof)
                       if n % q == zv[n // q])

        by_order: Dict[int, List[int]] = {}
        for z in repair_planes:
            by_order.setdefault(plane_order(z), []).append(z)
        for order in sorted(by_order):
            for z in by_order[order]:
                zv = self._plane_vector(z)
                for y in range(t):
                    for x in range(q):
                        n = y * q + x
                        if n in erasures:
                            continue
                        node_sw, z_sw = self._pair(x, y, z, zv)
                        i0, i1, i2, i3 = self._canon(x, zv[y])
                        if node_sw in aloof:
                            (u,) = self._pft_solve(
                                {i0: helpers[n][plane_ind[z]],
                                 i3: U[node_sw][z_sw]}, [i2])
                            U[n][z] = u
                        elif zv[y] != x:
                            (u,) = self._pft_solve(
                                {i0: helpers[n][plane_ind[z]],
                                 i1: helpers[node_sw][plane_ind[z_sw]]},
                                [i2])
                            U[n][z] = u
                        else:
                            U[n][z] = helpers[n][plane_ind[z]]
                self._decode_uncoupled(erasures, z, U)
                for n in sorted(erasures):
                    x, y = n % q, n // q
                    node_sw, z_sw = self._pair(x, y, z, zv)
                    i0, i1, i2, i3 = self._canon(x, zv[y])
                    if n in aloof:
                        continue
                    if x == zv[y]:
                        recovered[z] = U[n][z]
                    else:
                        # helper in the lost column: reconstruct the LOST
                        # node's coupled symbol at the reflected plane
                        (c_sw,) = self._pft_solve(
                            {i0: helpers[n][plane_ind[z]],
                             i2: U[n][z]}, [i1])
                        recovered[z_sw] = c_sw
        return recovered.reshape(chunk_size)


def _factory(profile: ErasureCodeProfile):
    codec = ErasureCodeClay()
    codec.init(profile)
    return codec


def register(registry) -> None:
    registry.add("clay", _factory)
