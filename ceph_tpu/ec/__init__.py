"""Erasure-code subsystem — codecs, plugin registry, and TPU data path.

Mirrors the capability surface of the reference's src/erasure-code/ tree
(ErasureCodeInterface.h, ErasureCodePlugin.cc, jerasure/isa/shec/clay/lrc
plugins) re-designed for batched array execution: profiles and matrix
preparation on host, stripe math as jitted bit-plane matmuls on TPU.
"""
from .interface import ErasureCodeInterface, ErasureCodeProfile  # noqa: F401
from .registry import ErasureCodePluginRegistry, instance  # noqa: F401
