"""Erasure-code plugin registry.

The reference gates every codec behind a singleton registry that dlopens
``libec_<name>.so``, checks a build-version symbol, and lets the plugin
register itself (src/erasure-code/ErasureCodePlugin.cc:86-178); daemons
preload a configured plugin list at startup (src/global/global_init.cc:591).

The TPU framework keeps the same seam with Python entry points: plugins
register factory callables under a name; ``factory(name, profile)``
instantiates and init()s a codec.  A version string is checked at
registration to preserve the reference's mismatched-plugin failure mode.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict

from .. import __version__
from .interface import ErasureCodeError, ErasureCodeInterface, \
    ErasureCodeProfile

PluginFactory = Callable[[ErasureCodeProfile], ErasureCodeInterface]


class ErasureCodePluginRegistry:
    """Thread-safe singleton registry (ErasureCodePlugin.cc:29-60)."""

    _instance: "ErasureCodePluginRegistry | None" = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._plugins: Dict[str, PluginFactory] = {}

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                reg = cls()
                reg._load_builtins()
                # publish only after builtins loaded, so a failed bootstrap
                # retries instead of pinning an empty registry
                cls._instance = reg
        return cls._instance

    # ----------------------------------------------------------- registry --
    def add(self, name: str, factory: PluginFactory,
            version: str = __version__) -> None:
        """Register a plugin; version mismatch fails loudly, mirroring the
        __erasure_code_version check (ErasureCodePlugin.cc:120-143)."""
        if version != __version__:
            raise ErasureCodeError(
                f"plugin {name!r} version {version!r} != runtime "
                f"{__version__!r}")
        with self._lock:
            if name in self._plugins:
                raise ErasureCodeError(f"plugin {name!r} already registered")
            self._plugins[name] = factory

    def remove(self, name: str) -> None:
        with self._lock:
            self._plugins.pop(name, None)

    def has(self, name: str) -> bool:
        with self._lock:
            return name in self._plugins

    def names(self):
        with self._lock:
            return sorted(self._plugins)

    # ------------------------------------------------------------ factory --
    def factory(self, name: str,
                profile: ErasureCodeProfile) -> ErasureCodeInterface:
        with self._lock:
            fac = self._plugins.get(name)
        if fac is None:
            raise ErasureCodeError(
                f"unknown erasure-code plugin {name!r}; "
                f"known: {self.names()}")
        codec = fac(profile)
        return codec

    def preload(self, names) -> None:
        """Import-side-effect preload hook (ErasureCodePlugin.cc:180-196);
        builtin plugins are always loaded, so this only validates names."""
        for n in names:
            if not self.has(n):
                raise ErasureCodeError(f"cannot preload unknown plugin {n!r}")

    # ----------------------------------------------------------- builtins --
    def _load_builtins(self) -> None:
        # local imports to avoid cycles; each module exposes register(reg)
        from . import plugin_jerasure, plugin_isa, plugin_jax
        for mod in (plugin_jerasure, plugin_isa, plugin_jax):
            mod.register(self)
        # layered codecs arrive in later milestones; tolerate absence
        for name in ("plugin_lrc", "plugin_shec", "plugin_clay"):
            try:
                import importlib
                mod = importlib.import_module(f".{name}", __package__)
                mod.register(self)
            except ImportError:
                continue


def instance() -> ErasureCodePluginRegistry:
    return ErasureCodePluginRegistry.instance()
