"""The 'isa' codec — the reference's throughput-baseline RS variant.

Mirrors the option surface and fast paths of the reference ISA-L plugin
(src/erasure-code/isa/ErasureCodeIsa.cc): technique ``reed_sol_van`` uses
the gf_gen_rs_matrix construction, ``cauchy`` uses gf_gen_cauchy1
(ErasureCodeIsa.cc:385-387); decode of a single data erasure with all
parities intact short-circuits to a pure region XOR when m == 1 or the
first parity row is all-ones (the xor_op fast path, ErasureCodeIsa.cc:152-210);
inverted decode matrices are LRU-cached per erasure signature
(ErasureCodeIsaTableCache.h:35-63 — here via MatrixCodec's cache).

This NumPy implementation doubles as the honest CPU baseline the TPU
plugin is benchmarked against (BASELINE.md config #2).
"""
from __future__ import annotations

import numpy as np

from ..ops import gf
from .interface import ErasureCodeError, ErasureCodeProfile
from .matrix_codec import MatrixCodec

DEFAULT_K = 7
DEFAULT_M = 3


class ErasureCodeIsa(MatrixCodec):
    def init(self, profile: ErasureCodeProfile) -> None:
        technique = profile.get("technique", "reed_sol_van")
        k = self.profile_int(profile, "k", DEFAULT_K, minimum=1)
        m = self.profile_int(profile, "m", DEFAULT_M, minimum=1)
        if k + m > 255:
            raise ErasureCodeError("isa requires k+m <= 255 (w=8)")
        if technique == "reed_sol_van":
            # the rs construction is not guaranteed MDS for m > 2; the
            # reference plugin inherits the same ISA-L caveat
            parity = gf.isa_rs_parity(k, m)
        elif technique == "cauchy":
            parity = gf.isa_cauchy_parity(k, m)
        else:
            raise ErasureCodeError(
                f"isa technique must be reed_sol_van|cauchy, got "
                f"{technique!r}")
        self.set_matrix(parity, 8)
        self._profile = dict(profile)
        self._profile.setdefault("plugin", "isa")
        self._profile["technique"] = technique
        self._profile.update(k=str(k), m=str(m))

    # ------------------------------------------------------ XOR fast path --
    def _xor_decodable(self, available_ids, erased_ids) -> bool:
        """Single data erasure + parity row of ones available → pure XOR."""
        if len(erased_ids) != 1:
            return False
        (e,) = erased_ids
        if e >= self.k:
            return False
        have = set(available_ids)
        return self.k in have and all(
            i in have for i in range(self.k) if i != e) and \
            bool(np.all(self.parity[0] == 1))

    def decode_chunks(self, available_ids, chunks, erased_ids):
        erased = sorted(erased_ids)
        if self._xor_decodable(available_ids, erased):
            (e,) = erased
            order = list(available_ids)
            acc = np.zeros_like(np.asarray(chunks[0], dtype=np.uint8))
            for c in [i for i in range(self.k) if i != e] + [self.k]:
                acc ^= np.asarray(chunks[order.index(c)], dtype=np.uint8)
            return acc[None, :]
        return super().decode_chunks(available_ids, chunks, erased)


def _factory(profile: ErasureCodeProfile):
    codec = ErasureCodeIsa()
    codec.init(profile)
    return codec


def register(registry) -> None:
    registry.add("isa", _factory)
