"""The erasure-code codec contract.

Re-expresses the reference's abstract codec interface
(src/erasure-code/ErasureCodeInterface.h:170-467) for an array-native
runtime: chunk payloads are NumPy uint8 arrays (host) or JAX arrays
(device), and every data-path method also has a batched form so the TPU
backend can amortize dispatch over many stripes — the capability the
reference approximates with thread pools.

Terminology (matches the reference):
  * k data chunks, m coding chunks; chunk ids 0..k+m-1.
  * ``minimum_to_decode(want, available)`` returns, per needed chunk, the
    sub-chunk index ranges to read (ErasureCodeInterface.h:297; the
    sub-chunk granularity exists for CLAY, h:259).
  * ``get_chunk_mapping`` permutes logical→physical chunk order
    (ErasureCodeInterface.h:448).
"""
from __future__ import annotations

import abc
from typing import Dict, List, Sequence, Set, Tuple

import numpy as np

# profile: string key/value map, as stored in the cluster map and validated
# by instantiating the plugin (reference: src/mon/OSDMonitor.cc:7349-7444)
ErasureCodeProfile = Dict[str, str]

# per-chunk list of (offset, count) sub-chunk ranges
SubChunkPlan = Dict[int, List[Tuple[int, int]]]


class ErasureCodeError(Exception):
    """Codec-level failure (bad profile, insufficient chunks, ...)."""


class ErasureCodeInterface(abc.ABC):
    """Abstract codec; concrete plugins register in the plugin registry."""

    # ------------------------------------------------------------ profile --
    @abc.abstractmethod
    def init(self, profile: ErasureCodeProfile) -> None:
        """Parse/validate profile and precompute matrices.  Raises
        ErasureCodeError on invalid profiles (the mon-side validation
        path relies on this)."""

    @abc.abstractmethod
    def get_profile(self) -> ErasureCodeProfile:
        ...

    # ----------------------------------------------------------- geometry --
    @abc.abstractmethod
    def get_chunk_count(self) -> int:
        """k + m."""

    @abc.abstractmethod
    def get_data_chunk_count(self) -> int:
        """k."""

    def get_coding_chunk_count(self) -> int:
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        """Sub-chunks per chunk (1 unless CLAY-style regenerating code)."""
        return 1

    @abc.abstractmethod
    def get_chunk_size(self, stripe_width: int) -> int:
        """Bytes per chunk for an object of ``stripe_width`` bytes
        (includes padding/alignment)."""

    def get_chunk_mapping(self) -> List[int]:
        """chunk_mapping[logical] = physical position; empty = identity."""
        return []

    # ------------------------------------------------------- decode plans --
    @abc.abstractmethod
    def minimum_to_decode(self, want_to_read: Set[int],
                          available: Set[int]) -> SubChunkPlan:
        """Cheapest read plan covering ``want_to_read`` given ``available``
        chunks, as {chunk_id: [(sub_offset, sub_count), ...]}."""

    def minimum_to_decode_with_cost(self, want_to_read: Set[int],
                                    available: Dict[int, int]) -> Set[int]:
        """Pick chunks minimizing total retrieval cost
        (ErasureCodeInterface.h:326). Default: cheapest-first greedy."""
        by_cost = sorted(available, key=lambda c: (available[c], c))
        chosen: Set[int] = set()
        for c in by_cost:
            chosen.add(c)
            try:
                return set(self.minimum_to_decode(want_to_read, chosen))
            except ErasureCodeError:
                continue
        raise ErasureCodeError("insufficient chunks to decode")

    # -------------------------------------------------------- single path --
    @abc.abstractmethod
    def encode(self, want_to_encode: Set[int],
               data: bytes | np.ndarray) -> Dict[int, np.ndarray]:
        """Pad+split ``data`` into k chunks, compute m parities, return the
        requested chunk payloads (ErasureCodeInterface.h:370 semantics)."""

    @abc.abstractmethod
    def encode_chunks(self, data_chunks: np.ndarray) -> np.ndarray:
        """[k, chunk_size] -> [m, chunk_size] parity."""

    @abc.abstractmethod
    def decode(self, want_to_read: Set[int], chunks: Dict[int, np.ndarray],
               chunk_size: int) -> Dict[int, np.ndarray]:
        """Reconstruct ``want_to_read`` chunk payloads from any sufficient
        subset (ErasureCodeInterface.h:411 semantics)."""

    @abc.abstractmethod
    def decode_chunks(self, available_ids: Sequence[int],
                      chunks: np.ndarray, erased_ids: Sequence[int]
                      ) -> np.ndarray:
        """chunks[len(available_ids), chunk_size] -> erased payloads
        [len(erased_ids), chunk_size]."""

    def decode_concat(self, chunks: Dict[int, np.ndarray]) -> np.ndarray:
        """Reconstruct and concatenate the k data chunks in order
        (ErasureCodeInterface.h:461)."""
        want = set(range(self.get_data_chunk_count()))
        size = len(next(iter(chunks.values())))
        dec = self.decode(want, chunks, size)
        return np.concatenate(
            [dec[i] for i in range(self.get_data_chunk_count())])

    # ------------------------------------------------------- batched path --
    # TPU-native extension: same contracts, leading stripe axis.  Default
    # implementations loop; the jax plugin overrides with one jitted call.

    def encode_chunks_batch(self, data: np.ndarray) -> np.ndarray:
        """[B, k, chunk] -> [B, m, chunk]."""
        return np.stack([self.encode_chunks(d) for d in data])

    def decode_chunks_batch(self, available_ids: Sequence[int],
                            chunks: np.ndarray, erased_ids: Sequence[int]
                            ) -> np.ndarray:
        """[B, len(available), chunk] -> [B, len(erased), chunk], one shared
        erasure signature for the whole batch."""
        return np.stack(
            [self.decode_chunks(available_ids, c, erased_ids)
             for c in chunks])
