"""The 'lrc' codec — layered locally-repairable erasure coding.

Re-creates the behavior of the reference LRC plugin
(src/erasure-code/lrc/ErasureCodeLrc.{h,cc}): a global ``mapping`` string
assigns each chunk position a role ('D' data, 'c' coding, '_' padding
hole), and ``layers`` — a JSON list of [chunks_map, profile] pairs — each
run an inner codec over their own 'D'/'c' positions (layers_init,
ErasureCodeLrc.cc:213-244).  Single-chunk failures repair from the
smallest covering layer instead of reading k chunks: _minimum_to_decode
walks layers in reverse preferring local groups (ErasureCodeLrc.cc:590+).

The k/m/l shorthand (DEFAULT_KML generation, ErasureCodeLrc.cc:347-367)
builds the canonical mapping: k data + m global parities followed by one
local parity per group of (k+m)/... — matching the reference's generated
layout.

Profiles:
  plugin=lrc mapping=__DD__DD layers=[["_cDD_cDD",""],["cDDD____",""],...]
  plugin=lrc k=4 m=2 l=3     (generated layout)
"""
from __future__ import annotations

import json
from typing import Dict, List, Sequence, Set

import numpy as np

from .base import ErasureCodeBase
from .interface import ErasureCodeError, ErasureCodeProfile, SubChunkPlan


class _Layer:
    def __init__(self, chunks_map: str, profile: Dict[str, str]):
        self.chunks_map = chunks_map
        self.data = [i for i, c in enumerate(chunks_map) if c == "D"]
        self.coding = [i for i, c in enumerate(chunks_map) if c == "c"]
        self.chunks = self.data + self.coding
        self.chunks_as_set = set(self.chunks)
        self.profile = dict(profile)
        self.profile.setdefault("k", str(len(self.data)))
        self.profile.setdefault("m", str(len(self.coding)))
        self.profile.setdefault("plugin", "jax")
        self.profile.setdefault("technique", "reed_sol_van")
        from .registry import ErasureCodePluginRegistry
        self.codec = ErasureCodePluginRegistry.instance().factory(
            self.profile["plugin"], self.profile)


def _generate_kml(k: int, m: int, l: int) -> Dict[str, str]:
    """The k/m/l layout generator (ErasureCodeLrc.cc:293-375 semantics):
    groups of l data-or-global-coding chunks each get one local parity."""
    if l <= 0 or (k + m) % l:
        raise ErasureCodeError(
            f"lrc k+m={k + m} must be a multiple of l={l}")
    local_group_count = (k + m) // l
    if k % local_group_count or m % local_group_count:
        raise ErasureCodeError(
            f"lrc k={k} and m={m} must be multiples of the group count "
            f"{local_group_count}")
    kg = k // local_group_count
    mg = m // local_group_count
    mapping = ("D" * kg + "_" * mg + "_") * local_group_count
    # global layer: all data positions, coding in the per-group m slots
    glob = ""
    for g in range(local_group_count):
        glob += "D" * kg + "c" * mg + "_"
    layers: List[List[str]] = [[glob, ""]]
    # one local parity layer per group covering its k+m slots
    for g in range(local_group_count):
        pre = "_" * (g * (kg + mg + 1))
        post = "_" * ((local_group_count - g - 1) * (kg + mg + 1))
        layers.append([pre + "D" * (kg + mg) + "c" + post, ""])
    return {"mapping": mapping, "layers": json.dumps(layers)}


class ErasureCodeLrc(ErasureCodeBase):
    def __init__(self) -> None:
        super().__init__()
        self.layers: List[_Layer] = []
        self.mapping = ""

    def init(self, profile: ErasureCodeProfile) -> None:
        prof = dict(profile)
        self._crush_profile = dict(profile)
        if "mapping" not in prof:
            k = self.profile_int(prof, "k", 4, minimum=1)
            m = self.profile_int(prof, "m", 2, minimum=1)
            l = self.profile_int(prof, "l", 3, minimum=1)
            prof.update(_generate_kml(k, m, l))
        self.mapping = prof["mapping"]
        try:
            layer_desc = json.loads(prof["layers"])
        except (KeyError, json.JSONDecodeError) as e:
            raise ErasureCodeError(f"lrc layers JSON invalid: {e}") from e
        if not isinstance(layer_desc, list) or not layer_desc:
            raise ErasureCodeError("lrc layers must be a non-empty list")
        n = len(self.mapping)
        self.layers = []
        for entry in layer_desc:
            cmap = entry[0] if isinstance(entry, list) else entry
            lprof: Dict[str, str] = {}
            if isinstance(entry, list) and len(entry) > 1 and entry[1]:
                if isinstance(entry[1], str):
                    for kv in entry[1].split():
                        key, _, val = kv.partition("=")
                        lprof[key] = val
                elif isinstance(entry[1], dict):
                    lprof = {k: str(v) for k, v in entry[1].items()}
            if len(cmap) != n:
                raise ErasureCodeError(
                    f"layer map {cmap!r} length != mapping length {n}")
            self.layers.append(_Layer(cmap, lprof))
        covered = set()
        for lay in self.layers:
            covered |= lay.chunks_as_set
        if covered != set(range(n)):
            raise ErasureCodeError(
                f"layers cover {sorted(covered)} != all {n} positions")
        self.k = sum(1 for c in self.mapping if c == "D")
        self.m = n - self.k
        # logical chunk ids: 0..k-1 data, k.. the rest; physical = the
        # position in the mapping string (what placement distributes)
        self._l2p = [i for i, c in enumerate(self.mapping) if c == "D"] + \
            [i for i, c in enumerate(self.mapping) if c != "D"]
        self._p2l = {p: i for i, p in enumerate(self._l2p)}
        self._profile = dict(profile)
        self._profile.setdefault("plugin", "lrc")

    def get_chunk_mapping(self) -> List[int]:
        return list(self._l2p)

    # ------------------------------------------------------------ encode --
    def encode_chunks(self, data_chunks: np.ndarray) -> np.ndarray:
        data = np.asarray(data_chunks, dtype=np.uint8)
        if data.shape[0] != self.k:
            raise ErasureCodeError(
                f"expected {self.k} data chunks, got {data.shape[0]}")
        n = len(self.mapping)
        chunk = data.shape[1]
        full = np.zeros((n, chunk), dtype=np.uint8)
        data_pos = [i for i, c in enumerate(self.mapping) if c == "D"]
        for i, pos in enumerate(data_pos):
            full[pos] = data[i]
        # layers run in order; later layers may consume earlier codings
        for lay in self.layers:
            sub = full[lay.data]
            parity = lay.codec.encode_chunks(sub)
            for j, pos in enumerate(lay.coding):
                full[pos] = parity[j]
        non_data = [i for i in range(n) if i not in data_pos]
        return full[non_data]

    # ------------------------------------------------------------ decode --
    def minimum_to_decode(self, want_to_read: Set[int],
                          available: Set[int]) -> SubChunkPlan:
        """Smallest covering layer first (ErasureCodeLrc.cc Case 1-3).
        Ids are logical; layers work in physical positions."""
        erasures_want = want_to_read - available
        if not erasures_want:
            return {c: [(0, 1)] for c in want_to_read}
        want_p = {self._l2p[c] for c in want_to_read}
        avail_p = {self._l2p[c] for c in available}
        # accumulate per-layer reads, most-local layers first, removing
        # erasures as a layer promises to recover them (Case 2,
        # ErasureCodeLrc.cc); wanted-and-available chunks always read
        minimum_p = want_p & avail_p
        era_not_recovered = set(range(len(self.mapping))) - avail_p
        era_want = {self._l2p[c] for c in erasures_want}
        for lay in reversed(self.layers):
            if not era_want:
                break
            layer_erasures = era_want & lay.chunks_as_set
            if not layer_erasures:
                continue
            unrecovered_in_layer = lay.chunks_as_set & era_not_recovered
            if len(unrecovered_in_layer) > len(lay.coding):
                continue            # too many for this layer; try a wider one
            minimum_p |= lay.chunks_as_set & avail_p
            era_not_recovered -= unrecovered_in_layer
            era_want -= layer_erasures
        if not era_want:
            return {self._p2l[c]: [(0, 1)] for c in minimum_p}
        # fall back: any combination across layers that can cascade-recover
        if self._can_recover(avail_p):
            return {self._p2l[c]: [(0, 1)] for c in avail_p}
        raise ErasureCodeError(
            f"lrc cannot recover {sorted(erasures_want)} from "
            f"{sorted(available)}")

    def _can_recover(self, available: Set[int]) -> bool:
        have = set(available)
        progress = True
        while progress:
            progress = False
            for lay in self.layers:
                missing = lay.chunks_as_set - have
                if missing and len(missing) <= len(lay.coding) and \
                        len(lay.chunks_as_set & have) >= len(lay.data):
                    have |= lay.chunks_as_set
                    progress = True
        return have >= set(range(len(self.mapping)))

    def decode_chunks(self, available_ids: Sequence[int],
                      chunks: np.ndarray, erased_ids: Sequence[int]
                      ) -> np.ndarray:
        """Cascading layer repair: repeatedly fix any layer with few
        enough erasures until targets are rebuilt.  Ids logical."""
        chunk = chunks.shape[-1]
        have: Dict[int, np.ndarray] = {
            self._l2p[c]: np.asarray(chunks[i], dtype=np.uint8)
            for i, c in enumerate(available_ids)}
        targets = [self._l2p[c] for c in sorted(erased_ids)]
        progress = True
        while progress and not all(t in have for t in targets):
            progress = False
            for lay in self.layers:
                missing = [c for c in lay.chunks if c not in have]
                if not missing:
                    continue
                avail_in = [c for c in lay.chunks if c in have]
                if len(avail_in) < len(lay.data) or \
                        len(missing) > len(lay.coding):
                    continue
                # express in layer-local indices
                local = {g: i for i, g in enumerate(lay.chunks)}
                try:
                    rebuilt = lay.codec.decode_chunks(
                        [local[c] for c in avail_in],
                        np.stack([have[c] for c in avail_in]),
                        [local[c] for c in missing])
                except ErasureCodeError:
                    continue
                for i, c in enumerate(sorted(missing,
                                             key=lambda g: local[g])):
                    have[c] = rebuilt[i]
                progress = True
        try:
            return np.stack([have[t] for t in targets]) if targets else \
                np.zeros((0, chunk), dtype=np.uint8)
        except KeyError as e:
            raise ErasureCodeError(
                f"lrc unrecoverable chunk {e} from {sorted(available_ids)}"
            ) from e


def lrc_crush_rule(codec: "ErasureCodeLrc", cmap, root_name: str = None):
    """Generate the locality-aware CRUSH rule for an LRC pool
    (ErasureCodeLrc::create_rule semantics, ErasureCodeLrc.h:127 /
    ErasureCodeLrc.cc create_rule): place one local group per
    `crush-locality` bucket, spreading the group's chunks across
    `crush-failure-domain` buckets inside it — so a local repair never
    leaves its locality domain.

    Profile keys (reference names): `crush-root` (default "default"),
    `crush-locality` (e.g. "rack"; omitted -> flat rule),
    `crush-failure-domain` (default "host").  Returns the ruleno added
    to ``cmap``.
    """
    from ..placement.crush_map import (
        Rule, RULE_CHOOSELEAF_INDEP, RULE_CHOOSE_INDEP, RULE_EMIT,
        RULE_TAKE)
    prof = getattr(codec, "_crush_profile", {})
    type_by_name = {v: k for k, v in cmap.type_names.items()}
    root_name = root_name or prof.get("crush-root", "default")
    name_to_id = {v: k for k, v in cmap.bucket_names.items()}
    if root_name not in name_to_id:
        raise ErasureCodeError(f"crush-root {root_name!r} not in map")
    root = name_to_id[root_name]
    fd_name = prof.get("crush-failure-domain", "host")
    if fd_name not in type_by_name:
        raise ErasureCodeError(
            f"crush-failure-domain {fd_name!r} not a map type")
    fd_type = type_by_name[fd_name]
    locality = prof.get("crush-locality")
    n = codec.get_chunk_count()
    steps = [(RULE_TAKE, root, 0)]
    if locality:
        if locality not in type_by_name:
            raise ErasureCodeError(
                f"crush-locality {locality!r} not a map type")
        # group structure comes from the k/m/l profile (the generated
        # layout guarantees one local group per (k+m)/l slice); custom
        # layer JSONs have no inferable grouping — layer-list
        # arithmetic would mislabel extra global layers as groups
        if not all(key in prof for key in ("k", "m", "l")):
            raise ErasureCodeError(
                "lrc locality rule needs the k/m/l profile; custom "
                "layer JSONs must supply their own crush rule")
        k = int(prof["k"])
        m = int(prof["m"])
        l = int(prof["l"])
        if l <= 0 or (k + m) % l:
            raise ErasureCodeError(
                f"lrc: k+m={k + m} not a multiple of l={l}")
        groups = (k + m) // l
        if groups <= 0 or n % groups:
            raise ErasureCodeError(
                f"lrc: {n} chunks not divisible into {groups} groups")
        per_group = n // groups
        # sanity: every local layer must sit inside one group slice
        for L in codec.layers[1:]:
            lo = min(L.chunks_as_set)
            hi = max(L.chunks_as_set)
            if lo // per_group != hi // per_group:
                raise ErasureCodeError(
                    "lrc: a local layer spans group boundaries; "
                    "cannot generate a locality rule")
        steps.append((RULE_CHOOSE_INDEP, groups,
                      type_by_name[locality]))
        steps.append((RULE_CHOOSELEAF_INDEP, per_group, fd_type))
    else:
        steps.append((RULE_CHOOSELEAF_INDEP, 0, fd_type))
    steps.append((RULE_EMIT, 0, 0))
    return cmap.add_rule(Rule(steps=steps, name="lrc_rule", type=3))


def _factory(profile: ErasureCodeProfile):
    codec = ErasureCodeLrc()
    codec.init(profile)
    return codec


def register(registry) -> None:
    registry.add("lrc", _factory)
