"""Shared codec scaffolding — the analog of the reference's ErasureCode base.

Provides the default single-stripe paths every matrix codec shares:
``encode_prepare`` pads the payload and splits it into aligned data chunks
(reference: src/erasure-code/ErasureCode.cc:151-186), default ``encode`` =
prepare + encode_chunks (ErasureCode.cc:188), default ``decode`` fills
erased chunk buffers then calls decode_chunks (ErasureCode.cc:206-242),
and chunk_index applies the logical→physical mapping (ErasureCode.cc:98).
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Set

import numpy as np

from .interface import (ErasureCodeError, ErasureCodeInterface,
                        ErasureCodeProfile, SubChunkPlan)

# Chunk payloads are padded to a multiple of this many bytes so device
# layouts stay lane-aligned (the reference uses SIMD_ALIGN=32 for AVX,
# ErasureCode.cc:42; TPU lanes want 128).
CHUNK_ALIGN = 128


class ErasureCodeBase(ErasureCodeInterface):
    k: int = 0
    m: int = 0

    def __init__(self) -> None:
        self._profile: ErasureCodeProfile = {}
        self.chunk_mapping: List[int] = []

    # ----------------------------------------------------------- profile --
    def get_profile(self) -> ErasureCodeProfile:
        return dict(self._profile)

    @staticmethod
    def profile_int(profile: ErasureCodeProfile, key: str, default: int,
                    *, minimum: int | None = None,
                    maximum: int | None = None) -> int:
        """Parse an integer profile entry with bounds (the to_int helper,
        ErasureCode.cc:251-281)."""
        raw = profile.get(key)
        if raw in (None, ""):
            return default
        try:
            v = int(str(raw), 0)
        except ValueError as e:
            raise ErasureCodeError(f"{key}={raw!r} is not an integer") from e
        if minimum is not None and v < minimum:
            raise ErasureCodeError(f"{key}={v} below minimum {minimum}")
        if maximum is not None and v > maximum:
            raise ErasureCodeError(f"{key}={v} above maximum {maximum}")
        return v

    # ---------------------------------------------------------- geometry --
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_chunk_size(self, stripe_width: int) -> int:
        align = CHUNK_ALIGN * self.k
        padded = -(-stripe_width // align) * align
        return padded // self.k

    def get_chunk_mapping(self) -> List[int]:
        return list(self.chunk_mapping)

    def chunk_index(self, i: int) -> int:
        return self.chunk_mapping[i] if self.chunk_mapping else i

    # ------------------------------------------------------ default paths --
    def encode_prepare(self, data: bytes | np.ndarray) -> np.ndarray:
        """Zero-pad to k*chunk_size and reshape to [k, chunk_size]."""
        buf = np.frombuffer(bytes(data), dtype=np.uint8) \
            if not isinstance(data, np.ndarray) else \
            np.ascontiguousarray(data, dtype=np.uint8).ravel()
        chunk = self.get_chunk_size(len(buf))
        padded = np.zeros(self.k * chunk, dtype=np.uint8)
        padded[:len(buf)] = buf
        return padded.reshape(self.k, chunk)

    def encode(self, want_to_encode: Set[int],
               data: bytes | np.ndarray) -> Dict[int, np.ndarray]:
        chunks = self.encode_prepare(data)
        parity = self.encode_chunks(chunks)
        all_chunks = np.concatenate([chunks, parity], axis=0)
        return {i: all_chunks[self.chunk_index(i)] for i in want_to_encode}

    def minimum_to_decode(self, want_to_read: Set[int],
                          available: Set[int]) -> SubChunkPlan:
        """MDS default: any k available chunks suffice; prefer the wanted
        chunks themselves (ErasureCode.cc:62-96 semantics)."""
        if want_to_read <= available:
            return {c: [(0, self.get_sub_chunk_count())] for c in want_to_read}
        if len(available) < self.k:
            raise ErasureCodeError(
                f"need {self.k} chunks, only {len(available)} available")
        picked = sorted(want_to_read & available)
        for c in sorted(available - want_to_read):
            if len(picked) >= self.k:
                break
            picked.append(c)
        picked = sorted(picked)[:self.k]
        return {c: [(0, self.get_sub_chunk_count())] for c in picked}

    def decode(self, want_to_read: Set[int], chunks: Dict[int, np.ndarray],
               chunk_size: int) -> Dict[int, np.ndarray]:
        available = sorted(chunks)
        have = set(available)
        if want_to_read <= have:
            return {c: np.asarray(chunks[c], dtype=np.uint8)
                    for c in want_to_read}
        # sufficiency is codec-specific (layered codecs decode locally
        # from fewer than k chunks); decode_chunks raises if impossible.
        # Rebuild only what was asked for — a local read plan deliberately
        # leaves unrelated chunks unread.
        use = available[:self.k + self.m]
        erased = sorted(want_to_read - have)
        stack = np.stack([np.asarray(chunks[c], dtype=np.uint8)
                          for c in use])
        rebuilt = self.decode_chunks(use, stack, erased)
        out = {c: np.asarray(chunks[c], dtype=np.uint8)
               for c in want_to_read if c in have}
        for idx, c in enumerate(erased):
            if c in want_to_read:
                out[c] = rebuilt[idx]
        return out
