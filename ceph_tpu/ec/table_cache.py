"""LRU cache for decode matrices, keyed by erasure signature.

Re-creates the role of the reference ISA plugin's decoding-table cache
(src/erasure-code/isa/ErasureCodeIsaTableCache.h:35-63, default 2516
entries): inverting the k x k sub-generator per erasure pattern is the
expensive host-side step, and real clusters see few distinct patterns at a
time, so recovered matrices are reused across stripes.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Hashable

DEFAULT_CAPACITY = 2516


class DecodeTableCache:
    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self._capacity = capacity
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, object]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key: Hashable):
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self.hits += 1
                return self._entries[key]
            self.misses += 1
            return None

    def put(self, key: Hashable, value) -> None:
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self._capacity:
                self._entries.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
