"""RAID-6 (m=2) minimal-density bitmatrix constructions.

The reference jerasure plugin ships three bitmatrix-native techniques —
liberation, blaum_roth, liber8tion (declared at
src/erasure-code/jerasure/ErasureCodeJerasure.h:192,229,240, prepared by
liberation_coding_bitmatrix / blaum_roth_coding_bitmatrix /
liber8tion_coding_bitmatrix in the vendored jerasure library, an EMPTY
submodule in this checkout).  They are GF(2) bitmatrix codes operating
on packet (plane) regions — exactly the layout of ops/gf2.py — with
far sparser Q matrices than a Cauchy expansion, which made them the
fast RAID-6 path on CPUs and makes them the cheapest XOR schedules
here.

Structure shared by all three: the parity bitmatrix is

        [ I   I   ...  I  ]      (P = XOR of all data chunks)
        [X_0 X_1 ... X_{k-1}]    (Q row; X_i are w x w 0/1 matrices)

and the code is MDS for 2 erasures iff every X_i and every X_i ^ X_j
is invertible over GF(2).

Constructions:

  * blaum_roth (w with w+1 prime, k <= w): X_i = C^i where C is the
    companion matrix of multiplication by x in the polynomial ring
    GF(2)[x] / (1 + x + ... + x^w) — the exact Blaum-Roth independent-
    parity construction; deterministic, no search.
  * liberation (w prime, k <= w): X_0 = I and X_i = sigma^i (cyclic
    down-shift by i) plus ONE extra bit, the minimal-density shape of
    Plank's Liberation codes.  The published extra-bit formula is not
    reproducible without the vendored library, so the extra position is
    found by deterministic search over the w^2 candidates (first one
    preserving pairwise invertibility wins); the resulting Q density is
    the Liberation minimum, k*w + k - 1 ones.
  * liber8tion (w=8, k <= 8): same minimal-density shape at w=8 (not
    prime).  The original liber8tion matrices were themselves FOUND by
    computer search (Plank, "The RAID-6 Liber8tion Code"); this module
    re-runs such a search deterministically over (shift, extra-bit)
    candidates with backtracking.

All constructions are validated for the full 2-erasure MDS property at
build time and are deterministic (same matrices every process), so the
non-regression corpus can pin their output bytes.
"""
from __future__ import annotations

import functools

import numpy as np

from ..ops import gf2


def _shift_matrix(w: int, s: int) -> np.ndarray:
    """sigma^s: X @ v rotates v down by s (X[j, (j + s) % w] = 1)."""
    X = np.zeros((w, w), dtype=np.uint8)
    for j in range(w):
        X[j, (j + s) % w] = 1
    return X


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    d = 2
    while d * d <= n:
        if n % d == 0:
            return False
        d += 1
    return True


def _pairwise_ok(X: np.ndarray, chosen: list) -> bool:
    if not gf2.gf2_invertible(X):
        return False
    return all(gf2.gf2_invertible(X ^ Y) for Y in chosen)


def _assemble(k: int, w: int, xs: list) -> np.ndarray:
    """[2w, kw] parity bitmatrix from the Q-row blocks."""
    bm = np.zeros((2 * w, k * w), dtype=np.uint8)
    eye = np.eye(w, dtype=np.uint8)
    for i in range(k):
        bm[:w, i * w:(i + 1) * w] = eye
        bm[w:, i * w:(i + 1) * w] = xs[i]
    return bm


@functools.lru_cache(maxsize=None)
def blaum_roth_bitmatrix(k: int, w: int) -> np.ndarray:
    """Blaum-Roth: X_i = (mult by x^i mod 1+x+...+x^w).  w+1 prime,
    k <= w (reference surface: ErasureCodeJerasure.h:229)."""
    if not _is_prime(w + 1):
        raise ValueError(f"blaum_roth requires w+1 prime, got w={w}")
    if k > w:
        raise ValueError(f"blaum_roth requires k <= w ({k} > {w})")
    # companion matrix: x * x^j = x^{j+1}; x^w = sum_{t<w} x^t
    C = np.zeros((w, w), dtype=np.uint8)
    for j in range(w - 1):
        C[j + 1, j] = 1
    C[:, w - 1] = 1
    xs, X = [], np.eye(w, dtype=np.uint8)
    for i in range(k):
        xs.append(X)
        X = gf2.gf2_matmul(C, X)
    bm = _assemble(k, w, xs)
    _validate_mds(bm, k, w, "blaum_roth")
    return bm


def _backtrack(k: int, candidates) -> list | None:
    """Depth-first search for k pairwise-compatible Q blocks.
    ``candidates(i)`` yields the column-i candidates in deterministic
    order; the first complete assignment wins (same matrices every
    process, so corpus pinning is stable)."""
    def go(i, chosen):
        if i == k:
            return chosen
        for X in candidates(i):
            if _pairwise_ok(X, chosen):
                out = go(i + 1, chosen + [X])
                if out is not None:
                    return out
        return None
    return go(0, [])


@functools.lru_cache(maxsize=None)
def liberation_bitmatrix(k: int, w: int) -> np.ndarray:
    """Liberation shape: X_0 = I, X_i = sigma^i + one searched extra bit
    (w prime, k <= w; reference surface: ErasureCodeJerasure.h:192).
    Backtracking over the extra-bit positions (greedy dead-ends exist,
    e.g. k=5 w=7)."""
    if not _is_prime(w):
        raise ValueError(f"liberation requires prime w, got {w}")
    if k > w:
        raise ValueError(f"liberation requires k <= w ({k} > {w})")

    def candidates(i):
        if i == 0:
            yield np.eye(w, dtype=np.uint8)
            return
        base = _shift_matrix(w, i)
        for r in range(w):
            for c in range(w):
                if base[r, c]:
                    continue
                X = base.copy()
                X[r, c] = 1
                yield X

    xs = _backtrack(k, candidates)
    if xs is None:  # pragma: no cover - prime w always succeeds
        raise ValueError(f"liberation search failed for k={k} w={w}")
    bm = _assemble(k, w, xs)
    _validate_mds(bm, k, w, "liberation")
    return bm


@functools.lru_cache(maxsize=None)
def liber8tion_bitmatrix(k: int, w: int = 8) -> np.ndarray:
    """Liber8tion surface at w=8 (m=2, k <= 8, packet layout;
    reference: ErasureCodeJerasure.h:240).

    The original liber8tion matrices were minimum-density tables found
    by a large computer search (Plank, "The RAID-6 Liber8tion Code")
    and shipped inside the vendored jerasure library — an empty
    submodule here, and not reconstructible from a formula.  Shift-plus-
    extra-bit families cannot work at w=8 at all (sigma^a ^ sigma^b is
    ALWAYS singular when w is a power of two: x^d + 1 shares the factor
    x + 1 with x^8 - 1), so this build fills the technique with the
    classic deterministic RAID-6 bitmatrix: X_i = C^i for C the
    companion matrix of the GF(2^8) polynomial 0x11d (multiplication by
    alpha^i).  MDS holds because C^a ^ C^b = C^b (C^{a-b} ^ I) and
    alpha^d != 1 for 0 < d < 255.  Same (k, m, w, layout) surface and
    packet semantics; Q density is ~2x the unpublished minimum, which
    the mask-XOR device kernel is insensitive to.
    """
    if w != 8:
        raise ValueError("liber8tion is defined for w=8")
    if k > 8:
        raise ValueError(f"liber8tion requires k <= 8, got {k}")
    # companion matrix of x^8 + x^4 + x^3 + x^2 + 1 (POLY8 = 0x11d)
    C = np.zeros((w, w), dtype=np.uint8)
    for j in range(w - 1):
        C[j + 1, j] = 1
    for b in range(w):
        if (0x11D >> b) & 1:
            C[b, w - 1] = 1
    xs, X = [], np.eye(w, dtype=np.uint8)
    for i in range(k):
        xs.append(X)
        X = gf2.gf2_matmul(C, X)
    bm = _assemble(k, w, xs)
    _validate_mds(bm, k, w, "liber8tion")
    return bm


def _validate_mds(bm: np.ndarray, k: int, w: int, name: str) -> None:
    """Assert every 2-erasure pattern is decodable (X_i, X_i^X_j
    invertible) — the build-time contract."""
    xs = [bm[w:, i * w:(i + 1) * w] for i in range(k)]
    for i in range(k):
        if not gf2.gf2_invertible(xs[i]):
            raise AssertionError(f"{name}: X_{i} singular")
        for j in range(i):
            if not gf2.gf2_invertible(xs[i] ^ xs[j]):
                raise AssertionError(f"{name}: X_{i}^X_{j} singular")
