"""Multi-chip scale-out: mesh construction and sharded data-path steps.

The reference scales out with one messenger connection per OSD peer
(SURVEY.md §2.4); the TPU framework scales the batch axes (stripes, PGs)
across a jax.sharding.Mesh, with XLA inserting ICI/DCN collectives.

``mesh.py`` holds the mesh/sharding plumbing and raw kernel steps;
``data_plane.py`` is the cluster-level subsystem (ShardedDataPlane)
that executes the put / degraded-get / recovery / remap hot loops
sharded, behind the ``parallel_data_plane`` option.

No eager submodule imports here: ``mesh`` imports jax AND enables
x64 at import time, and ``data_plane`` is imported by hot paths
(plugin encode, map_pgs_batch) that must stay jax-free while the
plane is disabled — import the submodule you need directly.
"""
