"""Multi-chip scale-out: mesh construction and sharded data-path steps.

The reference scales out with one messenger connection per OSD peer
(SURVEY.md §2.4); the TPU framework scales the batch axes (stripes, PGs)
across a jax.sharding.Mesh, with XLA inserting ICI/DCN collectives.
"""
from .mesh import (batch_sharding, distributed_encode_step,  # noqa: F401
                   make_mesh, replicated_sharding)
