"""ShardedDataPlane — the multi-chip execution tier for the CLUSTER
hot loops.

`parallel/mesh.py` shards the raw kernels; this module shards the
*system*: the batched put encode, the degraded-get / recovery decode
(signature-grouped masked-XOR), and the million-PG remap sweep all
dispatch over a 1-D device mesh on the stripe/PG batch axis, with
XLA-inserted ICI collectives carrying the cluster-wide accounting
(the psum the byte counters ride).  This is the reference's scale-out
— messenger fan-out across OSD processes plus the ParallelPGMapper
thread pool (src/osd/OSDMapMapping.h:18, SURVEY §2.4) — collapsed
into shardings, in the spirit of DrJAX's sharded-map primitives
(arxiv 2403.07128) and batched-XOR EC pipelines (arxiv 2108.02692).

Wiring (all behind the ``parallel_data_plane`` option, default off —
the single-device path is untouched when disabled):

  * ``ec/plugin_jax.py`` routes ``encode_words_device`` /
    ``decode_words_device`` through :meth:`ShardedDataPlane.xor_matmul_w32`,
    so every caller of the shared ECBackend engine — the simulator's
    put/get, the wire client's batched put, signature-grouped degraded
    reads — runs sharded without knowing it;
  * ``cluster/simulator.py`` dispatches the recovery sweep's
    full-width-mask rebuild through the same entry (per-stripe decode
    signatures ride the sharded batch axis);
  * ``cluster/osdmap.py`` passes the plane's mesh to
    ``XlaMapper.map_batch`` so ``map_pgs_batch`` splits PG lanes
    across chips (the multi-chip ParallelPGMapper);
  * ``cluster/ec_backend.py`` and ``cluster/device_store.py`` account
    sub-writes and HBM staging per chip by OSD-shard -> chip affinity.

Bit-exactness: the contraction is pure AND/XOR over int32 words — a
sharded leading axis changes the layout, never a value — and padding
rows are zeros that are sliced off before anyone reads them, so the
sharded path is bit-identical to the single-device path (asserted by
tests/test_data_plane.py and the ``dryrun_multichip`` cluster step).

Observability: per-chip counters land in the ``dataplane`` perf group
(``dataplane.shard<i>.put_stripes`` / ``..._bytes``, ``decode_*``,
``recover_*``, ``map_lanes``, ``staged_*``, ``subwrites``) and every
sharded dispatch tags the calling thread's tracked op with a
``dispatched_mesh`` event, so ``dump_historic_ops`` shows exactly
which client ops fanned out across the mesh.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from ..common.op_tracker import mark_active as _mark_active
from ..common.options import OptionError, config
from ..common.perf_counters import perf as _perf

# hot-path enablement cache (same pattern as perf_counters._counters
# _enabled): the staging/accounting probes run per shard put, so the
# layered-registry walk must not happen per call
_enabled: Optional[bool] = None
_enabled_lock = threading.Lock()


def enabled() -> bool:
    """Cheap cached read of the ``parallel_data_plane`` option."""
    global _enabled
    if _enabled is None:
        with _enabled_lock:
            if _enabled is None:
                cfg = config()
                try:
                    val = bool(cfg.get("parallel_data_plane"))
                except OptionError:
                    val = False

                def _refresh(_name, value):
                    global _enabled
                    # serialized with init: a set() firing between
                    # our observe() and the publish below must not be
                    # clobbered by the stale initial read
                    with _enabled_lock:
                        _enabled = bool(value)

                try:
                    cfg.observe("parallel_data_plane", _refresh)
                except OptionError:
                    pass
                if _enabled is None:
                    _enabled = val
    return _enabled


class ShardedDataPlane:
    """Owns a mesh and executes the cluster hot loops sharded over it."""

    def __init__(self, mesh):
        self.mesh = mesh
        self.n_shards = int(mesh.size)
        self._pc = _perf("dataplane")
        # (per_batch, mesh) -> jitted sharded step
        self._steps: Dict[Tuple, object] = {}
        # the latest dispatch's cross-shard psum scalar, UNREAD: the
        # collective runs in the graph but the hot path must not pay
        # a device->host sync per dispatch; psum_probe() reads it
        self.last_psum = None

    # ------------------------------------------------------------ affinity --
    def chip_of(self, osd_id: int) -> int:
        """OSD-shard -> chip affinity: which mesh position accounts for
        an OSD's staged shards and sub-writes.  A stable modulo keyed
        on the OSD id, so the partition survives map churn."""
        return int(osd_id) % self.n_shards

    # ------------------------------------------------------------- dispatch --
    def _step(self, per_batch: bool):
        """Jitted sharded masked-XOR step, cached per (mask mode,
        mesh): words batch-sharded on the stripe axis, masks sharded
        alongside when they carry per-stripe signatures (the recovery
        sweep) and replicated otherwise (encode / grouped decode),
        plus the cluster-wide row-count reduction — an explicit psum
        on the ICI ring (the collective the accounting rides).

        shard_map, not bare jit-with-shardings: the per-shard body
        calls the REAL kernel entry (ops.xor_kernel.xor_matmul_w32),
        so each chip runs the tiled Pallas kernel on TPU — a sharded
        jit around the XLA fallback graph would silently swap the
        flagship kernel for the slow path on exactly the hardware
        the mesh targets.  (CPU runs the XLA fallback either way,
        keeping the bit-identity tests meaningful.)"""
        from .mesh import SHARD_AXIS, mesh_cache_key
        key = (per_batch,) + mesh_cache_key(self.mesh)
        step = self._steps.get(key)
        if step is None:
            import jax
            import jax.numpy as jnp
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            from ..ops import xor_kernel

            def local(masks, words):
                out = xor_kernel.xor_matmul_w32(masks, words)
                rows = jax.lax.psum(
                    jnp.sum(jnp.ones((words.shape[0],), jnp.int32)
                            .astype(jnp.int64)), SHARD_AXIS)
                return out, rows

            from ..common.jit_profile import wrap as _jit_wrap
            mspec = P(SHARD_AXIS) if per_batch else P()
            step = self._steps[key] = _jit_wrap(
                jax.jit(shard_map(
                    local, mesh=self.mesh,
                    in_specs=(mspec, P(SHARD_AXIS)),
                    out_specs=(P(SHARD_AXIS), P()))),
                "data_plane.step", f"per_batch={per_batch}")
        return step

    def _collective_step(self, per_batch: bool):
        """Jitted sharded rebuild step with the RECOVERY collectives:
        each chip decodes its stripe slice with the real kernel, then
        the rebuilt rows ALL-GATHER across the mesh (tiled on the
        stripe axis), so every chip — hence every OSD-shard partition
        landing a rebuilt shard — holds the bytes chip-to-chip, with
        no host staging hop in between.  The psum row counter rides
        the same dispatch (the accounting collective).

        out_specs P() with check_rep=False: a tiled all_gather leaves
        the value identical on every mesh position by construction;
        shard_map cannot prove that, so the replication is asserted
        by the bit-identity tests instead."""
        from .mesh import SHARD_AXIS, mesh_cache_key
        key = ("collective", per_batch) + mesh_cache_key(self.mesh)
        step = self._steps.get(key)
        if step is None:
            import jax
            import jax.numpy as jnp
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            from ..ops import xor_kernel

            def local(masks, words):
                out = xor_kernel.xor_matmul_w32(masks, words)
                rows = jax.lax.psum(
                    jnp.sum(jnp.ones((words.shape[0],), jnp.int32)
                            .astype(jnp.int64)), SHARD_AXIS)
                full = jax.lax.all_gather(out, SHARD_AXIS, axis=0,
                                          tiled=True)
                return full, rows

            from ..common.jit_profile import wrap as _jit_wrap
            mspec = P(SHARD_AXIS) if per_batch else P()
            step = self._steps[key] = _jit_wrap(
                jax.jit(shard_map(
                    local, mesh=self.mesh,
                    in_specs=(mspec, P(SHARD_AXIS)),
                    out_specs=(P(), P()), check_rep=False)),
                "data_plane.collective", f"per_batch={per_batch}")
        return step

    def _ppermute_step(self, shift: int):
        """Jitted ring ppermute: each chip's stripe block moves
        ``shift`` positions around the ICI ring — the pairwise
        shard-landing primitive (a rebuilt block computed on chip i
        delivered to the chip owning its target OSD), and the
        building block the 2-D (stripe, shard) mesh plan composes."""
        from .mesh import SHARD_AXIS, mesh_cache_key
        key = ("ppermute", shift) + mesh_cache_key(self.mesh)
        step = self._steps.get(key)
        if step is None:
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            n = self.n_shards
            perm = [(i, (i + shift) % n) for i in range(n)]

            def local(x):
                return jax.lax.ppermute(x, SHARD_AXIS, perm=perm)

            from ..common.jit_profile import wrap as _jit_wrap
            step = self._steps[key] = _jit_wrap(
                jax.jit(shard_map(
                    local, mesh=self.mesh,
                    in_specs=(P(SHARD_AXIS),),
                    out_specs=P(SHARD_AXIS))),
                "data_plane.ppermute", f"shift={shift}")
        return step

    def ppermute_shift(self, arr, shift: int = 1):
        """Rotate a batch-sharded array ``shift`` mesh positions along
        the ring (block-granular: each chip's whole slice moves).  The
        leading axis must be a mesh multiple."""
        import jax
        from .mesh import batch_sharding
        if int(arr.shape[0]) % self.n_shards:
            raise ValueError(
                f"ppermute batch {arr.shape[0]} not a multiple of "
                f"{self.n_shards} mesh positions")
        arr = jax.device_put(arr, batch_sharding(self.mesh))
        out = self._ppermute_step(int(shift) % self.n_shards)(arr)
        self._pc.inc("ppermute_rows", int(arr.shape[0]))
        return out

    def rebuild_collective(self, masks, words, kind: str = "recover"):
        """The device-resident recovery dispatch: identical operands
        and bit-identical result to :meth:`xor_matmul_w32`, but the
        rebuilt rows land on EVERY chip via an in-graph tiled
        all-gather — a recovered shard's new home reads its bytes
        from its own chip's copy of the gathered buffer instead of a
        per-shard host round trip.  Padding rows (zero masks, zero
        words) gather as zeros and are sliced off."""
        import jax
        import jax.numpy as jnp
        words = jnp.asarray(words, jnp.int32)
        masks = jnp.asarray(masks, jnp.int32)
        lead = words.shape[:-2]
        C, W = words.shape[-2:]
        per_batch = masks.ndim > 2
        if per_batch and masks.shape[:-2] != lead:
            raise ValueError(
                f"mask batch {masks.shape[:-2]} != data batch {lead}")
        R = masks.shape[-2]
        B = int(np.prod(lead)) if lead else 1
        w3 = words.reshape(B, C, W)
        m3 = masks.reshape(B, R, masks.shape[-1]) if per_batch \
            else masks
        pad = (-B) % self.n_shards
        if pad:
            w3 = jnp.pad(w3, ((0, pad), (0, 0), (0, 0)))
            if per_batch:
                m3 = jnp.pad(m3, ((0, pad), (0, 0), (0, 0)))
        from .mesh import batch_sharding, replicated_sharding
        w3 = jax.device_put(w3, batch_sharding(self.mesh))
        m3 = jax.device_put(m3, batch_sharding(self.mesh) if per_batch
                            else replicated_sharding(self.mesh))
        out, rows = self._collective_step(per_batch)(m3, w3)
        self.last_psum = rows
        self.account(kind, B, 4 * C * W, padded_rows=B + pad)
        self._pc.inc("allgather_rows", B + pad)
        out = out[:B] if pad else out
        return out.reshape(lead + (R, W)) if lead else \
            out.reshape(R, W)

    def account_landed(self, target_osd: int, rows: int,
                       row_bytes: int) -> None:
        """One rebuilt shard landed chip-to-chip on ``target_osd``'s
        affine chip (the delivery half of rebuild_collective)."""
        chip = self.chip_of(target_osd)
        self._pc.inc(f"shard{chip}.recover_landed")
        self._pc.inc(f"shard{chip}.recover_landed_bytes",
                     rows * row_bytes)

    def xor_matmul_w32(self, masks, words, kind: str = "encode"):
        """Drop-in for ``ops.xor_kernel.xor_matmul_w32``, sharded on
        the leading (stripe) axis.  masks [R, C] (replicated) or
        [..., R, C] matching ``words``'s leading axes (per-stripe
        signatures, sharded); words [..., C, W] int32 -> [..., R, W].

        The batch pads with zero rows to a mesh multiple (zero inputs
        AND zero masks produce zero outputs, sliced off before
        return), so arbitrary batch sizes reuse the same executable
        family and the result is bit-identical to the single-device
        kernel.
        """
        import jax.numpy as jnp
        words = jnp.asarray(words, jnp.int32)
        masks = jnp.asarray(masks, jnp.int32)
        lead = words.shape[:-2]
        C, W = words.shape[-2:]
        per_batch = masks.ndim > 2
        if per_batch and masks.shape[:-2] != lead:
            raise ValueError(
                f"mask batch {masks.shape[:-2]} != data batch {lead}")
        if masks.shape[-1] != C:
            raise ValueError(
                f"masks contract {masks.shape[-1]} columns, data has "
                f"{C} planes")
        R = masks.shape[-2]
        B = int(np.prod(lead)) if lead else 1
        w3 = words.reshape(B, C, W)
        m3 = masks.reshape(B, R, masks.shape[-1]) if per_batch \
            else masks
        pad = (-B) % self.n_shards
        if pad:
            w3 = jnp.pad(w3, ((0, pad), (0, 0), (0, 0)))
            if per_batch:
                m3 = jnp.pad(m3, ((0, pad), (0, 0), (0, 0)))
        # explicit reshard: operands arrive committed to whatever
        # placement the producing dispatch left them with (a staged
        # buffer, a gather output) and pjit refuses a silent layout
        # change — device_put scatters the batch across the mesh
        import jax
        from .mesh import batch_sharding, replicated_sharding
        w3 = jax.device_put(w3, batch_sharding(self.mesh))
        m3 = jax.device_put(m3, batch_sharding(self.mesh) if per_batch
                            else replicated_sharding(self.mesh))
        out, rows = self._step(per_batch)(m3, w3)
        # keep the psum ON DEVICE: reading it here would host-sync
        # every dispatch (its value is deterministically B+pad, which
        # the counter records; psum_probe() verifies the collective)
        self.last_psum = rows
        self.account(kind, B, 4 * C * W, padded_rows=B + pad)
        out = out[:B] if pad else out
        return out.reshape(lead + (R, W)) if lead else \
            out.reshape(R, W)

    def psum_probe(self) -> Optional[int]:
        """Read back the latest dispatch's cross-shard psum (ONE
        host sync, on demand — tests/smokes verify the collective;
        the dispatch path never reads it)."""
        return None if self.last_psum is None else int(self.last_psum)

    # ----------------------------------------------------------- accounting --
    def account(self, kind: str, rows: int, row_bytes: int,
                padded_rows: Optional[int] = None) -> None:
        """Per-chip accounting for one sharded dispatch: the leading
        axis splits contiguously across the mesh, so chip i's REAL
        row count is derivable host-side; ``psum_rows`` records the
        padded total the in-graph collective reduces to (value known
        host-side — reading the device scalar per dispatch would
        host-sync the hot loop; see psum_probe)."""
        pc = self._pc
        pc.inc("dispatches")
        pc.inc(f"{kind}_dispatches")
        if padded_rows is not None:
            pc.inc("psum_rows", padded_rows)
        total = padded_rows if padded_rows is not None else rows
        per = -(-total // self.n_shards)
        unit = "lanes" if kind == "map" else "stripes"
        for i in range(self.n_shards):
            real = max(0, min(per, rows - i * per))
            if real:
                pc.inc(f"shard{i}.{kind}_{unit}", real)
                pc.inc(f"shard{i}.{kind}_bytes", real * row_bytes)
        _mark_active("dispatched_mesh", kind=kind,
                     shards=self.n_shards, rows=rows)

    def account_subwrite(self, target_osd: int) -> None:
        """One EC sub-write headed to ``target_osd``: counted on its
        affine chip (the fan-out half of the per-chip staging view)."""
        self._pc.inc(f"shard{self.chip_of(target_osd)}.subwrites")

    def account_staged(self, osd_or_shard: int, nbytes: int) -> None:
        """One shard staged into an HBM partition, attributed by
        OSD-shard -> chip affinity."""
        chip = self.chip_of(osd_or_shard)
        self._pc.inc(f"shard{chip}.staged_entries")
        self._pc.inc(f"shard{chip}.staged_bytes", int(nbytes))

    def stats(self) -> Dict:
        return self._pc.dump()


_planes: Dict[int, ShardedDataPlane] = {}
_planes_lock = threading.Lock()
# resolved-plane cache: plane() runs on per-shard hot paths (staging
# accounting), so the mesh-size option walk + jax.devices() must not
# repeat per call — the resolution is cached and invalidated by a
# config observer, like enabled()'s flag
_resolved: Optional[ShardedDataPlane] = None
_resolved_valid = False
_resolve_gen = 0
_observing_devices = False


def _invalidate_resolution(_name=None, _value=None) -> None:
    global _resolved_valid, _resolve_gen
    _resolve_gen += 1
    _resolved_valid = False


def plane() -> Optional[ShardedDataPlane]:
    """The process-wide data plane, or None when the option is off or
    fewer than two devices exist (single-device hosts fall through to
    the plain path — there is nothing to shard)."""
    global _resolved, _resolved_valid, _observing_devices
    if not enabled():
        return None
    if _resolved_valid:
        return _resolved
    if not _observing_devices:
        try:
            config().observe("parallel_data_plane_devices",
                             _invalidate_resolution)
            _observing_devices = True
        except OptionError:
            pass
    gen = _resolve_gen
    try:
        import jax
        n_avail = len(jax.devices())
    except Exception:
        return None
    want = 0
    try:
        want = int(config().get("parallel_data_plane_devices"))
    except OptionError:
        pass
    n = want or n_avail
    if n < 2 or n_avail < n:
        p = None
    else:
        with _planes_lock:
            p = _planes.get(n)
            if p is None:
                from .mesh import make_mesh
                p = _planes[n] = ShardedDataPlane(make_mesh(n))
    if gen == _resolve_gen:
        # publish only if no invalidation raced the resolution (a
        # mid-compute option change would otherwise be masked by a
        # stale cache entry until the next change)
        _resolved, _resolved_valid = p, True
    return p
