"""ShardedDataPlane — the multi-chip execution tier for the CLUSTER
hot loops (MeshPlane2D: 1-D stripe mesh or 2-D (stripe, shard) mesh).

`parallel/mesh.py` shards the raw kernels; this module shards the
*system*: the batched put encode, the degraded-get / recovery decode
(signature-grouped masked-XOR), and the million-PG remap sweep all
dispatch over a device mesh, with XLA-inserted ICI collectives
carrying the cluster-wide accounting (the psum the byte counters
ride).  This is the reference's scale-out — messenger fan-out across
OSD processes plus the ParallelPGMapper thread pool
(src/osd/OSDMapMapping.h:18, SURVEY §2.4) — collapsed into shardings,
in the spirit of DrJAX's sharded-map primitives (arxiv 2403.07128)
and batched-XOR EC pipelines (arxiv 2108.02692).

Mesh layouts (``parallel_data_plane_stripes``):

  * 1-D ``(shard,)`` (default, the legacy plane): the stripe/PG batch
    axis splits over every chip; masks replicate; collectives psum /
    all-gather over SHARD_AXIS.
  * 2-D ``(stripe, shard)`` (stripes >= 2, or one stripe row per host
    under the multi-process plane — parallel/multihost.py): the batch
    splits over the STRIPE rows while the k+m output-shard dimension
    (the masked-XOR contraction's R rows) splits over the SHARD
    columns — per-chip shard ownership matches the OSD→chip affinity
    the per-chip counters already track.  The EC contract rides
    per-axis collectives: the row counter psums along STRIPE_AXIS
    (per stripe row), rebuilt shards all-gather along SHARD_AXIS
    (assembling k+m per stripe row) then along STRIPE_AXIS (landing
    chip-to-chip on every target OSD's affine chip), and
    ``ppermute_shift`` runs the flat ring over BOTH axes row-major —
    the same block rotation the 1-D ring gave, now a true 2-D
    collective.  Results are bit-identical across layouts: the
    contraction is pure AND/XOR, axis splits change layout, never
    values, and padding rows/columns are zeros sliced off before
    anyone reads them.

Wiring (all behind the ``parallel_data_plane`` option, default off —
the single-device path is untouched when disabled):

  * ``ec/plugin_jax.py`` routes ``encode_words_device`` /
    ``decode_words_device`` through :meth:`ShardedDataPlane.xor_matmul_w32`,
    so every caller of the shared ECBackend engine — the simulator's
    put/get, the wire client's batched put, signature-grouped degraded
    reads — runs sharded without knowing it;
  * ``cluster/simulator.py`` dispatches the recovery sweep's
    full-width-mask rebuild through the same entry (per-stripe decode
    signatures ride the sharded batch axis);
  * ``cluster/osdmap.py`` passes the plane's mesh to
    ``XlaMapper.map_batch`` so ``map_pgs_batch`` splits PG lanes
    across chips (the multi-chip ParallelPGMapper);
  * ``cluster/ec_backend.py`` and ``cluster/device_store.py`` account
    sub-writes and HBM staging per chip by OSD-shard -> chip affinity.

Bit-exactness: the contraction is pure AND/XOR over int32 words — a
sharded leading axis changes the layout, never a value — and padding
rows are zeros that are sliced off before anyone reads them, so the
sharded path is bit-identical to the single-device path (asserted by
tests/test_data_plane.py and the ``dryrun_multichip`` cluster step).

Observability: per-chip counters land in the ``dataplane`` perf group
(``dataplane.shard<i>.put_stripes`` / ``..._bytes``, ``decode_*``,
``recover_*``, ``map_lanes``, ``staged_*``, ``subwrites``) and every
sharded dispatch tags the calling thread's tracked op with a
``dispatched_mesh`` event, so ``dump_historic_ops`` shows exactly
which client ops fanned out across the mesh.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from ..common.op_tracker import mark_active as _mark_active
from ..common.options import OptionError, config
from ..common.perf_counters import perf as _perf

# hot-path enablement cache (same pattern as perf_counters._counters
# _enabled): the staging/accounting probes run per shard put, so the
# layered-registry walk must not happen per call
_enabled: Optional[bool] = None
_enabled_lock = threading.Lock()


def enabled() -> bool:
    """Cheap cached read of the ``parallel_data_plane`` option."""
    global _enabled
    if _enabled is None:
        with _enabled_lock:
            if _enabled is None:
                cfg = config()
                try:
                    val = bool(cfg.get("parallel_data_plane"))
                except OptionError:
                    val = False

                def _refresh(_name, value):
                    global _enabled
                    # serialized with init: a set() firing between
                    # our observe() and the publish below must not be
                    # clobbered by the stale initial read
                    with _enabled_lock:
                        _enabled = bool(value)

                try:
                    cfg.observe("parallel_data_plane", _refresh)
                except OptionError:
                    pass
                if _enabled is None:
                    _enabled = val
    return _enabled


class ShardedDataPlane:
    """Owns a mesh and executes the cluster hot loops sharded over it."""

    def __init__(self, mesh):
        self.mesh = mesh
        self.n_shards = int(mesh.size)
        self._pc = _perf("dataplane")
        # MeshPlane2D shape facts: (rows, cols) of the device grid.
        # A 1-axis mesh is the legacy 1-D plane; a 2-axis mesh is the
        # (stripe, shard) plane — even at (1, n), so the dispatch
        # specs and counter namespaces are exercised identically on
        # single-row layouts.
        self.is_2d = len(mesh.axis_names) == 2
        if self.is_2d:
            self.n_rows, self.n_cols = (int(mesh.devices.shape[0]),
                                        int(mesh.devices.shape[1]))
        else:
            self.n_rows, self.n_cols = 1, self.n_shards
        # flat mesh positions whose device THIS process owns: under
        # the multi-process plane every process runs the same SPMD
        # dispatch, so host-side per-chip accounting must cover only
        # the local cells or the cluster rollup double-counts (each
        # host's counters sum to its own chips; the mgr mesh_rollup
        # reassembles the cluster view).  Single-process: all cells.
        from .multihost import process_index as _pidx
        me = _pidx()
        self._local_cells = frozenset(
            i for i, d in enumerate(mesh.devices.flat)
            if getattr(d, "process_index", 0) == me)
        # (per_batch, mesh) -> jitted sharded step
        self._steps: Dict[Tuple, object] = {}
        # the latest dispatch's cross-shard psum scalar, UNREAD: the
        # collective runs in the graph but the hot path must not pay
        # a device->host sync per dispatch; psum_probe() reads it
        self.last_psum = None

    # ------------------------------------------------------------ affinity --
    def chip_of(self, osd_id: int) -> int:
        """OSD-shard -> chip affinity: which mesh position accounts for
        an OSD's staged shards and sub-writes.  A stable modulo keyed
        on the OSD id, so the partition survives map churn."""
        return int(osd_id) % self.n_shards

    def coords_of(self, flat: int) -> Tuple[int, int]:
        """Flat mesh position -> (stripe_row, shard_col), row-major —
        the 2-D counter coordinate of a chip (a 1-D mesh is row 0)."""
        return divmod(int(flat), self.n_cols)

    def _prefixes(self, flat: int) -> Tuple[str, ...]:
        """Counter key prefixes for one chip: the coordinate key
        ``r<row>c<col>`` on the 2-D mesh plus the 1-D ``shard<flat>``
        alias existing dashboards/tests key on (satellite: the alias
        is ALWAYS written, so a layout change never orphans a
        dashboard)."""
        if self.is_2d:
            r, c = self.coords_of(flat)
            return (f"shard{flat}", f"r{r}c{c}")
        return (f"shard{flat}",)

    # ------------------------------------------------------------- dispatch --
    def _step(self, per_batch: bool):
        """Jitted sharded masked-XOR step, cached per (mask mode,
        mesh): words batch-sharded on the stripe axis, masks sharded
        alongside when they carry per-stripe signatures (the recovery
        sweep) and replicated otherwise (encode / grouped decode),
        plus the cluster-wide row-count reduction — an explicit psum
        on the ICI ring (the collective the accounting rides).

        shard_map, not bare jit-with-shardings: the per-shard body
        calls the REAL kernel entry (ops.xor_kernel.xor_matmul_w32),
        so each chip runs the tiled Pallas kernel on TPU — a sharded
        jit around the XLA fallback graph would silently swap the
        flagship kernel for the slow path on exactly the hardware
        the mesh targets.  (CPU runs the XLA fallback either way,
        keeping the bit-identity tests meaningful.)

        2-D mesh: the batch splits over STRIPE rows while the mask
        rows — the k+m output-shard dimension — split over SHARD
        columns, so each cell contracts its stripe block against its
        own output shards (per-chip shard ownership).  The row
        counter psums along STRIPE_AXIS only: the count is the padded
        batch total, identical to the 1-D plane's value, and every
        shard column computes the same scalar by construction
        (check_rep can't prove that, hence check_rep=False)."""
        from .mesh import SHARD_AXIS, STRIPE_AXIS, mesh_cache_key
        key = (per_batch,) + mesh_cache_key(self.mesh)
        step = self._steps.get(key)
        if step is None:
            import jax
            import jax.numpy as jnp
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            from ..ops import xor_kernel
            from ..common.jit_profile import wrap as _jit_wrap
            if self.is_2d:
                def local(masks, words):
                    out = xor_kernel.xor_matmul_w32(masks, words)
                    rows = jax.lax.psum(
                        jnp.sum(jnp.ones((words.shape[0],), jnp.int32)
                                .astype(jnp.int64)), STRIPE_AXIS)
                    return out, rows

                # per-batch masks [B, R, C]: B over stripe rows, R
                # (the k+m shards) over shard columns; replicated
                # masks [R, C]: R over shard columns
                mspec = P(STRIPE_AXIS, SHARD_AXIS) if per_batch \
                    else P(SHARD_AXIS)
                step = self._steps[key] = _jit_wrap(
                    jax.jit(shard_map(
                        local, mesh=self.mesh,
                        in_specs=(mspec, P(STRIPE_AXIS)),
                        out_specs=(P(STRIPE_AXIS, SHARD_AXIS), P()),
                        check_rep=False)),
                    "data_plane.step2d", f"per_batch={per_batch}")
                return step

            def local(masks, words):
                out = xor_kernel.xor_matmul_w32(masks, words)
                rows = jax.lax.psum(
                    jnp.sum(jnp.ones((words.shape[0],), jnp.int32)
                            .astype(jnp.int64)), SHARD_AXIS)
                return out, rows

            mspec = P(SHARD_AXIS) if per_batch else P()
            step = self._steps[key] = _jit_wrap(
                jax.jit(shard_map(
                    local, mesh=self.mesh,
                    in_specs=(mspec, P(SHARD_AXIS)),
                    out_specs=(P(SHARD_AXIS), P()))),
                "data_plane.step", f"per_batch={per_batch}")
        return step

    def _collective_step(self, per_batch: bool):
        """Jitted sharded rebuild step with the RECOVERY collectives:
        each chip decodes its stripe slice with the real kernel, then
        the rebuilt rows ALL-GATHER across the mesh (tiled on the
        stripe axis), so every chip — hence every OSD-shard partition
        landing a rebuilt shard — holds the bytes chip-to-chip, with
        no host staging hop in between.  The psum row counter rides
        the same dispatch (the accounting collective).

        out_specs P() with check_rep=False: a tiled all_gather leaves
        the value identical on every mesh position by construction;
        shard_map cannot prove that, so the replication is asserted
        by the bit-identity tests instead.

        2-D mesh: TWO per-axis gathers — first along SHARD_AXIS on
        the output-shard axis (each stripe row assembles its full k+m
        from the columns that own them), then along STRIPE_AXIS tiled
        on the batch axis (every rebuilt stripe lands chip-to-chip on
        every row, hence on each target OSD's affine chip)."""
        from .mesh import SHARD_AXIS, STRIPE_AXIS, mesh_cache_key
        key = ("collective", per_batch) + mesh_cache_key(self.mesh)
        step = self._steps.get(key)
        if step is None:
            import jax
            import jax.numpy as jnp
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            from ..ops import xor_kernel
            from ..common.jit_profile import wrap as _jit_wrap
            if self.is_2d:
                def local(masks, words):
                    out = xor_kernel.xor_matmul_w32(masks, words)
                    rows = jax.lax.psum(
                        jnp.sum(jnp.ones((words.shape[0],), jnp.int32)
                                .astype(jnp.int64)), STRIPE_AXIS)
                    full = jax.lax.all_gather(out, SHARD_AXIS, axis=1,
                                              tiled=True)
                    full = jax.lax.all_gather(full, STRIPE_AXIS,
                                              axis=0, tiled=True)
                    return full, rows

                mspec = P(STRIPE_AXIS, SHARD_AXIS) if per_batch \
                    else P(SHARD_AXIS)
                step = self._steps[key] = _jit_wrap(
                    jax.jit(shard_map(
                        local, mesh=self.mesh,
                        in_specs=(mspec, P(STRIPE_AXIS)),
                        out_specs=(P(), P()), check_rep=False)),
                    "data_plane.collective2d",
                    f"per_batch={per_batch}")
                return step

            def local(masks, words):
                out = xor_kernel.xor_matmul_w32(masks, words)
                rows = jax.lax.psum(
                    jnp.sum(jnp.ones((words.shape[0],), jnp.int32)
                            .astype(jnp.int64)), SHARD_AXIS)
                full = jax.lax.all_gather(out, SHARD_AXIS, axis=0,
                                          tiled=True)
                return full, rows

            mspec = P(SHARD_AXIS) if per_batch else P()
            step = self._steps[key] = _jit_wrap(
                jax.jit(shard_map(
                    local, mesh=self.mesh,
                    in_specs=(mspec, P(SHARD_AXIS)),
                    out_specs=(P(), P()), check_rep=False)),
                "data_plane.collective", f"per_batch={per_batch}")
        return step

    def _ppermute_step(self, shift: int):
        """Jitted ring ppermute: each chip's stripe block moves
        ``shift`` positions around the ICI ring — the pairwise
        shard-landing primitive (a rebuilt block computed on chip i
        delivered to the chip owning its target OSD).

        2-D mesh: the ring runs over BOTH axes — the axis-name tuple
        linearizes the (stripe, shard) grid row-major, so the perm's
        flat indices rotate blocks across stripe-row boundaries
        exactly like the flat 1-D ring did (a true 2-D collective:
        the boundary hops cross the stripe axis chip-to-chip)."""
        from .mesh import MESH_AXES, SHARD_AXIS, mesh_cache_key
        key = ("ppermute", shift) + mesh_cache_key(self.mesh)
        step = self._steps.get(key)
        if step is None:
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            n = self.n_shards
            perm = [(i, (i + shift) % n) for i in range(n)]
            axes = tuple(MESH_AXES) if self.is_2d else SHARD_AXIS
            lanes = P(tuple(MESH_AXES)) if self.is_2d \
                else P(SHARD_AXIS)

            def local(x):
                return jax.lax.ppermute(x, axes, perm=perm)

            from ..common.jit_profile import wrap as _jit_wrap
            step = self._steps[key] = _jit_wrap(
                jax.jit(shard_map(
                    local, mesh=self.mesh,
                    in_specs=(lanes,),
                    out_specs=lanes)),
                "data_plane.ppermute", f"shift={shift}")
        return step

    def ppermute_shift(self, arr, shift: int = 1):
        """Rotate a batch-sharded array ``shift`` mesh positions along
        the ring (block-granular: each chip's whole slice moves).  The
        leading axis must be a mesh multiple."""
        from jax.sharding import PartitionSpec as P
        from .mesh import MESH_AXES, SHARD_AXIS
        if int(arr.shape[0]) % self.n_shards:
            raise ValueError(
                f"ppermute batch {arr.shape[0]} not a multiple of "
                f"{self.n_shards} mesh positions")
        # flat row-major split over ALL axes, matching the flat-ring
        # perm's linearization of the (stripe, shard) grid
        spec = P(tuple(MESH_AXES)) if self.is_2d else P(SHARD_AXIS)
        arr = self._commit(arr, spec)
        out = self._ppermute_step(int(shift) % self.n_shards)(arr)
        self._pc.inc("ppermute_rows", int(arr.shape[0]))
        return self._canonical(out) if self.is_2d else out

    # ------------------------------------------------------------- packing --
    def _commit(self, arr, spec):
        """Scatter an operand onto the mesh under ``spec``.  Single
        process: a plain device_put (operands arrive committed to
        whatever placement the producing dispatch left them with and
        pjit refuses a silent layout change).  Multi-process plane:
        every process holds the SAME host value (SPMD dispatch), so
        the global array is assembled per-shard via
        make_array_from_callback — device_put cannot address another
        host's devices."""
        import jax
        from jax.sharding import NamedSharding
        sh = NamedSharding(self.mesh, spec)
        from .multihost import is_active
        if is_active():
            host = np.asarray(arr)
            return jax.make_array_from_callback(
                host.shape, sh, lambda idx: host[idx])
        return jax.device_put(arr, sh)

    def _canonical(self, out):
        """Re-commit a 2-D dispatch result as replicated before it
        leaves the plane.  Trimming the padded (stripe, shard) output
        leaves a device-order-permuted GSPMD sharding behind; a later
        unrelated jit that takes such a committed array as an operand
        (e.g. the device_store assemble gather) partitions against the
        permuted order and returns wrong bytes.  One explicit
        device_put pins the public contract: plane results read the
        same from any consumer, sharded or not."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(out, NamedSharding(self.mesh, P()))

    def _prepare(self, masks, words):
        """Shared operand packing for the sharded dispatches:
        validate, flatten the leading axes, pad to mesh multiples
        (zero inputs AND zero masks produce zero outputs, sliced off
        before return), and commit to the layout's shardings.  1-D:
        the batch pads to the mesh size.  2-D: the batch pads to the
        STRIPE row count and the mask rows — the k+m output shards —
        pad to the SHARD column count."""
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from .mesh import SHARD_AXIS, STRIPE_AXIS
        words = jnp.asarray(words, jnp.int32)
        masks = jnp.asarray(masks, jnp.int32)
        lead = words.shape[:-2]
        C, W = words.shape[-2:]
        per_batch = masks.ndim > 2
        if per_batch and masks.shape[:-2] != lead:
            raise ValueError(
                f"mask batch {masks.shape[:-2]} != data batch {lead}")
        if masks.shape[-1] != C:
            raise ValueError(
                f"masks contract {masks.shape[-1]} columns, data has "
                f"{C} planes")
        R = masks.shape[-2]
        B = int(np.prod(lead)) if lead else 1
        w3 = words.reshape(B, C, W)
        m3 = masks.reshape(B, R, masks.shape[-1]) if per_batch \
            else masks
        bpad = (-B) % (self.n_rows if self.is_2d else self.n_shards)
        rpad = ((-R) % self.n_cols) if self.is_2d else 0
        if bpad:
            w3 = jnp.pad(w3, ((0, bpad), (0, 0), (0, 0)))
            if per_batch:
                m3 = jnp.pad(m3, ((0, bpad), (0, 0), (0, 0)))
        if rpad:
            m3 = jnp.pad(m3, ((0, 0), (0, rpad), (0, 0)) if per_batch
                         else ((0, rpad), (0, 0)))
        if self.is_2d:
            wspec = P(STRIPE_AXIS)
            mspec = P(STRIPE_AXIS, SHARD_AXIS) if per_batch \
                else P(SHARD_AXIS)
        else:
            wspec = P(SHARD_AXIS)
            mspec = P(SHARD_AXIS) if per_batch else P()
        return (self._commit(m3, mspec), self._commit(w3, wspec),
                lead, per_batch, B, R, W, C, bpad, rpad)

    def rebuild_collective(self, masks, words, kind: str = "recover"):
        """The device-resident recovery dispatch: identical operands
        and bit-identical result to :meth:`xor_matmul_w32`, but the
        rebuilt rows land on EVERY chip via in-graph tiled
        all-gathers — a recovered shard's new home reads its bytes
        from its own chip's copy of the gathered buffer instead of a
        per-shard host round trip.  On the 2-D mesh the gather runs
        per axis (SHARD columns assemble each stripe row's k+m, then
        STRIPE rows land every rebuilt stripe everywhere) and the
        per-axis row counters record both legs.  Padding rows (zero
        masks, zero words) gather as zeros and are sliced off."""
        (m3, w3, lead, per_batch, B, R, W, C,
         bpad, rpad) = self._prepare(masks, words)
        out, rows = self._collective_step(per_batch)(m3, w3)
        self.last_psum = rows
        self.account(kind, B, 4 * C * W, padded_rows=B + bpad)
        self._pc.inc("allgather_rows", B + bpad)
        if self.is_2d:
            self._pc.inc("allgather_rows_stripe", B + bpad)
            self._pc.inc("allgather_rows_shard", R + rpad)
        out = out[:B]
        if rpad:
            out = out[:, :R]
        if self.is_2d:
            out = self._canonical(out)
        return out.reshape(lead + (R, W)) if lead else \
            out.reshape(R, W)

    def account_landed(self, target_osd: int, rows: int,
                       row_bytes: int) -> None:
        """One rebuilt shard landed chip-to-chip on ``target_osd``'s
        affine chip (the delivery half of rebuild_collective)."""
        chip = self.chip_of(target_osd)
        if chip not in self._local_cells:
            return
        for pfx in self._prefixes(chip):
            self._pc.inc(f"{pfx}.recover_landed")
            self._pc.inc(f"{pfx}.recover_landed_bytes",
                         rows * row_bytes)

    def xor_matmul_w32(self, masks, words, kind: str = "encode"):
        """Drop-in for ``ops.xor_kernel.xor_matmul_w32``, sharded over
        the mesh.  masks [R, C] (replicated across stripe rows, R
        sharded over shard columns on the 2-D mesh) or [..., R, C]
        matching ``words``'s leading axes (per-stripe signatures);
        words [..., C, W] int32 -> [..., R, W].

        Padding (batch to a stripe-row multiple, mask rows to a
        shard-column multiple on the 2-D mesh) is zeros in / zeros
        out, sliced off before return, so arbitrary shapes reuse the
        same executable family and the result is bit-identical to the
        single-device kernel — and across mesh layouts.
        """
        (m3, w3, lead, per_batch, B, R, W, C,
         bpad, rpad) = self._prepare(masks, words)
        out, rows = self._step(per_batch)(m3, w3)
        # keep the psum ON DEVICE: reading it here would host-sync
        # every dispatch (its value is deterministically B+bpad, which
        # the counter records; psum_probe() verifies the collective)
        self.last_psum = rows
        self.account(kind, B, 4 * C * W, padded_rows=B + bpad)
        out = out[:B]
        if rpad:
            out = out[:, :R]
        if self.is_2d:
            out = self._canonical(out)
        return out.reshape(lead + (R, W)) if lead else \
            out.reshape(R, W)

    def fused_ragged(self, bitmat_np: np.ndarray, pool: np.ndarray,
                     tile: int):
        """Sharded dispatch of the fused ragged encode+crc traversal
        (ops/ragged_fused.fused_block_math): the block pool [G, k, T]
        batch-shards over STRIPE rows (2-D) or the shard axis (1-D)
        while the GF bit-matrix and the crc matrix replicate — the
        block-granular analogue of xor_matmul_w32's stripe split.
        Zero pad blocks in, zero parity + crc-of-zero-block out,
        sliced off before return, so the result is bit-identical to
        the single-device jit on any mesh layout (the contraction is
        lane-wise — an axis split changes layout, never values).
        Returns (parity [G, m, T] u8, data crcs [G, k] u32, parity
        crcs [G, m] u32)."""
        import jax.numpy as jnp
        from .mesh import SHARD_AXIS, STRIPE_AXIS, mesh_cache_key
        from ..ops import ragged_fused
        G, k, T = (int(pool.shape[0]), int(pool.shape[1]),
                   int(pool.shape[2]))
        m = int(bitmat_np.shape[0]) // 8
        key = ("ragged", m, k, T, int(tile)) + mesh_cache_key(self.mesh)
        step = self._steps.get(key)
        if step is None:
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            from ..common.jit_profile import wrap as _jit_wrap
            A8, const = ragged_fused._crc_a8(int(tile))
            A8_dev = jnp.asarray(A8)
            axis = STRIPE_AXIS if self.is_2d else SHARD_AXIS

            def local(bm, pl):
                par, dcrc, pcrc = ragged_fused.fused_block_math(
                    bm, A8_dev, const, pl)
                return par, dcrc, pcrc

            spec = P(axis)
            step = self._steps[key] = _jit_wrap(
                jax.jit(shard_map(
                    local, mesh=self.mesh,
                    in_specs=(P(), spec),
                    out_specs=(spec, spec, spec),
                    check_rep=False)),
                "data_plane.ragged", f"k={k} m={m}")
        rows = self.n_rows if self.is_2d else self.n_shards
        gpad = (-G) % rows
        p3 = jnp.asarray(pool, jnp.uint8)
        if gpad:
            p3 = jnp.pad(p3, ((0, gpad), (0, 0), (0, 0)))
        from jax.sharding import PartitionSpec as P
        axis = STRIPE_AXIS if self.is_2d else SHARD_AXIS
        p3 = self._commit(p3, P(axis))
        parity, dcrc, pcrc = step(jnp.asarray(bitmat_np, jnp.int8), p3)
        self.account("ragged", G, (k + m) * T, padded_rows=G + gpad)
        parity, dcrc, pcrc = parity[:G], dcrc[:G], pcrc[:G]
        if self.is_2d:
            parity = self._canonical(parity)
            dcrc = self._canonical(dcrc)
            pcrc = self._canonical(pcrc)
        return parity, dcrc, pcrc

    def psum_probe(self) -> Optional[int]:
        """Read back the latest dispatch's cross-shard psum (ONE
        host sync, on demand — tests/smokes verify the collective;
        the dispatch path never reads it)."""
        return None if self.last_psum is None else int(self.last_psum)

    # ----------------------------------------------------------- accounting --
    def account(self, kind: str, rows: int, row_bytes: int,
                padded_rows: Optional[int] = None) -> None:
        """Per-chip accounting for one sharded dispatch, mesh-shape
        aware: the batch splits contiguously over the mesh (1-D) or
        over STRIPE rows (2-D stripe dispatches — every shard column
        in a row then reads the row's full stripe block to contract
        its own k+m slice, so per-chip ``*_bytes`` counts bytes
        touched per chip, which over-counts a stripe row vs the 1-D
        total by design).  Map sweeps split flat on any layout (see
        ``lane_shardings``).  Only cells whose device THIS process
        owns are incremented — under SPMD every process runs this
        call, and the mgr rollup sums hosts.  ``psum_rows`` records
        the padded total the in-graph collective reduces to (value
        known host-side — reading the device scalar per dispatch
        would host-sync the hot loop; see psum_probe)."""
        pc = self._pc
        pc.inc("dispatches")
        pc.inc(f"{kind}_dispatches")
        if padded_rows is not None:
            pc.inc("psum_rows", padded_rows)
        total = padded_rows if padded_rows is not None else rows
        unit = "lanes" if kind == "map" else "stripes"
        if self.is_2d and kind != "map":
            per = -(-total // self.n_rows)
            for r in range(self.n_rows):
                real = max(0, min(per, rows - r * per))
                if real <= 0:
                    continue
                for c in range(self.n_cols):
                    flat = r * self.n_cols + c
                    if flat not in self._local_cells:
                        continue
                    for pfx in self._prefixes(flat):
                        pc.inc(f"{pfx}.{kind}_{unit}", real)
                        pc.inc(f"{pfx}.{kind}_bytes",
                               real * row_bytes)
        else:
            per = -(-total // self.n_shards)
            for i in range(self.n_shards):
                real = max(0, min(per, rows - i * per))
                if real > 0 and i in self._local_cells:
                    for pfx in self._prefixes(i):
                        pc.inc(f"{pfx}.{kind}_{unit}", real)
                        pc.inc(f"{pfx}.{kind}_bytes",
                               real * row_bytes)
        _mark_active("dispatched_mesh", kind=kind,
                     shards=self.n_shards, rows=rows)

    def account_subwrite(self, target_osd: int) -> None:
        """One EC sub-write headed to ``target_osd``: counted on its
        affine chip (the fan-out half of the per-chip staging view)."""
        chip = self.chip_of(target_osd)
        if chip not in self._local_cells:
            return
        for pfx in self._prefixes(chip):
            self._pc.inc(f"{pfx}.subwrites")

    def account_staged(self, osd_or_shard: int, nbytes: int) -> None:
        """One shard staged into an HBM partition, attributed by
        OSD-shard -> chip affinity."""
        chip = self.chip_of(osd_or_shard)
        if chip not in self._local_cells:
            return
        for pfx in self._prefixes(chip):
            self._pc.inc(f"{pfx}.staged_entries")
            self._pc.inc(f"{pfx}.staged_bytes", int(nbytes))

    def stats(self) -> Dict:
        return self._pc.dump()


_planes: Dict[Tuple[int, int], ShardedDataPlane] = {}
_planes_lock = threading.Lock()
# resolved-plane cache: plane() runs on per-shard hot paths (staging
# accounting), so the mesh-size option walk + jax.devices() must not
# repeat per call — the resolution is cached and invalidated by a
# config observer, like enabled()'s flag
_resolved: Optional[ShardedDataPlane] = None
_resolved_valid = False
_resolve_gen = 0
_observing_devices = False


def _invalidate_resolution(_name=None, _value=None) -> None:
    global _resolved_valid, _resolve_gen
    _resolve_gen += 1
    _resolved_valid = False


def plane() -> Optional[ShardedDataPlane]:
    """The process-wide data plane, or None when the option is off or
    fewer than two devices exist (single-device hosts fall through to
    the plain path — there is nothing to shard).

    Layout resolution (MeshPlane2D): ``parallel_data_plane_stripes``
    >= 2 reshapes the device list row-major into a (stripes, n //
    stripes) 2-D mesh; 0/1 keeps the legacy 1-D mesh — UNLESS the
    multi-process plane is active, in which case the stripe axis
    defaults to one row per host so every process's local devices
    form one shard row.  A stripe count that does not divide the
    device count disables the plane (plain-path fallback) rather than
    failing the caller mid-put."""
    global _resolved, _resolved_valid, _observing_devices
    if not enabled():
        return None
    if _resolved_valid:
        return _resolved
    if not _observing_devices:
        obs = 0
        for opt in ("parallel_data_plane_devices",
                    "parallel_data_plane_stripes"):
            try:
                config().observe(opt, _invalidate_resolution)
                obs += 1
            except OptionError:
                pass
        _observing_devices = obs == 2
    gen = _resolve_gen
    try:
        import jax
        n_avail = len(jax.devices())
    except Exception:
        return None
    want = 0
    try:
        want = int(config().get("parallel_data_plane_devices"))
    except OptionError:
        pass
    stripes = 0
    try:
        stripes = int(config().get("parallel_data_plane_stripes"))
    except OptionError:
        pass
    from .multihost import is_active, process_count
    if stripes <= 1 and is_active():
        stripes = process_count()
    n = want or n_avail
    if n < 2 or n_avail < n:
        p = None
    elif stripes >= 2 and n % stripes:
        p = None
    else:
        key = (n, stripes if stripes >= 2 else 0)
        with _planes_lock:
            p = _planes.get(key)
            if p is None:
                import jax as _jax
                from .mesh import make_mesh, make_mesh_2d
                if stripes >= 2:
                    mesh = make_mesh_2d(stripes, n // stripes,
                                        devices=_jax.devices()[:n])
                else:
                    mesh = make_mesh(n)
                p = _planes[key] = ShardedDataPlane(mesh)
    if gen == _resolve_gen:
        # publish only if no invalidation raced the resolution (a
        # mid-compute option change would otherwise be masked by a
        # stale cache entry until the next change)
        _resolved, _resolved_valid = p, True
    return p
