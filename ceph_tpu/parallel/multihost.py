"""Multi-process data-plane boot — the MeshPlane2D scale-out half.

One process per host joins a ``jax.distributed`` fleet and the data
plane's mesh spans every host's devices: the STRIPE axis gets one row
per process (by default), the SHARD axis stays each host's local
chip row, and every sharded dispatch in ``data_plane`` runs SPMD
across the fleet — the same jitted step, the same bytes, with the
cross-host hops riding the collectives the 2-D mesh already names.

Boot handshake: process 0 serves the coordinator at
``multihost_coordinator`` (host:port); every process calls
:func:`ensure_initialized` with its rank before FIRST touching a jax
backend (the CPU fleet needs the gloo collectives flag set before
backend init).  Configuration comes from the options registry with
environment overrides for launchers::

    CEPH_TPU_COORDINATOR   overrides multihost_coordinator
    CEPH_TPU_NUM_PROCESSES overrides multihost_processes
    CEPH_TPU_PROCESS_ID    overrides multihost_process_id

Fallback rule (load-bearing): with no coordinator configured —
the default — :func:`ensure_initialized` is a no-op returning False,
``process_index()/process_count()`` report (0, 1), and every existing
single-process path is byte-for-byte unchanged.  Tests pin this.

Host-side rank reads MUST come through :func:`process_index` /
:func:`process_count` — never ``jax.process_index()`` inside traced
code, where per-process branching diverges the SPMD program (lint
rule CTL1006 flags exactly that).
"""
from __future__ import annotations

import os
import threading
from typing import List, Optional, Sequence, Tuple

from ..common.options import OptionError, config

_lock = threading.Lock()
_initialized = False   # ensure_initialized ran (either outcome)
_active = False        # jax.distributed actually connected

ENV_COORDINATOR = "CEPH_TPU_COORDINATOR"
ENV_NUM_PROCESSES = "CEPH_TPU_NUM_PROCESSES"
ENV_PROCESS_ID = "CEPH_TPU_PROCESS_ID"


def _spec() -> Tuple[str, int, int]:
    """Resolve (coordinator, num_processes, process_id) — env wins
    over the options registry so fleet launchers need no config
    plumbing; '' / 0 / -1 mean unset."""
    coord, procs, pid = "", 0, -1
    cfg = config()
    try:
        coord = str(cfg.get("multihost_coordinator") or "")
    except OptionError:
        pass
    try:
        procs = int(cfg.get("multihost_processes") or 0)
    except OptionError:
        pass
    try:
        pid = int(cfg.get("multihost_process_id"))
    except OptionError:
        pass
    coord = os.environ.get(ENV_COORDINATOR, coord)
    if os.environ.get(ENV_NUM_PROCESSES):
        procs = int(os.environ[ENV_NUM_PROCESSES])
    if os.environ.get(ENV_PROCESS_ID) is not None \
            and os.environ.get(ENV_PROCESS_ID, "") != "":
        pid = int(os.environ[ENV_PROCESS_ID])
    return coord, procs, pid


def ensure_initialized() -> bool:
    """Join the fleet if a coordinator is configured; no-op fallback
    otherwise.  Idempotent; returns whether the multi-process plane
    is active.  Must run before the first jax backend touch on CPU
    fleets (the gloo cross-process collectives flag binds at backend
    init)."""
    global _initialized, _active
    with _lock:
        if _initialized:
            return _active
        coord, procs, pid = _spec()
        if not coord or procs < 2 or pid < 0:
            _initialized = True
            return False
        import jax
        try:
            # CPU fleets need a cross-process collectives backend;
            # harmless on TPU where ICI/DCN collectives are native
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:
            pass
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=procs,
                                   process_id=pid)
        _initialized = True
        _active = True
    # the plane's layout depends on the fleet shape — drop any plane
    # resolved before the fleet came up (lazy import: data_plane
    # imports US at plane construction)
    from . import data_plane
    data_plane._invalidate_resolution()
    return True


def is_active() -> bool:
    """Whether this process is part of a live multi-process plane."""
    return _active


def process_index() -> int:
    """This process's rank — THE blessed host-side read (0 when
    single-process).  Never call ``jax.process_index()`` from
    jit/shard_map-reachable code (CTL1006)."""
    if not _active:
        return 0
    import jax
    return int(jax.process_index())


def process_count() -> int:
    """Fleet size (1 when single-process)."""
    if not _active:
        return 1
    import jax
    return int(jax.process_count())


def host_label(idx: Optional[int] = None) -> str:
    """Stable per-host daemon label for the cluster_stats rollup
    (``host<rank>`` — rank is the identity the coordinator
    assigned, so the label survives restarts with the same spec)."""
    return f"host{process_index() if idx is None else int(idx)}"


def global_mesh_2d(n_stripe: Optional[int] = None):
    """The fleet-wide (stripe, shard) mesh: all processes' devices,
    one stripe row per process by default — each host's local chips
    form one shard row, so SHARD-axis collectives stay on-host (ICI)
    and only STRIPE-axis legs cross hosts.  Works single-process too
    (one row spanning the local devices)."""
    from .mesh import make_mesh_2d
    import jax
    rows = n_stripe or process_count()
    return make_mesh_2d(rows, devices=jax.devices())


def host_of_chip(mesh, flat: int) -> int:
    """Which process owns flat mesh position ``flat`` (0 for every
    position on a single-process mesh)."""
    dev = list(mesh.devices.flat)[int(flat)]
    return int(getattr(dev, "process_index", 0))


def stripe_order(targets: Sequence, host_of=None) -> List[int]:
    """Submission order for a cross-host shard fan-out: indices into
    ``targets`` interleaved round-robin across hosts, so every host's
    dispatch queue fills from the first submit instead of draining
    host 0's shards before host 1 sees traffic.  Single-host (or no
    host resolver): identity order — the fan-out is byte-for-byte
    today's.  ``host_of`` maps a target to its host rank; default
    uses the target's affine chip on the resolved plane."""
    idxs = list(range(len(targets)))
    if not _active:
        return idxs
    if host_of is None:
        from .data_plane import plane
        p = plane()
        if p is None:
            return idxs

        def host_of(t):  # noqa: F811 — deliberate default binding
            return host_of_chip(p.mesh, p.chip_of(int(t)))
    buckets: dict = {}
    for i in idxs:
        buckets.setdefault(int(host_of(targets[i])), []).append(i)
    if len(buckets) < 2:
        return idxs
    order: List[int] = []
    queues = [buckets[h] for h in sorted(buckets)]
    while any(queues):
        for q in queues:
            if q:
                order.append(q.pop(0))
    return order


def shutdown() -> None:
    """Leave the fleet (test teardown); safe when inactive."""
    global _initialized, _active
    with _lock:
        if _active:
            import jax
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
        _initialized = False
        _active = False
