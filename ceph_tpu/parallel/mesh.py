"""Device-mesh utilities and the sharded cluster step.

Design per the scaling-book recipe: pick a mesh, annotate shardings on the
batch axes, let XLA insert collectives.  The framework's data plane is
embarrassingly parallel over stripes/PGs, so the shard axis carries
encode/decode/mapping work with zero cross-chip traffic; collectives
appear only in cluster-wide reductions (utilization stats, recovery
accounting) where a psum rides the ICI ring.

This replaces the reference's messenger fan-out/gather across OSD
processes (src/msg/async/, SURVEY.md §2.4) for the compute tier.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# int64 byte counters in the sharded steps must not depend on whether
# some OTHER module (placement.xla_mapper) was imported first to flip
# this flag — the wrapped-to-int32 trace would stick in the step cache
jax.config.update("jax_enable_x64", True)

# The shared axis-name vocabulary.  Every collective and PartitionSpec
# in the tree MUST name axes through these constants (CTL1001 flags
# hardcoded strings): the 2-D (stripe, shard) mesh rename then touches
# exactly this block.  SHARD_AXIS is today's 1-D stripe/PG batch axis;
# STRIPE_AXIS is the second axis the ROADMAP-item-1 refactor adds
# (intra-stripe parallelism / multi-process outer axis).
SHARD_AXIS = "shard"
STRIPE_AXIS = "stripe"
MESH_AXES: Tuple[str, str] = (STRIPE_AXIS, SHARD_AXIS)


def _pick_devices(n_devices: Optional[int],
                  devices: Optional[Sequence]) -> Sequence:
    """Resolve the device list, falling back to the CPU backend's
    virtual devices when the default backend has fewer than
    n_devices (the dry-run path on a 1-chip host with
    --xla_force_host_platform_device_count set)."""
    if devices is not None:
        return devices
    devices = jax.devices()
    if n_devices is not None and len(devices) < n_devices:
        try:
            cpus = jax.devices("cpu")
            if len(cpus) >= n_devices:
                devices = cpus
        except RuntimeError:
            pass
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return devices


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the stripe/PG batch axis (CPU fallback per
    ``_pick_devices``)."""
    return Mesh(np.asarray(_pick_devices(n_devices, devices)),
                (SHARD_AXIS,))


def make_mesh_2d(n_stripe: int, n_shard: Optional[int] = None,
                 devices: Optional[Sequence] = None) -> Mesh:
    """Named 2-D (stripe, shard) mesh — the MeshPlane2D data-plane
    shape.  ``n_stripe`` is the outer (multi-process) axis, ``n_shard``
    the per-host shard-column axis; the device list is reshaped
    row-major so shard neighbors stay ICI-adjacent.  A (1, n) mesh is
    a drop-in for the 1-D mesh everywhere a ``lane_shardings``-style
    leading-axis annotation is all the consumer needs.

    ``n_shard=None`` infers the column count from the available
    devices, with a clear divisibility error instead of a reshape
    traceback (the forced-CPU dry run hits this first)."""
    if n_stripe < 1:
        raise ValueError(f"n_stripe must be >= 1, got {n_stripe}")
    if n_shard is None:
        devs = list(devices) if devices is not None \
            else list(jax.devices())
        if len(devs) % n_stripe:
            raise ValueError(
                f"cannot split {len(devs)} device(s) into {n_stripe} "
                f"stripe row(s): {len(devs)} % {n_stripe} != 0 — pick "
                f"a stripe count that divides the device count, or "
                f"pass n_shard explicitly")
        n_shard = len(devs) // n_stripe
        devices = devs
    total = n_stripe * n_shard
    devs = _pick_devices(total, devices)
    if len(devs) < total:
        raise ValueError(
            f"need {total} devices for a ({n_stripe}, {n_shard}) "
            f"mesh, have {len(devs)}")
    grid = np.asarray(list(devs)[:total]).reshape(n_stripe, n_shard)
    return Mesh(grid, MESH_AXES)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (stripe/PG) axis; replicate the rest."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def lane_shardings(mesh: Mesh) -> Tuple[NamedSharding, NamedSharding]:
    """(batch, replicated) sharding pair for a data-plane lane, keyed
    off the mesh's OWN axis names — works for the 1-D (shard,) mesh
    and the 2-D (stripe, shard) mesh alike, and keeps consumers
    (placement mappers, serving lanes) free of axis-name strings
    entirely.  The batch annotation splits the leading array axis over
    ALL mesh axes, row-major (one lane block per flat mesh position),
    so a (r, c) mesh splits a sweep r*c ways exactly like the flat
    device list did — map sweeps stay bit-identical across layouts."""
    lead = mesh.axis_names[0] if len(mesh.axis_names) == 1 \
        else tuple(mesh.axis_names)
    return (NamedSharding(mesh, P(lead)),
            NamedSharding(mesh, P()))


_STEP_CACHE: dict = {}


def mesh_cache_key(mesh: Mesh):
    """Stable cache key for a mesh: the device objects (live per-platform
    singletons — hashable, never id-reused) + axis names.  Never use
    id(mesh): a freed mesh's id can be reused by a new mesh with different
    devices, yielding a stale executable with wrong shardings.  Raw
    integer device ids are also insufficient — they repeat across
    platforms (cpu:0 vs tpu:0)."""
    return (tuple(mesh.devices.flat), mesh.devices.shape, mesh.axis_names)


def _make_step_fn(mesh: Mesh, key_prefix: str, kernel):
    """Jitted sharded step, cached per (kind, mesh): replicated operand
    0, batch-sharded operand 1, plus a genuine cross-shard reduction
    (XLA lowers the sum to an ICI psum).  The byte counter sums in
    int64 — mesh import enables x64 (below) so the reduction cannot
    silently wrap to int32 depending on WHICH module was imported
    first (jit executables cache per mesh, so a wrapped trace would
    stick)."""
    key = (key_prefix,) + mesh_cache_key(mesh)
    if key not in _STEP_CACHE:
        def step(op, d):
            out = kernel(op, d)
            total = jnp.sum(d.astype(jnp.int64))
            return out, total

        _STEP_CACHE[key] = jax.jit(
            step,
            in_shardings=(replicated_sharding(mesh), batch_sharding(mesh)),
            out_shardings=(batch_sharding(mesh), None))
    return _STEP_CACHE[key]


def _encode_step_fn(mesh: Mesh):
    from ..ops.gf_jax import bitplane_matmul
    return _make_step_fn(mesh, "bitplane", bitplane_matmul)


def distributed_encode_step(mesh: Mesh, bitmat: jax.Array,
                            data: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """One sharded encode step: stripes split across the mesh, parity
    computed locally per chip, plus a cluster-wide psum byte counter
    (the collective the perf-counter aggregation rides).

    data: [B, k, L] uint8 sharded on B → (parity [B, m, L], total_bytes).
    """
    sharded = jax.device_put(data, batch_sharding(mesh))
    return _encode_step_fn(mesh)(bitmat, sharded)


def _xor_step_fn(mesh: Mesh):
    from ..ops.xor_kernel import xor_matmul_w32
    return _make_step_fn(mesh, "xor", xor_matmul_w32)


def distributed_xor_encode_step(mesh: Mesh, masks: jax.Array,
                                words: jax.Array
                                ) -> Tuple[jax.Array, jax.Array]:
    """Sharded FLAGSHIP encode: the bit-sliced masked-XOR kernel over a
    stripe-sharded batch (words [B, C, W] int32 sharded on B), masks
    replicated — the multi-chip form of the 101x kernel.  Returns
    (parity planes [B, R, W], cluster-wide psum byte counter)."""
    sharded = jax.device_put(jnp.asarray(words, jnp.int32),
                             batch_sharding(mesh))
    return _xor_step_fn(mesh)(jnp.asarray(masks, jnp.int32), sharded)
