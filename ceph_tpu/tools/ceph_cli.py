"""`ceph` — the cluster admin CLI against a live process cluster.

The reference's main operator surface (src/ceph.in dispatching mon
commands; outputs modeled on `ceph -s`, `ceph health`, `ceph osd
tree`, `ceph mon stat`, `ceph pg dump`, `ceph df`).  Talks to the
daemons of a vstart cluster dir through the authenticated wire client
(client/remote.py) — the same path any admin tool takes, no in-process
shortcuts.

    python -m ceph_tpu.tools.ceph_cli --dir /tmp/c1 status
    python -m ceph_tpu.tools.ceph_cli --dir /tmp/c1 health
    python -m ceph_tpu.tools.ceph_cli --dir /tmp/c1 mon stat
    python -m ceph_tpu.tools.ceph_cli --dir /tmp/c1 osd tree
    python -m ceph_tpu.tools.ceph_cli --dir /tmp/c1 osd out 3
    python -m ceph_tpu.tools.ceph_cli --dir /tmp/c1 osd pool ls --detail
    python -m ceph_tpu.tools.ceph_cli --dir /tmp/c1 pg dump 1
    python -m ceph_tpu.tools.ceph_cli --dir /tmp/c1 df
    python -m ceph_tpu.tools.ceph_cli --dir /tmp/c1 scrub 1

`daemon` subcommands talk to a single daemon's admin socket
(`<dir>/<name>.asok`, the `ceph daemon <name> ...` workflow —
src/ceph.in admin_socket path), not the mon.  mon/OSD daemons serve
theirs at startup; a long-running client process opts in with
`RemoteCluster.serve_admin()` (-> `<dir>/objecter.asok`):

    ... daemon osd.0 dump_ops_in_flight
    ... daemon osd.0 dump_historic_ops
    ... daemon osd.0 dump_historic_slow_ops
    ... daemon objecter perf dump
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional


def _client(cluster_dir: str):
    from ..client.remote import RemoteCluster
    return RemoteCluster(cluster_dir)


def _pool_types():
    from ..cluster.osdmap import POOL_ERASURE, POOL_REPLICATED
    return {POOL_REPLICATED: "replicated", POOL_ERASURE: "erasure"}


def cmd_status(rc, out) -> int:
    st = rc.status()
    m = rc.osdmap
    names = _pool_types()
    q = {}
    try:
        q = rc.mon_call({"cmd": "mon_status"})
    except Exception:
        pass
    out.write("  cluster:\n")
    health = "HEALTH_OK" if st["n_up"] == st["n_osds"] else "HEALTH_WARN"
    out.write(f"    health: {health}\n")
    if q:
        out.write(f"  mon: rank {q.get('rank')} of "
                  f"{q.get('n_mons')}, leader {q.get('leader')}, "
                  f"election epoch {q.get('election_epoch')}\n")
    out.write(f"  osd: {st['n_osds']} osds: {st['n_up']} up\n")
    out.write(f"  map: e{st['epoch']}\n")
    out.write("  pools:\n")
    for pid, pool in sorted(m.pools.items()):
        out.write(f"    pool {pid} '{pool.name}' "
                  f"{names.get(pool.type, pool.type)} "
                  f"size {pool.size} pg_num {pool.pg_num}\n")
    # the PGMap io line, from the mon's ClusterStats aggregator
    # (counter deltas across the daemons' heartbeat perf reports)
    try:
        cs = rc.mon_call({"cmd": "cluster_stats"})
        io = cs["io"]["cluster"]
        out.write("  io:\n")
        out.write(f"    client: {io.get('rd_bytes', 0.0) / 2**20:.1f}"
                  f" MiB/s rd, {io.get('wr_bytes', 0.0) / 2**20:.1f}"
                  f" MiB/s wr, {io.get('rd_ops', 0.0):.0f} op/s rd, "
                  f"{io.get('wr_ops', 0.0):.0f} op/s wr\n")
        # the MeshPlane2D line: the mgr rollup's (host, chip) view —
        # a two-host plane reads as ONE cluster here
        mesh = cs.get("mesh") or {}
        if mesh.get("n_chips"):
            shape = mesh.get("shape")
            grid = f", ({shape[0]}, {shape[1]}) mesh" if shape else ""
            stripes = int(mesh.get("totals", {}).get("put_stripes",
                                                     0))
            out.write(f"  plane: {mesh['n_hosts']} host(s), "
                      f"{mesh['n_chips']} chip(s){grid}, "
                      f"{stripes} put stripes\n")
    except Exception:
        pass
    return 0


def cmd_health(rc, out) -> int:
    st = rc.status()
    if st["n_up"] == st["n_osds"]:
        out.write("HEALTH_OK\n")
        return 0
    down = st["n_osds"] - st["n_up"]
    out.write(f"HEALTH_WARN {down} osds down\n")
    return 1


def cmd_mon_stat(rc, out) -> int:
    q = rc.mon_call({"cmd": "mon_status"})
    out.write(f"e{q.get('election_epoch', 0)}: {q.get('n_mons')} mons, "
              f"leader {q.get('leader')}, committed "
              f"{q.get('committed')}\n")
    return 0


def cmd_osd_tree(rc, cluster_dir: str, out) -> int:
    import os

    from ..placement.compiler import compile_crushmap
    from ..placement.treedump import tree_dump
    text = open(os.path.join(cluster_dir, "crushmap.txt")).read()
    cmap = compile_crushmap(text)
    st = rc.status()
    up = {i for i in range(st["n_osds"]) if bool(rc.osdmap.osd_up[i])}
    # tree_dump renders the id/class/weight/name table; append the
    # up/down STATUS column from the live map (`ceph osd tree` shape)
    for line in tree_dump(cmap).splitlines():
        mark = ""
        token = line.split()
        for t in token:
            if t.startswith("osd."):
                osd = int(t[4:])
                mark = "  up" if osd in up else "  down"
                break
        out.write(line + mark + "\n")
    return 0


def cmd_osd_out(rc, osd: int, out) -> int:
    r = rc.mon_call({"cmd": "mark_out", "osd": osd})
    out.write(f"marked out osd.{osd} ({json.dumps(r)})\n")
    return 0


def cmd_osd_in(rc, osd: int, out) -> int:
    r = rc.mon_call({"cmd": "mark_in", "osd": osd})
    out.write(f"marked in osd.{osd} ({json.dumps(r)})\n")
    return 0


def _pool_id(rc, name_or_id: str) -> int:
    # name match FIRST across every pool, numeric id only as a
    # fallback — a pool literally named "2" must win over pool id 2
    for pid, p in rc.osdmap.pools.items():
        if p.name == name_or_id:
            return pid
    for pid in rc.osdmap.pools:
        if str(pid) == name_or_id:
            return pid
    raise ValueError(f"no pool {name_or_id!r}")


def cmd_tier_add(rc, base: str, cache: str, out) -> int:
    rc.tier_add(_pool_id(rc, base), _pool_id(rc, cache))
    out.write(f"pool '{cache}' is now (and will remain) a tier of "
              f"'{base}'\n")
    return 0


def cmd_tier_remove(rc, base: str, cache: str, out) -> int:
    try:
        rc.tier_remove(_pool_id(rc, base), _pool_id(rc, cache))
    except IOError as e:
        out.write(f"Error: {e} (run `osd tier agent {base} 0` to "
                  f"flush+evict everything)\n")
        return 1
    out.write(f"pool '{cache}' is no longer a tier of '{base}'\n")
    return 0


def cmd_tier_agent(rc, base: str, target: Optional[str],
                   out) -> int:
    """One agent pass: flush dirty; with TARGET, also evict clean
    objects down to that count (0 = drain the cache completely)."""
    b = _pool_id(rc, base)
    if target is None:
        st = rc.tier_agent_work(b)
    else:
        st = rc.tier_agent_work(b, target_objects=int(target))
        if int(target) == 0:
            # target 0 means DRAIN: tier_agent_work's evictor keeps
            # `target` objects, so finish by evicting the remainder
            cache_id = rc.osdmap.pools[b].read_tier
            for nm in rc.list_objects(cache_id):
                rc.tier_evict(b, nm)
                st["evicted"] += 1
    out.write(f"tier agent on '{base}': flushed {st['flushed']}, "
              f"evicted {st['evicted']}\n")
    return 0


def cmd_pool_create(rc, name: str, pg_num: int, ptype: str,
                    size: int, out) -> int:
    from ..cluster.osdmap import POOL_ERASURE, POOL_REPLICATED
    r = rc.mon_call({
        "cmd": "pool_create", "name": name, "pg_num": pg_num,
        "type": POOL_ERASURE if ptype == "erasure"
        else POOL_REPLICATED,
        "size": size,
        "crush_rule": 1 if ptype == "erasure" else 0,
        "erasure_code_profile":
            "default" if ptype == "erasure" else ""})
    if r.get("existed"):
        out.write(f"pool '{name}' already exists (id "
                  f"{r['pool_id']})\n")
    else:
        out.write(f"pool '{name}' created (id {r['pool_id']}, "
                  f"epoch {r['epoch']})\n")
    return 0


def cmd_pool_rm(rc, name: str, out) -> int:
    r = rc.mon_call({"cmd": "pool_rm", "name": name})
    if r.get("existed"):
        out.write(f"pool '{name}' removed (epoch {r['epoch']})\n")
    else:
        out.write(f"pool '{name}' did not exist\n")
    return 0


def cmd_pool_ls(rc, detail: bool, out) -> int:
    names = _pool_types()
    for pid, pool in sorted(rc.osdmap.pools.items()):
        if detail:
            out.write(f"pool {pid} '{pool.name}' "
                      f"{names.get(pool.type, pool.type)} size "
                      f"{pool.size} pg_num {pool.pg_num} crush_rule "
                      f"{pool.crush_rule}\n")
        else:
            out.write(f"{pool.name}\n")
    return 0


def cmd_pg_dump(rc, pool_id: int, out) -> int:
    pool = rc.osdmap.pools[pool_id]
    out.write("PG  UP  PRIMARY\n")
    for pg in range(pool.pg_num):
        ups = rc._up(pool, pg)
        prim = next((o for o in ups if o >= 0), -1)
        out.write(f"{pool_id}.{pg}  {ups}  {prim}\n")
    return 0


def cmd_df(rc, out) -> int:
    stats = {}
    try:
        cs = rc.mon_call({"cmd": "cluster_stats"})
        df = cs.get("df") or {}
        stats = {int(k): v for k, v in (df.get("pools") or {}).items()}
        if df.get("total_bytes"):
            out.write(f"RAW USED: {df['total_used_bytes']} / "
                      f"{df['total_bytes']} bytes\n")
    except Exception:
        pass
    out.write("POOL  OBJECTS  RAW_SHARDS  RAW_BYTES\n")
    for pid, pool in sorted(rc.osdmap.pools.items()):
        row = stats.get(pid) or {}
        # daemons report per-pool shard COUNTS; byte attribution is
        # allocator-level (whole-store), so a zero here means "not
        # reported per pool", never "empty"
        nbytes = row.get("bytes", 0) or "-"
        out.write(f"{pool.name}  {len(rc.list_objects(pid))}  "
                  f"{row.get('objects', 0)}  {nbytes}\n")
    return 0


def cmd_osd_df(rc, out) -> int:
    """`ceph osd df` — per-OSD utilization from the ClusterStats
    aggregator (allocator-backed used/total bytes each daemon ships
    on its heartbeat), with recent write/read rate sparklines off
    the mon's metrics-history rings."""
    rows = rc.mon_call({"cmd": "cluster_stats"}).get("osd_df") or []
    out.write("NAME  OBJECTS  USED  TOTAL  %USE  WR  RD\n")
    for r in rows:
        out.write(f"{r['daemon']}  {r['objects']}  "
                  f"{r['bytes_used']}  {r['bytes_total']}  "
                  f"{100.0 * r['utilization']:.2f}  "
                  f"{r.get('wr_trend', '-')}  "
                  f"{r.get('rd_trend', '-')}\n")
    if not rows:
        out.write("(no daemon reports yet)\n")
    return 0


def cmd_telemetry_history(rc, counter: str, daemon: Optional[str],
                          out, as_json: bool = False) -> int:
    """`ceph telemetry history <counter> [--daemon osd.N]` — range-
    query the leader mon's metrics-history rings: retained samples +
    reset-clamped rates per reporter."""
    r = rc.mon_call({"cmd": "cluster_stats",
                     "history": {"counter": counter,
                                 "daemon": daemon}})
    if as_json:
        out.write(json.dumps(r, indent=2, sort_keys=True) + "\n")
        return 0
    series = r.get("series") or {}
    if not series:
        out.write(f"(no history for counter {counter!r})\n")
        return 1
    out.write(f"counter {counter} (cluster resets: "
              f"{r.get('counter_resets', 0)})\n")
    for name, s in sorted(series.items()):
        out.write(f"  {name}: {len(s['samples'])} samples, "
                  f"{s['resets']} resets\n")
        for (ts, v), (_rts, rate) in zip(s["samples"][1:],
                                         s["rates"]):
            out.write(f"    {ts:.3f}  {v:.0f}  ({rate:.3f}/s)\n")
    return 0


def cmd_pg_heat(rc, pool: Optional[int], top: Optional[int],
                out, as_json: bool = False) -> int:
    """`ceph pg heat [--pool P] [--top N]` — decayed per-PG client-io
    heat merged across the reporting OSDs, hottest first, plus the
    per-OSD rollup (asserted consistent with the osd.io counters)."""
    r = rc.mon_call({"cmd": "cluster_stats",
                     "heat": {"pool": pool, "top": top}})
    if as_json:
        out.write(json.dumps(r, indent=2, sort_keys=True) + "\n")
        return 0
    pgs = r.get("pgs") or []
    if not pgs:
        out.write("(no heat reported yet)\n")
        return 1
    out.write("PGID  HEAT  RD_OPS  WR_OPS  RD_B  WR_B  OSDS\n")
    for row in pgs:
        out.write(f"{row['pgid']}  {row['heat']:.3f}  "
                  f"{row['rd_ops']:.1f}  {row['wr_ops']:.1f}  "
                  f"{row['rd_bytes']:.0f}  {row['wr_bytes']:.0f}  "
                  f"{','.join(row['osds'])}\n")
    return 0


def cmd_balancer_eval(rc, max_moves: int, pool: Optional[int],
                      out, as_json: bool = False) -> int:
    """`ceph balancer eval` / `ceph balancer propose [--json]` — the
    dry-run advisor: imbalance score from heat x utilization and the
    proposed upmap moves, as a REPORT (nothing is actuated)."""
    r = rc.mon_call({"cmd": "balancer_eval", "max_moves": max_moves,
                     "pool": pool})
    if as_json:
        out.write(json.dumps(r, indent=2, sort_keys=True) + "\n")
        return 0
    out.write(f"current imbalance score: {r['score_before']:.6f} "
              f"(epoch {r['epoch']}, {r['pgs_considered']} hot "
              f"pgs)\n")
    props = r.get("proposals") or []
    if not props:
        out.write("no improving moves found (dry run; map "
                  "unchanged)\n")
        return 0
    out.write(f"proposed score: {r['score_after']:.6f} with "
              f"{len(props)} move(s):\n")
    for p in props:
        out.write(f"  pg {p['pgid']}: osd.{p['from']} -> "
                  f"osd.{p['to']} (heat {p['heat']:.3f}, score -> "
                  f"{p['score_after']:.6f})\n")
    out.write("dry run only — apply is not implemented in this "
              "release\n")
    return 0


def cmd_trace(cluster_dir: str, token: str, out,
              as_json: bool = False) -> int:
    """`ceph trace <op_id>` — the cluster-level trace assembly: find
    the op's trace id in ANY daemon/client tracked-op dump, gather
    `dump_traces` spans from every admin socket in the cluster dir,
    and assemble the cross-process tree (the Jaeger query role)."""
    import glob
    import os

    from ..common.admin import admin_request
    from ..common.tracer import assemble, render_trace
    socks = sorted(glob.glob(os.path.join(cluster_dir, "*.asok")))
    if not socks:
        out.write(f"Error: no admin sockets under {cluster_dir}\n")
        return 1
    trace_id = None
    if token.startswith("0x"):
        trace_id = int(token, 16)
    # op ids are PER-PROCESS counters, so "op 7" can exist on the
    # client AND on several daemons: collect every match and refuse
    # an ambiguous resolution instead of silently rendering the
    # first asok's unrelated trace
    matches: Dict[int, str] = {}
    spans = []
    for path in socks:
        name = os.path.basename(path)[:-len(".asok")]
        if trace_id is None:
            for dump in ("dump_historic_slow_ops",
                         "dump_historic_ops", "dump_ops_in_flight"):
                try:
                    r = admin_request(path, {"prefix": dump}) \
                        .get("result") or {}
                except (OSError, IOError):
                    break
                for op in r.get("ops", []):
                    if str(op.get("op_id")) == token and \
                            op.get("trace_id"):
                        matches.setdefault(int(op["trace_id"]), name)
        try:
            r = admin_request(path, {"prefix": "dump_traces"}) \
                .get("result") or {}
            spans.extend(r.get("spans") or [])
        except (OSError, IOError):
            continue
    if trace_id is None:
        if len(matches) > 1:
            out.write(f"Error: op id {token!r} is ambiguous (op ids "
                      f"are per-process) — candidates:\n")
            for tid, name in sorted(matches.items()):
                out.write(f"  {name}: trace {tid:#x}\n")
            out.write("re-run with the 0x<trace_id> form\n")
            return 1
        if matches:
            trace_id = next(iter(matches))
    if trace_id is None:
        out.write(f"Error: op {token!r} not found in any daemon's "
                  f"tracked-op dumps (or it carries no trace)\n")
        return 1
    trees = assemble(s for s in spans
                     if int(s.get("trace_id", 0)) == trace_id)
    tree = trees.get(trace_id)
    if tree is None:
        out.write(f"Error: no spans for trace {trace_id:#x}\n")
        return 1
    if as_json:
        out.write(json.dumps(tree, indent=2, sort_keys=True,
                             default=str) + "\n")
    else:
        out.write(render_trace(tree) + "\n")
    return 0


def cmd_scrub(rc, pool_id: int, out) -> int:
    r = rc.scrub_pool(pool_id)
    out.write(json.dumps(r) + "\n")
    return 0


DAEMON_COMMANDS = ("dump_ops_in_flight", "dump_historic_ops",
                   "dump_historic_slow_ops", "dump_traces",
                   "perf dump", "perf reset",
                   "config show", "config get", "config set",
                   "trace dump", "trace reset", "fault_injection",
                   "store_fsck", "help")


def cmd_daemon(cluster_dir: str, name: str, words: List[str],
               out) -> int:
    """`ceph daemon <osd.N|mon.N|objecter> <command...>` over the
    daemon's admin socket (admin_socket JSON protocol, common/admin.py).
    Multi-word admin prefixes ("perf dump") are joined; a trailing
    KEY[=VALUE] pair becomes the request's key/value args.

    `fault_injection` takes its own grammar (runtime fault control):

        ... daemon osd.0 fault_injection                 # status
        ... daemon osd.0 fault_injection arm wire.drop_frame \\
                mode=one_in n=5 seed=3 [count=2]
        ... daemon osd.0 fault_injection disarm [NAME]
    """
    import os

    from ..common.admin import admin_request
    path = os.path.join(cluster_dir, f"{name}.asok")
    if not os.path.exists(path):
        out.write(f"Error: no admin socket for {name!r} "
                  f"(expected {path})\n")
        return 1
    req = {"prefix": " ".join(words)}
    if words[0] == "store_fsck":
        # `... daemon osd.N store_fsck [repair]` — on-demand store
        # consistency walk; `repair` quarantines inconsistencies
        req = {"prefix": "store_fsck",
               "repair": "repair" in words[1:]}
    elif words[0] == "fault_injection":
        req = {"prefix": "fault_injection"}
        rest = words[1:]
        if rest:
            req["action"] = rest[0]
            pos = [w for w in rest[1:] if "=" not in w]
            if pos:
                req["name"] = pos[0]
            for w in rest[1:]:
                if "=" in w:
                    k, v = w.split("=", 1)
                    if k in ("mode", "n", "seed", "count"):
                        req[k] = v
                    elif k == "match":
                        # phase filter: a JSON object on the command
                        # line (match={"cmd":"put_shard"})
                        req["match"] = json.loads(v)
                    else:
                        # anything else (e.g. seconds=0.2) rides as a
                        # faultpoint param the fire site reads back
                        req.setdefault("params", {})[k] = v
    # `config get KEY` / `config set KEY VALUE` style trailing args
    elif len(words) >= 3 and " ".join(words[:2]) in DAEMON_COMMANDS:
        req["prefix"] = " ".join(words[:2])
        req["key"] = words[2]
        if len(words) >= 4:
            req["value"] = words[3]
    reply = admin_request(path, req)
    out.write(json.dumps(reply.get("result", reply), indent=2,
                         sort_keys=True, default=str) + "\n")
    return 0 if "error" not in reply else 1


def main(argv: Optional[List[str]] = None,
         out=None) -> int:
    out = out or sys.stdout
    ap = argparse.ArgumentParser(prog="ceph")
    ap.add_argument("--dir", default=None,
                    help="vstart cluster directory (required for "
                         "every command except `lint`)")
    ap.add_argument("--detail", action="store_true")
    ap.add_argument("--size", type=int, default=3,
                    help="replica count for `osd pool create`")
    ap.add_argument("words", nargs="+",
                    help="command, e.g.: status | health | mon stat | "
                         "osd tree | osd out N | osd in N | "
                         "osd set|unset noout|nodown | osd pool ls | "
                         "osd tier add|remove BASE CACHE | "
                         "osd tier agent BASE [TARGET] | "
                         "osd df | trace OP_ID [--json] | "
                         "pg dump POOL | pg heat [--pool=P --top=N] "
                         "| telemetry history COUNTER "
                         "[--daemon=osd.N] | "
                         "balancer eval|propose [--json] | "
                         "df | scrub POOL | "
                         "daemon NAME dump_ops_in_flight|"
                         "dump_historic_ops|dump_historic_slow_ops|"
                         "perf dump|fault_injection [...]|"
                         "store_fsck [repair] | "
                         "lint [--check|--json|--sarif|"
                         "--rule CTL###|--graph module.fn|...] | "
                         "thrash [--seed N --cycles K --netsplit "
                         "--powercycle --json] | "
                         "serve [--seed N --chaos --starve --json] | "
                         "serve --dr [--seed N --chaos "
                         "--lose-bilog --json] | "
                         "rgw POOL bucket reshard|limit ...")
    ns, extra = ap.parse_known_args(argv)
    if ns.words[0] == "lint":
        # static-analysis surface (ceph_tpu/analysis): needs no
        # cluster — unknown flags pass through to the lint driver
        # (`ceph lint --check`, `ceph lint --json`, ...)
        from ..analysis.runner import main as lint_main
        return lint_main(ns.words[1:] + extra, out=out)
    if ns.words[0] == "thrash":
        # robustness surface (`ceph thrash --seed N --cycles K
        # --json`): a seeded kill/revive soak with self-healing
        # invariants — builds its own in-process stack, no --dir
        from ..cluster.thrasher import main as thrash_main
        return thrash_main(ns.words[1:] + extra, out=out)
    if ns.words[0] == "serve":
        # serving surface (`ceph serve [--chaos --starve --json]`):
        # the multi-tenant S3 workload with the enforced SLO/QoS
        # gate — builds its own vstart cluster, exits nonzero on
        # any per-tenant breach (rgw/serving.py).  `serve --dr`
        # routes to the two-zone disaster-recovery drill
        # (cluster/dr_drill.py) and exits with its convergence gate
        from ..rgw.serving import serve_main
        return serve_main(ns.words[1:] + extra, out=out)
    if ns.words[0] == "rgw":
        # gateway admin over a live cluster: `ceph rgw <pool>
        # <radosgw-admin words...>` builds the pool's IoCtx and
        # hands through to radosgw-admin (bucket reshard / bucket
        # limit check / user ... against daemons)
        if ns.dir is None:
            ap.error("--dir is required for `rgw`")
        if len(ns.words) < 3:
            ap.error("rgw POOL COMMAND...")
        from ..client.remote_ioctx import RemoteIoCtx
        from .radosgw_admin import main as rgw_main
        rc = _client(ns.dir)
        try:
            io = RemoteIoCtx(rc, ns.words[1])
            return rgw_main(ns.words[2:] + extra, ioctx=io, out=out)
        except (RuntimeError, ValueError, OSError, KeyError) as e:
            out.write(f"Error: {e}\n")
            return 1
        finally:
            rc.close()
    if ns.words[0] == "trace":
        # cluster-level trace assembly over the daemons' admin
        # sockets: needs no mon connection (an op is usually traced
        # BECAUSE something is wedged)
        if ns.dir is None:
            ap.error("--dir is required for `trace`")
        if len(ns.words) < 2:
            ap.error("trace OP_ID|0xTRACE_ID [--json]")
        try:
            return cmd_trace(ns.dir, ns.words[1], out,
                             as_json="--json" in (ns.words[2:] +
                                                  extra))
        except (RuntimeError, ValueError, OSError) as e:
            out.write(f"Error: {e}\n")
            return 1
    if ns.words[0] in ("telemetry", "balancer") or \
            ns.words[:2] == ["pg", "heat"]:
        # ClusterScope observability verbs: their flags ride `extra`
        # (use --flag=value forms; argparse scrambles split pairs)
        if ns.dir is None:
            ap.error(f"--dir is required for `{ns.words[0]}`")
        sub = argparse.ArgumentParser(prog=f"ceph {ns.words[0]}")
        sub.add_argument("--daemon", default=None)
        sub.add_argument("--pool", type=int, default=None)
        sub.add_argument("--top", type=int, default=None)
        sub.add_argument("--max-moves", type=int, default=8,
                         dest="max_moves")
        sub.add_argument("--json", action="store_true",
                         dest="as_json")
        sub.add_argument("rest", nargs="*")
        fl = sub.parse_args(ns.words[1:] + extra)
        rc = _client(ns.dir)
        try:
            if ns.words[0] == "telemetry":
                if fl.rest[:1] != ["history"] or len(fl.rest) < 2:
                    ap.error("telemetry history COUNTER "
                             "[--daemon=osd.N] [--json]")
                return cmd_telemetry_history(rc, fl.rest[1],
                                             fl.daemon, out,
                                             fl.as_json)
            if ns.words[0] == "balancer":
                if fl.rest[:1] not in (["eval"], ["propose"]):
                    ap.error("balancer eval|propose "
                             "[--max-moves=N] [--pool=P] [--json]")
                return cmd_balancer_eval(
                    rc, fl.max_moves, fl.pool, out,
                    fl.as_json or fl.rest[0] == "propose")
            return cmd_pg_heat(rc, fl.pool, fl.top, out, fl.as_json)
        except (RuntimeError, ValueError, OSError) as e:
            out.write(f"Error: {e}\n")
            return 1
        finally:
            rc.close()
    if extra:
        ap.error(f"unrecognized arguments: {' '.join(extra)}")
    if ns.dir is None:
        ap.error("--dir is required for cluster commands")
    if ns.words[0] == "daemon":
        # admin-socket path: talks to ONE daemon directly, needs no
        # mon connection (and must work while the mon is down)
        if len(ns.words) < 3:
            ap.error("daemon NAME COMMAND...")
        try:
            return cmd_daemon(ns.dir, ns.words[1], ns.words[2:], out)
        except (RuntimeError, ValueError, OSError) as e:
            out.write(f"Error: {e}\n")
            return 1
    rc = _client(ns.dir)
    try:
        return _dispatch(ap, ns, rc, out)
    except (RuntimeError, ValueError, OSError) as e:
        out.write(f"Error: {e}\n")
        return 1
    finally:
        rc.close()


def _dispatch(ap, ns, rc, out) -> int:
    w = ns.words

    def arg(i: int) -> str:
        if len(w) <= i:
            ap.error(f"{' '.join(w)}: missing operand")
        return w[i]

    if w[0] in ("status", "-s"):
        return cmd_status(rc, out)
    if w[0] == "health":
        return cmd_health(rc, out)
    if w[:2] == ["mon", "stat"]:
        return cmd_mon_stat(rc, out)
    if w[:2] == ["osd", "tree"]:
        return cmd_osd_tree(rc, ns.dir, out)
    if w[:2] == ["osd", "out"]:
        return cmd_osd_out(rc, int(arg(2)), out)
    if w[:2] == ["osd", "in"]:
        return cmd_osd_in(rc, int(arg(2)), out)
    if w[:2] == ["osd", "set"]:
        # `ceph osd set noout|nodown` — ride out a known partition:
        # noout stops the down->out transition, nodown stops failure
        # reports from marking OSDs down (OSDMonitor flag commands)
        r = rc.mon_call({"cmd": "osd_set_flag", "flag": arg(2)})
        out.write(f"{arg(2)} is set (flags: "
                  f"{','.join(r['flags']) or '-'})\n")
        return 0
    if w[:2] == ["osd", "unset"]:
        r = rc.mon_call({"cmd": "osd_unset_flag", "flag": arg(2)})
        out.write(f"{arg(2)} is unset (flags: "
                  f"{','.join(r['flags']) or '-'})\n")
        return 0
    if w[:3] == ["osd", "pool", "ls"]:
        return cmd_pool_ls(rc, ns.detail, out)
    if w[:3] == ["osd", "pool", "create"]:
        name = arg(3)
        pg_num = int(w[4]) if len(w) > 4 else 16
        ptype = w[5] if len(w) > 5 else "replicated"
        return cmd_pool_create(rc, name, pg_num, ptype,
                               ns.size, out)
    if w[:3] == ["osd", "pool", "rm"]:
        return cmd_pool_rm(rc, arg(3), out)
    if w[:3] == ["osd", "tier", "add"]:
        return cmd_tier_add(rc, arg(3), arg(4), out)
    if w[:3] == ["osd", "tier", "remove"]:
        return cmd_tier_remove(rc, arg(3), arg(4), out)
    if w[:3] == ["osd", "tier", "agent"]:
        return cmd_tier_agent(rc, arg(3),
                              w[4] if len(w) > 4 else None, out)
    if w[:2] == ["osd", "df"]:
        return cmd_osd_df(rc, out)
    if w[:2] == ["pg", "dump"]:
        return cmd_pg_dump(rc, int(arg(2)), out)
    if w[0] == "df":
        return cmd_df(rc, out)
    if w[0] == "scrub":
        return cmd_scrub(rc, int(arg(1)), out)
    ap.error(f"unknown command: {' '.join(w)}")
    return 2


if __name__ == "__main__":
    sys.exit(main())
