"""EC benchmark sweep — the plot-harness role.

The reference drives `ceph_erasure_code_benchmark` over a plugin ×
technique × size matrix and emits plottable series
(qa/workunits/erasure-code/bench.sh:38-57, defaults SIZE=4096,
plugins isa/jerasure, techniques vandermonde/cauchy).  Same idea:
sweep (plugin, technique, k, m, object size) through the registry's
encode/decode paths and print one CSV row per cell —
`plugin,technique,k,m,size,workload,gbps`.

Usage:
    python -m ceph_tpu.tools.bench_sweep [--plugins jax,isa]
        [--k 4,8] [--m 2,3] [--sizes 4096,1048576]
        [--workloads encode,decode] [--iters 4] [--batch 16]

Note: ec_bench.py times the single-config reference-CLI contract
(`seconds\tKB`); this sweep shares the registry but intentionally keeps
its own minimal timing cell — if the two drift further, extract one
shared timing helper.
"""
from __future__ import annotations

import argparse
import sys
import time

import numpy as np

# "technique+bitsliced" runs the same matrix under the packet-plane
# layout (the flagship region-XOR kernel)
TECHNIQUES = {
    "jax": ["reed_sol_van", "cauchy", "reed_sol_van+bitsliced"],
    "jerasure": ["reed_sol_van", "cauchy_good",
                 "liberation", "blaum_roth", "liber8tion"],
    "isa": ["reed_sol_van", "cauchy"],
}


def bench_cell(plugin: str, technique: str, k: int, m: int, size: int,
               workload: str, iters: int, batch: int) -> float:
    from ..ec import instance as ec_registry
    prof = {"k": str(k), "m": str(m)}
    if technique:
        if "+" in technique:
            technique, layout = technique.split("+", 1)
            prof["layout"] = layout
        prof["technique"] = technique
    codec = ec_registry().factory(plugin, prof)
    chunk = codec.get_chunk_size(size)
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(batch, k, chunk), dtype=np.uint8)
    if workload == "encode":
        # warm with the FULL batch shape: jit executables are
        # shape-specialized, a [1,...] warm-up leaves the real compile
        # inside the timing window
        codec.encode_chunks_batch(data)
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = codec.encode_chunks_batch(data)
        np.asarray(out).sum()                            # force
        dt = time.perf_counter() - t0
    else:
        parity = np.asarray(codec.encode_chunks_batch(data))
        full = np.concatenate([data, parity], axis=1)
        erased = sorted(rng.choice(k + m, size=min(m, 2),
                                   replace=False).tolist())
        avail = [c for c in range(k + m) if c not in erased]
        plan = sorted(codec.minimum_to_decode(set(range(k)), set(avail)))
        sub = full[:, plan]
        codec.decode_chunks_batch(plan, sub, erased)      # warm (full)
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = codec.decode_chunks_batch(plan, sub, erased)
        np.asarray(out).sum()
        dt = time.perf_counter() - t0
    return iters * batch * k * chunk / dt / 1e9


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_sweep")
    ap.add_argument("--plugins", default="jax")
    ap.add_argument("--k", default="4,8")
    ap.add_argument("--m", default="2,3")
    ap.add_argument("--sizes", default="4096,1048576")
    ap.add_argument("--workloads", default="encode,decode")
    ap.add_argument("--iters", type=int, default=4)
    ap.add_argument("--batch", type=int, default=16)
    args = ap.parse_args(argv)
    print("plugin,technique,k,m,size,workload,gbps")
    for plugin in args.plugins.split(","):
        for technique in TECHNIQUES.get(plugin, [None]):
            for k in (int(v) for v in args.k.split(",")):
                for m in (int(v) for v in args.m.split(",")):
                    for size in (int(v) for v in args.sizes.split(",")):
                        for wl in args.workloads.split(","):
                            try:
                                gbps = bench_cell(
                                    plugin, technique, k, m, size, wl,
                                    args.iters, args.batch)
                            except Exception as e:
                                print(f"# {plugin}/{technique or ''} "
                                      f"k={k} m={m} {wl}: {e}",
                                      file=sys.stderr)
                                continue
                            print(f"{plugin},{technique or ''},{k},{m},"
                                  f"{size},{wl},{gbps:.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
