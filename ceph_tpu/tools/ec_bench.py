"""Erasure-code benchmark — ceph_erasure_code_benchmark equivalent.

Same option surface and output contract as the reference binary
(src/test/erasure-code/ceph_erasure_code_benchmark.cc:48-123: --plugin,
--workload encode|decode, -k/-m, --size, --iterations, --erasures;
prints ``seconds\tKB`` per run, :156-184 encode loop, :251-315 decode
loop), extended with --batch to amortize device dispatch across stripes —
the capability the TPU backend adds.

Usage:
    python -m ceph_tpu.tools.ec_bench --plugin jax --workload encode \
        -k 8 -m 3 --size $((1<<20)) --iterations 8 --batch 16 [--json]
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from ..ec import instance as ec_registry


def run(args) -> dict:
    profile = {"k": str(args.k), "m": str(args.m)}
    if args.technique:
        profile["technique"] = args.technique
    for kv in args.parameter or []:
        key, _, val = kv.partition("=")
        profile[key] = val
    codec = ec_registry().factory(args.plugin, profile)
    k, m = args.k, args.m
    chunk = codec.get_chunk_size(args.size)
    rng = np.random.default_rng(args.seed)
    batch = rng.integers(0, 256, size=(args.batch, k, chunk),
                         dtype=np.uint8) if args.batch > 1 else None
    single = rng.integers(0, 256, size=(k, chunk), dtype=np.uint8)

    erasures = args.erasures
    erased = sorted(rng.choice(k + m, size=erasures, replace=False).tolist()) \
        if not args.erased else sorted(args.erased)
    avail = [i for i in range(k + m) if i not in erased]

    def one_encode():
        if batch is not None:
            out = codec.encode_chunks_batch(batch)
        else:
            out = codec.encode_chunks(single)
        return out

    if args.workload == "decode":
        parity = codec.encode_chunks_batch(batch) if batch is not None \
            else codec.encode_chunks(single)
        if batch is not None:
            full = np.concatenate([batch, parity], axis=1)
            surv = full[:, avail]
        else:
            full = np.concatenate([single, parity], axis=0)
            surv = full[avail]

    # warmup (jit compile)
    if args.workload == "encode":
        one_encode()
    else:
        if batch is not None:
            codec.decode_chunks_batch(avail, surv, erased)
        else:
            codec.decode_chunks(avail, surv, erased)

    t0 = time.perf_counter()
    for _ in range(args.iterations):
        if args.workload == "encode":
            one_encode()
        elif batch is not None:
            codec.decode_chunks_batch(avail, surv, erased)
        else:
            codec.decode_chunks(avail, surv, erased)
    dt = time.perf_counter() - t0

    stripes = args.iterations * (args.batch if batch is not None else 1)
    payload_bytes = stripes * k * chunk
    result = {
        "plugin": args.plugin, "workload": args.workload,
        "k": k, "m": m, "chunk_size": chunk, "batch": args.batch,
        "iterations": args.iterations, "erased": erased,
        "seconds": dt, "KB": payload_bytes // 1024,
        "GBps": payload_bytes / dt / 1e9,
    }
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ec_bench")
    ap.add_argument("--plugin", "-p", default="jax")
    ap.add_argument("--workload", "-w", choices=("encode", "decode"),
                    default="encode")
    ap.add_argument("-k", type=int, default=8)
    ap.add_argument("-m", type=int, default=3)
    ap.add_argument("--technique", default=None)
    ap.add_argument("--parameter", "-P", action="append",
                    help="extra profile key=value")
    ap.add_argument("--size", "-s", type=int, default=1 << 20,
                    help="object size in bytes (split into k chunks)")
    ap.add_argument("--iterations", "-i", type=int, default=8)
    ap.add_argument("--batch", "-b", type=int, default=1,
                    help="stripes per device call")
    ap.add_argument("--erasures", "-e", type=int, default=2)
    ap.add_argument("--erased", type=int, action="append", default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    result = run(args)
    if args.json:
        print(json.dumps(result))
    else:
        # reference output contract: "seconds\tKB"
        print(f"{result['seconds']:.6f}\t{result['KB']}")
        print(f"# {result['GBps']:.3f} GB/s payload "
              f"({result['plugin']} {result['workload']} "
              f"k={result['k']} m={result['m']} batch={result['batch']})",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
