"""osdmaptool equivalent: full-cluster PG mapping sweeps.

Mirrors `osdmaptool --test-map-pgs` (reference: src/tools/osdmaptool.cc:630-676
— the per-pool, per-ps pg_to_up_acting_osds loop) with the sweep batched
per pool through OSDMap.map_pgs_batch.

The tool consumes a cluster JSON spec:
  {"crush": <CrushMap.to_spec()>,
   "pools": [{"id":1, "type":1, "size":3, "pg_num":64, "crush_rule":0}...],
   "osds": {"count": N} | {"down":[...], "out":[...]} }

Usage:
    python -m ceph_tpu.tools.osdmaptool cluster.json --test-map-pgs [--dump]
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from ..cluster.osdmap import OSDMap, PGPool
from ..placement.crush_map import ITEM_NONE, CrushMap


def load_cluster(spec: dict) -> OSDMap:
    cmap = CrushMap.from_spec(spec["crush"])
    om = OSDMap(cmap)
    om.mark_all_in_up()
    for o in spec.get("osds", {}).get("down", []):
        om.osd_up[o] = False
    for o in spec.get("osds", {}).get("out", []):
        om.osd_weight[o] = 0
    for p in spec["pools"]:
        om.add_pool(PGPool(**p))
    return om


def test_map_pgs(om: OSDMap, scalar: bool = False) -> dict:
    counts = np.zeros(om.max_osd, dtype=np.int64)
    primaries = np.zeros(om.max_osd, dtype=np.int64)
    total_pgs = 0
    t0 = time.perf_counter()
    for pid, pool in sorted(om.pools.items()):
        if scalar:
            rows = []
            prims = []
            for ps in range(pool.pg_num):
                up, upp, _, _ = om.pg_to_up_acting_osds(pid, ps)
                rows.append(up + [ITEM_NONE] * (pool.size - len(up)))
                prims.append(upp)
            up_b = np.asarray(rows, dtype=np.int64)
            prim_b = np.asarray(prims, dtype=np.int64)
        else:
            up_b, prim_b = om.map_pgs_batch(pid)
        total_pgs += pool.pg_num
        vals = up_b[up_b != ITEM_NONE]
        np.add.at(counts, vals, 1)
        pv = prim_b[prim_b >= 0]
        np.add.at(primaries, pv, 1)
    dt = time.perf_counter() - t0
    in_osds = counts[counts > 0]
    return {
        "total_pgs": int(total_pgs),
        "seconds": dt,
        "pg_per_osd_min": int(in_osds.min()) if len(in_osds) else 0,
        "pg_per_osd_max": int(in_osds.max()) if len(in_osds) else 0,
        "pg_per_osd_avg": float(in_osds.mean()) if len(in_osds) else 0.0,
        "osds_used": int((counts > 0).sum()),
        "counts": counts,
        "primaries": primaries,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="osdmaptool")
    ap.add_argument("mapfn", help="cluster JSON spec")
    ap.add_argument("--test-map-pgs", action="store_true")
    ap.add_argument("--scalar", action="store_true")
    ap.add_argument("--dump", action="store_true")
    ap.add_argument("--upmap", metavar="OUT",
                    help="balance PGs via pg_upmap_items (reference: "
                         "osdmaptool --upmap); writes the proposed "
                         "items as JSON")
    ap.add_argument("--upmap-deviation", type=float, default=1.0)
    ap.add_argument("--upmap-max", type=int, default=128,
                    help="max upmap moves per round")
    args = ap.parse_args(argv)
    with open(args.mapfn) as f:
        spec = json.load(f)
    om = load_cluster(spec)
    if args.dump:
        print(json.dumps({
            "epoch": om.epoch, "max_osd": om.max_osd,
            "pools": {p.id: vars(p) for p in om.pools.values()}},
            default=str, indent=2))
        return 0
    if args.upmap:
        from ..cluster.balancer import calc_pg_upmaps
        res = calc_pg_upmaps(om, max_deviation=args.upmap_deviation,
                             max_moves_per_round=args.upmap_max)
        items = {f"{pid}.{pg}": [[int(a), int(b)] for a, b in pairs]
                 for (pid, pg), pairs in sorted(res.upmap_items.items())}
        with open(args.upmap, "w") as f:
            json.dump({"pg_upmap_items": items}, f, indent=1)
        print(f"balanced in {res.rounds} rounds: {res.moves} moves, "
              f"max deviation {res.max_deviation_before:.2f} -> "
              f"{res.max_deviation_after:.2f}")
        return 0
    if args.test_map_pgs:
        stats = test_map_pgs(om, scalar=args.scalar)
        # timing is nondeterministic -> stderr (goldens pin stdout only)
        print(f"pool throughput: {stats['total_pgs']} pgs in "
              f"{stats['seconds']:.3f}s "
              f"({stats['total_pgs'] / stats['seconds']:,.0f} pg/s)",
              file=sys.stderr)
        print(f" avg {stats['pg_per_osd_avg']:.2f} "
              f"min {stats['pg_per_osd_min']} max {stats['pg_per_osd_max']} "
              f"over {stats['osds_used']} osds")
        size = sum(p.size * p.pg_num for p in om.pools.values())
        print(f" total replicas {size}")
        return 0
    ap.error("nothing to do")
    return 1


if __name__ == "__main__":
    sys.exit(main())
