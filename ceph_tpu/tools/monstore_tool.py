"""`ceph-monstore-tool` — offline mon store inspection/surgery.

The reference tool (src/tools/ceph_monstore_tool.cc): dump the
MonitorDBStore's committed state — map epochs, paxos versions, config
keys — and extract map blobs for disaster recovery.  Operates on a
stopped mon's WalDB directory (vstart lays them out as
<cluster>/mon-store[.<rank>]).

    python -m ceph_tpu.tools.monstore_tool <store-path> summary
    python -m ceph_tpu.tools.monstore_tool <store-path> dump-keys
    python -m ceph_tpu.tools.monstore_tool <store-path> get-osdmap [epoch]
    python -m ceph_tpu.tools.monstore_tool <store-path> dump-paxos
    python -m ceph_tpu.tools.monstore_tool <store-path> dump-config
"""
from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None, out=None) -> int:
    out = out or sys.stdout
    ap = argparse.ArgumentParser(prog="ceph-monstore-tool")
    ap.add_argument("path")
    ap.add_argument("words", nargs="+")
    ns = ap.parse_args(argv)
    from ..cluster.wal_kv import WalDB
    db = WalDB(ns.path, fsync=False)
    try:
        w = ns.words
        if w[0] == "summary":
            epochs = [k for k, _ in db.iterate("osdmap")]
            paxos = [k for k, _ in db.iterate("paxos")]
            cfg = [k for k, _ in db.iterate("config")]
            out.write(f"osdmap epochs: {len(epochs)}"
                      + (f" (first {int(epochs[0])}, last "
                         f"{int(epochs[-1])})" if epochs else "")
                      + "\n")
            out.write(f"paxos versions: {len(paxos)}"
                      + (f" (last {int(paxos[-1])})" if paxos else "")
                      + "\n")
            out.write(f"config keys: {len(cfg)}\n")
            return 0
        if w[0] == "dump-keys":
            for p in sorted({p for p, _ in db._keys}):
                for k, v in db.iterate(p):
                    out.write(f"{p}\t{k}\t({len(v)} bytes)\n")
            return 0
        if w[0] == "get-osdmap":
            epochs = [k for k, _ in db.iterate("osdmap")]
            if not epochs:
                out.write("(no committed osdmap incrementals)\n")
                return 1
            key = f"{int(w[1]):010d}" if len(w) > 1 else epochs[-1]
            blob = db.get("osdmap", key)
            if blob is None:
                out.write(f"(no osdmap epoch {int(key)})\n")
                return 1
            if hasattr(out, "buffer"):
                out.buffer.write(blob)
            else:
                out.write(blob.decode("latin-1"))
            return 0
        if w[0] == "dump-paxos":
            for k, v in db.iterate("paxos"):
                out.write(f"{int(k)}\t{v.decode(errors='replace')}\n")
            return 0
        if w[0] == "dump-config":
            for k, v in db.iterate("config"):
                out.write(f"{k} = {v.decode(errors='replace')}\n")
            return 0
        ap.error(f"unknown command {w[0]!r}")
        return 2
    finally:
        db.close()


if __name__ == "__main__":
    sys.exit(main())
