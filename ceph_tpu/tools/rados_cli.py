"""`rados` — the object CLI against a live process cluster.

The reference's rados tool (src/tools/rados/rados.cc: put/get/ls/rm/
stat/bench basics) over the authenticated wire client.

    python -m ceph_tpu.tools.rados_cli --dir /tmp/c1 -p rep put obj ./file
    python -m ceph_tpu.tools.rados_cli --dir /tmp/c1 -p rep get obj -
    python -m ceph_tpu.tools.rados_cli --dir /tmp/c1 -p rep ls
    python -m ceph_tpu.tools.rados_cli --dir /tmp/c1 -p rep rm obj
    python -m ceph_tpu.tools.rados_cli --dir /tmp/c1 -p rep bench 8
"""
from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional


def _pool_id(rc, name: str) -> int:
    for pid, pool in rc.osdmap.pools.items():
        if pool.name == name or str(pid) == name:
            return pid
    raise SystemExit(f"rados: no pool {name!r}")


def main(argv: Optional[List[str]] = None, out=None,
         data_in: Optional[bytes] = None) -> int:
    out = out or sys.stdout
    ap = argparse.ArgumentParser(prog="rados")
    ap.add_argument("--dir", required=True)
    ap.add_argument("-p", "--pool", required=True)
    ap.add_argument("words", nargs="+")
    ns = ap.parse_args(argv)
    from ..client.remote import RemoteCluster
    rc = RemoteCluster(ns.dir)
    try:
        pid = _pool_id(rc, ns.pool)
        w = ns.words
        _ARITY = {"ls": 1, "put": 3, "get": 3, "rm": 2, "bench": 1}
        if w[0] in _ARITY and len(w) < _ARITY[w[0]]:
            ap.error(f"rados {w[0]}: missing operand(s)")
        if w[0] == "ls":
            for n in rc.list_objects(pid):
                out.write(n + "\n")
            return 0
        if w[0] == "put":
            name, src = w[1], w[2]
            data = data_in if src == "-" and data_in is not None \
                else (sys.stdin.buffer.read() if src == "-"
                      else open(src, "rb").read())
            acks = rc.put(pid, name, data)
            out.write(f"wrote {len(data)} bytes ({acks} acks)\n")
            return 0
        if w[0] == "get":
            name, dst = w[1], w[2]
            data = rc.get(pid, name)
            if dst == "-":
                if hasattr(out, "buffer"):
                    out.buffer.write(data)
                else:
                    out.write(data.decode("latin-1"))
            else:
                open(dst, "wb").write(data)
            return 0
        if w[0] == "rm":
            acks = rc.delete(pid, w[1])
            out.write(f"removed {w[1]} ({acks} acks)\n")
            return 0 if acks else 1
        if w[0] == "bench":
            seconds = float(w[1]) if len(w) > 1 else 5.0
            payload = b"\xab" * (1 << 20)
            t0 = time.monotonic()
            n = 0
            while time.monotonic() - t0 < seconds:
                rc.put(pid, f"bench_{n}", payload)
                n += 1
            dt = time.monotonic() - t0
            out.write(f"{n} writes x 1 MiB in {dt:.2f}s = "
                      f"{n / dt:.1f} MiB/s\n")
            return 0
        ap.error(f"unknown command {w[0]!r}")
        return 2
    finally:
        rc.close()


if __name__ == "__main__":
    sys.exit(main())
