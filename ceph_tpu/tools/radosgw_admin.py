"""`radosgw-admin` — RGW administration CLI.

The reference's gateway admin tool (src/rgw/rgw_admin.cc): user
lifecycle (create/info/rm/suspend/enable/key create/list), bucket
listing and stats, GC listing/processing, and the realm/zonegroup/
zone/period command family.  Drives the same library objects the
gateway runs on (UserStore, RGWGateway, Realm), so everything it
prints is the gateway's own truth.

Library-style invocation (tests and embedders):

    main(["user", "create", "--uid", "alice"], ioctx=io, out=buf)

`--dir/--pool` process-cluster wiring is not exposed because the RGW
slice runs over librados in-process (the reference links librados
directly too); callers construct the ioctx.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None, ioctx=None, out=None) -> int:
    out = out or sys.stdout
    if ioctx is None:
        raise SystemExit("radosgw-admin: an ioctx must be provided "
                         "(library invocation)")
    ap = argparse.ArgumentParser(prog="radosgw-admin")
    ap.add_argument("words", nargs="+")
    ap.add_argument("--uid")
    ap.add_argument("--display-name", default="")
    ap.add_argument("--bucket")
    ap.add_argument("--num-shards", type=int, default=0,
                    help="target shard count for `bucket reshard`")
    ap.add_argument("--max-entries", type=int, default=1000,
                    help="per-shard entry ceiling for `bucket limit "
                         "check` (WARN past 90%%, OVER past it)")
    ap.add_argument("--realm", default="default")
    ap.add_argument("--rgw-zonegroup")
    ap.add_argument("--rgw-zone")
    ap.add_argument("--master", action="store_true")
    ap.add_argument("--commit", action="store_true")
    ns = ap.parse_args(argv)
    w = ns.words
    _MIN = {"user": 2, "key": 2, "bucket": 2, "gc": 2, "realm": 2,
            "zonegroup": 2, "zone": 2, "period": 2}
    if len(w) < _MIN.get(w[0], 1):
        ap.error(f"{w[0]}: missing subcommand")

    from ..rgw import Realm, RGWError, RGWGateway
    from ..rgw.users import UserError, UserStore

    def emit(obj) -> int:
        out.write(json.dumps(obj, indent=2, sort_keys=True) + "\n")
        return 0

    users = UserStore(ioctx)
    gw = RGWGateway(ioctx)
    try:
        # ------------------------------------------------------- user --
        if w[0] == "user":
            if w[1] == "create":
                if not ns.uid:
                    ap.error("user create requires --uid")
                return emit(users.create(ns.uid, ns.display_name))
            if w[1] == "info":
                return emit(users.info(ns.uid))
            if w[1] == "rm":
                users.rm(ns.uid)
                return emit({"removed": ns.uid})
            if w[1] == "suspend":
                return emit(users.suspend(ns.uid, True))
            if w[1] == "enable":
                return emit(users.suspend(ns.uid, False))
            if w[1] == "list":
                return emit(users.list_users())
        if w[0] == "key" and w[1] == "create":
            return emit(users.key_create(ns.uid))
        # ----------------------------------------------------- bucket --
        if w[0] == "bucket":
            if w[1] == "list":
                return emit(gw.list_buckets())
            if w[1] == "stats":
                names = [ns.bucket] if ns.bucket else gw.list_buckets()
                stats = {}
                for name in names:
                    b = gw.bucket(name)
                    objs = b.list_objects(max_keys=1 << 30)["contents"]
                    stats[name] = {
                        "num_objects": len(objs),
                        "num_shards": b.num_shards(),
                        "size": sum(o["size"] for o in objs)}
                return emit(stats)
            if w[1] == "reshard":
                # online bucket reshard (RGWBucketReshard role): a
                # new generation of index shards, committed in the
                # bucket directory, old generation dropped
                if not ns.bucket or ns.num_shards < 1:
                    ap.error("bucket reshard requires --bucket and "
                             "--num-shards >= 1")
                return emit(gw.reshard_bucket(ns.bucket,
                                              ns.num_shards))
            if w[1] == "limit" and len(w) > 2 and w[2] == "check":
                # per-shard entry counts + fill verdict (the hot-
                # shard / reshard-needed signal)
                return emit(gw.bucket_limit_check(
                    max_entries_per_shard=ns.max_entries))
        # --------------------------------------------------------- gc --
        if w[0] == "gc":
            if w[1] == "list":
                return emit(gw.gc_list())
            if w[1] == "process":
                return emit({"reclaimed": gw.gc_process()})
        # ------------------------------------------- realm/zone/period --
        if w[0] in ("realm", "zonegroup", "zone", "period"):
            # constructed only on this family: Realm load-or-create
            # durably writes a default record, and a failed unrelated
            # command must not mutate the pool
            realm = Realm(ioctx, ns.realm)
            if w[:2] == ["realm", "create"]:
                return emit({"realm": ns.realm,
                             "current_period":
                                 realm.current_period_id})
            if w[:2] == ["zonegroup", "create"]:
                if not ns.rgw_zonegroup:
                    ap.error("zonegroup create requires "
                             "--rgw-zonegroup")
                g = realm.create_zonegroup(ns.rgw_zonegroup,
                                           master=ns.master)
                return emit(g.to_dict())
            if w[:2] == ["zone", "create"]:
                if not (ns.rgw_zonegroup and ns.rgw_zone):
                    ap.error("zone create requires --rgw-zonegroup "
                             "and --rgw-zone")
                z = realm.create_zone(ns.rgw_zonegroup, ns.rgw_zone,
                                      master=ns.master)
                return emit(z.to_dict())
            if w[0] == "period":
                if w[1] == "update" and not ns.commit:
                    # staging is already durable; nothing else to do
                    return emit({"staged": True})
                if w[1] in ("update", "commit"):
                    p = realm.commit_period()
                    return emit(p.to_dict())
                if w[1] == "list":
                    return emit(realm.period_history())
                if w[1] == "get":
                    p = realm.current_period()
                    return emit(p.to_dict() if p else None)
        ap.error(f"unknown command: {' '.join(w)}")
        return 2
    except (UserError, RGWError) as e:
        out.write(str(e) + "\n")
        return 1


if __name__ == "__main__":
    main()
