"""`rbd` — block-image CLI over librbd.

The reference's rbd tool (src/tools/rbd/): image lifecycle, snapshot
family, clone layering.  Drives the librbd slice (client/rbd.py) over
an injected ioctx, like radosgw-admin (the reference links librbd
directly too).

    main(["create", "img", "--size", "8388608"], ioctx=io, out=buf)
    main(["ls"], ...)                 main(["info", "img"], ...)
    main(["snap", "create", "img@s1"], ...)
    main(["snap", "ls", "img"], ...)  main(["snap", "rollback", "img@s1"], ...)
    main(["snap", "protect", "img@s1"], ...)
    main(["clone", "img@s1", "child"], ...)
    main(["flatten", "child"], ...)   main(["children", "img@s1"], ...)
    main(["resize", "img", "--size", N], ...)   main(["rm", "img"], ...)
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _split_at(spec: str):
    if "@" not in spec:
        raise SystemExit(f"rbd: expected image@snap, got {spec!r}")
    return spec.split("@", 1)


def main(argv: Optional[List[str]] = None, ioctx=None, out=None) -> int:
    out = out or sys.stdout
    if ioctx is None:
        raise SystemExit("rbd: an ioctx must be provided")
    ap = argparse.ArgumentParser(prog="rbd")
    ap.add_argument("words", nargs="+")
    ap.add_argument("--size", type=int)
    ap.add_argument("--order", type=int, default=22)
    ns = ap.parse_args(argv)
    w = ns.words
    _MIN = {"create": 2, "info": 2, "rm": 2, "resize": 2, "snap": 3,
            "clone": 3, "flatten": 2, "children": 2}
    if len(w) < _MIN.get(w[0], 1):
        ap.error(f"{' '.join(w)}: missing operand(s)")

    from ..client.rbd import RBD, Image, ImageExists, ImageNotFound
    rbd = RBD(ioctx)

    def emit(obj) -> int:
        out.write(json.dumps(obj, indent=2, sort_keys=True) + "\n")
        return 0

    try:
        if w[0] == "create":
            if ns.size is None:
                ap.error("create requires --size")
            rbd.create(w[1], ns.size, order=ns.order)
            return emit({"created": w[1], "size": ns.size})
        if w[0] == "ls":
            return emit(rbd.list())
        if w[0] == "info":
            img = Image(ioctx, w[1])
            return emit({"name": w[1], "size": img.size(),
                         "order": img.info.order,
                         "snaps": img.snap_list(),
                         "parent": img.parent})
        if w[0] == "rm":
            rbd.remove(w[1])
            return emit({"removed": w[1]})
        if w[0] == "resize":
            if ns.size is None:
                ap.error("resize requires --size")
            Image(ioctx, w[1]).resize(ns.size)
            return emit({"resized": w[1], "size": ns.size})
        if w[0] == "snap":
            if w[1] == "ls":
                return emit(Image(ioctx, w[2]).snap_list())
            name, snap = _split_at(w[2])
            img = Image(ioctx, name)
            if w[1] == "create":
                img.snap_create(snap)
            elif w[1] == "rollback":
                img.snap_rollback(snap)
            elif w[1] == "rm":
                img.snap_remove(snap)
            elif w[1] == "protect":
                img.protect_snap(snap)
            elif w[1] == "unprotect":
                img.unprotect_snap(snap)
            else:
                ap.error(f"unknown snap command {w[1]!r}")
            return emit({"snap": f"{name}@{snap}", "op": w[1]})
        if w[0] == "clone":
            parent, snap = _split_at(w[1])
            rbd.clone(parent, snap, w[2])
            return emit({"cloned": w[2], "parent": w[1]})
        if w[0] == "flatten":
            Image(ioctx, w[1]).flatten()
            return emit({"flattened": w[1]})
        if w[0] == "children":
            parent, snap = _split_at(w[1])
            img = Image(ioctx, parent)
            if snap not in img.snaps:
                raise KeyError(f"{parent} has no snap {snap!r}")
            # only the NAMED snap's clones (reference `rbd children`)
            return emit(sorted(
                img.snaps[snap].get("children", [])))
        ap.error(f"unknown command: {' '.join(w)}")
        return 2
    except (ImageExists, ImageNotFound, ValueError, KeyError) as e:
        out.write(f"{type(e).__name__}: {e}\n")
        return 1


if __name__ == "__main__":
    main()
