"""`ceph-kvstore-tool` — offline KV store surgery.

The reference tool (src/tools/kvstore_tool.cc behind
`ceph-kvstore-tool`): list/get/set/rm keys on a KeyValueDB and
compact it.  Here it operates on WalDB directories — the store under
the mon (MonitorDBStore), BlueStore metadata and FileStore metadata
all use the same engine, so one tool inspects them all.

    python -m ceph_tpu.tools.kvstore_tool <db-path> list [prefix]
    python -m ceph_tpu.tools.kvstore_tool <db-path> get <prefix> <key>
    python -m ceph_tpu.tools.kvstore_tool <db-path> set <prefix> <key> <file|->
    python -m ceph_tpu.tools.kvstore_tool <db-path> rm <prefix> <key>
    python -m ceph_tpu.tools.kvstore_tool <db-path> compact
    python -m ceph_tpu.tools.kvstore_tool <db-path> stats
"""
from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None, out=None,
         data_in: Optional[bytes] = None) -> int:
    out = out or sys.stdout
    ap = argparse.ArgumentParser(prog="ceph-kvstore-tool")
    ap.add_argument("path")
    ap.add_argument("words", nargs="+")
    ns = ap.parse_args(argv)
    from ..cluster.kv import WriteBatch
    from ..cluster.wal_kv import WalDB
    db = WalDB(ns.path, fsync=True)
    try:
        w = ns.words
        if w[0] == "list":
            prefixes = ([w[1]] if len(w) > 1 else
                        sorted({p for p, _ in db._keys}))
            for p in prefixes:
                for k, v in db.iterate(p):
                    out.write(f"{p}\t{k}\t({len(v)} bytes)\n")
            return 0
        if w[0] == "get":
            if len(w) < 3:
                ap.error("get needs <prefix> <key>")
            v = db.get(w[1], w[2])
            if v is None:
                out.write("(no such key)\n")
                return 1
            if hasattr(out, "buffer"):
                out.buffer.write(v)
            else:
                out.write(v.decode("latin-1"))
            return 0
        if w[0] == "set":
            if len(w) < 4:
                ap.error("set needs <prefix> <key> <file|->")
            data = data_in if w[3] == "-" and data_in is not None \
                else (sys.stdin.buffer.read() if w[3] == "-"
                      else open(w[3], "rb").read())
            db.submit(WriteBatch().set(w[1], w[2], data))
            out.write(f"set {w[1]}/{w[2]} ({len(data)} bytes)\n")
            return 0
        if w[0] == "rm":
            if len(w) < 3:
                ap.error("rm needs <prefix> <key>")
            if db.get(w[1], w[2]) is None:
                out.write("(no such key)\n")
                return 1
            db.submit(WriteBatch().rm(w[1], w[2]))
            out.write(f"removed {w[1]}/{w[2]}\n")
            return 0
        if w[0] == "compact":
            db.compact()
            out.write("compacted\n")
            return 0
        if w[0] == "stats":
            prefixes: dict = {}
            total = 0
            for p, k in db._keys:
                v = db._data[(p, k)]
                s = prefixes.setdefault(p, {"keys": 0, "bytes": 0})
                s["keys"] += 1
                s["bytes"] += len(v)
                total += len(v)
            for p in sorted(prefixes):
                s = prefixes[p]
                out.write(f"{p}\t{s['keys']} keys\t{s['bytes']} bytes\n")
            out.write(f"TOTAL\t{sum(s['keys'] for s in prefixes.values())}"
                      f" keys\t{total} bytes\n")
            return 0
        ap.error(f"unknown command {w[0]!r}")
        return 2
    finally:
        db.close()


if __name__ == "__main__":
    sys.exit(main())
