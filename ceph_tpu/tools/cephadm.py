"""cephadm analog — declarative cluster orchestration (VERDICT r4
next #7; the L11 gap).

The reference's cephadm (src/cephadm/cephadm, ~8k lines) turns a
declarative service spec into a running containerized cluster and
performs health-gated rolling operations (restart, upgrade) against
it; ceph-volume provisions each OSD's backing store.  Same roles
here, against the process cluster:

  * ``ClusterSpec`` — the declarative input: mons, hosts with OSD
    counts, pools, a version string.  JSON on disk (``spec.json``).
  * ``CephAdm.deploy`` — provision (cluster dir, crushmap from the
    host layout, keyrings, per-OSD stores — the ceph-volume role) +
    launch every daemon + wait for health.
  * ``CephAdm.rolling_restart`` / ``upgrade`` — restart daemons ONE
    at a time, each gated on the cluster returning to health before
    the next goes down (the reference's ok-to-stop sequencing);
    upgrade additionally records the new version per daemon in the
    mon's central config db, so ``status`` shows upgrade progress
    exactly the way `ceph orch upgrade status` does.

The deployed spec and versions are COMMITTED mon state (config db
keys ``cephadm/spec`` and ``cephadm/version/*``): any client can
audit what the orchestrator deployed, and a mon restart replays it.

CLI: ``python -m ceph_tpu.tools.cephadm deploy|status|restart|
upgrade|stop ...``
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class ClusterSpec:
    """The declarative cluster description (service-spec role)."""
    name: str = "ceph-tpu"
    version: str = "1.0"
    mons: int = 1
    hosts: List[Dict] = field(default_factory=list)
    pools: List[Dict] = field(default_factory=list)
    fsync: bool = False
    objectstore: str = "bluestore"

    @property
    def n_osds(self) -> int:
        return sum(int(h.get("osds", 1)) for h in self.hosts)

    @property
    def osds_per_host(self) -> int:
        counts = {int(h.get("osds", 1)) for h in self.hosts}
        if len(counts) != 1:
            raise ValueError(
                "hosts must carry equal osd counts (crush builder "
                "provisions uniform hosts)")
        return counts.pop()

    @staticmethod
    def load(path: str) -> "ClusterSpec":
        d = json.load(open(path))
        return ClusterSpec(**d)

    def save(self, path: str) -> None:
        json.dump(self.__dict__, open(path, "w"), indent=1)


class HealthGateTimeout(IOError):
    pass


class CephAdm:
    """Orchestrator over one deployed cluster directory."""

    def __init__(self, cluster_dir: str):
        # daemons spawn with the repo as cwd: a relative dir from the
        # operator's shell must resolve from HERE, not from there
        self.dir = os.path.abspath(cluster_dir)
        from .vstart import Vstart
        self.v = Vstart(self.dir)
        self._rc = None

    # ------------------------------------------------------------ client --
    def rc(self):
        if self._rc is None:
            from ..client.remote import RemoteCluster
            self._rc = RemoteCluster(self.dir)
        return self._rc

    def _drop_rc(self) -> None:
        if self._rc is not None:
            try:
                self._rc.close()
            except Exception:
                pass
            self._rc = None

    # ------------------------------------------------------------ deploy --
    @staticmethod
    def deploy(spec: ClusterSpec, cluster_dir: str,
               timeout: float = 60.0) -> "CephAdm":
        """Provision + launch + health-gate (the cephadm bootstrap +
        apply flow; store/keyring provisioning is the ceph-volume
        role inside build_cluster_dir)."""
        from .vstart import build_cluster_dir
        cluster_dir = os.path.abspath(cluster_dir)
        pools = spec.pools or [
            {"id": 1, "name": "rep", "type": 1, "size": 3,
             "pg_num": 16, "crush_rule": 0}]
        build_cluster_dir(
            cluster_dir, n_osds=spec.n_osds,
            osds_per_host=spec.osds_per_host, pools=pools,
            fsync=spec.fsync, n_mons=spec.mons,
            objectstore=spec.objectstore)
        adm = CephAdm(cluster_dir)
        adm.v.start(spec.n_osds)
        adm.wait_health(timeout=timeout)
        # the deployed spec + version are committed mon state
        adm.rc().mon_call({"cmd": "config_set", "key": "cephadm/spec",
                           "value": spec.__dict__})
        for i in range(spec.n_osds):
            adm.rc().mon_call({
                "cmd": "config_set",
                "key": f"cephadm/version/osd.{i}",
                "value": spec.version})
        return adm

    # ------------------------------------------------------------ health --
    def health_ok(self) -> bool:
        try:
            rc = self.rc()
            st = rc.mon_call({"cmd": "status"})
            if st["n_up"] < st["n_osds"]:
                return False
            ms = rc.mon_call({"cmd": "mon_status"})
            if ms.get("n_mons", 1) > 1 and ms.get("leader") is None:
                return False
            return True
        except (OSError, IOError):
            self._drop_rc()
            return False

    def _wait_mon_rejoined(self, rank: int, n_mons: int,
                           timeout: float) -> None:
        """Poll the JUST-RESTARTED mon's own socket until it reports
        a leader (single-mon: until it serves at all)."""
        from ..cluster.daemon import WireClient
        from ..common import auth as cx
        ring = cx.Keyring.load(
            os.path.join(self.dir, "keyring.client"))
        sock = os.path.join(
            self.dir, f"mon.{rank}.sock" if n_mons > 1 else "mon.sock")
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                c = WireClient(sock, "client.admin",
                               secret=ring.secret("client.admin"),
                               timeout=3.0)
                try:
                    st = c.call({"cmd": "mon_status"})
                finally:
                    c.close()
                if n_mons == 1 or st.get("leader") is not None:
                    return
            except (OSError, IOError, cx.AuthError):
                pass
            time.sleep(0.3)
        raise HealthGateTimeout(
            f"mon.{rank} did not rejoin within {timeout}s")

    def wait_health(self, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.health_ok():
                return
            time.sleep(0.5)
        raise HealthGateTimeout(
            f"cluster not healthy within {timeout}s")

    # ----------------------------------------------------------- rolling --
    def spec(self) -> ClusterSpec:
        d = self.rc().mon_call({"cmd": "config_get",
                                "key": "cephadm/spec"})["value"]
        return ClusterSpec(**d)

    def status(self) -> Dict:
        rc = self.rc()
        spec = self.spec()
        versions: Dict[str, Optional[str]] = {}
        for i in range(spec.n_osds):
            versions[f"osd.{i}"] = rc.mon_call({
                "cmd": "config_get",
                "key": f"cephadm/version/osd.{i}"})["value"]
        st = rc.mon_call({"cmd": "status"})
        ms = rc.mon_call({"cmd": "mon_status"})
        healthy = st["n_up"] >= st["n_osds"] and (
            ms.get("n_mons", 1) <= 1 or
            ms.get("leader") is not None)
        return {"spec": spec.__dict__, "health_ok": healthy,
                "n_up": st["n_up"], "versions": versions}

    def rolling_restart(self, version: Optional[str] = None,
                        timeout: float = 90.0) -> Dict:
        """Restart every daemon ONE at a time, health-gated: the next
        daemon goes down only after the cluster has fully re-healed
        (the ok-to-stop gate).  With ``version``, each restarted OSD
        records the new version in the mon config db (`ceph orch
        upgrade` semantics: version flips as the daemon cycles)."""
        spec = self.spec()
        restarted = []
        # mons first (the reference upgrades monitors first), then
        # OSDs — each gated
        for rank in range(spec.mons):
            name = f"mon.{rank}" if spec.mons > 1 else "mon"
            self.v.kill9(name)
            self._drop_rc()
            time.sleep(0.3)
            self.v.start_mon(rank)
            # the restarted mon itself must REJOIN (know the leader)
            # before the next one goes down — a surviving peer
            # reporting a leader is not the restarted rank's health
            self._wait_mon_rejoined(rank, spec.mons, timeout)
            self.wait_health(timeout=timeout)
            restarted.append(name)
        for i in range(spec.n_osds):
            self.v.kill9(f"osd.{i}")
            # give heartbeats a beat to notice, then restart
            time.sleep(0.3)
            self.v.start_osd(i)
            self.wait_health(timeout=timeout)
            if version is not None:
                self.rc().mon_call({
                    "cmd": "config_set",
                    "key": f"cephadm/version/osd.{i}",
                    "value": version})
            restarted.append(f"osd.{i}")
        if version is not None:
            spec.version = version
            self.rc().mon_call({"cmd": "config_set",
                                "key": "cephadm/spec",
                                "value": spec.__dict__})
        return {"restarted": restarted,
                "version": version or spec.version}

    def upgrade(self, new_version: str, timeout: float = 90.0) -> Dict:
        """Rolling upgrade: the rolling restart with the version bump
        recorded per daemon as it cycles."""
        return self.rolling_restart(version=new_version,
                                    timeout=timeout)

    def stop(self) -> None:
        self._drop_rc()
        self.v.stop()


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="cephadm")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("deploy")
    p.add_argument("spec")
    p.add_argument("dir")
    p = sub.add_parser("status")
    p.add_argument("dir")
    p = sub.add_parser("restart")
    p.add_argument("dir")
    p = sub.add_parser("upgrade")
    p.add_argument("dir")
    p.add_argument("version")
    p = sub.add_parser("stop")
    p.add_argument("dir")
    args = ap.parse_args(argv)
    if args.cmd == "deploy":
        CephAdm.deploy(ClusterSpec.load(args.spec), args.dir)
        print(json.dumps({"deployed": args.dir}))
        return 0
    adm = CephAdm(args.dir)
    if args.cmd == "status":
        print(json.dumps(adm.status(), indent=1))
    elif args.cmd == "restart":
        print(json.dumps(adm.rolling_restart()))
    elif args.cmd == "upgrade":
        print(json.dumps(adm.upgrade(args.version)))
    elif args.cmd == "stop":
        adm.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
