"""crushtool equivalent: test/simulate CRUSH maps from JSON specs.

Mirrors the `crushtool --test` harness (reference: src/tools/crushtool.cc:365
→ CrushTester, src/crush/CrushTester.cc:477-680): sweeps x over
[min_x, max_x] × rules × replica counts and reports per-device utilization
and statistics — but the sweep is one batched device call per rule
(CrushTester.cc:612's per-x loop collapsed into XlaMapper.map_batch).

Also compiles/decompiles the crushmap text language (`-c`/`-d`, the
CrushCompiler role, src/crush/CrushCompiler.cc): input maps may be
either JSON specs or `.crush` text (auto-detected).

Usage:
    python -m ceph_tpu.tools.crushtool --infn map.crush --test \
        --min-x 0 --max-x 1023 --rule 0 --num-rep 3 \
        --show-utilization [--scalar] [--weight OSD W]...
    python -m ceph_tpu.tools.crushtool -c map.crush -o map.json
    python -m ceph_tpu.tools.crushtool -d map.json [-o map.crush]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from collections import Counter

import numpy as np

from ..placement.crush_map import ITEM_NONE, WEIGHT_ONE, CrushMap
from ..placement import scalar_mapper


def run_test(cmap: CrushMap, args) -> int:
    rules = [args.rule] if args.rule is not None else [
        i for i, r in enumerate(cmap.rules) if r is not None]
    weights = [WEIGHT_ONE] * cmap.max_devices
    for osd, w in args.weight or []:
        if 0 <= osd < len(weights):
            weights[osd] = int(float(w) * WEIGHT_ONE)
    xs = np.arange(args.min_x, args.max_x + 1, dtype=np.int64)
    reps = range(args.min_rep, args.max_rep + 1) if args.num_rep is None \
        else [args.num_rep]
    for ruleno in rules:
        if ruleno >= cmap.max_rules or cmap.rules[ruleno] is None:
            print(f"rule {ruleno} dne", file=sys.stderr)
            continue
        for nrep in reps:
            t0 = time.perf_counter()
            if args.scalar:
                results = [scalar_mapper.do_rule(cmap, ruleno, int(x), nrep,
                                                 weights) for x in xs]
                results = np.asarray(
                    [r + [ITEM_NONE] * (nrep - len(r)) for r in results])
            else:
                from ..placement.xla_mapper import XlaMapper
                mapper = XlaMapper(cmap)
                results = mapper.map_batch(ruleno, xs, nrep, weights)
            dt = time.perf_counter() - t0
            valid = results != ITEM_NONE
            sizes = Counter(int(v) for v in valid.sum(axis=1))
            total = len(xs)
            if args.show_mappings:
                for i, x in enumerate(xs):
                    row = [int(o) for o in results[i] if o != ITEM_NONE]
                    print(f"CRUSH rule {ruleno} x {int(x)} {row}")
            if args.show_utilization:
                counts = Counter(
                    int(o) for o in results[valid.astype(bool)].ravel())
                expected = valid.sum() / max(
                    1, sum(1 for w in weights if w > 0))
                print(f"rule {ruleno} (num_rep {nrep}) "
                      f"num_osds_mapped {len(counts)}")
                for osd in sorted(counts):
                    dev = counts[osd] / expected if expected else 0.0
                    print(f"  device {osd}:\t\t stored : {counts[osd]}"
                          f"\t expected : {expected:.2f}"
                          f"\t deviation : {dev:.2f}")
            if args.show_statistics:
                for sz, n in sorted(sizes.items()):
                    print(f"rule {ruleno} (num_rep {nrep}) size {sz}:\t"
                          f"{n}/{total}")
            bad = total - sizes.get(nrep, 0)
            if args.show_bad_mappings and bad:
                print(f"rule {ruleno} (num_rep {nrep}): "
                      f"{bad}/{total} bad mappings")
            print(f"rule {ruleno} num_rep {nrep}: {total} mappings in "
                  f"{dt:.3f}s ({total / dt:,.0f} mappings/s)",
                  file=sys.stderr)
    return 0


def load_map(path: str) -> CrushMap:
    """JSON spec or crushmap text, auto-detected."""
    with open(path) as f:
        text = f.read()
    stripped = text.lstrip()
    if stripped.startswith("{"):
        return CrushMap.from_spec(json.loads(text))
    from ..placement.compiler import compile_crushmap
    return compile_crushmap(text)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="crushtool")
    ap.add_argument("--infn", "-i",
                    help="crush map: JSON spec or crushmap text")
    ap.add_argument("-c", "--compile", metavar="SRC",
                    help="compile crushmap text -> JSON spec")
    ap.add_argument("-d", "--decompile", metavar="SRC",
                    help="decompile map -> crushmap text")
    ap.add_argument("-o", "--outfn", help="output file (default stdout)")
    ap.add_argument("--test", action="store_true")
    ap.add_argument("--min-x", type=int, default=0)
    ap.add_argument("--max-x", type=int, default=1023)
    ap.add_argument("--rule", type=int, default=None)
    ap.add_argument("--num-rep", type=int, default=None)
    ap.add_argument("--min-rep", type=int, default=1)
    ap.add_argument("--max-rep", type=int, default=10)
    ap.add_argument("--show-utilization", action="store_true")
    ap.add_argument("--show-mappings", action="store_true")
    ap.add_argument("--show-statistics", action="store_true")
    ap.add_argument("--show-bad-mappings", action="store_true")
    ap.add_argument("--scalar", action="store_true",
                    help="use the scalar reference mapper (oracle)")
    ap.add_argument("--weight", nargs=2, action="append",
                    metavar=("OSD", "W"), type=float, default=None)
    ap.add_argument("--dump", action="store_true",
                    help="print the parsed map spec")
    ap.add_argument("--tree", action="store_true",
                    help="print the hierarchy (ceph osd tree style)")
    args = ap.parse_args(argv)
    if args.weight:
        args.weight = [(int(o), w) for o, w in args.weight]

    def emit(text: str) -> None:
        if args.outfn:
            with open(args.outfn, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)

    if args.compile:
        from ..placement.compiler import compile_crushmap
        with open(args.compile) as f:
            cmap = compile_crushmap(f.read())
        emit(json.dumps(cmap.to_spec(), indent=2) + "\n")
        return 0
    if args.decompile:
        from ..placement.compiler import decompile_crushmap
        emit(decompile_crushmap(load_map(args.decompile)))
        return 0
    if not args.infn:
        ap.error("need --infn (or -c/-d)")
    cmap = load_map(args.infn)
    if args.tree:
        from ..placement.treedump import tree_dump
        emit(tree_dump(cmap))          # honors -o like -c/-d
        return 0
    if args.dump:
        json.dump(cmap.to_spec(), sys.stdout, indent=2)
        print()
        return 0
    if args.test:
        return run_test(cmap, args)
    ap.error("nothing to do (--test, --dump, -c or -d)")
    return 1


if __name__ == "__main__":
    sys.exit(main())
