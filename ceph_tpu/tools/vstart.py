"""vstart — dev cluster launcher (the src/vstart.sh role).

Builds a cluster directory (crushmap text, pool spec, cephx keyrings,
per-daemon durable stores), then launches ONE mon process and N OSD
processes (``python -m ceph_tpu.cluster.daemon``) talking authenticated
typed envelopes over unix sockets.  The chaos tier kills these with
real SIGKILL and restarts them against the same stores.

Usage (also importable as a library — tests drive Vstart directly):
    python -m ceph_tpu.tools.vstart --dir /tmp/c1 --osds 6 start
    python -m ceph_tpu.tools.vstart --dir /tmp/c1 status
    python -m ceph_tpu.tools.vstart --dir /tmp/c1 stop
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ..common import auth as cx


def build_cluster_dir(cluster_dir: str, n_osds: int = 6,
                      osds_per_host: int = 2,
                      pools: Optional[List[dict]] = None,
                      fsync: bool = True, n_mons: int = 1,
                      objectstore: str = "bluestore",
                      bluestore_device_bytes: int = 1 << 28,
                      bluestore_min_alloc_size: int = 4096,
                      bluestore_compression: str = "",
                      fsck_on_mount: bool = False,
                      ms_inject_socket_failures: int = 0,
                      qos_tenants: Optional[Dict[str, dict]] = None
                      ) -> None:
    """Write crushmap.txt, cluster.json and keyrings.

    ``qos_tenants``: {tenant: {"res": r, "wgt": w, "lim": l}} —
    per-tenant dmClock client-class overrides every OSD daemon loads
    at boot (the osd_mclock_scheduler_client_* per-client profiles).
    """
    os.makedirs(cluster_dir, exist_ok=True)
    from ..placement.builder import TYPE_HOST, build_flat_cluster
    from ..placement.compiler import decompile_crushmap
    from ..placement.crush_map import (
        RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP, RULE_EMIT,
        RULE_TAKE, Rule)
    n_hosts = -(-n_osds // osds_per_host)
    cmap, root = build_flat_cluster(n_hosts=n_hosts,
                                    osds_per_host=osds_per_host)
    cmap.add_rule(Rule(steps=[(RULE_TAKE, root, 0),
                              (RULE_CHOOSELEAF_FIRSTN, 0, TYPE_HOST),
                              (RULE_EMIT, 0, 0)], name="replicated"))
    cmap.add_rule(Rule(steps=[(RULE_TAKE, root, 0),
                              (RULE_CHOOSELEAF_INDEP, 0, TYPE_HOST),
                              (RULE_EMIT, 0, 0)], name="ec"))
    with open(os.path.join(cluster_dir, "crushmap.txt"), "w") as f:
        f.write(decompile_crushmap(cmap))
    if pools is None:
        pools = [{"id": 1, "name": "rep", "type": 1, "size": 3,
                  "pg_num": 16, "crush_rule": 0}]
    json.dump({"pools": pools, "fsync": fsync, "n_osds": n_osds,
               "n_mons": n_mons, "objectstore": objectstore,
               "bluestore_device_bytes": bluestore_device_bytes,
               "bluestore_min_alloc_size": bluestore_min_alloc_size,
               "bluestore_compression_algorithm": bluestore_compression,
               "fsck_on_mount": fsck_on_mount,
               "ms_inject_socket_failures": ms_inject_socket_failures,
               "qos_tenants": qos_tenants or {}},
              open(os.path.join(cluster_dir, "cluster.json"), "w"))
    names = ["mon.", "client.admin"] + \
        [f"mon.{r}" for r in range(n_mons)] + \
        [f"osd.{i}" for i in range(n_osds)]
    ring = cx.Keyring.generate(names)
    ring.save(os.path.join(cluster_dir, "keyring.mon"))
    ring.subset("client.admin").save(
        os.path.join(cluster_dir, "keyring.client"))
    for i in range(n_osds):
        ring.subset(f"osd.{i}").save(
            os.path.join(cluster_dir, f"keyring.osd.{i}"))


class Vstart:
    """Process supervisor for one dev cluster."""

    def __init__(self, cluster_dir: str):
        self.dir = cluster_dir
        self.procs: Dict[str, subprocess.Popen] = {}

    def _spawn(self, *args: str,
               log_name: Optional[str] = None) -> subprocess.Popen:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"      # daemons never touch the TPU
        # share the persistent XLA compilation cache: dozens of daemon
        # processes otherwise re-compile the same tiny jitted helpers
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env.setdefault("JAX_COMPILATION_CACHE_DIR",
                       os.path.join(repo, ".jax_cache"))
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                       "0.5")
        # daemon stderr lands in <dir>/<name>.log (the vstart.sh
        # out/ dir role): a daemon that dies to an unhandled
        # exception must leave its traceback somewhere a human — or
        # a flake hunt — can find it, not in /dev/null
        err = subprocess.DEVNULL
        if log_name is not None:
            err = open(os.path.join(self.dir, f"{log_name}.log"),
                       "ab")
        p = subprocess.Popen(
            [sys.executable, "-m", "ceph_tpu.cluster.daemon", *args],
            env=env, stdout=subprocess.DEVNULL,
            stderr=err,
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
        if err is not subprocess.DEVNULL:
            err.close()                   # the child owns the fd now
        return p

    @staticmethod
    def _clear_stale_sock(path: str) -> None:
        """A SIGKILLed daemon leaves its socket file behind; remove it
        so the readiness wait below observes the NEW daemon's bind,
        not a stale file that refuses connections."""
        try:
            os.unlink(path)
        except OSError:
            pass

    def _n_mons(self) -> int:
        from ..cluster.daemon import mon_sockets
        return len(mon_sockets(self.dir))

    def start_mon(self, rank: int = 0, timeout: float = 30.0) -> None:
        from ..cluster.daemon import mon_sockets
        sock = mon_sockets(self.dir)[rank]
        self._clear_stale_sock(sock)
        p = self._spawn("mon", "--cluster-dir", self.dir,
                        "--id", str(rank),
                        log_name=f"mon.{rank}")
        self.procs[f"mon.{rank}"] = p
        if rank == 0:
            self.procs["mon"] = p          # legacy alias
        self._wait_sock(sock, timeout)

    def start_osd(self, osd_id: int, timeout: float = 30.0,
                  hb_interval: float = 0.5) -> None:
        sock = os.path.join(self.dir, f"osd.{osd_id}.sock")
        self._clear_stale_sock(sock)
        self.procs[f"osd.{osd_id}"] = self._spawn(
            "osd", "--cluster-dir", self.dir, "--id", str(osd_id),
            "--hb-interval", str(hb_interval),
            log_name=f"osd.{osd_id}")
        self._wait_sock(sock, timeout)

    @staticmethod
    def _wait_sock(path: str, timeout: float) -> None:
        t_end = time.monotonic() + timeout
        while time.monotonic() < t_end:
            if os.path.exists(path):
                return
            time.sleep(0.05)
        raise TimeoutError(f"daemon socket {path} never appeared")

    def start(self, n_osds: int, hb_interval: float = 0.5) -> None:
        for r in range(self._n_mons()):
            self.start_mon(r)
        for i in range(n_osds):
            self.start_osd(i, hb_interval=hb_interval)

    def kill9(self, name: str) -> None:
        """Real SIGKILL — the Thrasher's kill_osd."""
        p = self.procs.get(name)
        if p and p.poll() is None:
            os.kill(p.pid, signal.SIGKILL)
            p.wait()

    def stop(self) -> None:
        for name, p in self.procs.items():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        self.procs.clear()

    def alive(self, name: str) -> bool:
        p = self.procs.get(name)
        return p is not None and p.poll() is None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="vstart")
    ap.add_argument("--dir", required=True)
    ap.add_argument("--osds", type=int, default=6)
    ap.add_argument("action", choices=["start", "stop", "status"])
    args = ap.parse_args(argv)
    # daemons spawn with the repo as cwd: a relative dir from the
    # operator's shell must resolve from HERE, not from there
    args.dir = os.path.abspath(args.dir)
    if args.action == "start":
        if not os.path.exists(os.path.join(args.dir, "cluster.json")):
            build_cluster_dir(args.dir, n_osds=args.osds)
        v = Vstart(args.dir)
        v.start(args.osds)
        pids = {n: p.pid for n, p in v.procs.items()}
        json.dump(pids, open(os.path.join(args.dir, "pids.json"), "w"))
        print(json.dumps(pids))
        # detach: daemons keep running
        return 0
    if args.action == "stop":
        try:
            pids = json.load(open(os.path.join(args.dir, "pids.json")))
        except FileNotFoundError:
            return 0
        for name, pid in pids.items():
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
        print("stopped")
        return 0
    # status
    from ..cluster.daemon import WireClient
    ring = cx.Keyring.load(os.path.join(args.dir, "keyring.client"))
    mon = WireClient(os.path.join(args.dir, "mon.sock"), "client.admin",
                     secret=ring.secret("client.admin"))
    print(json.dumps(mon.call({"cmd": "status"})))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
