"""Offline tools: crushtool/osdmaptool/ec benchmark equivalents
(reference: src/tools/crushtool.cc, src/tools/osdmaptool.cc,
src/test/erasure-code/ceph_erasure_code_benchmark.cc)."""
