"""objectstore-tool — offline FileStore surgery (ceph-objectstore-tool).

The reference tool (src/tools/ceph_objectstore_tool.cc) mounts a
stopped OSD's store for offline inspection and repair: list PGs and
objects, export/import objects, fsck, remove.  Same surface over the
durable FileStore:

    python -m ceph_tpu.tools.objectstore_tool --store DIR <op> [...]

    ops: list-pgs
         list [--pg POOL.PG]
         info  --pg POOL.PG --oid OID
         export --pg POOL.PG --oid OID --file OUT
         import --pg POOL.PG --oid OID --file IN
         remove --pg POOL.PG --oid OID
         fsck
         gc

Export files are JSON envelopes (data base64 + xattrs + omap), so an
object can move between stores byte-faithfully — the export/import
PG-surgery role.
"""
from __future__ import annotations

import argparse
import base64
import json
import sys
from typing import Tuple


def _pg(s: str) -> Tuple[int, int]:
    pool, pg = s.split(".")
    return int(pool), int(pg)


def _open(store_dir: str, fsck_on_mount: bool = False):
    from ..cluster.filestore import FileStore
    return FileStore(store_dir, fsync=False,
                     fsck_on_mount=fsck_on_mount)


def _obj_rows(fs, coll, oid):
    """xattr + omap rows for an object via the kv iterators (key schema
    comes from the store itself, never re-derived here)."""
    from ..cluster.filestore import _objkey
    out = {"xattrs": {}, "omap": {}}
    prefix_key = _objkey(coll, oid) + "\x00"
    for kind, dest in (("xattr", "xattrs"), ("omap", "omap")):
        for k, v in fs.kv.iterate(kind, start=prefix_key):
            if not k.startswith(prefix_key):
                break
            out[dest][k[len(prefix_key):]] = base64.b64encode(v).decode()
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="objectstore-tool")
    ap.add_argument("--store", required=True)
    ap.add_argument("op", choices=["list-pgs", "list", "info", "export",
                                   "import", "remove", "fsck", "gc"])
    ap.add_argument("--pg")
    ap.add_argument("--oid")
    ap.add_argument("--file")
    args = ap.parse_args(argv)
    _REQUIRED = {"info": ("pg", "oid"), "export": ("pg", "oid", "file"),
                 "import": ("file",), "remove": ("pg", "oid")}
    for need in _REQUIRED.get(args.op, ()):
        if getattr(args, need) is None:
            ap.error(f"{args.op} requires --{need}")
    fs = _open(args.store)
    try:
        if args.op == "list-pgs":
            for coll in fs.list_collections():
                print(f"{coll[0]}.{coll[1]}")
        elif args.op == "list":
            colls = [_pg(args.pg)] if args.pg else fs.list_collections()
            for coll in colls:
                for oid in fs.list_objects(coll):
                    print(f"{coll[0]}.{coll[1]}\t{oid}")
        elif args.op == "info":
            coll = _pg(args.pg)
            st = fs.stat(coll, args.oid)
            rows = _obj_rows(fs, coll, args.oid)
            print(json.dumps({"pg": args.pg, "oid": args.oid,
                              "size": st["size"],
                              "crc32": st["csum"],
                              "n_xattrs": len(rows["xattrs"]),
                              "n_omap": len(rows["omap"])}))
        elif args.op == "export":
            coll = _pg(args.pg)
            data = fs.read(coll, args.oid)
            env = {"pg": args.pg, "oid": args.oid,
                   "data": base64.b64encode(data).decode()}
            env.update(_obj_rows(fs, coll, args.oid))
            with open(args.file, "w") as f:
                json.dump(env, f)
            print(f"exported {args.oid} ({len(data)} bytes)")
        elif args.op == "import":
            from ..cluster.objectstore import Transaction
            with open(args.file) as f:
                env = json.load(f)
            # --pg/--oid override the export envelope's placement
            coll = _pg(args.pg or env["pg"])
            oid = args.oid or env["oid"]
            txn = Transaction()
            txn.write_full(coll, oid, base64.b64decode(env["data"]))
            for k, v in env.get("xattrs", {}).items():
                txn.setattr(coll, oid, k, base64.b64decode(v))
            for k, v in env.get("omap", {}).items():
                txn.omap_set(coll, oid, k, base64.b64decode(v))
            fs.apply_transaction(txn)
            print(f"imported {oid}")
        elif args.op == "remove":
            from ..cluster.objectstore import Transaction
            fs.apply_transaction(
                Transaction().remove(_pg(args.pg), args.oid))
            print(f"removed {args.oid}")
        elif args.op == "fsck":
            bad = fs.fsck()
            print(json.dumps({
                "bad_objects": [[list(c), o] for c, o in bad],
                "orphan_bytes": fs.last_fsck_orphan_bytes}))
            return 1 if bad else 0
        elif args.op == "gc":
            print(f"reclaimed {fs.gc_data_log()} bytes")
    finally:
        fs.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
