"""In-OSD object classes — the src/cls/ + ClassHandler role.

The reference executes registered "object class" methods INSIDE the
OSD, against the object an op targets (src/osd/ClassHandler.cc loading
cls_lock, cls_refcount, cls_rbd, ...; invoked via the CEPH_OSD_OP_CALL
op).  Same seam here: classes register (name, method) handlers that
run against a MethodContext scoped to one object on the PRIMARY's
objectstore; mutations are applied transactionally, so a failing
method leaves the object untouched.

Shipped classes (the reference's most-used pair):
  * lock     — advisory shared/exclusive object locks in an xattr
               (src/cls/lock/cls_lock.cc: lock/unlock/break_lock/info)
  * refcount — reference counting with put-deletes-at-zero
               (src/cls/refcount/cls_refcount.cc: get/put/read)

Surfaces: ClusterSim.exec_cls(...) (the OSD CALL op) and
IoCtx.exec(oid, cls, method, input) (the librados exec entry point).
"""
from __future__ import annotations

import json
from typing import Callable, Dict, Optional, Tuple

from .objectstore import ObjectStoreError, Transaction

Coll = Tuple[int, int]


class ClsError(IOError):
    pass


class MethodContext:
    """What a class method may touch: ONE object on the local store
    (the cls_method_context_t role)."""

    def __init__(self, store, coll: Coll, oid: str):
        self.store = store
        self.coll = coll
        self.oid = oid
        self._txn = Transaction()

    # -------------------------------------------------------------- read --
    def exists(self) -> bool:
        return self.store.exists(self.coll, self.oid)

    def read(self) -> bytes:
        return self.store.read(self.coll, self.oid)

    def getxattr(self, key: str) -> Optional[bytes]:
        try:
            return self.store.getattr(self.coll, self.oid, key)
        except (KeyError, ObjectStoreError):
            return None

    def omap_get(self, key: str) -> Optional[bytes]:
        try:
            return self.store.omap_get(self.coll, self.oid, key)
        except (KeyError, ObjectStoreError):
            return None

    # ------------------------------------------------------------- write --
    def create(self) -> None:
        self._txn.touch(self.coll, self.oid)

    def write_full(self, data: bytes) -> None:
        self._txn.write_full(self.coll, self.oid, data)

    def setxattr(self, key: str, value: bytes) -> None:
        self._txn.setattr(self.coll, self.oid, key, value)

    def omap_set(self, key: str, value: bytes) -> None:
        self._txn.omap_set(self.coll, self.oid, key, value)

    def remove(self) -> None:
        self._txn.remove(self.coll, self.oid)

    def commit(self) -> None:
        if len(self._txn.ops):
            self.store.apply_transaction(self._txn)
            self._txn = Transaction()


Method = Callable[[MethodContext, bytes], bytes]


class ClassHandler:
    """Registry + dispatcher (ClassHandler::open_class/get_method)."""

    def __init__(self):
        self._methods: Dict[Tuple[str, str], Method] = {}
        register_standard_classes(self)

    def register(self, cls: str, method: str, fn: Method) -> None:
        self._methods[(cls, method)] = fn

    def call(self, store, coll: Coll, oid: str, cls: str, method: str,
             inp: bytes = b"") -> bytes:
        fn = self._methods.get((cls, method))
        if fn is None:
            raise ClsError(f"no method {cls}.{method}")
        ctx = MethodContext(store, coll, oid)
        out = fn(ctx, inp)
        ctx.commit()
        return out


# ----------------------------------------------------------- cls_lock ----

_LOCK_XATTR = "cls_lock"


def _lock_state(ctx) -> dict:
    raw = ctx.getxattr(_LOCK_XATTR)
    return json.loads(raw.decode()) if raw else {"type": "", "holders": []}


def _lock_lock(ctx: MethodContext, inp: bytes) -> bytes:
    req = json.loads(inp.decode())          # {name, type, cookie}
    st = _lock_state(ctx)
    want = req["type"]                      # "exclusive" | "shared"
    holder = {"name": req["name"], "cookie": req.get("cookie", "")}
    if st["holders"]:
        if want == "exclusive" or st["type"] == "exclusive":
            if holder not in st["holders"]:
                raise ClsError("EBUSY: lock held")
    if not ctx.exists():
        ctx.create()
    if holder not in st["holders"]:
        st["holders"].append(holder)
    st["type"] = want if not st["holders"][:-1] else st["type"] or want
    ctx.setxattr(_LOCK_XATTR, json.dumps(st).encode())
    return b""


def _lock_unlock(ctx: MethodContext, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    st = _lock_state(ctx)
    holder = {"name": req["name"], "cookie": req.get("cookie", "")}
    if holder not in st["holders"]:
        raise ClsError("ENOENT: not a lock holder")
    st["holders"].remove(holder)
    if not st["holders"]:
        st["type"] = ""
    ctx.setxattr(_LOCK_XATTR, json.dumps(st).encode())
    return b""


def _lock_break(ctx: MethodContext, inp: bytes) -> bytes:
    req = json.loads(inp.decode())          # {name}: evict this holder
    st = _lock_state(ctx)
    st["holders"] = [h for h in st["holders"]
                     if h["name"] != req["name"]]
    if not st["holders"]:
        st["type"] = ""
    ctx.setxattr(_LOCK_XATTR, json.dumps(st).encode())
    return b""


def _lock_info(ctx: MethodContext, inp: bytes) -> bytes:
    return json.dumps(_lock_state(ctx)).encode()


# ------------------------------------------------------- cls_refcount ----

_REF_XATTR = "cls_refcount"


def _ref_get(ctx: MethodContext, inp: bytes) -> bytes:
    tag = inp.decode()
    raw = ctx.getxattr(_REF_XATTR)
    refs = json.loads(raw.decode()) if raw else []
    if tag not in refs:
        refs.append(tag)
    if not ctx.exists():
        ctx.create()
    ctx.setxattr(_REF_XATTR, json.dumps(refs).encode())
    return str(len(refs)).encode()


def _ref_put(ctx: MethodContext, inp: bytes) -> bytes:
    tag = inp.decode()
    raw = ctx.getxattr(_REF_XATTR)
    refs = json.loads(raw.decode()) if raw else []
    if tag in refs:
        refs.remove(tag)
    if not refs:
        if ctx.exists():
            ctx.remove()            # last ref drops the object
        return b"0"
    ctx.setxattr(_REF_XATTR, json.dumps(refs).encode())
    return str(len(refs)).encode()


def _ref_read(ctx: MethodContext, inp: bytes) -> bytes:
    raw = ctx.getxattr(_REF_XATTR)
    return raw if raw else b"[]"


def register_standard_classes(h: ClassHandler) -> None:
    h.register("lock", "lock", _lock_lock)
    h.register("lock", "unlock", _lock_unlock)
    h.register("lock", "break_lock", _lock_break)
    h.register("lock", "info", _lock_info)
    h.register("refcount", "get", _ref_get)
    h.register("refcount", "put", _ref_put)
    h.register("refcount", "read", _ref_read)
