"""Upmap balancer — calc_pg_upmaps as a batched workload.

Role of the reference's `OSDMap::calc_pg_upmaps` (src/osd/OSDMap.h:1428,
impl OSDMap.cc) driven by the mgr balancer module's upmap mode
(src/pybind/mgr/balancer/module.py:1019): compute per-OSD deviation
from target PG counts and emit `pg_upmap_items` exception-table entries
that move single replicas from overfull to underfull OSDs, without
violating the CRUSH rule's failure-domain separation.

Batched design: the expensive part — mapping every PG of every pool —
is one `map_pgs_batch` device sweep per pool per round; deviations,
candidate selection, and domain checks are NumPy/host logic on the
resulting [N, R] arrays.  Domain validity uses the map's ancestor
tables (the role of CrushWrapper::verify_upmap): a replacement OSD must
not share its failure-domain ancestor with any other OSD in the PG's
up set.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..placement.crush_map import (
    ITEM_NONE, RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP,
    RULE_CHOOSE_FIRSTN, RULE_CHOOSE_INDEP, CrushMap)
from .osdmap import OSDMap, PGPool


def rule_failure_domain(cmap: CrushMap, ruleno: int) -> int:
    """The bucket type a rule separates replicas across (the last
    choose step's type; 0 = device)."""
    rule = cmap.rules[ruleno]
    domain = 0
    for op, a1, a2 in rule.steps:
        if op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSE_INDEP,
                  RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP):
            domain = a2
    return domain


def osd_ancestors(cmap: CrushMap, domain_type: int) -> np.ndarray:
    """[max_devices] bucket id of each device's ancestor of
    ``domain_type`` (ITEM_NONE if unplaced); devices are their own
    domain when domain_type == 0."""
    anc = np.full(cmap.max_devices, ITEM_NONE, dtype=np.int64)
    if domain_type == 0:
        anc[:] = np.arange(cmap.max_devices)
        return anc
    # walk down from every bucket of the domain type
    shadows = set(cmap.class_bucket_ids.values())
    for b in cmap.buckets:
        if b is None or b.type != domain_type or b.id in shadows:
            continue
        stack = [b.id]
        while stack:
            cur = stack.pop()
            cb = cmap.bucket(cur)
            if cb is None:
                continue
            for it in cb.items:
                if it >= 0:
                    if it < len(anc):
                        anc[it] = b.id
                else:
                    stack.append(it)
    return anc


def osd_crush_weights(cmap: CrushMap) -> np.ndarray:
    """[max_devices] 16.16 crush weight of each device (sum over
    appearances outside class shadows)."""
    w = np.zeros(cmap.max_devices, dtype=np.float64)
    shadows = set(cmap.class_bucket_ids.values())
    for b in cmap.buckets:
        if b is None or b.id in shadows:
            continue
        for pos, it in enumerate(b.items):
            if it >= 0 and it < len(w):
                w[it] += b.item_weight(pos)
    return w


@dataclass
class BalanceResult:
    rounds: int
    moves: int
    max_deviation_before: float
    max_deviation_after: float
    upmap_items: Dict[Tuple[int, int], List[Tuple[int, int]]] = \
        field(default_factory=dict)


def calc_pg_upmaps(om: OSDMap, pool_ids: Optional[Sequence[int]] = None,
                   max_deviation: float = 1.0, max_rounds: int = 32,
                   max_moves_per_round: int = 64) -> BalanceResult:
    """Greedy upmap optimization (OSDMap::calc_pg_upmaps semantics).

    Mutates ``om.pg_upmap_items`` (and bumps the epoch once if any
    moves landed); returns a summary.  Deviation is measured in
    replicas vs the crush-weight-proportional target over in+up OSDs.
    """
    pools = [om.pools[p] for p in (pool_ids or sorted(om.pools))]
    cw = osd_crush_weights(om.crush)
    in_w = (om.osd_weight[:len(cw)] / 0x10000) * om.osd_up[:len(cw)] * \
        om.osd_exists[:len(cw)]
    eff = cw * in_w
    if eff.sum() <= 0:
        return BalanceResult(0, 0, 0.0, 0.0)
    domains = {p.id: osd_ancestors(om.crush,
                                   rule_failure_domain(om.crush,
                                                       p.crush_rule))
               for p in pools}
    total_moves = 0
    dev_before = None
    rounds = 0
    for rounds in range(1, max_rounds + 1):
        # one batched sweep per pool: PG -> up set
        ups = {p.id: om.map_pgs_batch(p.id)[0] for p in pools}
        counts = np.zeros(len(eff), dtype=np.float64)
        for p in pools:
            vals = ups[p.id][ups[p.id] != ITEM_NONE]
            np.add.at(counts, vals[(vals >= 0) & (vals < len(eff))], 1)
        total = counts.sum()
        target = eff / eff.sum() * total
        dev = counts - target
        if dev_before is None:
            dev_before = float(np.abs(dev).max())
        if np.abs(dev).max() <= max_deviation:
            break
        moves = 0
        # most-overfull first
        for src in np.argsort(-dev):
            if moves >= max_moves_per_round or dev[src] <= max_deviation:
                break
            src = int(src)
            for p in pools:
                up = ups[p.id]
                rows, cols = np.nonzero(up == src)
                if not len(rows):
                    continue
                dom = domains[p.id]
                order = np.argsort(dev)     # most-underfull candidates
                for r, c in zip(rows, cols):
                    pgid = (p.id, p.raw_pg_to_pg(int(r)))
                    if pgid in om.pg_upmap_items or pgid in om.pg_upmap:
                        continue            # one exception per PG
                    pg_doms = {dom[o] for o in up[r]
                               if o != ITEM_NONE and o != src}
                    dst = None
                    for cand in order:
                        cand = int(cand)
                        if dev[cand] >= -max_deviation / 2 and \
                                dev[cand] >= dev[src] - 1:
                            break
                        if eff[cand] <= 0 or cand in up[r]:
                            continue
                        if dom[cand] != ITEM_NONE and \
                                dom[cand] in pg_doms:
                            continue        # would collapse domains
                        dst = cand
                        break
                    if dst is None:
                        continue
                    om.pg_upmap_items[pgid] = \
                        om.pg_upmap_items.get(pgid, []) + [(src, dst)]
                    dev[src] -= 1
                    dev[dst] += 1
                    moves += 1
                    total_moves += 1
                    if dev[src] <= max_deviation or \
                            moves >= max_moves_per_round:
                        break
                if dev[src] <= max_deviation or \
                        moves >= max_moves_per_round:
                    break
        if moves == 0:
            break
    # final measurement
    counts = np.zeros(len(eff), dtype=np.float64)
    for p in pools:
        up, _ = om.map_pgs_batch(p.id)
        vals = up[up != ITEM_NONE]
        np.add.at(counts, vals[(vals >= 0) & (vals < len(eff))], 1)
    target = eff / eff.sum() * counts.sum()
    dev_after = float(np.abs(counts - target).max())
    if total_moves:
        om.bump_epoch()
    return BalanceResult(rounds, total_moves, dev_before or 0.0,
                        dev_after, dict(om.pg_upmap_items))
