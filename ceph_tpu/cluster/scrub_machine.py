"""Chunked, reservation-gated background scrub statechart.

VERDICT r2 missing #6: the repo's scrub was a synchronous full pass;
the reference runs scrub as a boost::statechart machine
(src/osd/scrub_machine.cc, pg_scrubber.cc): reserve replica scrub
slots, then loop chunk-by-chunk — select an object range, wait for
in-flight writes, build per-replica scrub maps, compare — releasing
the reservations at the end, and restarting a chunk that a concurrent
write preempted.

Same shape here, driven by explicit ``tick()`` calls (one state step
per tick) so daemons and tests can pump it incrementally:

    INACTIVE -> RESERVING -> NEW_CHUNK -> BUILD_MAPS -> COMPARE_MAPS
         ^          |            ^______________________/   |
         |          v (slots busy: stay RESERVING)           v
         +------ FINISHED  <---------------- (no more objects)

Reservations model osd_max_scrubs (default 1 concurrent scrub per
OSD): a second machine touching any reserved OSD waits in RESERVING —
the backoff/reservation protocol of ScrubReservations.  Preemption:
each chunk snapshots the PG log head; if a write lands in the chunk's
range before COMPARE_MAPS, the chunk is rebuilt (the reference's
write-blocked/preempted chunk replay).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

# states
INACTIVE = "inactive"
RESERVING = "reserving"
NEW_CHUNK = "new_chunk"
BUILD_MAPS = "build_maps"
COMPARE_MAPS = "compare_maps"
FINISHED = "finished"

OSD_MAX_SCRUBS = 1           # reference option osd_max_scrubs default


class ScrubReservations:
    """Cluster-wide replica scrub slots (one registry per sim)."""

    def __init__(self, max_scrubs: int = OSD_MAX_SCRUBS):
        self.max_scrubs = max_scrubs
        self._held: Dict[int, int] = {}

    def try_reserve(self, osds: List[int]) -> bool:
        if any(self._held.get(o, 0) >= self.max_scrubs for o in osds):
            return False
        for o in osds:
            self._held[o] = self._held.get(o, 0) + 1
        return True

    def release(self, osds: List[int]) -> None:
        for o in osds:
            n = self._held.get(o, 0) - 1
            if n <= 0:
                self._held.pop(o, None)
            else:
                self._held[o] = n


@dataclass
class ScrubResult:
    pg: Tuple[int, int]
    objects_scrubbed: int = 0
    chunks: int = 0
    preemptions: int = 0
    reserve_waits: int = 0
    inconsistent: List[Tuple[str, int]] = field(default_factory=list)
    missing: List[Tuple[str, int]] = field(default_factory=list)


class ScrubMachine:
    """One PG's scrub, advanced a state per tick()."""

    def __init__(self, sim, pool_id: int, pg: int,
                 reservations: Optional[ScrubReservations] = None,
                 chunk_objects: int = 4):
        self.sim = sim
        self.pool = sim.osdmap.pools[pool_id]
        self.pg = pg
        self.chunk_objects = chunk_objects
        self.reservations = reservations if reservations is not None \
            else ScrubReservations()
        self.state = INACTIVE
        self.result = ScrubResult(pg=(pool_id, pg))
        self._todo: List[str] = []
        self._chunk: List[str] = []
        self._chunk_version = None
        self._maps: Dict[str, Dict[int, Optional[bytes]]] = {}
        self._reserved: List[int] = []

    # ------------------------------------------------------------- drive --
    def start(self) -> None:
        if self.state != INACTIVE:
            raise RuntimeError(f"scrub already {self.state}")
        self.state = RESERVING

    def tick(self) -> str:
        """Advance one state step; returns the state AFTER the step."""
        handler = {
            RESERVING: self._tick_reserving,
            NEW_CHUNK: self._tick_new_chunk,
            BUILD_MAPS: self._tick_build_maps,
            COMPARE_MAPS: self._tick_compare,
        }.get(self.state)
        if handler is not None:
            handler()
        return self.state

    def run_to_completion(self, max_ticks: int = 10_000) -> ScrubResult:
        if self.state == INACTIVE:
            self.start()
        try:
            for _ in range(max_ticks):
                if self.state == FINISHED:
                    return self.result
                self.tick()
        except Exception:
            self.abort()
            raise
        self.abort()
        raise RuntimeError("scrub did not finish (stuck reservations?)")

    def abort(self) -> None:
        """Release held reservation slots (idempotent) — abandoned or
        failed machines must not starve later scrubs."""
        if self._reserved:
            self.reservations.release(self._reserved)
            self._reserved = []
        if self.state != FINISHED:
            self.state = INACTIVE

    # ------------------------------------------------------------- states --
    def _up(self) -> List[int]:
        from ..placement.crush_map import ITEM_NONE
        up = self.sim.pg_up(self.pool, self.pg)
        return [o for o in up if o != ITEM_NONE]

    def _tick_reserving(self) -> None:
        osds = self._up()
        if not self.reservations.try_reserve(osds):
            self.result.reserve_waits += 1      # stay RESERVING
            return
        self._reserved = osds
        self._todo = sorted(
            name for (pid, name) in self.sim.objects
            if pid == self.pool.id and "@" not in name and
            self.sim.object_pg(self.pool, name) == self.pg)
        self.state = NEW_CHUNK

    def _head_version(self):
        log = self.sim.pg_logs.get((self.pool.id, self.pg))
        return log.head if log is not None else None

    def _tick_new_chunk(self) -> None:
        if not self._todo:
            self._finish()
            return
        self._chunk = self._todo[:self.chunk_objects]
        self._chunk_version = self._head_version()
        self._maps = {}
        self.state = BUILD_MAPS

    def _tick_build_maps(self) -> None:
        """Per-object digests over the chunk (the replica scrub-map
        build).  EC pools digest per SHARD INDEX; replicated pools
        digest the shard-0 copy ON EACH REPLICA OSD individually, so
        divergent replicas are comparable.  Shard payloads are kept for
        the chunk's lifetime so the deep compare doesn't re-read."""
        import zlib
        from ..placement.crush_map import ITEM_NONE
        from .osdmap import POOL_ERASURE
        up = self.sim.pg_up(self.pool, self.pg)

        def digest(f):
            return None if f is None else \
                zlib.crc32(f.tobytes()).to_bytes(4, "little") + \
                len(f).to_bytes(8, "little")

        self._shards = {}
        for name in self._chunk:
            per_shard: Dict[int, Optional[bytes]] = {}
            payloads = {}
            if self.pool.type == POOL_ERASURE:
                for shard in range(self.pool.size):
                    f = self.sim._read_shard(self.pool.id, self.pg,
                                             name, shard, up)
                    if f is not None:
                        payloads[shard] = f
                    per_shard[shard] = digest(f)
            else:
                # replica axis: the same shard-0 object on each up OSD
                for pos, osd in enumerate(up):
                    f = None if osd == ITEM_NONE else self.sim.osds[
                        osd].get((self.pool.id, self.pg, name, 0))
                    if f is not None and pos not in payloads:
                        payloads[pos] = f
                    per_shard[pos] = digest(f)
            self._maps[name] = per_shard
            self._shards[name] = payloads
        self.state = COMPARE_MAPS

    def _tick_compare(self) -> None:
        # preemption: a write in this PG since the chunk started makes
        # the maps stale — redo the chunk (reference: preempted chunk)
        if self._head_version() != self._chunk_version:
            self.result.preemptions += 1
            self.state = NEW_CHUNK
            return
        from .osdmap import POOL_ERASURE
        codec = self.sim.codec_for(self.pool) \
            if self.pool.type == POOL_ERASURE else None
        for name in self._chunk:
            info = self.sim.objects.get((self.pool.id, name))
            if info is None:
                continue                     # deleted mid-scrub
            per_shard = self._maps[name]
            if codec is None:
                # replicated: every present replica digest must agree
                digests = [d for d in per_shard.values() if d is not None]
                for shard, d in per_shard.items():
                    if d is None:
                        self.result.missing.append((name, shard))
                if digests and len(set(digests)) > 1:
                    self.result.inconsistent.append((name, -1))
            else:
                k = codec.get_data_chunk_count()
                mm = codec.get_coding_chunk_count()
                for shard in range(k + mm):
                    if per_shard.get(shard) is None:
                        self.result.missing.append((name, shard))
                self._deep_compare_ec(codec, name, info, k, mm)
            self.result.objects_scrubbed += 1
        self._todo = self._todo[len(self._chunk):]
        self.result.chunks += 1
        self.state = NEW_CHUNK

    def _deep_compare_ec(self, codec, name, info, k, mm) -> None:
        """Deep scrub: re-encode data shards, compare stored parity
        (shard bytes come from the chunk's build_maps read)."""
        U = info.chunk_size
        files = {s: f for s, f in self._shards.get(name, {}).items()
                 if len(f) >= info.n_stripes * U}
        if not set(range(k)) <= set(files):
            return
        dchunks = np.stack(
            [files[c].reshape(info.n_stripes, U) for c in range(k)],
            axis=1)
        parity = np.asarray(codec.encode_chunks_batch(dchunks))
        for j in range(mm):
            if k + j in files:
                want = files[k + j].reshape(info.n_stripes, U)
                if not np.array_equal(parity[:, j], want):
                    self.result.inconsistent.append((name, k + j))

    def _finish(self) -> None:
        self.reservations.release(self._reserved)
        self._reserved = []
        self.state = FINISHED
