"""WalDB — durable ordered KV with crash-consistent write batches.

The role of RocksDBStore under the mon store and the object store's
metadata (src/kv/RocksDBStore.cc; src/mon/MonitorDBStore.h sits directly
on this seam).  Same interface as cluster/kv.py's MemDB (WriteBatch
submit / get / iterate / prefix scans), plus:

  * every submitted batch is appended to a write-ahead log as one
    length-prefixed, CRC32-protected record BEFORE mutating the
    in-memory index — the RocksDB WAL contract (batch atomicity +
    prefix durability);
  * mount() replays the WAL over the newest snapshot, discarding any
    torn tail (a partial append from a crash mid-write);
  * when the WAL exceeds ``compact_bytes``, the full state is written
    to a new snapshot (temp file + fsync + atomic rename, then a
    MANIFEST pointer flip) and the WAL restarts — the memtable-flush /
    compaction role.

Crash model: kill -9 at ANY instruction leaves the store mountable with
exactly the batches whose WAL record was fully written, in order (see
tests/test_durable.py's torn-write and kill -9 tests).

Record encoding (little-endian):
  WAL record:   u32 magic | u64 seq | u32 len | u32 crc | payload
  payload:      u32 n_ops | n x (u8 op | u16 plen | u16 klen | u32 vlen
                                 | prefix | key | value)
  snapshot:     u64 last_seq | records in the same framing (op=set)
"""
from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from typing import Iterator, List, Optional, Tuple

from . import blockdev
from .kv import MemDB, WriteBatch

_MAGIC = 0x57414C31                      # "WAL1"
_HDR = struct.Struct("<IQII")            # magic, seq, len, crc
_OPH = struct.Struct("<BHHI")            # op, plen, klen, vlen
_OPS = {"set": 1, "rm": 2, "rm_prefix": 3}
_OPS_R = {v: k for k, v in _OPS.items()}


def _encode_batch(ops) -> bytes:
    out = [struct.pack("<I", len(ops))]
    for op, prefix, key, value in ops:
        p = prefix.encode()
        k = key.encode()
        v = value if value is not None else b""
        out.append(_OPH.pack(_OPS[op], len(p), len(k), len(v)))
        out.append(p)
        out.append(k)
        out.append(v)
    return b"".join(out)


def _decode_batch(payload: bytes) -> List[Tuple]:
    (n,) = struct.unpack_from("<I", payload, 0)
    off = 4
    ops = []
    for _ in range(n):
        opc, plen, klen, vlen = _OPH.unpack_from(payload, off)
        off += _OPH.size
        prefix = payload[off:off + plen].decode(); off += plen
        key = payload[off:off + klen].decode(); off += klen
        value = payload[off:off + vlen]; off += vlen
        op = _OPS_R[opc]
        ops.append((op, prefix, key, value if op == "set" else None))
    return ops


class WalDB(MemDB):
    """MemDB index + write-ahead durability on a directory."""

    def __init__(self, path: str, *, fsync: bool = True,
                 compact_bytes: int = 64 << 20):
        super().__init__()
        self.path = path
        self.fsync = fsync
        self.compact_bytes = compact_bytes
        self._wlock = threading.Lock()
        self._seq = 0
        # cold-restart observability: what the last mount's WAL
        # replay cost (records/bytes applied, seconds) — the
        # bluestore.wal_replay_* perf counters read this
        self.replay_stats = {"records": 0, "bytes": 0, "seconds": 0.0}
        os.makedirs(path, exist_ok=True)
        self._mount()

    # ------------------------------------------------------------- mount --
    def _manifest_path(self) -> str:
        return os.path.join(self.path, "MANIFEST")

    def _wal_path(self) -> str:
        return os.path.join(self.path, "wal.log")

    def _mount(self) -> None:
        snap_id = 0
        mf = self._manifest_path()
        if os.path.exists(mf):
            try:
                snap_id = int(open(mf).read().strip() or "0")
            except ValueError:
                snap_id = 0
        if snap_id:
            self._load_snapshot(
                os.path.join(self.path, f"snap.{snap_id}"))
        self._replay_wal()
        # reopen the WAL for appends (preserving any replayed tail)
        # through the BlockDevice barrier API — every byte this store
        # persists must be visible to the crash-state recorder
        self._wal = blockdev.BlockDevice(self._wal_path())

    def _load_snapshot(self, path: str) -> None:
        with open(path, "rb") as f:
            blob = f.read()
        (self._seq,) = struct.unpack_from("<Q", blob, 0)
        off = 8
        crc_stored, ln = struct.unpack_from("<II", blob, off)
        off += 8
        payload = blob[off:off + ln]
        if len(payload) != ln or zlib.crc32(payload) != crc_stored:
            raise IOError(f"snapshot {path} corrupt")
        batch = WriteBatch()
        batch.ops = _decode_batch(payload)
        MemDB.submit(self, batch)

    def _replay_wal(self) -> None:
        path = self._wal_path()
        if not os.path.exists(path):
            return
        t0 = time.perf_counter()
        with open(path, "rb") as f:
            blob = f.read()
        off = 0
        good_end = 0
        replayed = 0
        while off + _HDR.size <= len(blob):
            magic, seq, ln, crc = _HDR.unpack_from(blob, off)
            if magic != _MAGIC:
                break
            payload = blob[off + _HDR.size:off + _HDR.size + ln]
            if len(payload) != ln or zlib.crc32(payload) != crc:
                break                     # torn tail: discard
            if seq > self._seq:          # records <= snapshot seq skip
                batch = WriteBatch()
                batch.ops = _decode_batch(payload)
                MemDB.submit(self, batch)
                self._seq = seq
                replayed += 1
            off += _HDR.size + ln
            good_end = off
        if good_end < len(blob):
            # truncate the torn tail so future appends are clean
            dev = blockdev.BlockDevice(path)
            dev.truncate(good_end)
            dev.close()
        self.replay_stats = {"records": replayed, "bytes": good_end,
                             "seconds": time.perf_counter() - t0}

    # ------------------------------------------------------------- write --
    def submit(self, batch: WriteBatch) -> None:
        payload = _encode_batch(batch.ops)
        with self._wlock:
            self._seq += 1
            rec = _HDR.pack(_MAGIC, self._seq, len(payload),
                            zlib.crc32(payload)) + payload
            # the durability order IS the contract CrashDev proves:
            # WAL record on media and fsynced BEFORE the in-memory
            # index mutates (= before any caller can observe the
            # batch as committed)
            self._wal.append(rec)
            if self.fsync:
                self._wal.fsync()
            MemDB.submit(self, batch)
            if self._wal.tell() >= self.compact_bytes:
                self._compact_locked()

    def sync(self) -> None:
        with self._wlock:
            self._wal.fsync()

    # ----------------------------------------------------------- compact --
    def _compact_locked(self) -> None:
        """Snapshot full state, flip MANIFEST, restart the WAL."""
        snap_id = self._seq
        ops = [("set", p, k, self._data[(p, k)]) for p, k in self._keys]
        payload = _encode_batch(ops)
        tmp = os.path.join(self.path, "snap.tmp")
        # write-tmp / fsync / atomic-rename: the snapshot's bytes are
        # on media BEFORE any name points at them (the idiom that
        # makes blockdev's ordered-rename crash model sound)
        dev = blockdev.BlockDevice(tmp, fresh=True)
        dev.append(struct.pack("<Q", self._seq))
        dev.append(struct.pack("<II", zlib.crc32(payload),
                               len(payload)))
        dev.append(payload)
        dev.fsync()
        dev.close()
        final = os.path.join(self.path, f"snap.{snap_id}")
        blockdev.replace(tmp, final)
        mtmp = self._manifest_path() + ".tmp"
        dev = blockdev.BlockDevice(mtmp, fresh=True)
        dev.append(str(snap_id).encode())
        dev.fsync()
        dev.close()
        blockdev.replace(mtmp, self._manifest_path())
        # WAL restart: records up to _seq are in the snapshot
        self._wal.close()
        self._wal = blockdev.BlockDevice(self._wal_path(), fresh=True)
        # drop superseded snapshots
        for name in os.listdir(self.path):
            if name.startswith("snap.") and name != f"snap.{snap_id}" \
                    and name != "snap.tmp":
                blockdev.unlink(os.path.join(self.path, name))

    def compact(self) -> None:
        with self._wlock:
            self._compact_locked()

    def close(self) -> None:
        with self._wlock:
            if self._wal and not self._wal.closed:
                if self.fsync:
                    self._wal.fsync()
                self._wal.close()
