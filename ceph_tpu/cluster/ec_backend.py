"""ECBackend — the PGBackend seam, shared by both cluster tiers.

The reference instantiates ONE abstract IO backend per PG and picks
Replicated vs EC by pool type (PGBackend::build_pg_backend,
src/osd/PGBackend.cc:571); ECBackend then owns the stripe math, the
encode-on-write / decode-on-degraded-read pipelines and recovery
reconstruction (src/osd/ECBackend.cc:934,1015,757), calling the codec
through the plugin registry.  Here the same seam exists with the tiers
split along the TPU boundary instead of the process boundary:

  * ``ECBackend`` (this class) is the data-plane ENGINE: batched
    word-domain encode dispatches, shard-ref construction (zero-copy
    columns of the encode buffers, cluster/device_store.py),
    minimum_to_decode planning, signature-GROUPED decode (all objects
    that lost the same shard set decode in ONE kernel call — the
    ISA-L table-cache idea lifted to whole dispatch batches,
    src/erasure-code/isa/ErasureCodeIsaTableCache.h:35), and degraded
    assembly.
  * ``ShardIO`` is the transport half: WHERE shard bytes/refs live
    and how sub-ops reach them.  The wire client implements it over
    authenticated sockets to OSD daemons plus a client-side HBM
    staging cache (the client is the TPU-attached primary,
    ARCHITECTURE.md §4: client/remote.py WireShardIO); the in-process
    simulator implements it over its SimOSD async service queues
    (cluster/simulator.py SimShardIO).

One engine, two transports — the structural fix for the two-tier
divergence VERDICT r4 called out (Missing #1/#5).
"""
from __future__ import annotations

import abc
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..placement.crush_map import ITEM_NONE

ShardKey = Tuple[int, int, str, int]     # (pool, pg, name, shard)


class SubWrite:
    """One shard sub-op of an EC write (the MOSDECSubOpWrite payload,
    src/osd/ECBackend.cc:1976): destination + the shard's durable
    bytes (lazy) + its zero-copy device ref + object metadata."""

    __slots__ = ("pg", "shard", "target", "name", "ref", "bytes_fn",
                 "attrs")

    def __init__(self, pg, shard, target, name, ref, bytes_fn, attrs):
        self.pg = pg
        self.shard = shard
        self.target = target
        self.name = name
        self.ref = ref                  # ShardRef (device plane words)
        self.bytes_fn = bytes_fn        # () -> bytes | None (durable)
        self.attrs = attrs


class ShardIO(abc.ABC):
    """Transport seam: sub-op delivery + shard retrieval for one pool."""

    @abc.abstractmethod
    def up_set(self, pg: int) -> List[int]:
        """Acting/up OSDs of a PG, positional by shard id."""

    @abc.abstractmethod
    def fanout(self, writes: Sequence[SubWrite]) -> List[SubWrite]:
        """Deliver sub-writes concurrently; return the COMMITTED ones
        (the gather half of issue_repop: the caller decides whether
        the commit set satisfies the write contract)."""

    @abc.abstractmethod
    def purge_shard(self, pg: int, shard: int, name: str,
                    keep_target: Optional[int]) -> None:
        """Remove stale copies of a shard everywhere but its new home
        (a failed/re-homed sub-write must not leave an older version
        servable)."""

    @abc.abstractmethod
    def get_shard_ref(self, pg: int, shard: int, name: str):
        """The shard as a device ShardRef (HBM staging hit or upload),
        or None when this transport/holder cannot serve it."""

    @abc.abstractmethod
    def get_shard_bytes(self, pg: int, shard: int,
                        name: str) -> Optional[bytes]:
        """The shard's durable bytes, or None when absent."""

    @abc.abstractmethod
    def getattr(self, pg: int, name: str, shard: int,
                key: str) -> Optional[bytes]:
        """One shard attr (object_info metadata travels as attrs)."""


class ObjectGeom:
    """Stripe geometry of one stored object (stripe_info_t role,
    src/osd/ECUtil.h:28-60): S stripes of k chunks of U bytes."""

    __slots__ = ("size", "S", "U")

    def __init__(self, size: int, S: int, U: int):
        self.size = int(size)
        self.S = int(S)
        self.U = int(U)

    @property
    def W(self) -> int:
        return self.U // 4

    def attrs(self) -> Dict[str, bytes]:
        return {"size": str(self.size).encode(),
                "S": str(self.S).encode(),
                "U": str(self.U).encode()}


class ECBackend:
    """The EC data-plane engine over a ShardIO transport."""

    def __init__(self, codec, shard_io: ShardIO):
        self.codec = codec
        self.io = shard_io
        self.k = codec.get_data_chunk_count()
        self.n = codec.get_chunk_count()
        self.m = self.n - self.k

    # ------------------------------------------------------------ layout --
    def words_supported(self) -> bool:
        return hasattr(self.codec, "encode_words_device") and \
            getattr(self.codec, "layout", None) == "bitsliced"

    def to_words(self, payload, S: int, U: int):
        """Payload (host bytes/array or device u8/i32) -> [S, k, W]
        int32 plane words, the at-rest domain."""
        import jax
        import jax.numpy as jnp
        W = U // 4
        if isinstance(payload, (bytes, bytearray, memoryview)):
            payload = np.frombuffer(payload, dtype=np.uint8)
        if isinstance(payload, np.ndarray):
            return jnp.asarray(np.ascontiguousarray(payload)
                               .view(np.int32).reshape(S, self.k, W))
        if payload.dtype == jnp.int32:
            return payload.reshape(S, self.k, W)
        u8 = payload.reshape(S, self.k, W, 4)
        return jax.lax.bitcast_convert_type(u8, jnp.int32)

    def batch_geometry(self, lengths: Sequence[int],
                       stripe_unit: int) -> Tuple[int, int]:
        """Common (S, U) for a same-batch object set: every object
        pads to S stripes of k chunks of U bytes.  U is clamped to
        >= 32 so chunks stay 32-byte aligned for the bitsliced plane
        view (the SIMD_ALIGN role, ErasureCode.cc:42)."""
        U = max(32, int(stripe_unit))
        stripe = self.k * U
        S = max(1, -(-max(lengths) // stripe))
        return S, U

    # ------------------------------------------------------- write path --
    def encode_to_writes(self, pg_of: Dict[str, int],
                         names: Sequence[str], payload,
                         geom: ObjectGeom,
                         durable: bool = True,
                         sizes: Optional[Dict[str, int]] = None,
                         d_host=None) -> List[SubWrite]:
        """ONE encode dispatch for N same-geometry objects
        ([N*S, k, W] payload), then per-object/per-shard SubWrites
        whose refs are zero-copy columns of the payload/parity
        buffers.  ``durable=False`` defers byte materialization
        (staged/WAL flush mode — device refs are authoritative until
        flushed).  ``d_host`` lets a caller that already holds the
        payload host-side skip the data readback."""
        from .device_store import ShardRef
        from ..parallel.data_plane import plane as _data_plane
        S, U, W = geom.S, geom.U, geom.W
        N = len(names)
        d = self.to_words(payload, N * S, U)
        par = self.codec.encode_words_device(d)
        p_host = None
        if durable:
            if d_host is None:
                d_host = np.asarray(d)
            p_host = np.asarray(par)
        dp = _data_plane()
        writes: List[SubWrite] = []
        for i, name in enumerate(names):
            attrs = geom.attrs()
            if sizes is not None and name in sizes:
                attrs["size"] = str(int(sizes[name])).encode()
            pg = pg_of[name]
            up = self.io.up_set(pg)
            s0, s1 = i * S, (i + 1) * S
            for shard in range(self.n):
                tgt = up[shard] if shard < len(up) else ITEM_NONE
                if dp is not None and tgt != ITEM_NONE:
                    # fan-out accounting by OSD-shard -> chip affinity
                    dp.account_subwrite(tgt)
                ref = (ShardRef(d, shard, axis=1, s0=s0, s1=s1)
                       if shard < self.k else
                       ShardRef(par, shard - self.k, axis=1,
                                s0=s0, s1=s1))

                def mk_bytes(i=i, shard=shard):
                    if not durable:
                        return None
                    h, c = (d_host, shard) if shard < self.k else \
                        (p_host, shard - self.k)
                    return np.ascontiguousarray(
                        h[i * S:(i + 1) * S, c]).tobytes()

                writes.append(SubWrite(pg, shard, tgt, name, ref,
                                       mk_bytes, attrs))
        return writes

    def submit_loose(self, writes: Sequence[SubWrite]
                     ) -> Dict[str, Dict[int, int]]:
        """Fan out; purge homeless slots; return {name: {shard:
        target}} of what committed, with NO completeness verdict —
        the simulator tier's degraded-write semantics (callers log
        the placed set and recovery heals the gap)."""
        homeless = [w for w in writes if w.target == ITEM_NONE]
        live = [w for w in writes if w.target != ITEM_NONE]
        for w in homeless:
            self.io.purge_shard(w.pg, w.shard, w.name, None)
        committed = self.io.fanout(live)
        acked: Dict[str, Dict[int, int]] = {}
        for w in committed:
            acked.setdefault(w.name, {})[w.shard] = w.target
        return acked

    def submit(self, writes: Sequence[SubWrite]
               ) -> Dict[str, Dict[int, int]]:
        """submit_loose + the gather-all-commits verdict per object:
        every MAPPED shard must commit AND >= k overall, else the
        object's write FAILED (the r3 EC write gate;
        src/osd/ECBackend.cc:1150).  Raises IOError naming the
        incomplete objects."""
        acked = self.submit_loose(writes)
        failed: List[str] = []
        by_name: Dict[str, List[SubWrite]] = {}
        for w in writes:
            by_name.setdefault(w.name, []).append(w)
        for name, ws in by_name.items():
            got = acked.get(name, {})
            mapped = [w for w in ws if w.target != ITEM_NONE]
            if len(got) < len(mapped) or len(got) < self.k:
                failed.append(name)
        if failed:
            for name in failed:
                acked.pop(name, None)
            raise IOError(
                f"EC write incomplete for {failed} "
                f"(gather-all-commits contract)")
        return acked

    # -------------------------------------------------------- read path --
    def read_geom(self, pg: int, name: str) -> Optional[ObjectGeom]:
        """Object geometry from shard attrs (any holder).  Single-
        stripe legacy objects (no S/U attrs) report S=1 with U derived
        at assembly time."""
        for shard in range(self.n):
            raw = self.io.getattr(pg, name, shard, "size")
            if raw is None:
                continue
            size = int(raw)
            s_raw = self.io.getattr(pg, name, shard, "S")
            u_raw = self.io.getattr(pg, name, shard, "U")
            if s_raw is not None and u_raw is not None:
                return ObjectGeom(size, int(s_raw), int(u_raw))
            return ObjectGeom(size, 1, 0)     # legacy single-stripe
        return None

    def plan(self, have: Sequence[int]) -> Tuple[List[int], List[int]]:
        """(read_plan, missing_data) via the codec's
        minimum_to_decode (src/osd/ECBackend.cc:1631)."""
        have_set = set(have)
        missing = [c for c in range(self.k) if c not in have_set]
        if not missing:
            return sorted(have_set & set(range(self.k))), []
        plan = sorted(self.codec.minimum_to_decode(set(range(self.k)),
                                                   have_set))
        return plan, missing

    def gather_refs(self, pg: int, name: str
                    ) -> Dict[int, object]:
        refs = {}
        for shard in range(self.n):
            r = self.io.get_shard_ref(pg, shard, name)
            if r is not None:
                refs[shard] = r
        return refs

    def assemble_object_words(self, refs: Dict[int, object],
                              geom: ObjectGeom):
        """[S, k, W] device words of one object, decoding missing data
        columns (the handle_sub_read_reply -> ECUtil::decode flow,
        src/osd/ECBackend.cc:1183)."""
        from .device_store import assemble_object, assemble_refs
        if len(refs) < self.k:
            raise IOError(f"unrecoverable: only shards {sorted(refs)}")
        try:
            plan, missing = self.plan(list(refs))
        except Exception:
            raise IOError(
                f"unrecoverable: only shards {sorted(refs)}") from None
        dec = None
        if missing:
            sub = assemble_refs([refs[c] for c in plan],
                                geom.S, geom.W)
            dec = self.codec.decode_words_device(plan, sub, missing)
        return assemble_object([refs.get(c) for c in range(self.k)],
                               dec, geom.S, geom.W)

    def read_many_words(self, items):
        """Batched word-domain read: ``items`` is [(pg, name,
        ObjectGeom)]; returns each object's [S, k, W] device words,
        item-order.  Healthy same-geometry objects assemble in ONE
        dispatch (assemble_many); degraded objects decode + stitch in
        signature-GROUPED dispatches — the bench_recovery batching on
        the serving path (VERDICT r4 next #6), shared by both tiers
        through the ShardIO seam."""
        from .device_store import assemble_many, assemble_objects_dec
        out: List[Optional[object]] = [None] * len(items)
        healthy: Dict = {}
        degraded: Dict = {}
        for idx, (pg, name, geom) in enumerate(items):
            refs = {c: r for c, r in self.gather_refs(pg, name).items()
                    if r.size >= geom.S * geom.U}
            if all(c in refs for c in range(self.k)):
                healthy.setdefault((geom.S, geom.W), []).append(
                    (idx, [refs[c] for c in range(self.k)]))
                continue
            if len(refs) < self.k:
                raise IOError(f"{name}: unrecoverable "
                              f"(only shards {sorted(refs)})")
            plan, missing = self.plan(list(refs))
            degraded.setdefault(
                (tuple(plan), tuple(missing), geom.S, geom.W),
                []).append((idx, refs))
        for (S, W), its in healthy.items():
            stacked = assemble_many([r for _, r in its], S, W)
            for j, (idx, _) in enumerate(its):
                out[idx] = stacked[j * S:(j + 1) * S]
        for (plan, missing, S, W), its in degraded.items():
            plan, missing = list(plan), list(missing)
            stacked = assemble_many(
                [[refs[c] for c in plan] for _, refs in its], S, W)
            dec = self.codec.decode_words_device(plan, stacked,
                                                 missing)
            stitched = assemble_objects_dec(
                [[refs.get(c) for c in range(self.k)]
                 for _, refs in its], dec, S, W)
            for j, (idx, _) in enumerate(its):
                out[idx] = stitched[j * S:(j + 1) * S]
        return out

    # ------------------------------------------- signature-grouped decode --
    def decode_signature_groups(
            self, jobs: Sequence[Tuple[List[int], object, List[int]]]):
        """Batch-decode many objects in FEW dispatches: jobs with the
        same (available-plan, erased) signature and word width stack
        into one kernel call ([sum_S, n_avail, W]); the per-job slices
        come back out.  jobs: (plan, words [S, n_avail, W], erased).
        Returns a list of [S, n_erased, W] device arrays, job-order.

        This is the read-side analog of the batched write dispatch,
        and exactly what bench_recovery does for the rebuild sweep —
        applied to the serving path (VERDICT r4 weak #4 / next #6)."""
        import jax.numpy as jnp
        out: List[Optional[object]] = [None] * len(jobs)
        groups: Dict[Tuple, List[int]] = {}
        for idx, (plan, words, erased) in enumerate(jobs):
            sig = (tuple(plan), tuple(erased), int(words.shape[-1]))
            groups.setdefault(sig, []).append(idx)
        for (plan, erased, W), idxs in groups.items():
            if not erased:
                for i in idxs:
                    out[i] = jobs[i][1][..., :0, :]
                continue
            stack = jnp.concatenate([jobs[i][1] for i in idxs]) \
                if len(idxs) > 1 else jobs[idxs[0]][1]
            dec = self.codec.decode_words_device(list(plan), stack,
                                                 list(erased))
            off = 0
            for i in idxs:
                S = jobs[i][1].shape[0]
                out[i] = dec[off:off + S]
                off += S
        return out
