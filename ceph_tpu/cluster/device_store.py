"""Device-resident EC shard staging — the HBM tier of the objectstore.

With ``layout=bitsliced`` (the default for jax-plugin EC pools) a shard's
at-rest bytes ARE the plane words the flagship masked-XOR kernel
consumes: chunk bytes [L] viewed as [8, L/8] plane regions, packed 32
GF(2) lanes per int32 word (ops/gf2.py).  This module keeps those words
resident in device HBM so the whole EC data plane — encode on ingest,
degraded-read decode, recovery rebuild — runs device-to-device, exactly
the reference property that ECBackend shard stores hold chunks in the
layout its codecs consume (jerasure packet layout,
src/erasure-code/jerasure/ErasureCodeJerasure.cc:162,274; shard store
src/osd/ECBackend.cc:934,1015).

The durable objectstore (MemStore/FileStore) stays the source of truth
for *durability*; this cache is the staging tier with two flush modes:

  * eager (default): every device put also writes the identical bytes
    through to the objectstore in the same op — crash semantics are
    exactly the non-staged path's, and entries are validated against
    the store's checksum on read (an external byte poke — corruption
    tests, objectstore surgery — invalidates the staged copy).
  * staged: device puts mark entries dirty and defer the host write
    until ``flush()`` — the BlueStore deferred-write/WAL shape; the
    dirty entry itself is the authoritative copy until flushed.

Keys are the simulator's ShardKey (pool, pg, object, shard).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, Optional, Tuple

from ..common import faults

ShardKey = Tuple[int, int, str, int]

faults.declare("device.staging_drop",
               "evict a CLEAN staged HBM entry at read time (forced "
               "re-upload from the durable bytes) — models HBM "
               "pressure/invalidation racing the read path; dirty "
               "entries are never dropped (they are the only copy)")

# process-wide HBM staging occupancy (summed across every cache in
# the process: per-OSD caches + the client-side one), exported as
# perf("hbm") GAUGES — the ClusterScope staging-pressure signal next
# to the jit compile counters
_hbm_entries = 0
_hbm_bytes = 0


def _hbm_account(d_entries: int, d_bytes: int) -> None:
    global _hbm_entries, _hbm_bytes
    _hbm_entries = max(0, _hbm_entries + d_entries)
    _hbm_bytes = max(0, _hbm_bytes + d_bytes)
    try:
        from ..common.perf_counters import perf as _perf
        pc = _perf("hbm")
        pc.set("staged_entries", _hbm_entries)
        pc.set("staged_bytes", _hbm_bytes)
    except Exception:
        pass


@dataclass(frozen=True)
class ShardRef:
    """A staged shard = one row/column of a shared device buffer.

    An object's k+m shard files are views of the buffers the encode
    dispatch already produced: data shards are columns of the client's
    [S, k, U] stripe view, parity shards are columns of the [S, m, U]
    encode output, rebuilt shards are columns of a decode output.
    Staging k+m shards therefore costs ZERO extra device ops — no
    pack/slice dispatches — which matters doubly on this driver, where
    every dispatch pays tens of ms of tunnel latency; on real hardware
    it is simply the zero-copy layout.

    axis=0: ``buf[idx]`` is the shard file ([n, L] row buffer).
    axis=1: ``buf[s0:s1, idx]`` flattened is the shard file ([S, n, U]
    stripewise buffer — the at-rest order of ECUtil stripe_info_t;
    ``s0/s1`` select one object's stripe range out of a batched
    multi-object buffer, None = the whole leading axis).
    """
    buf: object            # jax.Array uint8 plane words
    idx: int
    axis: int = 0
    s0: int = 0
    s1: Optional[int] = None

    def _rows(self) -> int:
        end = self.buf.shape[0] if self.s1 is None else self.s1
        return int(end - self.s0)

    @property
    def size(self) -> int:
        """Shard payload size in BYTES (buffers are int32 plane words
        on the staged path; u8 only for host-upload wrappers)."""
        itemsize = int(getattr(self.buf.dtype, "itemsize", 1))
        if self.axis == 0:
            return int(self.buf.shape[-1]) * itemsize
        return self._rows() * int(self.buf.shape[2]) * itemsize

    def materialize(self):
        """The shard as its own device array (one slice dispatch)."""
        if self.axis == 0:
            return self.buf[self.idx]
        return self.buf[self.s0:self.s0 + self._rows(),
                        self.idx].reshape(-1)

    def __array__(self, dtype=None):
        import numpy as np
        a = np.asarray(self.materialize())
        return a.astype(dtype) if dtype is not None else a


def as_ref(arr) -> ShardRef:
    """Wrap a bare [L] device array as a single-row ref."""
    return ShardRef(arr.reshape(1, -1), 0)


def materialize_bulk(refs) -> list:
    """Host arrays for many refs with ONE readback per DISTINCT
    buffer: refs sharing a packed buffer (an encode output's parity
    columns, a put batch's stripe view, a rebuilt decode batch) read
    back together and slice host-side.  The per-ref alternative pays
    one slice dispatch plus one device->host readback EACH — on a
    remote-attached driver that is the flush-readback floor BENCH r05
    measured at ~0.024 GB/s; batching by buffer collapses it to a
    handful of bulk transfers."""
    import numpy as np
    host = {}
    for r in refs:
        if id(r.buf) not in host:
            host[id(r.buf)] = np.asarray(r.buf)
    out = []
    for r in refs:
        b = host[id(r.buf)]
        if r.axis == 0:
            out.append(np.ascontiguousarray(b[r.idx]))
        else:
            out.append(np.ascontiguousarray(
                b[r.s0:r.s0 + r._rows(), r.idx]).reshape(-1))
    return out


@dataclass
class _Entry:
    arr: ShardRef          # plane words (row of a packed buffer)
    csum: Optional[int]    # objectstore crc at staging time; None=dirty
    nbytes: int


# --------------------------------------------------- jitted layout ops --
# Each helper is ONE device dispatch over shared packed buffers; jit
# instances are created lazily so importing this module needs no jax.

_jits: Dict[str, object] = {}


def _jit(name, fn, static):
    if name not in _jits:
        import jax
        _jits[name] = jax.jit(fn, static_argnames=static)
    return _jits[name]


def _dedup(refs, index=None, bufs=None):
    """Unique buffers + per-ref (buf_index, idx, axis, s0, rows) spec.
    Shards of one object share buffers; passing each once keeps the
    XLA argument footprint at one buffer, not k copies."""
    if bufs is None:
        bufs, index = [], {}
    spec = []
    for r in refs:
        i = index.get(id(r.buf))
        if i is None:
            i = index[id(r.buf)] = len(bufs)
            bufs.append(r.buf)
        # axis-0 entries pin the range fields so irrelevant values
        # don't key extra jit recompiles
        spec.append((i, r.idx, 1, r.s0, r._rows()) if r.axis
                    else (i, r.idx, 0, 0, 0))
    return bufs, index, tuple(spec)


def _col(bufs, entry, S, U):
    """One shard as [S, U] inside a trace.  Row refs slice through a
    [n_rows, S, U] view so the slice keeps a TPU-friendly (S, U)
    tiling (a flat 1-row slice pads 4x); column refs index the
    stripewise buffer directly (zero layout change)."""
    b, i, axis, s0, rows = entry
    if axis == 0:
        return bufs[b].reshape(-1, S, U)[i]
    return bufs[b][s0:s0 + rows, i]


def assemble_refs(refs, S: int, U: int):
    """[S, n, U] device stack of n shard refs — one dispatch (the
    gather half of handle_sub_read_reply, src/osd/ECBackend.cc:1183)."""
    def impl(bufs, spec, S, U):
        import jax.numpy as jnp
        return jnp.stack([_col(bufs, e, S, U) for e in spec], axis=1)
    f = _jit("assemble", impl, ("spec", "S", "U"))
    bufs, _, spec = _dedup(refs)
    return f(tuple(bufs), spec=spec, S=S, U=U)


def assemble_object(refs_by_col, dec, S: int, U: int):
    """Object stripe view [S, k, U] on device in one dispatch: column
    c reads its shard ref, missing columns read decode output
    dec[:, j].  Returned untrimmed/unflattened: a flat u8 view of a
    >=2 GiB object would need 64-bit slice indices, which the TPU
    backend rejects — callers flatten+trim only when small."""
    def impl(bufs, dec, spec, S, U):
        import jax.numpy as jnp
        cols = [dec[:, e[1]] if e[0] < 0 else _col(bufs, e, S, U)
                for e in spec]
        return jnp.stack(cols, axis=1)
    f = _jit("assemble_obj", impl, ("spec", "S", "U"))
    present = [r for r in refs_by_col if r is not None]
    bufs, _, pspec = _dedup(present)
    spec, pi, di = [], 0, 0
    for ref in refs_by_col:
        if ref is None:
            spec.append((-1, di, 0, 0, 0))
            di += 1
        else:
            spec.append(pspec[pi])
            pi += 1
    if dec is None:
        import jax.numpy as jnp
        dec = jnp.zeros((1, 1, 1), dtype=jnp.uint8)
    return f(tuple(bufs), dec, spec=tuple(spec), S=S, U=U)


def assemble_windows(col_bufs, starts, S: int):
    """[G*S, n_cols, W] stack of G same-geometry objects whose column
    j lives in ``col_bufs[j]`` = (stripewise buffer [rows, n, W],
    column index), with per-object window starts as a DYNAMIC operand.

    The static-spec assemblers (assemble_refs/assemble_many) key one
    XLA executable per exact buffer/window layout — a recovery sweep
    over hundreds of objects would compile hundreds of one-shot
    programs (seconds each through a remote-compile tunnel).  Here the
    layout is static only in (column composition, S, G-bucket): the
    window POSITIONS travel as data, so every sweep after the first
    reuses one compiled gather.  G pads to a power-of-two bucket
    (repeating the last window; callers slice the tail off)."""
    import numpy as np
    import jax.numpy as jnp
    G = int(len(starts))
    Gp = 1
    while Gp < G:
        Gp <<= 1
    pad = np.full(Gp, starts[-1] if G else 0, dtype=np.int32)
    pad[:G] = starts
    def impl(bufs, starts_d, cols, S):
        idx = (starts_d[:, None] +
               jnp.arange(S, dtype=jnp.int32)[None]).reshape(-1)
        return jnp.stack([bufs[bi][idx, col]
                          for bi, col in cols], axis=1)
    f = _jit("assemble_windows", impl, ("cols", "S"))
    bufs, index = [], {}
    cols = []
    for buf, col in col_bufs:
        bi = index.get(id(buf))
        if bi is None:
            bi = index[id(buf)] = len(bufs)
            bufs.append(buf)
        cols.append((bi, int(col)))
    out = f(tuple(bufs), jnp.asarray(pad), cols=tuple(cols), S=S)
    return out[:G * S]


def assemble_objects_dec(refs_per_object, dec, S: int, U: int):
    """[G*S, k, U] device stack of G same-signature DEGRADED objects
    in ONE dispatch: each object's missing columns (None refs) read
    its stripe slice of the group decode output ``dec``
    ([G*S, n_missing, U]).  The grouped-final-assembly half of the
    signature-batched degraded read — per-object assemble_object
    calls would pay one dispatch each."""
    def impl(bufs, dec, spec, n_cols, S, U):
        import jax.numpy as jnp
        blocks = []
        G = len(spec) // n_cols
        for g in range(G):
            cols = []
            di = 0
            for e in spec[g * n_cols:(g + 1) * n_cols]:
                if e[0] < 0:
                    cols.append(dec[g * S:(g + 1) * S, e[1]])
                    di += 1
                else:
                    cols.append(_col(bufs, e, S, U))
            blocks.append(jnp.stack(cols, axis=1))
        return jnp.concatenate(blocks)
    f = _jit("assemble_objs_dec", impl, ("spec", "n_cols", "S", "U"))
    bufs, index = [], {}
    spec = []
    n_cols = len(refs_per_object[0])
    for refs in refs_per_object:
        present = [r for r in refs if r is not None]
        bufs, index, pspec = _dedup(present, index, bufs)
        pi, di = 0, 0
        for ref in refs:
            if ref is None:
                spec.append((-1, di, 0, 0, 0))
                di += 1
            else:
                spec.append(pspec[pi])
                pi += 1
    return f(tuple(bufs), dec, spec=tuple(spec), n_cols=n_cols,
             S=S, U=U)


def assemble_many(refs_per_object, S: int, U: int):
    """[N*S, k, U] batched stripe view of N same-geometry objects in
    ONE dispatch — the read half of the batched client surface
    (get_many_to_device).  ``refs_per_object`` is a list of per-object
    column-ref lists (no missing columns; degraded objects go through
    assemble_object)."""
    def impl(bufs, spec, n_cols, S, U):
        import jax.numpy as jnp
        blocks = []
        for o in range(len(spec) // n_cols):
            cols = [_col(bufs, e, S, U)
                    for e in spec[o * n_cols:(o + 1) * n_cols]]
            blocks.append(jnp.stack(cols, axis=1))
        return jnp.concatenate(blocks)
    f = _jit("assemble_many", impl, ("spec", "n_cols", "S", "U"))
    bufs, index = [], {}
    spec = []
    n_cols = len(refs_per_object[0])
    for refs in refs_per_object:
        bufs, index, s = _dedup(refs, index, bufs)
        spec.extend(s)
    return f(tuple(bufs), spec=tuple(spec), n_cols=n_cols, S=S, U=U)


class DeviceShardCache:
    """Per-OSD HBM staging of shard plane words.

    ``owner`` is the hosting OSD's id (None for the client-side
    cache): with the sharded data plane active, every staged entry is
    attributed to its OSD-shard -> chip affinity partition
    (``dataplane.shard<i>.staged_*`` counters) — the per-chip staging
    view of the mesh-sharded put path."""

    def __init__(self, owner: Optional[int] = None):
        self.owner = owner
        self._entries: Dict[ShardKey, _Entry] = {}
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    # ------------------------------------------------------------ writes --
    def put(self, key: ShardKey, ref: ShardRef,
            csum: Optional[int]) -> None:
        """Stage a shard ref; ``csum=None`` marks it dirty (staged
        flush mode — the device copy is authoritative until flush)."""
        prev = self._entries.get(key)
        self._entries[key] = _Entry(ref, csum, int(ref.size))
        _hbm_account(0 if prev is not None else 1,
                     int(ref.size) - (prev.nbytes if prev else 0))
        from ..parallel import data_plane
        if data_plane.enabled():
            dp = data_plane.plane()
            if dp is not None:
                # affinity: the hosting OSD when known, else the EC
                # shard index (client-side staging)
                dp.account_staged(
                    self.owner if self.owner is not None else key[3],
                    int(ref.size))

    def evict(self, key: ShardKey) -> None:
        e = self._entries.pop(key, None)
        if e is not None:
            self.invalidations += 1
            _hbm_account(-1, -e.nbytes)

    def evict_object(self, pool_id: int, pg: int, name: str) -> None:
        """Drop every staged shard of one object (overwrite/delete
        invalidation: dirty entries are served unconditionally, so a
        stale dirty entry would resurrect overwritten data)."""
        for k in [k for k in self._entries
                  if k[0] == pool_id and k[1] == pg and k[2] == name]:
            self.evict(k)

    def clear(self) -> None:
        if self._entries:
            _hbm_account(-len(self._entries),
                         -sum(e.nbytes for e in self._entries.values()))
        self._entries.clear()

    # ------------------------------------------------------------- reads --
    def has(self, key: ShardKey) -> bool:
        return key in self._entries

    def dirty_get(self, key: ShardKey):
        """The staged array IF the entry is dirty (device copy is the
        authoritative one awaiting flush); else None."""
        e = self._entries.get(key)
        return e.arr if e is not None and e.csum is None else None

    def get(self, key: ShardKey, store_csum: Optional[int]):
        """Return the staged array, validating against the durable
        tier's current checksum.  Dirty entries are authoritative and
        served unconditionally; a csum mismatch (external mutation of
        the bytes underneath) drops the stale staging."""
        e = self._entries.get(key)
        if e is None:
            self.misses += 1
            return None
        if e.csum is not None and \
                faults.fire("device.staging_drop") is not None:
            # clean entries only: a dirty entry is the authoritative
            # copy awaiting flush and must never be injected away
            self.evict(key)
            self.misses += 1
            return None
        if e.csum is not None and e.csum != store_csum:
            self.evict(key)
            self.misses += 1
            return None
        self.hits += 1
        return e.arr

    def dirty_items(self) -> Iterable[Tuple[ShardKey, object]]:
        return [(k, e.arr) for k, e in self._entries.items()
                if e.csum is None]

    def mark_clean(self, key: ShardKey, csum: int) -> None:
        e = self._entries.get(key)
        if e is not None:
            e.csum = csum

    def stats(self) -> Dict[str, int]:
        return {"entries": len(self._entries),
                "bytes": sum(e.nbytes for e in self._entries.values()),
                "hits": self.hits, "misses": self.misses,
                "invalidations": self.invalidations}
