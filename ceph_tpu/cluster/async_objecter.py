"""AsyncObjecter — the completion-callback wire data path.

Role of the reference's asynchronous Objecter + AsyncMessenger pair
(src/osdc/Objecter.cc op_submit/_op_submit_with_budget returning to
the caller before the op completes, completions delivered as
``Context::complete`` callbacks; src/msg/async/ — every connection a
pipelined state machine): an op here is SUBMITTED, not executed —

    submit -> encode -> fan-out -> gather-commits -> complete

with completions delivered by callback from the messenger's reader
threads.  BENCH r05 showed why this exists: the device kernels run at
hundreds of GB/s while one blocking encrypted wire stream moves
~150 MiB/s — the wire tier was three orders of magnitude behind the
math it feeds, bounded by one-frame-at-a-time round trips and a
per-byte seal, not by the sockets.

Three pieces live here:

  * ``AioCompletion`` — the librados ``rados_completion_t`` role: a
    future the submitter can wait on, poll, or hang callbacks off.
  * ``AioEngine`` — a small completion-dispatch pool with per-key FIFO
    ordering: ops submitted under the same key run strictly in
    submission order (the librados per-object write ordering
    contract), distinct keys run concurrently.  Retries and op state
    machines run here, never in messenger callback context (a
    callback that blocks on a connect RTT stalls every completion
    behind it — the CTL110 lint rule polices exactly this).
  * ``AsyncObjecter`` — the wire core: N parallel pipelined streams
    per OSD daemon (msg/wire.py ``StreamPool``), scatter-gather frame
    encoding so bulk shard payloads go buffer -> socket without
    passing through the typed encoder, (session, seq) replay stamping
    threaded through UNCHANGED from the blocking path, and a single
    fresh-stream resubmit on stream death (the blocking osd_call's
    reconnect-retry, callback-shaped).

The blocking ``RemoteCluster`` paths are thin shims over this core
(``call()`` = ``call_async().result()``): one code path for stamping,
resend and backoff, sync results byte-identical to the async ones.
Completions ride OpTracker: submission marks ``dispatched_wire`` and
the ``stage_wire_to_done_s`` histogram measures the in-flight wire
window that ``dump_ops_in_flight`` exposes.
"""
from __future__ import annotations

import concurrent.futures as _cf
import os
import queue as _queue
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..common import tracer as _trace
from ..common.lockdep import LockdepLock
from ..common.op_tracker import (EVENT_DISPATCHED_WIRE,
                                 tracker as _op_tracker)
from ..common.options import config
from ..common.perf_counters import perf as _perf
from ..msg import encoding, wire


class AioCompletion(_cf.Future):
    """One async op's completion (librados ``rados_completion_t``):
    a concurrent.futures.Future (so ``asyncio.wrap_future`` and the
    whole waiting toolbox work on it) wearing the librados verbs."""

    def is_complete(self) -> bool:
        return self.done()

    def wait_for_complete(self, timeout: Optional[float] = None) -> int:
        """Block until complete (librados returns 0; errors surface
        from get_return_value, not the wait).  A wait that times out
        with the op still in flight returns -ETIMEDOUT — callers gate
        stall detection on a nonzero return, which must not be
        vacuous."""
        _cf.wait([self], timeout=timeout)
        if not self.done():
            import errno
            return -errno.ETIMEDOUT
        return 0

    def get_return_value(self) -> Any:
        """The op's result; raises the op's error (the pythonic shape
        of librados' negative-errno return)."""
        return self.result()

    def set_complete_callback(self, cb) -> None:
        """``cb(completion)`` fires when the op completes — from the
        completing thread, so callbacks must not block (CTL110)."""
        self.add_done_callback(lambda _fut: cb(self))

    # internal completion entry points: tolerant of double delivery
    # (a raced retry may complete after the first delivery landed)
    def _complete(self, result: Any) -> None:
        try:
            self.set_result(result)
        except _cf.InvalidStateError:
            pass

    def _fail(self, exc: BaseException) -> None:
        try:
            self.set_exception(exc)
        except _cf.InvalidStateError:
            pass


class AioEngine:
    """Completion-dispatch worker pool with per-key FIFO ordering.

    Ops submitted under the same ``key`` execute strictly in
    submission order — op i+1 for an object does not start until op i
    completed (the librados write-ordering guarantee overlapping
    ``aio_write_full`` calls rely on); ops under distinct keys (or
    key=None) run concurrently across the workers.  The engine is
    also where the async core schedules work that must never run in
    messenger callback context (stream rebuilds, resubmits)."""

    def __init__(self, workers: int = 2, name: str = "aio"):
        self._q: "_queue.Queue" = _queue.Queue()
        self._lock = LockdepLock(f"aio.engine.{name}", recursive=False)
        # key -> deque of (fn, comp) queued BEHIND the running op
        self._keys: Dict[Any, deque] = {}
        self._stopped = False
        self._tls = threading.local()
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"{name}-{i}")
            for i in range(max(1, int(workers)))]
        for t in self._threads:
            t.start()

    # ---------------------------------------------------------- submit --
    def submit(self, fn, key: Any = None,
               completion: Optional[AioCompletion] = None
               ) -> AioCompletion:
        """Queue ``fn`` (its return value / exception completes the
        completion).  Same-key ops serialize in submission order."""
        comp = completion or AioCompletion()
        with self._lock:
            if self._stopped:
                comp._fail(RuntimeError("aio engine closed"))
                return comp
            if key is not None:
                pending = self._keys.get(key)
                if pending is not None:
                    pending.append((fn, comp))
                    return comp
                self._keys[key] = deque()
        self._q.put((key, fn, comp))
        return comp

    def run(self, fn, key: Any = None) -> Any:
        """Blocking shim: run ``fn`` through the engine and wait.
        Called FROM a worker it runs inline (a sync verb inside an
        async completion must not deadlock on its own worker pool)."""
        if getattr(self._tls, "in_worker", False):
            return fn()
        return self.submit(fn, key=key).result()

    def in_worker(self) -> bool:
        return bool(getattr(self._tls, "in_worker", False))

    # ---------------------------------------------------------- workers --
    def _worker(self) -> None:
        self._tls.in_worker = True
        while True:
            item = self._q.get()
            if item is None:
                return
            key, fn, comp = item
            try:
                comp._complete(fn())
            except BaseException as e:          # completion carries it
                comp._fail(e)
            if key is not None:
                self._advance(key)

    def _advance(self, key: Any) -> None:
        with self._lock:
            pending = self._keys.get(key)
            if not pending:
                self._keys.pop(key, None)
                return
            fn, comp = pending.popleft()
        self._q.put((key, fn, comp))

    def close(self) -> None:
        with self._lock:
            self._stopped = True
            orphans = [c for q in self._keys.values() for _, c in q]
            self._keys.clear()
        for c in orphans:
            c._fail(RuntimeError("aio engine closed"))
        for _ in self._threads:
            self._q.put(None)


class AsyncObjecter:
    """The wire tier's async op core over per-OSD stream pools.

    Owned by a ``RemoteCluster``; the blocking ``osd_call`` is a shim
    over :meth:`call` so the stamping / resend / scatter-gather logic
    exists exactly once.  Streams negotiate the configured data mode
    (``objecter_wire_mode``, default crc — the reference's
    intra-cluster ms_mode) after the cephx handshake."""

    # payloads at or above this ride the scatter-gather frame tail,
    # straight from their buffer (below it, the typed encoder's copy
    # is cheaper than a second sendmsg segment)
    SG_MIN = wire.SG_MIN

    def __init__(self, rc):
        self.rc = rc
        cfg = config()
        self.n_streams = int(cfg.get("objecter_wire_streams"))
        self.window = int(cfg.get("objecter_wire_window"))
        self.mode = str(cfg.get("objecter_wire_mode"))
        # same-host shared-memory lane (msg/shm_ring.py): ring bytes
        # per OSD pool; 0 disables and every payload rides the socket
        self.shm_bytes = int(cfg.get("wire_shm_ring_kib")) << 10
        if self.mode == wire.MODE_SECURE:
            # sealed payloads must never cross the plaintext mmap
            # ring: the lane is integrity-only (crc bound into the
            # MAC'd doorbell), so secure mode keeps every byte on
            # the sealed socket frames
            self.shm_bytes = 0
        self._pools: Dict[int, wire.StreamPool] = {}
        self._lock = LockdepLock("objecter.async", recursive=False)
        self.engine = AioEngine(workers=2, name="objecter-aio")
        # resubmits run on their own single worker: the op engine's
        # workers BLOCK in gather steps, and a retry queued behind a
        # blocked worker that is itself waiting on that retry's
        # completion would deadlock the pool — the io engine only ever
        # does pool.submit (bounded connect RTTs), never waits
        self._io = AioEngine(workers=1, name="objecter-io")
        self._pc = _perf("objecter.wire")

    # ------------------------------------------------------------ pools --
    def pool(self, osd: int) -> wire.StreamPool:
        with self._lock:
            p = self._pools.get(osd)
            if p is None:
                # ring files live next to the daemon's socket (both
                # processes reach them through the cluster dir, and
                # the server only maps paths from its own dir)
                shm_dir = None
                try:
                    shm_dir = os.path.dirname(self.rc.addrs[osd])
                except (KeyError, IndexError, AttributeError,
                        TypeError):
                    pass
                p = self._pools[osd] = wire.StreamPool(
                    factory=lambda o=osd: self.rc._stream_conn(o),
                    size=self.n_streams, mode=self.mode,
                    window=self.window, name=f"osd.{osd}",
                    shm_dir=shm_dir, shm_bytes=self.shm_bytes)
            return p

    @property
    def reply_wanted(self) -> bool:
        """True when pools built by this objecter will ask daemons
        for the shm REPLY ring (RingReply): requires a live shm lane
        (secure mode zeroes ``shm_bytes`` — sealed payloads never
        cross the plaintext mmap, in either direction) AND the
        ``wire_reply_ring`` option.  The observability twin of the
        gate each StreamPool latches at build time."""
        from ..common import crcutil
        return self.shm_bytes > 0 and crcutil.flag("wire_reply_ring")

    def drop_pool(self, osd: int) -> None:
        with self._lock:
            p = self._pools.pop(osd, None)
        if p is not None:
            p.close()

    def streams_live(self, osd: int) -> int:
        with self._lock:
            p = self._pools.get(osd)
        return 0 if p is None else p.streams_live()

    # ------------------------------------------------------------- ops --
    @staticmethod
    def _sg_payload(req: Dict[str, Any]):
        """Split a bulk ``data`` payload out of the request for the
        scatter-gather frame tail; returns (meta_req, data|None,
        csums|None) — the shared wire.extract_bulk contract (one
        threshold, one view-passing policy, for every sender)."""
        return wire.extract_bulk(req, "sg_payload")

    def call_async(self, osd: int, req: Dict[str, Any],
                   completion: Optional[AioCompletion] = None
                   ) -> AioCompletion:
        """Submit one OSD request; returns immediately with its
        completion.  Mutating commands are stamped ONCE with this
        client's (session, seq) — the single fresh-stream resubmit
        after a stream death replays the SAME stamp, so the daemon's
        dup table applies the op at most once (the PR-5 session-replay
        contract, unchanged underneath the async core)."""
        comp = completion or AioCompletion()
        tenant = getattr(self.rc, "tenant", None)
        if tenant is not None and "tenant" not in req and \
                req.get("klass", "client") == "client":
            # tenant identity (S3 auth -> set_tenant) rides every
            # client-class request so the daemon dispatches it under
            # the tenant's own dmClock class; background traffic
            # (recovery, scrub) keeps its background class untagged
            req = dict(req, tenant=tenant)
        if req.get("cmd") in self.rc._REPLAY_CMDS and \
                "session" not in req:
            req = dict(req, **self.rc._next_stamp(osd))
        tr_span = None
        if _trace.enabled():
            # wire-submit stage span, opened MANUALLY (submit and
            # completion run on different threads, so no context
            # manager can bracket it) and stamped into the request
            # meta — the trace-context wire propagation for both
            # MSG_REQ and scatter-gather MSG_REQ_SG frames
            tr_span = _trace.tracer().span_open(
                "objecter.wire_submit", osd=osd, cmd=req.get("cmd"))
            if tr_span.trace_id:
                req = dict(req)
                req["tctx"] = [tr_span.trace_id, tr_span.span_id]
        req, data, csums = self._sg_payload(req)
        pool = self.pool(osd)
        shm_tok = None
        if data is not None:
            # same-host shared-memory lane: the payload goes to the
            # ring and only a doorbell (meta + extent + crc) crosses
            # the socket.  Any failure (ring full, lane refused,
            # daemon restarted without the mapping) falls back to the
            # socket scatter-gather tail for THIS frame — the lane is
            # an optimization, never a dependency.
            shm_tok = pool.ring_put(data, csums)
        if shm_tok is not None:
            meta = encoding.dumps(dict(req, _shm=shm_tok.meta))
            # the ORIGINAL payload stays referenced: the one resend
            # re-frames it onto the socket (below) instead of
            # replaying a doorbell whose ring record may be the very
            # thing that failed (poisoned/overwritten extent — a
            # doorbell replay would fail identically forever)
            send_data = send_csums = None
        else:
            meta = encoding.dumps(req)
            send_data, send_csums = data, csums
        self._pc.inc("submits")
        tr = _op_tracker()
        cur = tr.current()
        own = None
        if cur is not None:
            # nested under a tracked client op (put/get): the wire
            # dispatch is a STAGE of that op, not its own record
            cur.mark_event(EVENT_DISPATCHED_WIRE, osd=osd)
        else:
            own = tr.create(req.get("cmd", "op"), service="objecter",
                            osd=osd, oid=req.get("oid"))
            own.mark_event(EVENT_DISPATCHED_WIRE, osd=osd)
            if tr_span is not None and own.tracked and \
                    tr_span.trace_id:
                own.tags["trace_id"] = tr_span.trace_id
        state = {"retried": False}

        def _finish(result, exc) -> None:
            if shm_tok is not None:
                # the op is terminal either way: the ring extent is
                # reusable (a resubmit-in-flight never reaches here —
                # it reuses the SAME extent until its own completion)
                pool.ring_free(shm_tok)
            if tr_span is not None:
                _trace.tracer().finish_span(
                    tr_span, error=None if exc is None
                    else type(exc).__name__)
            if own is not None:
                tr.finish(own, error=None if exc is None
                          else type(exc).__name__)
            if exc is None:
                comp._complete(result)
            else:
                self._pc.inc("errors")
                comp._fail(exc)

        def _resend_args():
            """The one resubmit always rides the SOCKET: re-encode
            the meta WITHOUT the doorbell and re-frame the original
            payload — a dead stream, a refused re-attach and a
            poisoned ring record all heal the same way (the (session,
            seq) stamp makes the replay at-most-once regardless of
            which lane the first attempt used)."""
            if shm_tok is None:
                return meta, data, csums
            return encoding.dumps(req), data, csums

        def _cb(result, exc) -> None:
            if exc is not None and isinstance(exc, (OSError, IOError)) \
                    and not state["retried"]:
                # stream died under the op (daemon restart, injected
                # socket failure, partition): one resubmit on a fresh
                # stream with the SAME stamp — scheduled on the
                # engine, never in this reader-callback context (the
                # rebuild does connect RTTs)
                state["retried"] = True
                self._pc.inc("resubmits")
                self._io.submit(
                    lambda: self._resend(osd, _resend_args(),
                                         _cb, _finish))
                return
            _finish(result, exc)

        try:
            pool.submit(meta, data=send_data, cb=_cb,
                        csums=send_csums)
        except (OSError, IOError) as e:
            if state["retried"]:
                _finish(None, e)
            else:
                state["retried"] = True
                self._pc.inc("resubmits")
                self._io.submit(
                    lambda: self._resend(osd, _resend_args(),
                                         _cb, _finish))
        return comp

    def _resend(self, osd: int, framed, cb, finish) -> None:
        meta, data, csums = framed
        try:
            self.pool(osd).submit(meta, data=data, cb=cb,
                                  csums=csums)
        except (OSError, IOError) as e:
            finish(None, e)

    # -------------------------------------------------- blocking shims --
    def call(self, osd: int, req: Dict[str, Any]) -> Any:
        """Blocking shim — the code path every sync RemoteCluster op
        rides (osd_call), so sync and async share one implementation."""
        return self.call_async(osd, req).result()

    @staticmethod
    def gather(comps: List[AioCompletion]
               ) -> List[Tuple[Any, Optional[BaseException]]]:
        """Wait for every completion; per-op (result, error) pairs in
        input order (the gather-commits step of write fan-outs, where
        per-shard failures feed the resend verdict, not an exception)."""
        out: List[Tuple[Any, Optional[BaseException]]] = []
        for c in comps:
            try:
                out.append((c.result(), None))
            except BaseException as e:
                out.append((None, e))
        return out

    def close(self) -> None:
        with self._lock:
            pools, self._pools = dict(self._pools), {}
        for p in pools.values():
            p.close()
        self.engine.close()
        self._io.close()
