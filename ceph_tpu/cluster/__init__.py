"""Cluster map layer: pools, OSD states, the pg→osd pipeline, and the
batched full-cluster mapper (the ParallelPGMapper replacement)."""
from .osdmap import OSDMap, PGPool, PGId  # noqa: F401
