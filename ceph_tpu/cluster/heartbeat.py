"""Heartbeat-based failure detection.

Role of the reference's OSD↔OSD heartbeats (OSD::handle_osd_ping,
src/osd/OSD.cc:5327; peer selection maybe_update_heartbeat_peers
:5188): each OSD pings a small peer set every tick; peers that miss
`grace` consecutive ticks get reported to the mon, which marks them
down after enough distinct reporters (Monitor.report_failure).

Simulation-time driven (tick()), deterministic peer rings — the piece
under test is the detection/report/mark-down pipeline, not wall-clock
timers.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from .monitor import Monitor


@dataclass
class HeartbeatConfig:
    n_peers: int = 3          # ring neighbors each OSD monitors
    grace_ticks: int = 3      # missed ticks before reporting


class HeartbeatMonitor:
    """Drives ping rounds over a ClusterSim's OSD liveness."""

    def __init__(self, sim, mon: Monitor,
                 cfg: Optional[HeartbeatConfig] = None):
        self.sim = sim
        self.mon = mon
        # None -> a FRESH config per monitor: the old
        # `cfg=HeartbeatConfig()` default was evaluated once at class
        # definition, so every default-constructed monitor SHARED one
        # mutable instance (a test tweaking grace_ticks on its monitor
        # silently retuned every other default monitor in the process)
        self.cfg = cfg if cfg is not None else HeartbeatConfig()
        self.missed: Dict[int, Dict[int, int]] = {}   # target -> {peer: n}
        self.marked_down: List[int] = []

    def peers_of(self, osd: int) -> List[int]:
        """Deterministic ring peers (the front/back messenger peer set)."""
        n = len(self.sim.osds)
        return [(osd + d) % n for d in range(1, self.cfg.n_peers + 1)]

    def tick(self) -> List[int]:
        """One heartbeat round; returns OSDs newly marked down."""
        newly_down: List[int] = []
        om = self.sim.osdmap
        for osd in range(len(self.sim.osds)):
            if not self.sim.osds[osd].alive or not om.is_up(osd):
                continue                      # dead OSDs don't ping
            for peer in self.peers_of(osd):
                if not om.is_up(peer):
                    continue                  # already marked down
                if self.sim.osds[peer].alive:
                    self.missed.get(peer, {}).pop(osd, None)
                    continue
                cnt = self.missed.setdefault(peer, {})
                cnt[osd] = cnt.get(osd, 0) + 1
                if cnt[osd] >= self.cfg.grace_ticks:
                    if self.mon.report_failure(peer, reporter=osd):
                        newly_down.append(peer)
                        self.missed.pop(peer, None)
                        break
        self.marked_down.extend(newly_down)
        return newly_down
