"""Heartbeat-based failure detection.

Role of the reference's OSD↔OSD heartbeats (OSD::handle_osd_ping,
src/osd/OSD.cc:5327; peer selection maybe_update_heartbeat_peers
:5188): each OSD pings a small peer set every tick; peers that miss
`grace` consecutive ticks get reported to the mon, which marks them
down after enough distinct reporters (Monitor.report_failure).

Partition tolerance (ISSUE 6): pings consult the ``net.partition``
faultpoint — a peer that is ALIVE but unreachable (netsplit) misses
heartbeats exactly like a dead one, and a reporter cut off from the
mon cannot deliver its report (the minority side of a split detects
the majority as down but can never act on it).  The tick counter is
installed as the Monitor's flap clock so markdown hysteresis runs on
deterministic sim time, and the optional ``down_out_ticks`` grace
drives the automatic down→out transition (mon_osd_down_out_interval
role) that the ``noout`` cluster flag vetoes.

Simulation-time driven (tick()), deterministic peer rings — the piece
under test is the detection/report/mark-down pipeline, not wall-clock
timers.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..common import faults
from .monitor import Monitor


@dataclass
class HeartbeatConfig:
    n_peers: int = 3          # ring neighbors each OSD monitors
    grace_ticks: int = 3      # missed ticks before reporting
    down_out_ticks: int = 0   # down->out grace (0 = no auto-out)


class HeartbeatMonitor:
    """Drives ping rounds over a ClusterSim's OSD liveness."""

    def __init__(self, sim, mon: Monitor,
                 cfg: Optional[HeartbeatConfig] = None):
        self.sim = sim
        self.mon = mon
        # None -> a FRESH config per monitor: the old
        # `cfg=HeartbeatConfig()` default was evaluated once at class
        # definition, so every default-constructed monitor SHARED one
        # mutable instance (a test tweaking grace_ticks on its monitor
        # silently retuned every other default monitor in the process)
        self.cfg = cfg if cfg is not None else HeartbeatConfig()
        self.missed: Dict[int, Dict[int, int]] = {}   # target -> {peer: n}
        self.marked_down: List[int] = []
        self.ticks = 0
        # boot-fsck damage delivery (the STORE_DAMAGED pipeline): an
        # OSD whose power-loss boot quarantined objects reports the
        # count on its next heartbeat; one clearing zero follows on
        # the tick after, mirroring the daemon tier's slow-op rollup
        self._damage_reported: Set[int] = set()
        self._down_ticks: Dict[int, int] = {}   # map-down tick counts
        self._util_cache: Dict[int, Dict] = {}  # osd -> last util scan
        self.auto_outs: List[int] = []
        # deterministic time for the mon's flap-dampening windows: the
        # heartbeat tick IS the sim's clock (never clobber a clock a
        # test installed explicitly)
        if mon.flap_clock is None:
            mon.flap_clock = lambda: float(self.ticks)

    def peers_of(self, osd: int) -> List[int]:
        """Deterministic ring peers (the front/back messenger peer set)."""
        n = len(self.sim.osds)
        return [(osd + d) % n for d in range(1, self.cfg.n_peers + 1)]

    def _reaches(self, src: int, dst_entity: str) -> bool:
        """Can osd.src deliver a frame to dst right now?  A severed
        link counts a net.partition fire (the proof the cut carried)."""
        return not faults.partitioned(f"osd.{src}", dst_entity)

    # utilization scans are O(store); refresh every N ticks and ship
    # the cached snapshot in between (the daemon tier's
    # _UTIL_SCAN_INTERVAL_S, sim-clock shaped)
    UTIL_SCAN_TICKS = 5

    def _scan_util(self, o) -> Dict:
        """One OSD's store utilization.  Iterates over SNAPSHOTS of
        the store dicts (dispatcher threads mutate them concurrently)
        and treats a mid-scan mutation as 'keep last snapshot' — a
        failed scan must never abort the tick that marks peers down."""
        objects = 0
        nbytes = 0
        pools: Dict = {}
        try:
            for coll, objs in list(o.objectstore._colls.items()):
                vals = list(objs.values())
                objects += len(vals)
                row = pools.setdefault(int(coll[0]),
                                       {"objects": 0, "bytes": 0})
                row["objects"] += len(vals)
                for ob in vals:
                    sz = len(ob.data)
                    nbytes += sz
                    row["bytes"] += sz
        except RuntimeError:
            return self._util_cache.get(o.id) or {
                "bytes": 0, "total_bytes": 0, "objects": 0,
                "pools": {}}
        return {"bytes": nbytes, "total_bytes": 0,
                "objects": objects, "pools": pools}

    def _report_telemetry(self) -> None:
        """ClusterStats rollup, sim tier: per-OSD store utilization,
        per-OSD PG heat tables, and per-OSD ``osd.io`` counters
        SYNTHESIZED from the heat ledger's raw totals (one process is
        one perf domain, so real per-OSD counters don't exist here —
        deriving them from the same ledger makes the heat↔osd.io
        agreement exact by construction and feeds the metrics-history
        rate pipeline per OSD).  The process perf dump still ships
        once under the client entity, mirroring what daemonized OSDs
        ship on their wire heartbeats."""
        import time as _time
        from ..common.perf_counters import COUNTER
        from ..common.perf_counters import perf as _perf
        now = _time.time()
        rescan = (self.ticks % self.UTIL_SCAN_TICKS == 1)
        services = getattr(self.sim, "services", None) or []
        for o in self.sim.osds:
            if not o.alive or not self._reaches(o.id, "mon"):
                continue
            if rescan or o.id not in self._util_cache:
                self._util_cache[o.id] = self._scan_util(o)
            report = {"util": self._util_cache[o.id], "ts": now}
            svc = services[o.id] if o.id < len(services) else None
            heat = getattr(svc, "heat", None)
            if heat is not None:
                # decay runs on the TICK clock: deterministic per seed
                heat.advance(float(self.ticks))
                report["heat"] = heat.dump()
                report["perf"] = {
                    "osd.io": {k: (COUNTER, v)
                               for k, v in heat.totals().items()}}
            self.mon.record_daemon_perf(f"osd.{o.id}", report)
        # the process perf dump carries the data-plane chip counters;
        # under the multi-process plane each rank reports as its own
        # client daemon tagged with its host label, so the mgr's
        # mesh_rollup sees per-(host, chip) cells instead of two
        # ranks overwriting one "client" row
        from ..parallel import multihost as _mh
        label = _mh.host_label()
        entity = "client" if not _mh.is_active() else f"client.{label}"
        self.mon.record_daemon_perf(
            entity, {"perf": _perf().dump_typed(), "ts": now,
                     "host": label})

    def tick(self) -> List[int]:
        """One heartbeat round; returns OSDs newly marked down."""
        self.ticks += 1
        self._report_telemetry()
        newly_down: List[int] = []
        om = self.sim.osdmap
        # store-damage rollup: deliver boot-fsck counts to the mon
        # (only when the reporter can actually reach it), then one
        # clearing zero once the damage report has been delivered
        for o in self.sim.osds:
            if not o.alive or not self._reaches(o.id, "mon"):
                continue
            if o.fsck_errors:
                self.mon.record_store_damage(
                    f"osd.{o.id}", o.fsck_errors,
                    repaired=o.fsck_errors)
                self._damage_reported.add(o.id)
                o.fsck_errors = 0
            elif o.id in self._damage_reported:
                self.mon.record_store_damage(f"osd.{o.id}", 0)
                self._damage_reported.discard(o.id)
        for osd in range(len(self.sim.osds)):
            if not self.sim.osds[osd].alive or not om.is_up(osd):
                continue                      # dead OSDs don't ping
            for peer in self.peers_of(osd):
                if not om.is_up(peer):
                    continue                  # already marked down
                if self.sim.osds[peer].alive and \
                        self._reaches(osd, f"osd.{peer}") and \
                        self._reaches(peer, f"osd.{osd}"):
                    # a ping is a ROUND TRIP: the request must reach
                    # the peer AND the reply must come back, so a
                    # one-way cut in EITHER direction reads as a miss
                    # (the mute-minority half-open link included)
                    self.missed.get(peer, {}).pop(osd, None)
                    continue
                # dead OR alive-but-partitioned: a netsplit looks
                # exactly like death to the ping path
                cnt = self.missed.setdefault(peer, {})
                cnt[osd] = cnt.get(osd, 0) + 1
                if cnt[osd] >= self.cfg.grace_ticks:
                    if not self._reaches(osd, "mon"):
                        continue   # cut off from the mon: the report
                        # never lands (minority-side reporters)
                    if self.mon.report_failure(peer, reporter=osd):
                        newly_down.append(peer)
                        self.missed.pop(peer, None)
                        break
        self.marked_down.extend(newly_down)
        if self.cfg.down_out_ticks:
            self._tick_down_out()
        return newly_down

    def _tick_down_out(self) -> None:
        """Automatic down->out after the grace (the reference mon's
        mon_osd_down_out_interval); ``noout`` vetoes inside the mon."""
        om = self.sim.osdmap
        for osd in range(len(self.sim.osds)):
            if om.is_up(osd):
                self._down_ticks.pop(osd, None)
                continue
            if om.osd_weight[osd] == 0:
                continue                      # already out
            n = self._down_ticks.get(osd, 0) + 1
            self._down_ticks[osd] = n
            if n >= self.cfg.down_out_ticks:
                if self.mon.auto_out_down(osd):
                    self.auto_outs.append(osd)
                    self._down_ticks.pop(osd, None)
