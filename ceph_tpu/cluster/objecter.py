"""Objecter — the client-side op engine.

Role of the reference Objecter (src/osdc/Objecter.cc: op_submit :2191,
_calc_target :2688, resend on map change): a client holds its OWN
cached OSDMap, computes each op's target from it, and when the cluster
map moves on — targets down, epoch stale — it catches up via the mon's
incremental stream and recomputes/resends instead of failing.

The simulator plays the OSD side; ops land through ClusterSim's data
path only when the client's computed target agrees with the current
map (a mismatched target = the op would have been sent to the wrong
daemon and rejected, triggering resend).
"""
from __future__ import annotations

import copy
import itertools
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..common import faults
from ..common.backoff import ExpBackoff, TickClock
from ..common.op_tracker import tracker as _op_tracker
from ..common.perf_counters import perf as _perf
from ..common.tracer import tracer as _tracer
from ..placement.crush_map import ITEM_NONE
from .monitor import Monitor
from .osdmap import OSDMap
from .simulator import ClusterSim


class TooManyRetries(IOError):
    pass


class WriteBlocked(IOError):
    """An EC write landed RECOVERABLE (>= k) but sub-``min_size``
    shards, and the map offers no progress that would change that —
    the reference refuses to ack such writes (min_size = k+1: a write
    acked with zero parity headroom is one failure away from loss) and
    BLOCKS them until the PG heals (src/osd/PrimaryLogPG.cc
    check_pool_min_size / PG_STATE_DEGRADED wait).  Raised only after
    the bounded in-objecter probe gave up; the bytes ARE durably
    applied at >= k, the op just never acked.  Callers that can park
    and resume (the thrasher's mid-cut ride-outs) re-drive the write
    after heal; treating this as a plain failure loses the
    write-is-still-pending distinction."""


faults.declare(
    "msg.drop_ack",
    "drop the COMPLETION of a client op after the cluster durably "
    "applied it (the lost-reply half of a cut: op committed, ack "
    "never arrived) — the client must resend and the (session, seq) "
    "dup detection must apply it at most once")

_SESSION_IDS = itertools.count(1)


class Objecter:
    """Client with a cached map; submits ops with retry-on-map-change."""

    def __init__(self, sim: ClusterSim, mon: Monitor,
                 max_retries: int = 8, seed: int = 0):
        self.sim = sim
        self.mon = mon
        # the client's PRIVATE map copy, caught up via incrementals
        self.osdmap = copy.deepcopy(sim.osdmap)
        self.max_retries = max_retries
        # retry pacing: deterministic exponential backoff with jitter
        # on a SIM-TICK clock — retries are instantaneous in wall time
        # but carry a reproducible schedule (the thrasher's clock; a
        # wall sleep here would make seeded soaks unreproducible)
        self.clock = TickClock()
        self._backoff = ExpBackoff(base=0.05, cap=2.0, seed=seed,
                                   sleep=self.clock.sleep)
        self._pc = _perf("objecter")
        # messenger session: one id per objecter lifetime, a fresh seq
        # per MUTATING logical op.  Retries/replays of one op reuse
        # its (session, seq), so the sim's dup detection applies it at
        # most once even when the first apply's ack was lost
        self.session = f"objecter.{next(_SESSION_IDS)}.{seed}"
        self._op_seq = 0
        self.replay_dups = 0      # resends suppressed by dup-detect
        self.acks_dropped = 0     # injected completion losses

    # ------------------------------------------------------------- maps --
    def maybe_update_map(self) -> int:
        """Consume the mon's incremental stream (subscription model).
        A client partitioned from the mon sees NO new epochs — its
        map simply stops advancing, the stale-target resend loop keeps
        spinning against old state until the cut heals (the
        subscription half of a netsplit)."""
        if faults.partitioned("client", "mon"):
            return 0
        incs = self.mon.get_incrementals(self.osdmap.epoch)
        for inc in incs:
            self.osdmap.apply_incremental(inc)
            self._pc.inc("map_epochs_applied")
        return len(incs)

    def calc_target(self, pool_id: int, name: str
                    ) -> Tuple[int, List[int]]:
        """(pg, up set) from the CLIENT's cached map
        (Objecter::_calc_target)."""
        pool = self.osdmap.pools[pool_id]
        pg = self.sim.object_pg(pool, name)
        up, _, acting, _ = self.osdmap.pg_to_up_acting_osds(pool_id, pg)
        return pg, (acting or up)

    def _target_current(self, pool_id: int, name: str) -> bool:
        """Would the op reach the right daemons?  (the wrong-epoch
        rejection an OSD gives a stale client)."""
        _, client_up = self.calc_target(pool_id, name)
        pool = self.sim.osdmap.pools[pool_id]
        pg = self.sim.object_pg(pool, name)
        real_up = self.sim.pg_up(pool, pg)
        if client_up != real_up:
            return False
        primary = next((o for o in client_up if o != ITEM_NONE), None)
        return primary is not None and self.sim.osds[primary].alive

    def _next_reqid(self) -> Tuple[str, int]:
        """One (session, seq) per mutating LOGICAL op — resends reuse
        it (the osd_op_reqid_t the reference dedups on in the pg log)."""
        self._op_seq += 1
        return (self.session, self._op_seq)

    # -------------------------------------------------------------- ops --
    def _submit(self, op, pool_id: int, name: str, optype: str = "op",
                names: Optional[List[str]] = None,
                reqid: Optional[Tuple[str, int]] = None):
        """op_submit: compute target, send; on stale target refresh the
        map and resend (bounded).  Traced (the jspan threaded through
        ops, src/osd/PrimaryLogPG.cc:11060 role) and TRACKED: the op
        gets a lifecycle record, active for the duration of the data-
        path call so the OSD service / device layers tag it.
        ``names`` widens the target-currency check to a whole batch
        (put_many): ANY stale member resends the batch — the rewrite
        is idempotent (stale copies are superseded).
        ``reqid`` (mutating ops) is the replay contract: every resend
        of this logical op carries the same id; an op the cluster
        already durably committed is NOT re-applied — the recorded
        completion is returned instead (at-most-once apply, even when
        the first apply's ack was dropped on a cut)."""
        self._pc.inc("op_submit")
        check = names if names else [name]
        tr = _op_tracker()
        top = tr.create(optype, service="objecter", pool=pool_id,
                        obj=name)
        error = None
        try:
            with _tracer().start_span("objecter.op", pool=pool_id,
                                      obj=name, optype=optype) as span:
                if span.trace_id and top.tracked:
                    # op id -> trace id mapping: `ceph trace <op>`
                    # resolves through the tracked-op record, and a
                    # slow finish auto-pins this trace (op_tracker)
                    top.tags["trace_id"] = span.trace_id
                blocked: Optional[WriteBlocked] = None
                for attempt in range(self.max_retries):
                    transient = False
                    blocked = None
                    if reqid is not None:
                        hit = self.sim.reqid_cached(reqid)
                        if hit is not None:
                            # this resend is a REPLAY of a committed
                            # op: dup-suppressed, completion recalled
                            self.replay_dups += 1
                            self._pc.inc("replay_dups")
                            top.mark_event("replay_dup",
                                           attempt=attempt)
                            span.set_tag("replayed", True)
                            return hit[0]
                    if all(self._target_current(pool_id, nm)
                           for nm in check):
                        try:
                            with tr.track(top):
                                result = op()
                            if reqid is not None:
                                self.sim.reqid_commit(reqid, result)
                                if faults.fire("msg.drop_ack",
                                               optype=optype
                                               ) is not None:
                                    # committed, ack lost: the caller
                                    # never hears — resend and let the
                                    # dup detection prove idempotency
                                    self.acks_dropped += 1
                                    self._pc.inc("acks_dropped")
                                    top.mark_event("ack_dropped",
                                                   attempt=attempt)
                                    transient = True
                                else:
                                    span.set_tag("attempts",
                                                 attempt + 1)
                                    return result
                            else:
                                span.set_tag("attempts", attempt + 1)
                                return result
                        except WriteBlocked as wb:
                            # durable at >= k but below the min_size
                            # write floor: keep probing (map progress
                            # / recovery may restore headroom), and
                            # if the budget runs out surface the
                            # BLOCKED state, not a retry failure
                            blocked = wb
                            self._pc.inc("op_blocked_min_size")
                            top.mark_event("blocked_min_size",
                                           attempt=attempt)
                            transient = True
                        except IOError:
                            # transient failure at a CURRENT target
                            # (EIO, injected drop): worth retrying on
                            # its own, map progress or not
                            self._pc.inc("op_eio_retries")
                            top.mark_event("eio_retry", attempt=attempt)
                            transient = True
                    else:
                        self._pc.inc("op_resends")
                        top.mark_event("resend",
                                       epoch=self.osdmap.epoch)
                    got = self.maybe_update_map()
                    if got:
                        # map-wait stall resolved: new epochs arrived
                        top.mark_event("map_update", epochs=got,
                                       epoch=self.osdmap.epoch)
                    if not got and not transient and attempt:
                        # stale target and the mon has nothing newer:
                        # no amount of resending reaches a daemon the
                        # map doesn't know about
                        span.set_tag("error", "no_usable_target")
                        error = "no_usable_target"
                        raise TooManyRetries(
                            f"{name}: no usable target at epoch "
                            f"{self.osdmap.epoch}")
                    if attempt + 1 < self.max_retries:
                        # deterministic exponential backoff with
                        # jitter, on the sim-tick clock (no wall wait)
                        self._pc.tinc("op_backoff_wait_s",
                                      self._backoff.sleep(attempt))
                if blocked is not None:
                    # never acked, still pending: the caller may park
                    # this op and re-drive it after heal (the write is
                    # durably applied at >= k; a re-drive is an
                    # idempotent full rewrite)
                    span.set_tag("error", "blocked_min_size")
                    error = "blocked_min_size"
                    raise blocked
                span.set_tag("error", "retries_exhausted")
                error = "retries_exhausted"
                raise TooManyRetries(f"{name}: gave up after "
                                     f"{self.max_retries} resends")
        except BaseException as e:
            if error is None:
                error = type(e).__name__
            raise
        finally:
            tr.finish(top, error=error)

    def _durable(self, pool_id: int, placed: List[int]) -> List[int]:
        """The client half of the EC write contract
        (src/osd/ECBackend.cc:1150 gather-all-commits, as the wire
        client already enforces): a write that landed fewer than k
        shards is NOT recoverable and must not ack — raising here
        sends it back through the resend loop (stale copies were
        purged, so the full rewrite is idempotent)."""
        from .osdmap import POOL_ERASURE
        pool = self.sim.osdmap.pools[pool_id]
        if pool.type == POOL_ERASURE:
            k = self.sim.codec_for(pool).get_data_chunk_count()
            if len(placed) < k:
                raise IOError(
                    f"EC write degraded below k "
                    f"({len(placed)} < {k} shards committed): "
                    f"un-ackable, resend")
            # the reference's min_size = k+1 write floor: a landing at
            # exactly k is durable but has ZERO parity headroom until
            # the next recovery pass — it must not ack.  (min() keeps
            # a degenerate m=0 profile writable at k.)
            min_size = min(k + 1, pool.size)
            if len(placed) < min_size:
                raise WriteBlocked(
                    f"EC write below min_size write floor "
                    f"({len(placed)} < {min_size} shards committed, "
                    f"k={k}): blocked until the PG heals")
        return placed

    def put(self, pool_id: int, name: str, data: bytes) -> List[int]:
        return self._submit(
            lambda: self._durable(pool_id,
                                  self.sim.put(pool_id, name, data)),
            pool_id, name, optype="put", reqid=self._next_reqid())

    def put_many(self, pool_id: int, names: List[str],
                 datas: List[bytes]) -> Dict[str, List[int]]:
        """Batched put: ONE tracked op, one encode dispatch per
        stripe class (ClusterSim.put_many) — sharded across the mesh
        when the parallel data plane is on, so the op's lifecycle
        record carries the ``dispatched_mesh`` event.  Each member
        object individually honors the EC >= k durability contract;
        any short landing resends the whole (idempotent) batch."""
        if not names:
            return {}

        def op():
            placed = self.sim.put_many(pool_id, names, datas)
            for nm in names:
                self._durable(pool_id, placed.get(nm, []))
            return placed

        return self._submit(op, pool_id, names[0], optype="put_many",
                            names=list(names),
                            reqid=self._next_reqid())

    def get(self, pool_id: int, name: str) -> bytes:
        return self._submit(
            lambda: self.sim.get(pool_id, name), pool_id, name,
            optype="get")

    def write(self, pool_id: int, name: str, offset: int,
              data: bytes) -> List[int]:
        return self._submit(
            lambda: self._durable(pool_id,
                                  self.sim.write(pool_id, name,
                                                 offset, data)),
            pool_id, name, optype="write", reqid=self._next_reqid())
