"""Objecter — the client-side op engine.

Role of the reference Objecter (src/osdc/Objecter.cc: op_submit :2191,
_calc_target :2688, resend on map change): a client holds its OWN
cached OSDMap, computes each op's target from it, and when the cluster
map moves on — targets down, epoch stale — it catches up via the mon's
incremental stream and recomputes/resends instead of failing.

The simulator plays the OSD side; ops land through ClusterSim's data
path only when the client's computed target agrees with the current
map (a mismatched target = the op would have been sent to the wrong
daemon and rejected, triggering resend).
"""
from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..common.op_tracker import tracker as _op_tracker
from ..common.perf_counters import perf as _perf
from ..common.tracer import tracer as _tracer
from ..placement.crush_map import ITEM_NONE
from .monitor import Monitor
from .osdmap import OSDMap
from .simulator import ClusterSim


class TooManyRetries(IOError):
    pass


class Objecter:
    """Client with a cached map; submits ops with retry-on-map-change."""

    def __init__(self, sim: ClusterSim, mon: Monitor,
                 max_retries: int = 8):
        self.sim = sim
        self.mon = mon
        # the client's PRIVATE map copy, caught up via incrementals
        self.osdmap = copy.deepcopy(sim.osdmap)
        self.max_retries = max_retries
        self._pc = _perf("objecter")

    # ------------------------------------------------------------- maps --
    def maybe_update_map(self) -> int:
        """Consume the mon's incremental stream (subscription model)."""
        incs = self.mon.get_incrementals(self.osdmap.epoch)
        for inc in incs:
            self.osdmap.apply_incremental(inc)
            self._pc.inc("map_epochs_applied")
        return len(incs)

    def calc_target(self, pool_id: int, name: str
                    ) -> Tuple[int, List[int]]:
        """(pg, up set) from the CLIENT's cached map
        (Objecter::_calc_target)."""
        pool = self.osdmap.pools[pool_id]
        pg = self.sim.object_pg(pool, name)
        up, _, acting, _ = self.osdmap.pg_to_up_acting_osds(pool_id, pg)
        return pg, (acting or up)

    def _target_current(self, pool_id: int, name: str) -> bool:
        """Would the op reach the right daemons?  (the wrong-epoch
        rejection an OSD gives a stale client)."""
        _, client_up = self.calc_target(pool_id, name)
        pool = self.sim.osdmap.pools[pool_id]
        pg = self.sim.object_pg(pool, name)
        real_up = self.sim.pg_up(pool, pg)
        if client_up != real_up:
            return False
        primary = next((o for o in client_up if o != ITEM_NONE), None)
        return primary is not None and self.sim.osds[primary].alive

    # -------------------------------------------------------------- ops --
    def _submit(self, op, pool_id: int, name: str, optype: str = "op"):
        """op_submit: compute target, send; on stale target refresh the
        map and resend (bounded).  Traced (the jspan threaded through
        ops, src/osd/PrimaryLogPG.cc:11060 role) and TRACKED: the op
        gets a lifecycle record, active for the duration of the data-
        path call so the OSD service / device layers tag it."""
        self._pc.inc("op_submit")
        tr = _op_tracker()
        top = tr.create(optype, service="objecter", pool=pool_id,
                        obj=name)
        error = None
        try:
            with _tracer().start_span("objecter.op", pool=pool_id,
                                      obj=name) as span:
                for attempt in range(self.max_retries):
                    if self._target_current(pool_id, name):
                        try:
                            with tr.track(top):
                                result = op()
                            span.set_tag("attempts", attempt + 1)
                            return result
                        except IOError:
                            self._pc.inc("op_eio_retries")
                            top.mark_event("eio_retry", attempt=attempt)
                    else:
                        self._pc.inc("op_resends")
                        top.mark_event("resend",
                                       epoch=self.osdmap.epoch)
                    got = self.maybe_update_map()
                    if got:
                        # map-wait stall resolved: new epochs arrived
                        top.mark_event("map_update", epochs=got,
                                       epoch=self.osdmap.epoch)
                    if not got and attempt:
                        # nothing new from the mon and still failing
                        span.set_tag("error", "no_usable_target")
                        error = "no_usable_target"
                        raise TooManyRetries(
                            f"{name}: no usable target at epoch "
                            f"{self.osdmap.epoch}")
                span.set_tag("error", "retries_exhausted")
                error = "retries_exhausted"
                raise TooManyRetries(f"{name}: gave up after "
                                     f"{self.max_retries} resends")
        except BaseException as e:
            if error is None:
                error = type(e).__name__
            raise
        finally:
            tr.finish(top, error=error)

    def put(self, pool_id: int, name: str, data: bytes) -> List[int]:
        return self._submit(
            lambda: self.sim.put(pool_id, name, data), pool_id, name,
            optype="put")

    def get(self, pool_id: int, name: str) -> bytes:
        return self._submit(
            lambda: self.sim.get(pool_id, name), pool_id, name,
            optype="get")

    def write(self, pool_id: int, name: str, offset: int,
              data: bytes) -> List[int]:
        return self._submit(
            lambda: self.sim.write(pool_id, name, offset, data),
            pool_id, name, optype="write")
