"""Thrasher — seeded kill/revive soak with self-healing invariants.

The role of teuthology's ``thrashosds`` task (qa/tasks/thrashosds.py +
ceph_manager.py kill_osd/revive_osd/out_osd/in_osd): under client
load, randomly kill and revive OSDs, mark them out and in, and arm
fault-injection points — then prove the failure pipeline actually
self-heals:

  I1  every client op completes (OpTracker shows zero stuck in-flight)
  I2  zero data loss (readback of every object matches the oracle)
  I3  deep scrub reports 0 inconsistencies after repair
  I4  health converges to HEALTH_OK within a bounded number of ticks
  I5  every armed faultpoint fired at least once (perf-counter proof —
      a soak whose injections never happened proves nothing)

Everything is driven off ONE seeded ``random.Random``: the kill/revive
schedule, write payloads, and the faultpoint schedules (seeded from
the run seed) — the same seed reproduces the identical schedule and
identical fire counts, which is what turns "it survived chaos once"
into a regression test (the determinism the online-EC studies need to
measure degraded-mode behavior under *correlated* failures).

Runs against the in-process tier (ClusterSim + Monitor +
HeartbeatMonitor + Objecter): kills are undetected process deaths
(``fail_osd``) that the heartbeat → failure-report → mark-down →
peering → log-delta-recovery pipeline must notice and repair, exactly
the pipeline the reference exercises.  Time is simulation ticks —
heartbeat ticks and the objecter's TickClock — so a full soak takes no
wall-clock sleeps.

Netsplit mode (ISSUE 6, ``ceph thrash --netsplit``): instead of
killing processes, seeded cut/heal cycles sever a minority of OSDs
from the rest of the cluster via the ``net.partition`` faultpoint —
sometimes one-way (half-open links), sometimes ridden out under the
operator's ``noout``/``nodown`` flags — while ``msg.drop_ack`` loses
committed ops' completions so the session-replay dedup is exercised.
Two invariants join the set: **no op applies twice** (the replay
idempotency oracle, ``ClusterSim.reqid_stats``) and **mon epoch
history is linear** (gapless, forkless — no split brain).  Flap
dampening (markdown hysteresis) runs on the heartbeat tick clock, so
repeated cut/heal flapping holds the flapper down and the settle loop
must out-wait the hold, exactly as a real cluster would.
"""
from __future__ import annotations

import json
import os
import random
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..common import faults
from ..common.op_tracker import tracker as _op_tracker
from .heartbeat import HeartbeatConfig, HeartbeatMonitor
from .monitor import Monitor
from .objecter import Objecter, TooManyRetries, WriteBlocked

# (name, mode, n) triples armed by default: the wire axis (in-process
# messenger frame drops) and the device-EIO axis — the acceptance
# pair.  Seeds derive from the run seed so schedules reproduce.
DEFAULT_FAULTPOINTS: Tuple[Tuple[str, str, int], ...] = (
    ("msg.drop_op", "one_in", 6),
    ("device.eio", "one_in", 8),
)

# the netsplit scenario's default mix: ack loss rides along so the
# session-replay dedup is exercised (committed op, dropped completion,
# resend suppressed) — net.partition itself is armed per cut with its
# seeded GROUPS, not from this table
NETSPLIT_FAULTPOINTS: Tuple[Tuple[str, str, int], ...] = (
    ("msg.drop_op", "one_in", 10),
    ("device.eio", "one_in", 10),
    ("msg.drop_ack", "one_in", 4),
)


@dataclass
class ThrashConfig:
    seed: int = 0
    cycles: int = 5                   # kill/revive rounds
    objects: int = 6                  # oracle objects per pool
    object_size: int = 6144
    writes_per_cycle: int = 3         # client load between fault events
    reads_per_cycle: int = 3          # oracle reads between fault
    # events (continuous I2 verification AND the read-path injection
    # surface — a writes-only soak never evaluates device.eio)
    max_down: int = 2                 # concurrent undetected deaths;
    # must stay <= EC m and < replicated size or kills alone lose data
    revive_prob: float = 0.5          # chance a cycle revives someone
    mark_out_prob: float = 0.3        # chance a down OSD is marked out
    settle_ticks: int = 25            # health-convergence bound (I4)
    grace_ticks: int = 1              # heartbeat grace before report
    faultpoints: Sequence[Tuple[str, str, int]] = DEFAULT_FAULTPOINTS
    # ---- netsplit scenario (`ceph thrash --netsplit`) ----
    netsplit: bool = False            # cut/heal instead of kill/revive
    partition_prob: float = 0.7       # chance a cycle cuts (when whole)
    heal_prob: float = 0.6            # chance a cycle heals (when cut)
    oneway_prob: float = 0.25         # asymmetric (half-open) cuts
    flags_prob: float = 0.2           # ride a cut out under noout+nodown
    max_minority: int = 2             # minority size; <= EC m and
    # < replicated size so the majority side always stays writable
    # markdown hysteresis (Monitor flap dampening), in heartbeat ticks:
    flap_count: int = 3               # markdowns in window -> hold
    flap_window: float = 200.0
    flap_hold: float = 2.0
    flap_hold_cap: float = 12.0


class Thrasher:
    """One seeded soak over a ClusterSim + Monitor stack."""

    def __init__(self, sim, mon: Monitor, pool_ids: Sequence[int],
                 cfg: Optional[ThrashConfig] = None):
        self.sim = sim
        self.mon = mon
        self.pool_ids = list(pool_ids)
        self.cfg = cfg or ThrashConfig()
        self.rng = random.Random(self.cfg.seed)
        self.hb = HeartbeatMonitor(
            sim, mon, HeartbeatConfig(grace_ticks=self.cfg.grace_ticks))
        if self.cfg.netsplit:
            # markdown hysteresis on the heartbeat TICK clock (the
            # HeartbeatMonitor installed itself as mon.flap_clock):
            # repeated cut/heal flapping holds the flapper down
            mon.configure_flap_dampening(
                count=self.cfg.flap_count,
                window=self.cfg.flap_window,
                hold=self.cfg.flap_hold,
                hold_cap=self.cfg.flap_hold_cap)
        self.client = Objecter(sim, mon, max_retries=16,
                               seed=self.cfg.seed)
        self.schedule: List[Tuple] = []   # the reproducibility record
        self.oracle: Dict[Tuple[int, str], bytes] = {}
        self.down: List[int] = []         # currently-killed OSDs
        self.out: List[int] = []          # currently-marked-out OSDs
        self.partition: Optional[Dict[str, Any]] = None  # active cut
        self.flags_set: List[str] = []    # cluster flags we set
        self.failures: List[str] = []     # broken invariants, as found
        # writes blocked below the min_size floor mid-cut, PARKED for
        # re-drive once the cluster can give them parity headroom
        # (heal / markdown re-home): (pool_id, name, data)
        self.parked: List[Tuple[int, str, bytes]] = []
        self.writes_parked = 0            # cumulative park events

    # ------------------------------------------------------------ pieces --
    def _log(self, *event: Any) -> None:
        self.schedule.append(tuple(event))

    def _blob(self, n: int) -> bytes:
        return bytes(self.rng.getrandbits(8) for _ in range(n))

    def _write(self, pool_id: int, name: str) -> None:
        """One tracked client write; retried across map catch-up (the
        resend contract) — a TooManyRetries here after detection ticks
        is a genuine invariant failure and surfaces in the report."""
        data = self._blob(self.cfg.object_size)
        try:
            self.client.put(pool_id, name, data)
        except WriteBlocked:
            # sub-(k+1) landing under a ride-out: the write is durably
            # applied at >= k (reads see the new bytes) but must not
            # ack until the PG has parity headroom again — PARK it
            # first, re-drive after heal/markdown gives the map a way
            # forward.  A parked write that never unblocks is an
            # invariant failure at settle, not here.
            self.parked.append((pool_id, name, data))
            self.writes_parked += 1
            self.oracle[(pool_id, name)] = data
            self._log("write_blocked", pool_id, name)
            return
        except TooManyRetries as e:
            self.failures.append(f"write {pool_id}/{name} did not "
                                 f"complete: {e}")
            return
        self.oracle[(pool_id, name)] = data
        self._log("write", pool_id, name)

    def _read(self, pool_id: int, name: str) -> None:
        """One tracked client read, checked against the oracle AS the
        cluster degrades — reads mid-thrash are both continuous
        data-loss verification and the read-path injection surface
        (device.eio / replica failover / degraded decode)."""
        want = self.oracle.get((pool_id, name))
        if want is None:
            return
        try:
            got = self.client.get(pool_id, name)
        except (TooManyRetries, IOError) as e:
            self.failures.append(f"read {pool_id}/{name} did not "
                                 f"complete: {e}")
            return
        if got != want:
            self.failures.append(f"read {pool_id}/{name}: payload "
                                 f"mismatch mid-thrash")
        self._log("read", pool_id, name)

    def _pick(self) -> Tuple[int, str]:
        pool_id = self.pool_ids[self.rng.randrange(
            len(self.pool_ids))]
        return pool_id, f"thrash-{self.rng.randrange(self.cfg.objects)}"

    def _load(self) -> None:
        for _ in range(self.cfg.writes_per_cycle):
            self._write(*self._pick())
        for _ in range(self.cfg.reads_per_cycle):
            self._read(*self._pick())

    def _kill_one(self) -> None:
        alive = [o.id for o in self.sim.osds
                 if o.alive and o.id not in self.down]
        if not alive or len(self.down) >= self.cfg.max_down:
            return
        victim = alive[self.rng.randrange(len(alive))]
        self.sim.fail_osd(victim)          # undetected death: the
        self.down.append(victim)           # heartbeat pipeline's job
        self._log("kill", victim)
        if self.rng.random() < self.cfg.mark_out_prob:
            inc = self.mon.next_incremental()
            inc.new_weight[victim] = 0
            if self.mon.commit_incremental(inc):
                self.out.append(victim)
                self._log("out", victim)

    def _revive_one(self) -> None:
        if not self.down:
            return
        osd = self.down.pop(self.rng.randrange(len(self.down)))
        self.sim.restart_osd(osd)
        self.mon.osd_boot(osd)             # epoch reaches subscribers
        if osd in self.out:
            self.out.remove(osd)
            self._log("in", osd)
        self._log("revive", osd)

    def _tick_detection(self) -> None:
        """Heartbeat rounds until every current death is map-visible
        (bounded): client resends need the epoch to move."""
        for _ in range(self.cfg.grace_ticks + 2):
            newly = self.hb.tick()
            if newly:
                self._log("marked_down", tuple(sorted(newly)))

    # ------------------------------------------------------- netsplit --
    def _cut(self) -> None:
        """Sever a seeded minority of OSDs from the rest of the
        cluster (client and mon ride the majority side — the sim has
        ONE mon; quorum-side splits are the wire/mon_quorum tier's
        scenario).  Sometimes asymmetric, sometimes ridden out under
        the operator flags."""
        cfg = self.cfg
        candidates = [o.id for o in self.sim.osds if o.alive]
        size = 1 + self.rng.randrange(cfg.max_minority)
        if len(candidates) <= size:
            return
        minority = sorted(self.rng.sample(candidates, size))
        min_ent = [f"osd.{o}" for o in minority]
        maj_ent = ["client", "mon"] + [
            f"osd.{o.id}" for o in self.sim.osds
            if o.id not in minority]
        oneway = self.rng.random() < cfg.oneway_prob
        # oneway cuts groups[0] -> others; orientation decides which
        # half-open shape we get (majority can't reach the minority,
        # or the minority is mute toward the majority)
        min_first = self.rng.random() < 0.5
        groups = [min_ent, maj_ent] if min_first else [maj_ent,
                                                       min_ent]
        if self.rng.random() < cfg.flags_prob:
            # operator rides the known partition out: no markdowns,
            # no auto-outs while the flags hold
            for flag in ("noout", "nodown"):
                if self.mon.set_flag(flag, True):
                    self.flags_set.append(flag)
            self._log("flags_set", tuple(self.flags_set))
        faults.arm("net.partition", groups=groups, oneway=oneway)
        self.partition = {"minority": minority, "oneway": oneway,
                          "min_first": min_first}
        self._log("cut", tuple(minority), oneway, min_first)

    def _heal(self) -> None:
        """Disarm the cut, clear ride-out flags, and re-announce every
        partition victim the map marked down (flap dampening may HOLD
        a flapper — the settle loop keeps re-announcing, exactly like
        the daemon's heartbeat re-boot)."""
        if self.partition is None:
            return
        faults.disarm("net.partition")
        for flag in self.flags_set:
            self.mon.set_flag(flag, False)
        if self.flags_set:
            self._log("flags_cleared", tuple(self.flags_set))
        self.flags_set = []
        self._log("heal", tuple(self.partition["minority"]))
        self.partition = None
        self._boot_survivors()

    def _boot_survivors(self) -> int:
        """Re-announce alive-but-marked-down OSDs (the OSD's own
        MOSDBoot re-send when it sees itself down in a newer map).
        Returns how many announcements the mon REFUSED (held by flap
        dampening or quorum-less)."""
        held = 0
        om = self.sim.osdmap
        for o in self.sim.osds:
            if not o.alive or om.is_up(o.id) or o.id in self.down:
                continue
            if self.mon.osd_boot(o.id):
                self._log("boot", o.id)
            else:
                held += 1
        return held

    def _recover(self) -> None:
        for pool_id in self.pool_ids:
            st = self.sim.recover_delta(pool_id)
            self._log("recover", pool_id, st.get("delta_objects", 0),
                      st.get("backfill_pgs", 0))

    def _unpark(self) -> None:
        """Re-drive writes parked below the min_size floor — an
        idempotent full rewrite under a fresh reqid.  Ones that ack
        unblock; ones still below the floor stay parked for the next
        pass (heal or markdown must eventually free them: a write
        still parked at settle end is an invariant failure)."""
        if not self.parked:
            return
        still: List[Tuple[int, str, bytes]] = []
        for pool_id, name, data in self.parked:
            try:
                self.client.put(pool_id, name, data)
            except WriteBlocked:
                still.append((pool_id, name, data))
                continue
            except TooManyRetries as e:
                self.failures.append(
                    f"parked write {pool_id}/{name} failed on "
                    f"re-drive: {e}")
                continue
            self._log("write_unblocked", pool_id, name)
        self.parked = still

    # --------------------------------------------------------------- run --
    def run(self) -> Dict[str, Any]:
        cfg = self.cfg
        # fire counts are reported as THIS run's delta: the registry's
        # cumulative tally survives disarm (by design — proof outlives
        # the schedule), so back-to-back runs must not double-count
        fires0 = faults.fire_counts()
        reqid0 = self.sim.reqid_stats()
        for i, (name, mode, n) in enumerate(cfg.faultpoints):
            faults.arm(name, mode=mode, n=n, seed=cfg.seed * 1000 + i)
            self._log("arm", name, mode, n)
        proven = [name for name, _, _ in cfg.faultpoints]
        if cfg.netsplit:
            proven.append("net.partition")
        failures = self.failures
        try:
            # steady-state oracle before the first fault
            for pool_id in self.pool_ids:
                for j in range(cfg.objects):
                    self._write(pool_id, f"thrash-{j}")
            for cycle in range(cfg.cycles):
                self._log("cycle", cycle)
                if cfg.netsplit:
                    if self.partition is None and \
                            self.rng.random() < cfg.partition_prob:
                        self._cut()
                    self._tick_detection()
                    self._load()
                    self._recover()
                    if self.partition is not None and \
                            self.rng.random() < cfg.heal_prob:
                        self._heal()
                        self._tick_detection()
                        self._recover()
                    # parked sub-min_size writes re-drive once the
                    # cluster moved (heal above, or a non-ride-out
                    # cut's markdowns re-homed their PGs)
                    self._unpark()
                else:
                    self._kill_one()
                    self._tick_detection()
                    self._load()
                    self._recover()
                    if self.rng.random() < cfg.revive_prob:
                        self._revive_one()
                        self._tick_detection()
                        self._recover()
                    self._unpark()
            # settle: stop injecting, bring everyone back, repair
            # until health converges (the reference's thrasher also
            # stops thrashing before its final wait_for_clean)
            fire_counts = {
                name: faults.fire_counts().get(name, 0) -
                fires0.get(name, 0)
                for name in proven}
            for name, _, _ in cfg.faultpoints:
                faults.disarm(name)
            self._log("settle")
            if cfg.netsplit:
                self._heal()       # also disarms net.partition
            # _revive_one un-marks out AND restores in-weight
            # (osd_boot commits weight 0x10000), so draining `down`
            # also drains `out` — out is only ever a subset of down
            while self.down:
                self._revive_one()
            self._tick_detection()
            # every parked write must unblock once the cluster is
            # whole — the min_size floor blocks, it must not lose
            self._unpark()
            if self.parked:
                failures.append(
                    f"{len(self.parked)} write(s) still blocked "
                    f"below min_size after full heal")
            health = ""
            health_ticks = cfg.settle_ticks
            for tick in range(cfg.settle_ticks):
                if cfg.netsplit:
                    # flap-held victims keep re-announcing each tick
                    # (the daemon heartbeat's MOSDBoot re-send); the
                    # hold expires on this same tick clock
                    self._boot_survivors()
                self._recover()
                self.hb.tick()
                health = self.mon.health_status(self.sim)
                if health == "HEALTH_OK":
                    health_ticks = tick + 1
                    break
            if health != "HEALTH_OK":                        # I4
                checks = [f"{c.code}: {c.summary}"
                          for c in self.mon.health(self.sim)]
                failures.append(
                    f"health did not converge within "
                    f"{cfg.settle_ticks} ticks: {health} ({checks})")
            # I1: nothing stuck in flight
            inflight = _op_tracker().dump_ops_in_flight()["num_ops"]
            if inflight:
                failures.append(f"{inflight} ops stuck in flight")
            # I2: readback against the oracle — zero data loss
            lost: List[str] = []
            for (pool_id, name), want in sorted(self.oracle.items()):
                try:
                    got = self.client.get(pool_id, name)
                except (IOError, KeyError) as e:
                    lost.append(f"{pool_id}/{name}: unreadable ({e})")
                    continue
                if got != want:
                    lost.append(f"{pool_id}/{name}: payload mismatch")
            failures.extend(lost)
            # I3: deep scrub (EC parity re-encode) clean after repair
            scrub_bad = 0
            for pool_id in self.pool_ids:
                bad = self.sim.scrub(pool_id)
                if bad:
                    self._recover()              # repair, then re-check
                    bad = self.sim.scrub(pool_id)
                scrub_bad += len(bad)
            if scrub_bad:
                failures.append(
                    f"deep scrub: {scrub_bad} inconsistencies "
                    f"after repair")
            # I5: the injections really happened
            for name in proven:
                if fire_counts.get(name, 0) < 1:
                    failures.append(
                        f"faultpoint {name} armed but never fired — "
                        f"the soak exercised nothing")
            # I6 (netsplit): replay idempotency — no logical op was
            # durably applied twice, however many times the cut/ack
            # loss forced the client to resend it
            reqid = self.sim.reqid_stats()
            double_commits = reqid["double_commits"] - \
                reqid0["double_commits"]
            replay_dups = self.client.replay_dups
            if double_commits:
                failures.append(
                    f"replay idempotency broken: {double_commits} "
                    f"ops applied more than once")
            if cfg.netsplit and \
                    fire_counts.get("msg.drop_ack", 0) >= 1 and \
                    replay_dups < 1:
                failures.append(
                    "acks were dropped but no resend was ever "
                    "dup-suppressed — the replay path never ran")
            # I7 (netsplit): mon epoch history is LINEAR — committed
            # incrementals form one gapless, forkless chain ending at
            # the live map (a split brain would fork or repeat epochs)
            epochs = [i.epoch for i in self.mon.incrementals]
            linear = epochs == sorted(set(epochs)) and \
                (not epochs or
                 (epochs == list(range(epochs[0], epochs[-1] + 1)) and
                  epochs[-1] == self.sim.osdmap.epoch))
            if cfg.netsplit and not linear:
                failures.append(
                    f"mon epoch history not linear: "
                    f"{epochs[:5]}..{epochs[-5:]} vs map epoch "
                    f"{self.sim.osdmap.epoch}")
            return {
                "seed": cfg.seed,
                "cycles": cfg.cycles,
                "netsplit": cfg.netsplit,
                "schedule": [list(e) for e in self.schedule],
                "fire_counts": fire_counts,
                "invariants": {
                    "ops_in_flight": inflight,
                    "objects_checked": len(self.oracle),
                    "data_loss": lost,
                    "scrub_inconsistencies": scrub_bad,
                    "health": health,
                    "health_ticks": health_ticks,
                    "backoff_ticks": self.client.clock.sleeps,
                    "replay_double_commits": double_commits,
                    "replay_dups_suppressed": replay_dups,
                    "mon_epochs_linear": linear,
                    "boots_held": self.mon.boots_held,
                    "writes_parked": self.writes_parked,
                    "writes_still_parked": len(self.parked),
                },
                "failures": failures,
                "ok": not failures,
            }
        finally:
            for name, _, _ in cfg.faultpoints:
                faults.disarm(name)
            faults.disarm("net.partition")


# ----------------------------------------------------------- powercycle --

@dataclass
class PowerCycleConfig:
    """`ceph thrash --powercycle`: power-cycle whole OSD *daemons* —
    SIGKILL-class death driven by the store-tier power-loss
    faultpoints, crash-state mutation of the backing BlueStore, then
    reboot under client load."""
    seed: int = 0
    cycles: int = 3
    n_osds: int = 4
    objects: int = 6                  # steady-state oracle objects
    object_size: int = 3072
    writes_per_cycle: int = 3         # steady overwrites (must ack)
    kill_writes: int = 14             # fresh-name writes driven while
    # the armed faultpoint waits to brown the victim out; ones that
    # ack join the oracle, ones the cut interrupts carry no promise
    hb_interval: float = 0.25
    wait_ticks: int = 240             # state-poll budget (0.25s each)


class PowerCycleThrasher:
    """Seeded daemon power-cycle soak (the thrashosds powercycle
    flavor: qa's thrashosds with powercycle=true).

    Per cycle: seeded steady writes (retried until acked), then a
    victim OSD gets ``device.power_loss`` or ``device.torn_write``
    armed over its OWN asok (``exit=True``) — its next store barrier
    or data write browns it out mid-transaction, exactly a power cut.
    If the schedule's write budget never touches the victim's store,
    a SIGKILL fallback keeps the run moving WITHOUT entering the
    schedule (so schedules stay bit-identical per seed).  The dead
    store then takes a crash-state mutation (``tear_wal_tail``: bytes
    off the trailing *partial* WAL record — a fragment that never
    completed its commit), and the daemon reboots: its boot sees the
    POWER_LOSS marker, runs fsck(repair=True), and reports
    STORE_DAMAGED up the heartbeat.

    Invariants: **zero acked-write loss** against the oracle after
    recovery, fsck errors post-cycle reported (and expected 0 — the
    WAL/COW ordering makes power cuts lossless), and the same seed
    reproduces the identical schedule."""

    def __init__(self, cluster_dir: str,
                 cfg: Optional[PowerCycleConfig] = None):
        self.dir = cluster_dir
        self.cfg = cfg or PowerCycleConfig()
        self.rng = random.Random(self.cfg.seed)
        self.schedule: List[Tuple] = []
        self.oracle: Dict[Tuple[int, str], bytes] = {}
        self.failures: List[str] = []
        self.fsck_errors_post_cycle = 0
        self.fsck_repaired = 0
        self.powercycles = 0
        self.fallback_kills = 0

    def _log(self, *event: Any) -> None:
        self.schedule.append(tuple(event))

    def _blob(self, n: int) -> bytes:
        return bytes(self.rng.getrandbits(8) for _ in range(n))

    def _wait(self, fn, desc: str) -> bool:
        """Bounded wait-for-state: the budget is POLLS, not wall
        clock, and a connection error costs one poll (a rebooting
        daemon must not burn the whole window)."""
        import time as _time
        for _ in range(self.cfg.wait_ticks):
            try:
                if fn():
                    return True
            except (OSError, IOError):
                pass
            _time.sleep(0.25)
        self.failures.append(f"wait-for-state timed out: {desc}")
        return False

    def _steady_write(self, rc, name: str) -> None:
        data = self._blob(self.cfg.object_size)
        # the schedule event is logged BEFORE the attempt: whether
        # the write needed one try or twenty is timing, and timing
        # must never leak into the seeded schedule
        self._log("write", 1, name)
        # steady writes are the oracle seed and MUST ack — give them
        # the same poll budget as every other wait-for-state (a
        # daemon rebooting from the previous cycle can eat the put
        # path's own retry budget under contention)
        if self._wait(lambda: rc.put(1, name, data) >= 1,
                      f"steady write {name} acked"):
            self.oracle[(1, name)] = data

    def _powercycle(self, rc, v, cycle: int) -> None:
        from ..common.admin import admin_request
        cfg = self.cfg
        victim = self.rng.randrange(cfg.n_osds)
        point = ("device.power_loss"
                 if self.rng.random() < 0.5 else "device.torn_write")
        n_in = 2 + self.rng.randrange(3)
        self._log("powercycle", cycle, victim, point, n_in)
        asok = os.path.join(self.dir, f"osd.{victim}.asok")
        try:
            admin_request(asok, {
                "prefix": "fault_injection", "action": "arm",
                "name": point, "mode": "one_in", "n": n_in,
                "seed": cfg.seed * 1000 + cycle,
                "params": {"exit": True}})
        except (OSError, IOError) as e:
            self.failures.append(f"arming {point} on osd.{victim} "
                                 f"failed: {e}")
        # fresh-name kill-window writes: acked ones join the oracle
        # (an ack means every landing daemon fsynced), interrupted
        # ones carry no promise.  The rng draws are unconditional so
        # the schedule never depends on WHEN the victim dies.
        for i in range(cfg.kill_writes):
            name = f"pc-{cycle}-{i}"
            data = self._blob(cfg.object_size)
            self._log("kill_write", 1, name)
            try:
                rc.put(1, name, data)
                self.oracle[(1, name)] = data
            except (OSError, IOError):
                pass                  # unacked: no promise
            if not v.alive(f"osd.{victim}"):
                break
        if v.alive(f"osd.{victim}"):
            # the write budget never hit the victim's store: SIGKILL
            # keeps the soak moving (timing-dependent, so it stays
            # OUT of the seeded schedule)
            v.kill9(f"osd.{victim}")
            self.fallback_kills += 1
        self.powercycles += 1
        # crash-state mutation of the dead backing store: tear the
        # WAL's trailing partial record (never a completed commit)
        from .crashdev import tear_wal_tail
        store = os.path.join(self.dir, f"osd.{victim}.store")
        # torn-byte count is timing-dependent (did a partial record
        # exist?) so it stays OUT of the seeded schedule; the rng
        # draw inside tear_wal_tail is unconditional, keeping rng
        # state — and therefore the schedule — bit-identical per seed
        tear_wal_tail(store, self.rng)
        self._log("wal_tear", cycle, victim)
        # reboot: boot-time fsck(repair) runs iff a POWER_LOSS marker
        # landed; collect its verdict over the asok
        v.start_osd(victim, hb_interval=cfg.hb_interval)
        self._wait(lambda: rc.status()["n_up"] >= cfg.n_osds - 1,
                   f"osd.{victim} back up after cycle {cycle}")
        try:
            r = admin_request(asok, {"prefix": "store_fsck"})["result"]
            self.fsck_errors_post_cycle += int(r["n_errors"])
        except (OSError, IOError, KeyError) as e:
            self.failures.append(
                f"post-cycle fsck on osd.{victim} failed: {e}")
        try:
            rc.refresh_map()
        except (OSError, IOError):
            pass

    def run(self) -> Dict[str, Any]:
        from ..client.remote import RemoteCluster
        from ..tools.vstart import Vstart, build_cluster_dir
        cfg = self.cfg
        build_cluster_dir(self.dir, n_osds=cfg.n_osds,
                          osds_per_host=1, fsync=True)
        v = Vstart(self.dir)
        v.start(cfg.n_osds, hb_interval=cfg.hb_interval)
        rc = None
        try:
            rc = RemoteCluster(self.dir)
            for j in range(cfg.objects):
                self._steady_write(rc, f"pcobj-{j}")
            for cycle in range(cfg.cycles):
                self._log("cycle", cycle)
                for _ in range(cfg.writes_per_cycle):
                    self._steady_write(
                        rc, f"pcobj-{self.rng.randrange(cfg.objects)}")
                self._powercycle(rc, v, cycle)
            # settle: everyone up, recover, then the oracle readback
            self._wait(lambda: rc.status()["n_up"] == cfg.n_osds,
                       "all OSDs up at settle")
            rc.refresh_map()
            try:
                rc.recover_pool(1)
            except (OSError, IOError) as e:
                self.failures.append(f"settle recovery failed: {e}")
            lost: List[str] = []
            for (pool_id, name), want in sorted(self.oracle.items()):
                try:
                    got = rc.get(pool_id, name)
                except (OSError, IOError, KeyError) as e:
                    lost.append(f"{pool_id}/{name}: unreadable ({e})")
                    continue
                if got != want:
                    lost.append(f"{pool_id}/{name}: payload mismatch")
            if lost:
                self.failures.extend(lost)
            if self.fsck_errors_post_cycle:
                self.failures.append(
                    f"boot fsck found {self.fsck_errors_post_cycle} "
                    f"damaged objects after power cycles (the WAL/COW "
                    f"ordering should make cuts lossless)")
            return {
                "seed": cfg.seed,
                "cycles": cfg.cycles,
                "powercycle": True,
                "schedule": [list(e) for e in self.schedule],
                "invariants": {
                    "acked_writes_lost": len(lost),
                    "objects_checked": len(self.oracle),
                    "fsck_errors_post_cycle":
                        self.fsck_errors_post_cycle,
                    "powercycles": self.powercycles,
                    "fallback_kills": self.fallback_kills,
                },
                "failures": self.failures,
                "ok": not self.failures,
            }
        finally:
            if rc is not None:
                rc.close()
            v.stop()


# ------------------------------------------------------------ standalone --

def build_default_stack(n_hosts: int = 8, osds_per_host: int = 3,
                        k: int = 4, m: int = 2):
    """A self-contained sim cluster for `ceph thrash` and the
    robustness smoke: replicated + EC pools over a flat host tree
    (same geometry as the test suite's standard sim, so persistent
    XLA cache entries are shared)."""
    from ..placement.builder import build_flat_cluster
    from ..placement.crush_map import (RULE_CHOOSELEAF_FIRSTN,
                                       RULE_CHOOSELEAF_INDEP,
                                       RULE_EMIT, RULE_TAKE, Rule)
    from .osdmap import OSDMap, PGPool, POOL_ERASURE, POOL_REPLICATED
    from .simulator import ClusterSim
    cmap, root = build_flat_cluster(n_hosts=n_hosts,
                                    osds_per_host=osds_per_host,
                                    seed=0)
    host_type = 1
    cmap.add_rule(Rule(steps=[(RULE_TAKE, root, 0),
                              (RULE_CHOOSELEAF_FIRSTN, 0, host_type),
                              (RULE_EMIT, 0, 0)]))
    cmap.add_rule(Rule(steps=[(RULE_TAKE, root, 0),
                              (RULE_CHOOSELEAF_INDEP, 0, host_type),
                              (RULE_EMIT, 0, 0)]))
    om = OSDMap(cmap)
    om.mark_all_in_up()
    om.add_pool(PGPool(id=1, name="rep", type=POOL_REPLICATED, size=3,
                       pg_num=32, crush_rule=0))
    om.add_pool(PGPool(id=2, name="ec", type=POOL_ERASURE, size=k + m,
                       pg_num=32, crush_rule=1,
                       erasure_code_profile="default"))
    sim = ClusterSim(om)
    sim.create_ec_profile("default", {"plugin": "jax", "k": str(k),
                                      "m": str(m)})
    mon = Monitor(sim.osdmap, failure_reports_needed=2)
    return sim, mon


def main(argv: Optional[Sequence[str]] = None, out=None) -> int:
    """`ceph thrash --seed N --cycles K --json`: a self-contained
    seeded soak emitting the invariant report (exit 1 on any broken
    invariant).  Needs no cluster dir — like `ceph lint`, it builds
    its own stack."""
    import argparse
    import sys
    out = out or sys.stdout
    ap = argparse.ArgumentParser(
        prog="ceph thrash",
        description="seeded kill/revive soak with self-healing "
                    "invariants (the thrashosds role)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cycles", type=int, default=5)
    ap.add_argument("--objects", type=int, default=6)
    ap.add_argument("--netsplit", action="store_true",
                    help="seeded partition/heal soak instead of "
                         "kill/revive: cuts a minority of OSDs off "
                         "(sometimes one-way, sometimes ridden out "
                         "under noout/nodown), with session-replay "
                         "and mon-epoch-linearity invariants")
    ap.add_argument("--powercycle", action="store_true",
                    help="power-cycle whole OSD daemons instead: arm "
                         "device.power_loss/torn_write over each "
                         "victim's asok so its store barrier browns "
                         "it out mid-transaction, tear the dead "
                         "store's partial WAL tail, reboot (boot "
                         "fsck reports STORE_DAMAGED) — invariants: "
                         "zero acked-write loss, fsck clean, "
                         "bit-identical schedule per seed")
    ap.add_argument("--json", action="store_true")
    ns = ap.parse_args(argv)
    if ns.powercycle:
        import tempfile
        import shutil
        d = tempfile.mkdtemp(prefix="ceph-powercycle-")
        try:
            t = PowerCycleThrasher(d, PowerCycleConfig(
                seed=ns.seed, cycles=ns.cycles,
                objects=ns.objects))
            report = t.run()
        finally:
            shutil.rmtree(d, ignore_errors=True)
        if ns.json:
            out.write(json.dumps(report, indent=2, sort_keys=True,
                                 default=str) + "\n")
        else:
            inv = report["invariants"]
            out.write(
                f"powercycle seed={report['seed']} "
                f"cycles={report['cycles']}: "
                f"{inv['powercycles']} power cycles "
                f"({inv['fallback_kills']} SIGKILL fallbacks), "
                f"{inv['objects_checked']} objects checked, "
                f"acked_writes_lost={inv['acked_writes_lost']}, "
                f"fsck_errors_post_cycle="
                f"{inv['fsck_errors_post_cycle']}\n")
            for f in report["failures"]:
                out.write(f"FAIL: {f}\n")
            if report["ok"]:
                out.write("all invariants held\n")
        return 0 if report["ok"] else 1
    sim, mon = build_default_stack()
    try:
        cfg = ThrashConfig(seed=ns.seed, cycles=ns.cycles,
                           objects=ns.objects)
        if ns.netsplit:
            cfg.netsplit = True
            cfg.faultpoints = NETSPLIT_FAULTPOINTS
            cfg.settle_ticks = max(cfg.settle_ticks, 40)
        t = Thrasher(sim, mon, [1, 2], cfg)
        report = t.run()
    finally:
        sim.shutdown()
    if ns.json:
        out.write(json.dumps(report, indent=2, sort_keys=True,
                             default=str) + "\n")
    else:
        inv = report["invariants"]
        out.write(
            f"thrash seed={report['seed']} cycles={report['cycles']}: "
            f"{len(report['schedule'])} events, "
            f"fires={report['fire_counts']}, "
            f"objects={inv['objects_checked']}, "
            f"health={inv['health']} "
            f"(in {inv['health_ticks']} ticks)\n")
        for f in report["failures"]:
            out.write(f"FAIL: {f}\n")
        if report["ok"]:
            out.write("all invariants held\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":      # pragma: no cover
    raise SystemExit(main())
