"""Striping math: byte streams → objects (the long-sequence sharding).

Pure index arithmetic re-creating file_layout_t and Striper::file_to_extents
(reference: src/include/fs_types.h:127-148, src/osdc/Striper.h:26-31): a
logical byte stream is round-robined in ``stripe_unit`` blocks across
``stripe_count`` objects, rolling to a new object set every
``object_size`` bytes per object.  Within an EC pool each object is then
further split into k sub-chunks by the codec (stripe_info_t,
src/osd/ECUtil.h:28-60) — giving the TPU batch layout
[num_stripes, k, chunk_bytes].
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np


@dataclass(frozen=True)
class FileLayout:
    stripe_unit: int = 1 << 22
    stripe_count: int = 1
    object_size: int = 1 << 22

    def __post_init__(self):
        if self.stripe_unit <= 0 or self.stripe_count <= 0 or \
                self.object_size <= 0:
            raise ValueError("layout fields must be positive")
        if self.object_size % self.stripe_unit:
            raise ValueError("object_size must be a multiple of stripe_unit")

    @property
    def stripes_per_object(self) -> int:
        return self.object_size // self.stripe_unit


def file_to_extents(layout: FileLayout, offset: int, length: int
                    ) -> List[Tuple[int, int, int]]:
    """[(objectno, offset_in_object, length), ...] covering
    [offset, offset+length), in stream order."""
    out: List[Tuple[int, int, int]] = []
    su, sc = layout.stripe_unit, layout.stripe_count
    spo = layout.stripes_per_object
    cur = offset
    end = offset + length
    while cur < end:
        blockno = cur // su
        stripeno = blockno // sc
        stripepos = blockno % sc
        objectsetno = stripeno // spo
        objectno = objectsetno * sc + stripepos
        block_start = (stripeno % spo) * su
        block_off = cur % su
        x_offset = block_start + block_off
        x_len = min(end - cur, su - block_off)
        out.append((objectno, x_offset, x_len))
        cur += x_len
    return out


def extents_to_objects(layout: FileLayout, data: bytes, offset: int = 0
                       ) -> Dict[int, Dict[int, bytes]]:
    """Split a write into per-object fragments {objectno: {off: bytes}}."""
    frags: Dict[int, Dict[int, bytes]] = {}
    pos = 0
    for objno, ooff, olen in file_to_extents(layout, offset, len(data)):
        frags.setdefault(objno, {})[ooff] = data[pos:pos + olen]
        pos += olen
    return frags


def read_from_objects(layout: FileLayout, objects: Dict[int, bytes],
                      offset: int, length: int) -> bytes:
    """Inverse of extents_to_objects for already-assembled object payloads
    (missing bytes read as zeros, matching sparse object semantics)."""
    out = bytearray(length)
    pos = 0
    for objno, ooff, olen in file_to_extents(layout, offset, length):
        payload = objects.get(objno, b"")
        piece = payload[ooff:ooff + olen]
        out[pos:pos + len(piece)] = piece
        pos += olen
    return bytes(out)
